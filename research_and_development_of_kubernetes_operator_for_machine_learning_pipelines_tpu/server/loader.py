"""Model loading: URI -> artifact directory -> Predictor.

Tiered resolution (SURVEY §7 hard part 2 — not every MLflow model is
jit-compilable):

1. read the artifact's ``MLmodel`` YAML (MLflow layout) when present;
2. pick the best flavor: our native ``tpumlops`` flavor (params.npz +
   config.json, fully TPU-native) > ``sklearn`` (lifted into JAX via the
   registry's converters) > ``python_function`` (host-side pyfunc tier);
3. bare directories fall back on file sniffing (params.npz / model.pkl).

URI schemes: local paths and ``file://`` load directly.  Object-store URIs
(``s3://``, ``gs://``) resolve through ``TPUMLOPS_ARTIFACT_MIRROR`` — a
local mount of the bucket (in-cluster the CSI driver or an init container
materializes ``s3://<bucket>/<path>`` under the mirror root, keyed by
bucket).  This keeps the server free of cloud-SDK dependencies.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import time
from pathlib import Path
from typing import Any

import numpy as np

from ..models.registry import Predictor, get_builder

_log = logging.getLogger(__name__)
# The model-capacity startup line (weights by dtype, KV bytes/row, max
# cache rows) — its own logger so dashboards/tests grep one name.
# Emitted for every causal-LM load regardless of deviceTelemetry.
_capacity_log = logging.getLogger("tpumlops.capacity")

MIRROR_ENV = "TPUMLOPS_ARTIFACT_MIRROR"


class ModelLoadError(Exception):
    pass


# ---------------------------------------------------------------------------
# URI resolution
# ---------------------------------------------------------------------------


def resolve_uri(model_uri: str) -> Path:
    """Resolve a model URI to a local directory."""
    if model_uri.startswith("file://"):
        path = Path(model_uri[len("file://"):])
    elif "://" in model_uri:
        scheme, rest = model_uri.split("://", 1)
        mirror = os.environ.get(MIRROR_ENV)
        if not mirror:
            raise ModelLoadError(
                f"cannot fetch {model_uri!r}: no {MIRROR_ENV} mirror configured "
                f"(mount the {scheme} bucket and set {MIRROR_ENV})"
            )
        path = Path(mirror) / rest
    else:
        path = Path(model_uri)
    if not path.exists():
        raise ModelLoadError(f"model path {path} does not exist")
    return path


# ---------------------------------------------------------------------------
# Native tpumlops format: params.npz (flattened pytree) + config.json
# ---------------------------------------------------------------------------

_SEP = "|"


def _flatten(
    tree: Any, prefix: str = "", convert: bool = True
) -> dict[str, np.ndarray]:
    """Flatten a pytree to ``{joined-key: leaf}``.  ``convert=False``
    keeps device arrays as-is (the snapshot writer needs their SHARDING,
    which ``np.asarray`` would collapse by gathering to host)."""
    out: dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}{_SEP}", convert))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}#{i}{_SEP}", convert))
    else:
        out[prefix.rstrip(_SEP)] = np.asarray(tree) if convert else tree
    return out


def _unflatten(flat: dict[str, np.ndarray]) -> Any:
    root: dict = {}
    for key, value in flat.items():
        parts = key.split(_SEP)
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value

    def listify(node):
        if not isinstance(node, dict):
            return node
        if node and all(k.startswith("#") for k in node):
            return [listify(node[f"#{i}"]) for i in range(len(node))]
        return {k: listify(v) for k, v in node.items()}

    return listify(root)


def save_native_model(
    path: str | Path,
    flavor: str,
    params: Any,
    config: dict | None = None,
    builder_kwargs: dict | None = None,
) -> Path:
    """Write our native artifact layout (with an MLmodel file so MLflow-side
    tooling still recognizes the directory)."""
    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    np.savez(path / "params.npz", **_flatten(params))
    meta = {
        "flavor": flavor,
        "config": config or {},
        "builder_kwargs": builder_kwargs or {},
    }
    (path / "config.json").write_text(json.dumps(meta, indent=2))
    (path / "MLmodel").write_text(
        "flavors:\n"
        "  tpumlops:\n"
        "    format: params-npz\n"
        f"    flavor: {flavor}\n"
    )
    return path


def save_sklearn_model(path: str | Path, model: Any, flavor: str) -> Path:
    """Write an MLflow-sklearn-compatible artifact (pickle + MLmodel)."""
    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    with open(path / "model.pkl", "wb") as f:
        pickle.dump(model, f)
    (path / "MLmodel").write_text(
        "flavors:\n"
        "  sklearn:\n"
        "    pickled_model: model.pkl\n"
        "  python_function:\n"
        "    loader_module: mlflow.sklearn\n"
        f"# tpumlops flavor hint: {flavor}\n"
    )
    (path / "config.json").write_text(json.dumps({"flavor": flavor}))
    return path


def save_xgboost_model(path: str | Path, model_json: dict) -> Path:
    """Write an MLflow-xgboost-compatible artifact from a parsed JSON model
    (the dict ``Booster.save_model("model.json")`` produces)."""
    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    (path / "model.json").write_text(json.dumps(model_json))
    (path / "MLmodel").write_text(
        "flavors:\n"
        "  xgboost:\n"
        "    data: model.json\n"
        "    model_format: json\n"
        "  python_function:\n"
        "    loader_module: mlflow.xgboost\n"
        "    data: model.json\n"
    )
    return path


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------

_CONFIG_CLASSES = {
    "bert-classifier": ("bert", "BertConfig"),
    "resnet-classifier": ("resnet", "ResNetConfig"),
    "llama-generate": ("llama", "LlamaConfig"),
}


def _build_config(flavor: str, config_dict: dict) -> Any:
    if flavor not in _CONFIG_CLASSES:
        return None
    mod_name, cls_name = _CONFIG_CLASSES[flavor]
    import importlib

    mod = importlib.import_module(f"..models.{mod_name}", __package__)
    cls = getattr(mod, cls_name)
    known = {f for f in cls.__dataclass_fields__}
    return cls(**{k: v for k, v in config_dict.items() if k in known})


def _shard_for_flavor(flavor: str, params: Any, cfg: Any, mesh_shape: dict) -> Any:
    """Place params on a device mesh.

    The mesh covers the first ``prod(mesh_shape)`` visible devices (the
    reconcile-time topology check pins prod == chip count in-cluster;
    dev environments with more devices shard over a prefix).  llama goes
    through the ``models/partition.py`` regex rule table — the same
    table the engine's cache/state shardings and per-shard snapshots
    key off — with the meshShape/geometry divisibility check applied
    FIRST so a bad tp fails typed here, not as an XLA shape error at
    the first warmup dispatch.  Other flavors keep their logical-axes
    tables."""
    from ..models.partition import build_serving_mesh
    from ..parallel import shard_pytree

    if flavor == "llama-generate":
        from ..models import partition

        try:
            partition.validate_llama_mesh(cfg, mesh_shape)
        except ValueError as e:
            raise ModelLoadError(str(e)) from None
        mesh = build_serving_mesh(mesh_shape)
        _log.info("sharding %s params over mesh %s", flavor, mesh_shape)
        return partition.shard_llama_params(params, mesh)
    mesh = build_serving_mesh(mesh_shape)
    if flavor == "bert-classifier":
        from ..models import bert

        axes = bert.param_logical_axes(params)
    elif flavor == "resnet-classifier":
        from ..models import resnet

        axes = resnet.param_logical_axes(params)
    else:
        import jax

        axes = jax.tree.map(lambda _: None, params)
    _log.info("sharding %s params over mesh %s", flavor, mesh_shape)
    return shard_pytree(params, axes, mesh)


def _finish_native(
    flavor: str,
    params: Any,
    cfg: Any,
    builder_kwargs: dict,
    mesh_shape: dict | None,
    quantize: str | None,
    raw_config: dict | None = None,
    stats: dict | None = None,
) -> Predictor:
    """Shared tail for JAX-native param trees: shard, quantize, build.

    ``raw_config`` is the artifact's config dict as written — used to
    tell an explicit ``hidden_act`` pin apart from a dataclass default.
    ``stats`` (optional dict) accrues the ``shard_s`` / ``quantize_s``
    stage walls so the load breakdown covers this tail too."""
    n_devices = 1
    for v in (mesh_shape or {}).values():
        n_devices *= int(v)
    if mesh_shape and n_devices > 1:
        t0 = time.perf_counter()
        params = _shard_for_flavor(flavor, params, cfg, mesh_shape)
        if stats is not None:
            stats["shard_s"] = round(
                stats.get("shard_s", 0.0) + time.perf_counter() - t0, 2
            )
    t_quant = time.perf_counter()
    if quantize and quantize != "none":
        # After sharding: the jitted quantizer preserves input shardings
        # and computes per-channel scales with an on-mesh reduction.
        if quantize not in ("int8", "int8kv"):
            raise ModelLoadError(f"unknown quantize mode {quantize!r}")
        if flavor == "llama-generate":
            # Decode is HBM-bound: weight-only int8 halves the bytes
            # streamed per token (int8kv additionally quantizes the cache).
            from ..models.quantization import quantize_llama

            params = quantize_llama(params)
        elif flavor == "bert-classifier":
            # Prefill-style classify is MXU-bound: encoder matmuls run as
            # true int8 x int8 -> int32 on the MXU with dynamic per-token
            # activation scales (models/quantization.dense_q8).
            if quantize == "int8kv":
                raise ModelLoadError(
                    "int8kv quantizes a KV cache; bert-classifier has "
                    "none — use quantize: int8"
                )
            from ..models.quantization import quantize_bert

            params = quantize_bert(params)
            # quantize: int8 is an explicit speed-for-approximation
            # opt-in, so the MLP activation also drops to tanh-GELU
            # (error ~1e-3, far under int8 quant noise; erf is ~1.8 ms
            # of unfused VPU work per b32/s128 batch on v5e).  An
            # artifact that pins hidden_act keeps its pin.
            if cfg is not None and "hidden_act" not in (raw_config or {}):
                import dataclasses

                cfg = dataclasses.replace(cfg, hidden_act="gelu_tanh")
                # Numerics change vs the same artifact served bf16 —
                # surface it at load time, not just in a code comment.
                _log.info(
                    "int8 path substituting hidden_act=gelu_tanh for "
                    "artifact without a hidden_act pin (set hidden_act "
                    "in the saved config to keep exact-erf GELU)"
                )
        else:
            raise ModelLoadError(
                f"quantize={quantize!r} is not supported for flavor "
                f"{flavor!r} (supported: llama-generate, bert-classifier)"
            )
        if mesh_shape and n_devices > 1 and flavor == "llama-generate":
            # Re-pin the quantized tree to the rule table's canonical
            # shardings: the jitted quantizer keeps everything ON the
            # mesh but XLA may pick its own layout for the new q8/scale
            # planes, and the per-shard snapshot (plus the engine's
            # explicit output shardings) key off the canonical one.
            from ..models import partition

            mesh = partition.build_serving_mesh(mesh_shape)
            params = partition.shard_llama_params(params, mesh)
        _log.info("quantized %s weights to int8 (mode=%s)", flavor, quantize)
        if stats is not None:
            stats["quantize_s"] = round(
                stats.get("quantize_s", 0.0) + time.perf_counter() - t_quant, 2
            )
    kwargs = dict(builder_kwargs)
    if cfg is not None:
        kwargs["cfg"] = cfg
    return get_builder(flavor)(params, **kwargs)


def _log_capacity(
    predictor, quantize: str | None, load_stats: dict | None = None
) -> None:
    """One startup capacity line per causal-LM load: the analytic HBM
    story (weights bytes by dtype, KV bytes per cache row, max rows the
    device could hold) a capacity planner needs BEFORE any traffic —
    emitted even with deviceTelemetry off (the telemetry layer serves
    the live, cross-checked version at /debug/device).  The load-stage
    breakdown (disk/transfer/quantize/shard — or restore_s on the
    snapshot path) rides the same line so cold-start regressions show up
    on a dashboard grep, not just in bench JSON."""
    lm = getattr(predictor, "causal_lm", None)
    if not lm:
        return
    try:
        from .device_telemetry import capacity_log_line

        line = capacity_log_line(
            lm["params"], lm["cfg"], kv_quant=quantize == "int8kv"
        )
        if load_stats:
            line += " load_breakdown_s=" + json.dumps(
                load_stats, sort_keys=True
            )
        _capacity_log.info("%s", line)
    except Exception:
        # Telemetry must never fail a load.
        _log.debug("capacity summary failed", exc_info=True)


def _find_hf_checkpoint(path: Path) -> Path | None:
    """Locate a HuggingFace checkpoint inside an MLflow transformers
    artifact (or a bare checkpoint directory).

    MLflow's transformers flavor stores the pipeline under ``model/`` (the
    MLmodel declares ``flavors.transformers``); a directory counts as a
    checkpoint when it has an HF ``config.json`` (with ``model_type``)
    plus weights."""
    candidates = [path, path / "model", path / "pipeline"]
    candidates += [p for p in sorted(path.iterdir()) if p.is_dir()] if path.is_dir() else []
    seen = set()
    for cand in candidates:
        if cand in seen or not cand.is_dir():
            continue
        seen.add(cand)
        cfg_file = cand / "config.json"
        if not cfg_file.exists():
            continue
        try:
            hf_cfg = json.loads(cfg_file.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(hf_cfg, dict) or "model_type" not in hf_cfg:
            continue
        weight_markers = (
            "pytorch_model.bin",
            "model.safetensors",
            # sharded checkpoints (the norm at 7B+) ship an index file
            "model.safetensors.index.json",
            "pytorch_model.bin.index.json",
        )
        if any((cand / w).exists() for w in weight_markers):
            return cand
    return None


def _load_transformers(hf_dir: Path):
    """HF checkpoint -> (flavor, JAX params, config) via the from_torch
    converters (weight-copy parity tested in tests/test_models_*).

    Params are cast to bf16 for serving (matmuls accumulate in f32
    model-side); a 7B checkpoint would not fit HBM in the f32 torch
    loads produce."""
    import jax
    import jax.numpy as jnp

    hf_cfg = json.loads((hf_dir / "config.json").read_text())
    model_type = hf_cfg.get("model_type")

    if model_type == "llama":
        from transformers import LlamaForCausalLM

        from ..models import llama

        scaling = hf_cfg.get("rope_scaling")
        if scaling:
            # Our RoPE is plain theta-based; serving a llama3/linear-scaled
            # checkpoint with it would produce silently degraded tokens.
            raise ModelLoadError(
                f"rope_scaling {scaling!r} is not supported by the "
                "TPU-native llama (plain RoPE only)"
            )
        tm = LlamaForCausalLM.from_pretrained(hf_dir)
        raw_config = {}
        cfg = llama.LlamaConfig(
            vocab_size=int(hf_cfg["vocab_size"]),
            hidden_size=int(hf_cfg["hidden_size"]),
            num_layers=int(hf_cfg["num_hidden_layers"]),
            num_heads=int(hf_cfg["num_attention_heads"]),
            num_kv_heads=int(
                hf_cfg.get("num_key_value_heads")
                or hf_cfg["num_attention_heads"]
            ),
            intermediate_size=int(hf_cfg["intermediate_size"]),
            max_seq=int(hf_cfg.get("max_position_embeddings", 4096)),
            rope_theta=float(hf_cfg.get("rope_theta", 10000.0)),
            rms_eps=float(hf_cfg.get("rms_norm_eps", 1e-5)),
        )
        params = llama.from_torch(tm, cfg)
        flavor = "llama-generate"
        eos = hf_cfg.get("eos_token_id")
        if isinstance(eos, list):  # some checkpoints ship a list of eos ids
            eos = eos[0] if eos else None
        builder_kwargs = {"eos_id": int(eos)} if eos is not None else {}
    elif model_type == "bert":
        from transformers import BertForSequenceClassification

        from ..models import bert

        tm = BertForSequenceClassification.from_pretrained(hf_dir)
        # HF config.json always pins hidden_act explicitly; serving a
        # different activation than the checkpoint was trained with
        # would be silently wrong logits.  "gelu" in HF-land is exact
        # erf; the *_tanh/_new spellings are the tanh approximation.
        hf_act = str(hf_cfg.get("hidden_act", "gelu"))
        act_map = {
            "gelu": "gelu",
            "gelu_python": "gelu",
            "gelu_new": "gelu_tanh",
            "gelu_pytorch_tanh": "gelu_tanh",
        }
        if hf_act not in act_map:
            raise ModelLoadError(
                f"unsupported BERT hidden_act {hf_act!r} "
                f"(supported: {sorted(act_map)})"
            )
        cfg = bert.BertConfig(
            vocab_size=int(hf_cfg["vocab_size"]),
            hidden_size=int(hf_cfg["hidden_size"]),
            num_layers=int(hf_cfg["num_hidden_layers"]),
            num_heads=int(hf_cfg["num_attention_heads"]),
            intermediate_size=int(hf_cfg["intermediate_size"]),
            max_position_embeddings=int(
                hf_cfg.get("max_position_embeddings", 512)
            ),
            type_vocab_size=int(hf_cfg.get("type_vocab_size", 2)),
            layer_norm_eps=float(hf_cfg.get("layer_norm_eps", 1e-12)),
            num_labels=int(getattr(tm.config, "num_labels", 2)),
            hidden_act=act_map[hf_act],
        )
        params = bert.from_torch(tm, cfg)
        flavor = "bert-classifier"
        builder_kwargs = {}
        raw_config = {"hidden_act": act_map[hf_act]}
    else:
        raise ModelLoadError(
            f"unsupported transformers model_type {model_type!r} "
            "(supported: llama, bert)"
        )
    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if hasattr(x, "dtype") and x.dtype == jnp.float32
        else x,
        params,
    )
    return flavor, params, cfg, builder_kwargs, raw_config


# The llama leaves worth int8-ing at load time (mirrors
# quantization._LLAMA_LAYER_MATS + lm_head, in the npz's flat key space).
_LLAMA_STREAM_QUANT = tuple(
    f"layers{_SEP}{m}" for m in ("q", "k", "v", "o", "gate", "up", "down")
) + ("lm_head",)


def _stream_native_params(
    npz_path: Path,
    quantize_leaves: tuple = (),
    stats: dict | None = None,
) -> Any:
    """Load ``params.npz`` leaf-by-leaf onto the device, pipelined.

    Leaves named in ``quantize_leaves`` are int8-quantized ON ARRIVAL and
    their full-precision device copy freed before the next transfer.
    That bounds peak HBM at (int8 tree + one full-precision leaf) —
    without it a Llama-2-7B load with ``quantize: int8`` would need the
    whole bf16 tree (~13.5 GiB) **plus** its int8 copy simultaneously,
    which does not fit a 16 GiB v5e chip.

    A reader thread decompresses the next leaves from disk while the
    caller quantizes/transfers the current one (bounded queue, so host
    memory stays at a few leaves): disk and compute/wire time overlap
    instead of adding — a 7B cold load is disk-read dominated (VERDICT
    r3 weak #3).  ``stats`` (optional dict) is filled with the per-stage
    breakdown: ``disk_s`` / ``quantize_s`` / ``transfer_s`` / ``wall_s``
    / ``read_gib`` so a slow load says WHICH stage was slow.

    npz stores bfloat16 as raw void ``V2`` (numpy has no native bf16);
    such arrays are viewed back through ml_dtypes before transfer.
    """
    import queue as _queue
    import threading

    t_wall = time.perf_counter()
    timing = {"disk_s": 0.0, "quantize_s": 0.0, "transfer_s": 0.0,
              "read_bytes": 0}
    q: _queue.Queue = _queue.Queue(maxsize=2)
    reader_error: list[BaseException] = []
    abort = threading.Event()  # consumer died: reader must stop + clean up

    def reader() -> None:
        try:
            with np.load(npz_path) as z:
                for k in z.files:
                    if abort.is_set():
                        return
                    t0 = time.perf_counter()
                    arr = z[k]
                    if arr.dtype.kind == "V" and arr.dtype.itemsize == 2:
                        import ml_dtypes

                        arr = arr.view(ml_dtypes.bfloat16)
                    timing["disk_s"] += time.perf_counter() - t0
                    timing["read_bytes"] += arr.nbytes
                    q.put((k, arr))
        except BaseException as e:
            reader_error.append(e)
        finally:
            q.put(None)

    rthread = threading.Thread(target=reader, daemon=True, name="npz-reader")
    rthread.start()

    leaves: dict[str, Any] = {}
    try:
        _consume_leaves(q, leaves, quantize_leaves, timing)
    except BaseException:
        # A consumer failure (e.g. device OOM in jnp.asarray) must not
        # strand the reader on the bounded q.put — that would leak the
        # thread, the open npz handle, and buffered leaves for the life
        # of the process (a server retrying load_predictor accumulates
        # one wedged reader per attempt).  Signal + drain so the reader
        # observes the abort and its `with np.load` closes.
        abort.set()
        while True:
            try:
                if q.get_nowait() is None:
                    break
            except _queue.Empty:
                if not rthread.is_alive():
                    break
                time.sleep(0.01)
        raise
    if reader_error:
        raise reader_error[0]
    if stats is not None:
        stats.update(
            disk_s=round(timing["disk_s"], 2),
            quantize_s=round(timing["quantize_s"], 2),
            transfer_s=round(timing["transfer_s"], 2),
            wall_s=round(time.perf_counter() - t_wall, 2),
            read_gib=round(timing["read_bytes"] / 2**30, 2),
        )
    return _unflatten(leaves)


def _consume_leaves(
    q, leaves: dict, quantize_leaves: tuple, timing: dict
) -> None:
    """Drain the reader queue, quantizing/transferring each leaf.

    Quantized leaves go bf16-to-device then int8 ON DEVICE via the one
    canonical ``quantization.quantize_tensor`` (jitted once, reused per
    leaf).  Round 4 measured the host-side numpy quantize this replaces
    at ~1300 s for a 7B tree against ~17 s on-chip — the entire
    "9.4x cold-start variance" of VERDICT r3 weak #3 was that
    single-threaded host loop, not environment flakiness.  The HBM peak
    is int8 tree + one bf16 leaf + its f32 temporary (~3 GiB transient
    at 7B), well inside a 16 GiB chip; environments that cannot afford
    that headroom (or want half the wire bytes) can force the old host
    path with TPUMLOPS_HOST_QUANTIZE=1 — same scheme, parity asserted
    in tests/test_quantization.py::
    test_streamed_host_quantize_matches_device_quantize.
    """
    import jax
    import jax.numpy as jnp

    host_quant = os.environ.get("TPUMLOPS_HOST_QUANTIZE") == "1"
    dev_quant = None
    if quantize_leaves and not host_quant:
        from ..models.quantization import quantize_tensor

        dev_quant = jax.jit(quantize_tensor)

    while True:
        item = q.get()
        if item is None:
            break
        k, arr = item
        if k in quantize_leaves and dev_quant is not None:
            t0 = time.perf_counter()
            leaf = jnp.asarray(arr)
            leaf.block_until_ready()
            timing["transfer_s"] += time.perf_counter() - t0
            del arr
            t0 = time.perf_counter()
            out = dev_quant(leaf)
            jax.block_until_ready(out)
            del leaf  # free the bf16 copy before the next leaf arrives
            timing["quantize_s"] += time.perf_counter() - t0
            leaves[f"{k}{_SEP}q8"] = out["q8"]
            leaves[f"{k}{_SEP}scale"] = out["scale"]
            del out
        elif k in quantize_leaves:
            t0 = time.perf_counter()
            w32 = np.asarray(arr, dtype=np.float32)
            del arr
            amax = np.max(np.abs(w32), axis=-2, keepdims=True)
            scale = np.maximum(amax, 1e-12) / 127.0
            q8 = np.clip(np.round(w32 / scale), -127, 127).astype(np.int8)
            del w32
            timing["quantize_s"] += time.perf_counter() - t0
            t0 = time.perf_counter()
            leaves[f"{k}{_SEP}q8"] = jnp.asarray(q8)
            leaves[f"{k}{_SEP}scale"] = jnp.asarray(scale)
            timing["transfer_s"] += time.perf_counter() - t0
            del q8
        else:
            t0 = time.perf_counter()
            leaves[k] = jnp.asarray(arr)
            timing["transfer_s"] += time.perf_counter() - t0
            del arr


def release_predictor(predictor: Any) -> None:
    """Free a predictor's device tree before loading a replacement.

    An in-place version swap (warm reload, /admin/attach replace, bench
    warm-load) used to stream the new tree into an HBM still holding the
    old one plus every executable cache pinning its buffers — the 7B
    warm reload died RESOURCE_EXHAUSTED exactly that way
    (BENCH_7B_FULL.json warm_load_error).  Deleting the device buffers
    explicitly (not just dropping the Python refs) and clearing the jit
    caches returns the HBM before the replacement's first byte
    transfers."""
    import gc

    import jax

    lm = getattr(predictor, "causal_lm", None)
    trees = []
    if lm:
        trees.append(lm.get("params"))
    params_attr = getattr(predictor, "params", None)
    if params_attr is not None:
        trees.append(params_attr)
    for tree in trees:
        for leaf in jax.tree.leaves(tree):
            delete = getattr(leaf, "delete", None)
            if delete is not None:
                try:
                    delete()
                except Exception:  # already deleted / donated
                    pass
    # Executable caches pin device buffers even after the params are
    # garbage (measured: a "warm" reload into a near-full HBM ran 1204 s
    # of allocator pathology vs 154 s fresh — BENCH_7B_FULL.json).
    jax.clear_caches()
    gc.collect()


def _try_restore_snapshot(
    model_uri: str,
    snapshot_dir: str,
    mesh_shape: dict | None,
    quantize: str | None,
    load_stats: dict | None,
) -> Predictor | None:
    """Snapshot restore attempt: a valid snapshot streams straight to
    device (no quantize, no reshard); any miss/mismatch/corruption logs
    ONE structured warning (mismatch) or warning (corruption) and
    returns None so the caller cold-loads — and re-bakes."""
    from . import snapshot as _snap

    spath = _snap.snapshot_path_for(snapshot_dir, model_uri)
    if not (spath / _snap.MANIFEST_NAME).exists():
        return None  # never baked: ordinary cold start
    ident = _snap.snapshot_identity(model_uri, quantize, mesh_shape)
    try:
        stats: dict = {}
        params, manifest = _snap.load_snapshot(
            spath, identity=ident, stats=stats
        )
        cfg = _build_config(manifest["flavor"], manifest.get("config", {}))
        pred = get_builder(manifest["flavor"])(
            params,
            **{
                **manifest.get("builder_kwargs", {}),
                **({"cfg": cfg} if cfg is not None else {}),
            },
        )
        if load_stats is not None:
            load_stats.update(stats)
        _log.info(
            "restored %s from snapshot %s (%.2f GiB in %.2fs, zero "
            "transform work)",
            manifest["flavor"],
            spath,
            stats.get("read_gib", 0.0),
            stats.get("restore_s", 0.0),
        )
        _log_capacity(pred, quantize, load_stats)
        return pred
    except _snap.SnapshotMismatch as e:
        _log.warning(
            "snapshot invalidated, falling back to cold load "
            "(will re-bake): %s",
            e,
        )
    except _snap.SnapshotError as e:
        _log.warning(
            "snapshot unusable (%s), falling back to cold load", e
        )
        # Quarantine: the manifest's identity still matches, so without
        # this the post-cold-load bake would "write-once" skip and the
        # corrupt chunks would fail every future restore.
        try:
            os.replace(spath, f"{spath}.corrupt-{os.getpid()}")
        except OSError:
            pass
    return None


def _maybe_write_snapshot(
    pred: Predictor,
    model_uri: str,
    snapshot_dir: str,
    mesh_shape: dict | None,
    quantize: str | None,
    flavor: str,
    meta: dict,
) -> None:
    """Bake (or re-bake) the snapshot after a successful cold load.

    Write-once: a snapshot already valid for this identity is left
    alone.  Multi-device (tp > 1) trees bake PER-SHARD: each device's
    bytes are indexed separately in the manifest, so restore streams
    shard->device without ever assembling the full tree on host (the
    identity folds the mesh in, so a meshShape change misses, warns
    once, and re-bakes here).  A write failure warns and never fails
    the load."""
    from . import snapshot as _snap

    lm = getattr(pred, "causal_lm", None)
    if not lm:
        return  # only causal-LM trees are snapshot-restorable today
    import jax

    if any(
        not getattr(leaf, "sharding", None) is None
        and not leaf.sharding.is_fully_addressable
        for leaf in jax.tree.leaves(lm["params"])
    ):
        # Multi-HOST mesh: this process holds only its local shards, so
        # a bake here would index a partial tree the restore could never
        # place ("has no shard at offset" -> quarantine -> re-bake loop,
        # one model-sized .corrupt-* copy per boot).  Per-shard
        # snapshots cover multi-DEVICE single-host; multi-host restore
        # needs a per-process manifest — future work.
        _log.info(
            "snapshot skipped: params span non-addressable devices "
            "(multi-host unit); per-shard bake is single-host only"
        )
        return
    ident = _snap.snapshot_identity(model_uri, quantize, mesh_shape)
    spath = _snap.snapshot_path_for(snapshot_dir, model_uri)
    try:
        if (spath / _snap.MANIFEST_NAME).exists():
            try:
                _snap.check_identity(_snap.read_manifest(spath), ident)
                return  # already baked for this identity: write-once
            except _snap.SnapshotError:
                pass  # stale or corrupt: re-bake below
        _snap.write_snapshot(
            snapshot_dir,
            lm["params"],
            identity=ident,
            flavor=flavor,
            config=dict(meta.get("config", {})),
            builder_kwargs=(
                {"eos_id": int(lm["eos_id"])}
                if lm.get("eos_id") is not None
                else {}
            ),
        )
    except Exception as e:
        _log.warning("snapshot write failed (serving unaffected): %s", e)


def load_predictor(
    model_uri: str,
    flavor: str | None = None,
    mesh_shape: dict | None = None,
    quantize: str | None = None,
    load_stats: dict | None = None,
    snapshot_dir: str | None = None,
    release_first: Any = None,
) -> Predictor:
    """See :func:`_load_predictor_impl`; this wrapper guarantees every
    load path — the HF/transformers converter included, which has no
    internal stage timers — reports at least ``wall_s``, so the
    cold-start ladder's ``load`` stage is never silently 0 on exactly
    the slow path it exists to attribute."""
    t0 = time.perf_counter()
    try:
        return _load_predictor_impl(
            model_uri, flavor, mesh_shape, quantize, load_stats,
            snapshot_dir, release_first,
        )
    finally:
        if (
            load_stats is not None
            and "restore_s" not in load_stats
            and "wall_s" not in load_stats
        ):
            load_stats["wall_s"] = round(time.perf_counter() - t0, 2)


def _load_predictor_impl(
    model_uri: str,
    flavor: str | None = None,
    mesh_shape: dict | None = None,
    quantize: str | None = None,
    load_stats: dict | None = None,
    snapshot_dir: str | None = None,
    release_first: Any = None,
) -> Predictor:
    """Load a model artifact into a servable Predictor.

    ``load_stats`` (optional dict) receives the native-path load's stage
    breakdown (disk / quantize / transfer / shard seconds — or
    ``restore_s`` when a snapshot serviced the load) so slow cold starts
    are attributable (VERDICT r3 weak #3).

    ``snapshot_dir`` enables the pre-baked-weights fast path: a valid
    snapshot (see ``server/snapshot.py``) restores the exact post-shard,
    post-quantize device tree with zero transform work; a miss or
    invalidated snapshot cold-loads and re-bakes.  ``release_first``
    (an old Predictor) is freed — device buffers deleted, jit caches
    cleared — BEFORE any replacement bytes stream, so in-place version
    swaps and repeated bench loads cannot OOM HBM holding two trees.
    """
    if release_first is not None:
        release_predictor(release_first)
    if snapshot_dir:
        pred = _try_restore_snapshot(
            model_uri, snapshot_dir, mesh_shape, quantize, load_stats
        )
        if pred is not None:
            return pred
    path = resolve_uri(model_uri)
    cfg_file = path / "config.json"
    meta = json.loads(cfg_file.read_text()) if cfg_file.exists() else {}
    flavor = flavor or meta.get("flavor")

    if (path / "params.npz").exists():
        if not flavor:
            raise ModelLoadError(f"{path} has params.npz but no flavor recorded")
        n_devices = 1
        for v in (mesh_shape or {}).values():
            n_devices *= int(v)
        stream_quant = (
            quantize in ("int8", "int8kv")
            and flavor == "llama-generate"
            and n_devices <= 1
        )
        params = _stream_native_params(
            path / "params.npz",
            quantize_leaves=_LLAMA_STREAM_QUANT if stream_quant else (),
            stats=load_stats,
        )
        cfg = _build_config(flavor, meta.get("config", {}))
        _log.info(
            "loaded native %s model from %s%s",
            flavor,
            path,
            " (int8 quantized on arrival)" if stream_quant else "",
        )
        pred = _finish_native(
            flavor,
            params,
            cfg,
            dict(meta.get("builder_kwargs", {})),
            mesh_shape,
            "none" if stream_quant else quantize,
            raw_config=meta.get("config", {}),
            stats=load_stats,
        )
        if snapshot_dir:
            _maybe_write_snapshot(
                pred, model_uri, snapshot_dir, mesh_shape, quantize,
                flavor, meta,
            )
        _log_capacity(pred, quantize, load_stats)
        return pred

    hf_dir = _find_hf_checkpoint(path)
    if hf_dir is not None:
        flavor, params, cfg, builder_kwargs, raw_config = _load_transformers(
            hf_dir
        )
        _log.info("loaded transformers %s model from %s", flavor, hf_dir)
        pred = _finish_native(
            flavor, params, cfg, builder_kwargs, mesh_shape, quantize,
            raw_config=raw_config, stats=load_stats,
        )
        if snapshot_dir:
            import dataclasses as _dc

            _maybe_write_snapshot(
                pred, model_uri, snapshot_dir, mesh_shape, quantize,
                flavor,
                {"config": _dc.asdict(cfg) if cfg is not None else {}},
            )
        _log_capacity(pred, quantize, load_stats)
        return pred

    if quantize and quantize != "none":
        # The JAX-native paths (llama, bert) handled quantize above; what
        # remains are sklearn/xgboost/pyfunc artifacts with no quantizable
        # weight matmuls — reject loudly instead of ignoring.
        raise ModelLoadError(
            f"quantize={quantize!r} is only supported for JAX-native "
            "flavors (llama-generate, bert-classifier)"
        )

    xgb_file = _find_xgboost_file(path)
    if xgb_file is not None:
        raw = xgb_file.read_bytes()
        if not raw.lstrip()[:1] == b"{":
            raise ModelLoadError(
                f"{xgb_file.name} is a binary xgboost model (UBJ/legacy); "
                're-save it as JSON (booster.save_model("model.json")) for '
                "TPU-native serving, or use the pyfunc tier"
            )
        _log.info("loaded xgboost JSON model from %s", xgb_file)
        return get_builder("xgboost")(json.loads(raw))

    if (path / "model.pkl").exists():
        with open(path / "model.pkl", "rb") as f:
            model = pickle.load(f)
        flavor = flavor or _sniff_sklearn_flavor(model)
        _log.info("loaded sklearn %s model from %s as flavor %s", type(model).__name__, path, flavor)
        return get_builder(flavor)(model)

    raise ModelLoadError(
        f"{path} is not a recognized artifact "
        "(no params.npz, xgboost model file, or model.pkl)"
    )


def _find_xgboost_file(path: Path) -> Path | None:
    """Locate the model file of an MLflow xgboost artifact.

    MLflow's xgboost flavor records the filename in MLmodel as
    ``data: <file>``; fall back on the conventional names.
    """
    mlmodel = path / "MLmodel"
    if mlmodel.exists():
        text = mlmodel.read_text()
        if "xgboost" not in text:
            return None  # a declared non-xgboost artifact; don't sniff names
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("data:"):
                cand = path / line.split(":", 1)[1].strip().strip("\"'")
                if cand.exists():
                    return cand
    for name in ("model.json", "model.ubj", "model.xgb", "model.bst"):
        cand = path / name
        if cand.exists():
            return cand
    return None


def _sniff_sklearn_flavor(model: Any) -> str:
    name = type(model).__name__
    if hasattr(model, "estimators_"):
        return "sklearn-forest"
    if hasattr(model, "coef_"):
        return "sklearn-linear"
    if hasattr(model, "predict"):
        _log.warning("model %s has no TPU-native lowering; using pyfunc tier", name)
        return "pyfunc"
    raise ModelLoadError(f"cannot serve object of type {name}")
