"""Continuous-batching text-generation engine (baseline config 4).

The reference serves every model as stateless request/response through
Seldon's ``MLFLOW_SERVER`` (``mlflow_operator.py:198``) — it has no notion
of autoregressive decoding.  A TPU data plane serving Llama-class models
needs one: without cross-request batching, each decode step is a batch-1
matmul that leaves the MXU ~idle, and chip utilization collapses.

Design (vLLM-style scheduling, TPU-static shapes):

- The engine owns a :class:`~..models.llama.RaggedKVCache` with a fixed
  number of batch rows ("slots").  Every device computation has a static
  shape — slot count, cache capacity, and prefill bucket lengths are all
  fixed at compile time, so XLA compiles each program exactly once.
- A new request is right-padded to a power-of-two bucket, prefilled as
  batch 1, and its K/V inserted into a free slot (one fused+donated jit
  per bucket).  Padding beyond the real length is progressively
  overwritten by decode writes before it can ever be attended — see
  ``decode_ragged``'s slot-reuse note.
- Every scheduler tick runs ONE batched decode step over all slots at
  their own positions (``lengths`` is per-row).  Requests join and leave
  between ticks; a slot frees as soon as its request finishes, and the
  next queued request takes it — no barrier on batch completion
  ("continuous batching").
- Inactive slots still compute (the MXU does not care) and advance
  nothing; their sampled tokens are discarded host-side (and their cache
  writes DROP — an inactive row may belong to a packed admission
  mid-prefill).
- Packed multi-admission prefill (``prefill_batch`` > 1): a queue of
  in-flight admissions each reserves a cache row, and every engine tick
  up to ``prefill_batch`` of their next prompt chunks run as ONE batched
  call — the per-chunk HBM weight stream is paid once per tick instead
  of once per admission, which is what holds TTFT through a cold-start
  burst or traffic ramp.  ``prefill_token_budget`` caps the packed work
  per tick so decode cadence survives long-prompt bursts.

The big cache buffers are donated through both jitted programs, so steady
state allocates no new HBM per token.  Greedy decoding only — matching
``llama.generate_greedy`` exactly (tested in float64, where no backend
fast-math can blur the comparison).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, Sequence

import numpy as np

_log = logging.getLogger("tpumlops.generation")


class EngineShutdown(RuntimeError):
    """The engine shut down before this request's admission completed.

    Raised into the futures of queued (not-yet-admitted) and mid-prefill
    requests at shutdown, so callers get a clear error instead of a bare
    ``CancelledError`` (or a hang on a future nobody will resolve)."""


class EngineOverloaded(RuntimeError):
    """Admission shed: the request was refused at the door.

    Raised SYNCHRONOUSLY from :meth:`GenerationEngine.submit` /
    :meth:`GenerationEngine.reserve_admission` — nothing was enqueued —
    either because accepting the request would push the estimated tokens
    (prompt + max_new) of queued-but-unadmitted work past the admission
    budget, or because the engine is draining for shutdown/scale-down.
    The HTTP layer maps it to ``429`` with a ``Retry-After`` header so
    clients (and the router) retry on another replica; shed is the
    loss-free pressure valve that keeps admitted requests' TTFT bounded
    while the autoscaler boots more capacity.
    """

    def __init__(self, message: str, reason: str = "budget",
                 retry_after_s: int = 1, slo_class: str | None = None):
        super().__init__(message)
        # "budget" | "draining" | "class_<name>" (per-class threshold)
        self.reason = reason
        self.retry_after_s = int(retry_after_s)
        # The shed request's SLO class (None when classes are unarmed):
        # rides the 429 body so dashboards and clients can tell
        # best-effort load-shedding from real overload.
        self.slo_class = slo_class


class PoisonRequest(ValueError):
    """The request's prompt fingerprint is quarantined.

    A request whose admission/prefill crashed the engine twice (same
    prompt hash both times) is rejected SYNCHRONOUSLY from
    :meth:`GenerationEngine.submit` instead of being given a third shot
    at crash-looping the replica — every crash fails ALL in-flight
    sequences and reallocates device state, so one poison prompt
    retried by a well-meaning client would take the whole replica's
    traffic down with it on every attempt.  The HTTP layer maps this to
    a typed ``422`` (the request is unprocessable HERE AND EVERYWHERE —
    a retry on another replica would crash it too, so no Retry-After).
    """

    def __init__(self, fingerprint: str, crashes: int):
        super().__init__(
            f"prompt quarantined: admission crashed the engine {crashes} "
            f"times (fingerprint {fingerprint})"
        )
        self.fingerprint = fingerprint
        self.crashes = int(crashes)


def _safe_resolve(fut: Future, value) -> None:
    """set_result tolerating a concurrent client-side cancel (TOCTOU: the
    cancelled() check and set_result are not atomic across threads)."""
    try:
        fut.set_result(value)
    except Exception:  # InvalidStateError: client cancelled in the gap
        pass


def _safe_fail(fut: Future, exc: Exception) -> None:
    try:
        fut.set_exception(exc)
    except Exception:
        pass

class _Wake:
    """Queue sentinel that only unblocks the scheduler's idle wait (a
    control op arrived); carries no request and must never be confused
    with the None shutdown sentinel."""


_WAKE = _Wake()

# SLO priority classes (spec.sloClass / per-request "slo_class").
# Higher priority drains first from the admission queue; under
# preemption a waiting higher-class request may evict a lower-class
# slot at a tick boundary.  Order below is priority DESCENDING.
SLO_CLASSES = ("interactive", "batch", "best-effort")
_CLASS_PRIORITY = {name: i for i, name in enumerate(reversed(SLO_CLASSES))}
# Fraction of the admission budget each class may fill before ITS
# submissions shed (reason "class_<name>"): lower classes give up queue
# room early so the headroom stays available to interactive traffic.
_CLASS_BUDGET_FACTOR = {"interactive": 1.0, "batch": 0.75,
                        "best-effort": 0.5}

_MIN_BUCKET = 16


def prefill_bucket(length: int, capacity: int) -> int:
    """Power-of-two prompt bucket (>= _MIN_BUCKET, <= cache capacity)."""
    from .batching import next_bucket

    return min(max(_MIN_BUCKET, next_bucket(length, capacity)), capacity)


def decode_window_bucket(length: int, capacity: int) -> int:
    """Attention-window bucket: smallest of {2^k, 3*2^(k-1)} >= length.

    Decode attention cost is LINEAR in the attended window W at the
    G=1 MXU matvec floor (docs/PERF.md round 5), so pure power-of-two
    buckets overpay up to 2x just under each boundary (serving at
    position 260 attends 512).  The 1.5x intermediate steps (96, 192,
    384, 768, ...) cap the overshoot at 33% for one more compiled
    variant per octave — measured on chip at 1.35B/32 slots, window
    384 vs 512 is 1.085x the step rate (15.10 -> 13.92 ms/step; the
    weight-stream constant dilutes the linear attention term).

    Interaction with ``_DECODE_ATTN='pallas_vpu'``: the 3/4 steps are
    not all multiples of 128, and the VPU kernel requires W % 128 == 0,
    so that opt-in config runs the VPU kernel only on the W%128==0
    buckets and warn-falls-back to XLA on the others (see the
    ``_DECODE_ATTN`` note in models/llama.py)."""
    w = prefill_bucket(length, capacity)
    # The 3/4 step applies only to an UNCAPPED power-of-two bucket: when
    # next_bucket was clamped to a non-power capacity, 3*(w//4) is an
    # arbitrary value the warmup enumeration never compiles, and a lazy
    # compile on the scheduler thread is exactly what buckets prevent.
    if length > 0 and w >= 2 * _MIN_BUCKET and w & (w - 1) == 0:
        threeq = 3 * (w // 4)
        if length <= threeq:
            return threeq
    return w


def decode_window_buckets(capacity: int) -> list[int]:
    """Every window :func:`decode_window_bucket` can return, ascending —
    the warmup sweep compiles exactly this set (pinned by an exhaustive
    reachability test over power and non-power capacities)."""
    out = {min(capacity, _MIN_BUCKET)}
    b = _MIN_BUCKET
    while b < capacity:
        out.add(b)
        # 3*(b//2) is reachable only when the NEXT power of two (2b) is
        # itself an admissible uncapped bucket.
        if 2 * b <= capacity:
            out.add(3 * (b // 2))
        b *= 2
    out.add(capacity)
    return sorted(out)


def superstep_window(
    decode_hi: int, other_hi: int, steps: int, capacity: int
) -> int:
    """Window pre-pick for a MIXED-role unified super-step dispatch.

    One static window serves every row of the tick, so it must cover
    each role's WORST case: a decode row at next-write position
    ``decode_hi`` attends up to ``decode_hi + steps - 1`` by the last
    fused iteration (the scan cannot grow the window mid-flight —
    exactly ``_step_fused``'s bound), while a verify row at length L or
    a prefill row committing at offset O attends strictly below L / O
    (``other_hi`` is the max of those).  Taking the bucket of the max
    keeps a K-step decode row and a long verify/prefill row sharing one
    dispatch both inside their worst-case window (pinned exhaustively
    in tests/test_multistep.py)."""
    need = max(1, decode_hi + (steps - 1) if decode_hi > 0 else 1, other_hi)
    return decode_window_bucket(min(need, capacity), capacity)


@dataclass
class _Slot:
    future: Future
    remaining: int  # new tokens still to produce
    eos_id: int | None
    sampling: bool = False  # temperature > 0 (selects the decode variant)
    on_token: Callable[[int], None] | None = None  # streaming callback
    prompt_len: int = 0  # for the decode attention window (host mirror)
    generated: list[int] = field(default_factory=list)
    t_start: float = 0.0
    # Self-speculative decoding (engine speculative config only): an
    # incrementally-appended history buffer holding prompt + generated
    # tokens (the drafter context, built WITHOUT a per-tick
    # re-concatenation — at long context that copy would be serial
    # scheduler-thread work ahead of every dispatch; the prompt alone is
    # ``history[:prompt_len]``), and the slot's adaptive draft budget.
    # Both None when speculation is disabled.
    history: np.ndarray | None = None  # int64 [capacity]; valid: [:hist_len]
    hist_len: int = 0
    draft: "object | None" = None  # speculative.DraftState
    # Request tracing (flight_recorder.RequestTrace | None): per-request
    # timing the HTTP layer returns under ``"debug": true`` and logs on
    # completion.  None (direct engine callers, warmup) = no bookkeeping.
    request_id: str = ""
    trace: "object | None" = None
    t_last_token: float = 0.0  # previous token's wall (inter-token latency)
    # SLO class / preemption state (defaults when classes are unarmed —
    # the armed engine records the class, and under preemption also the
    # prompt and sampling params so an evicted slot can be rebuilt
    # exactly on restore).
    slo_class: str = "interactive"
    prompt: np.ndarray | None = None
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0


@dataclass(eq=False)  # identity semantics: list membership/removal must
# never field-compare (numpy prompt arrays make == a broadcast, not a bool)
class _PrefillProgress:
    """A chunked admission in flight.

    Single-admission mode (``prefillBatch`` 1, the default) holds at
    most one of these and threads a batch-1 scratch cache through the
    engine's ``_seq_state``; packed mode holds a queue of them, each
    with a RESERVED cache row (``slot``) its chunks are written into
    directly.

    ``chunks`` covers only the UNCACHED suffix when a radix-cached
    prefix was found at admission (``cached_tokens`` > 0): the prefix's
    K/V is seeded straight into the sequence cache (``cached_kv``, one
    host pair per chunk) and never re-prefilled."""

    req: _Request
    chunks: list  # padded [1, C] int32 arrays (uncached suffix only)
    next_idx: int = 0
    cached_tokens: int = 0
    cached_kv: list = field(default_factory=list)
    seeded: bool = False
    slot: int = -1  # packed mode: reserved cache row (-1 = scratch path)


@dataclass
class _Request:
    prompt: np.ndarray  # int32 [L]
    max_new_tokens: int
    eos_id: int | None
    future: Future
    temperature: float = 0.0  # <= 0: greedy
    top_k: int = 0  # <= 0: disabled
    top_p: float = 1.0  # >= 1: disabled
    seed: int | None = None  # None: engine-assigned (boot-nonce fold_in)
    on_token: Callable[[int], None] | None = None  # streaming callback
    t_submit: float = 0.0  # perf_counter at submit (admission-wait / TTFT)
    request_id: str = ""  # inbound X-Request-Id / traceparent (or generated)
    trace: "object | None" = None  # flight_recorder.RequestTrace | None
    # Estimated tokens (prompt + max_new) this request holds against the
    # admission budget while queued; released exactly once at dequeue
    # (0 = nothing reserved, e.g. budget disabled).
    est_tokens: int = 0
    slo_class: str = "interactive"


@dataclass(eq=False)  # identity semantics (numpy fields)
class _Preempted:
    """An evicted mid-decode sequence awaiting re-admission.

    Everything a restore needs to resume the sequence EXACTLY where the
    eviction cut it: the committed K/V chunks (host copies — the radix
    cache holds the full-chunk ones too, but an interleaved admission
    may evict them before restore), the PRNG carry, the pending
    not-yet-fed token, and the slot bookkeeping.  Queued at the FRONT
    of its class deque so an evicted sequence re-admits before newer
    work of its own class — no starvation pile-up behind the flood that
    evicted it."""

    future: Future
    remaining: int
    eos_id: int | None
    sampling: bool
    on_token: Callable[[int], None] | None
    prompt: np.ndarray
    generated: list[int]
    t_start: float
    request_id: str
    trace: "object | None"
    slo_class: str
    temperature: float
    top_k: int
    top_p: float
    key_data: np.ndarray  # PRNG carry at eviction (jax.random.key_data)
    chunks: list  # host (k, v) pairs covering hist, chunk-strided
    hist: int  # committed cache positions (prompt + generated - 1)
    history: np.ndarray | None  # speculative drafter context
    hist_len: int
    draft: "object | None"
    # Queue-protocol shims: a _Preempted rides the class deques next to
    # _Request items, and the admission loop's reservation-release and
    # wait-metric paths read these (0 = nothing reserved / no metric).
    est_tokens: int = 0
    t_submit: float = 0.0


class GenerationEngine:
    """Schedules concurrent generation requests onto one ragged KV cache.

    ``submit`` is thread-safe and returns a ``concurrent.futures.Future``
    resolving to the generated token ids (``np.ndarray[int32]``); the
    aiohttp handler awaits it via ``asyncio.wrap_future``.  All JAX work
    happens on the single scheduler thread.
    """

    def __init__(
        self,
        params,
        cfg,
        *,
        max_slots: int = 4,
        dtype=None,
        eos_id: int | None = None,
        on_step: Callable[[int, float, int, int], None] | None = None,
        on_tokens: Callable[[int], None] | None = None,
        channel=None,
        kv_quant: bool = False,
        prefill_chunk: int | None = None,
        prefix_cache=None,  # PrefixCacheConfig | None
        on_prefix_hit: Callable[[int], None] | None = None,
        on_prefix_evict: Callable[[], None] | None = None,
        on_prefix_l2: Callable[[str], None] | None = None,
        speculative=None,  # speculative.SpeculativeConfig | None
        on_spec: Callable[[int, int], None] | None = None,
        prefill_batch: int = 1,
        prefill_token_budget: int = 0,
        on_prefill_batch: Callable[[int], None] | None = None,
        on_admission_wait: Callable[[float], None] | None = None,
        on_ttft: Callable[[float], None] | None = None,
        on_itl: Callable[[float], None] | None = None,
        on_request_tokens: Callable[[int], None] | None = None,
        on_tick: Callable[[str, float], None] | None = None,
        recorder=None,  # flight_recorder.FlightRecorder | None
        admission_queue_budget: int = 0,
        on_shed: Callable[[str], None] | None = None,
        telemetry=None,  # device_telemetry.DeviceTelemetry | None
        decode_steps: int = 1,
        unified_step: bool = False,
        on_dispatch: Callable[[str], None] | None = None,
        watchdog=None,  # watchdog.EngineWatchdog | None (leader-side)
        on_poison: Callable[[str], None] | None = None,
        mesh_shape=None,  # {"dp": N, "sp": N, "tp": N} | None
        sp_prefill_threshold: int = 1024,
        slo_class: str | None = None,  # default class for submissions
        preemption: bool = False,  # mid-decode eviction of lower classes
        on_preempt: Callable[[str], None] | None = None,  # "evict"|"restore"
    ):
        import jax
        import jax.numpy as jnp

        from ..models import llama

        self._params = params
        self._cfg = cfg
        self._eos_default = eos_id
        # (active_slots, step_seconds, queue_depth, admitting) per
        # decode/verify tick — queue_depth is QUEUED-BUT-UNADMITTED only;
        # admitting counts in-flight (mid-prefill) admissions.
        self._on_step = on_step
        self._on_tokens = on_tokens  # (n,) per token delivered to a client
        # multihost.UnitChannel: leader broadcasts every device call so
        # follower processes replay it in lockstep (None = single-host).
        self._channel = channel
        self._in_warmup = False  # suppress metrics/counters during warmup
        self.max_slots = int(max_slots)
        self.capacity = int(cfg.max_seq)
        dtype = dtype or jnp.bfloat16
        self._dtype = dtype
        self._kv_quant = bool(kv_quant)
        # Serving mesh (spec.tpu.meshShape).  None or a product-1 shape
        # — the default — arms NOTHING: no mesh object, no sharding
        # handles, and every jit below compiles exactly the
        # single-device program it always did (pinned byte-for-byte in
        # tests/test_tensor_parallel.py).  Three axes light up:
        #
        # - tp > 1: params arrive pre-sharded (loader) over the same
        #   device prefix this mesh covers, the KV cache shards its
        #   heads axis, sampling state replicates, and every program
        #   compiles with EXPLICIT output shardings so K/V commits, the
        #   on-device sampling chain, and donated buffers stay sharded
        #   across ticks — no per-tick gather.
        # - dp > 1: the ragged cache ALSO shards its row (batch) axis —
        #   each dp shard holds max_slots/dp rows, weights replicate
        #   over dp, and GSPMD partitions every batched program on the
        #   row axis.  Slot bookkeeping stays host-side and identical
        #   (sampling state replicates), so replay op count is
        #   unchanged; _free_slot spreads admissions across the row
        #   blocks so shards fill evenly.
        # - sp > 1: long prompts (>= sp_prefill_threshold tokens, cold
        #   prefix) prefill in ONE ring-attention pass with the
        #   sequence axis split over sp (models.llama.prefill_ring),
        #   then insert through the existing scratch path.
        #
        # pp/ep stay rejected: no pipeline or expert machinery exists.
        self._mesh = None
        self._shard_rep = self._shard_kv = self._shard_seq = None
        self._dp = 1
        self._sp = 1
        self._sp_threshold = int(sp_prefill_threshold)
        if mesh_shape:
            from ..models import partition

            if partition.mesh_device_count(mesh_shape) > 1:
                bad = {
                    a: int(n) for a, n in dict(mesh_shape).items()
                    if a not in ("dp", "sp", "tp") and int(n) > 1
                }
                if bad:
                    raise ValueError(
                        "the generation engine shards over dp/sp/tp "
                        f"only; meshShape axes {bad} must be 1 (no "
                        "pipeline or expert parallelism exists here)"
                    )
                # Typed rejects BEFORE any device state: an indivisible
                # axis would otherwise surface as an opaque XLA shape
                # error at the first warmup dispatch.
                partition.validate_llama_mesh(cfg, mesh_shape)
                dp = partition.dp_degree(mesh_shape)
                sp = partition.sp_degree(mesh_shape)
                if dp > 1 and self.max_slots % dp != 0:
                    raise ValueError(
                        f"meshShape dp={dp} does not divide maxSlots "
                        f"{self.max_slots}: the ragged cache's row axis "
                        "shards over dp in equal blocks"
                    )
                if sp > 1 and (
                    sp & (sp - 1) != 0 or sp > _MIN_BUCKET
                ):
                    raise ValueError(
                        f"meshShape sp={sp} must be a power of two <= "
                        f"{_MIN_BUCKET} so every prefill bucket divides "
                        "evenly across the ring"
                    )
                self._dp = dp
                self._sp = sp
                self._mesh = partition.build_serving_mesh(mesh_shape)
                (
                    self._shard_rep,
                    self._shard_kv,
                    self._shard_seq,
                ) = partition.engine_state_shardings(
                    self._mesh, self._kv_quant
                )
        # Chunked prefill: split prompts into fixed-size chunks so (a) one
        # compiled program serves every prompt length and (b) the scheduler
        # interleaves a decode tick between chunks — a long prompt no
        # longer stalls in-flight streams' token cadence for its whole
        # prefill.  None = whole-prompt bucketed prefill (fused, fastest
        # time-to-first-token when nothing else is decoding).
        self._prefill_chunk_size = int(prefill_chunk) if prefill_chunk else None
        # Radix prefix KV cache (cross-request prompt reuse).  The reuse
        # unit IS the prefill chunk, so enabling the cache enables chunked
        # prefill at ``chunk_tokens`` when prefillChunk is unset; when both
        # are set they must agree — a mismatched reuse unit would make
        # cached chunk boundaries fall mid-prefill-chunk.
        self._prefix_cache = None
        self._on_prefix_hit = on_prefix_hit
        self._on_prefix_evict = on_prefix_evict
        prefix_enabled = prefix_cache is not None and prefix_cache.enabled
        if prefix_enabled:
            ct = int(prefix_cache.chunk_tokens)
            if ct <= 0:
                raise ValueError(
                    f"prefixCache.chunkTokens must be positive, got {ct}"
                )
            if self._prefill_chunk_size is None:
                self._prefill_chunk_size = ct
            elif self._prefill_chunk_size != ct:
                raise ValueError(
                    f"prefixCache.chunkTokens {ct} must equal prefillChunk "
                    f"{self._prefill_chunk_size}: the prefill chunk is the "
                    "prefix reuse unit"
                )
        if self._prefill_chunk_size is not None:
            C = self._prefill_chunk_size
            if C <= 0:
                raise ValueError(f"prefill_chunk must be positive, got {C}")
            if self.capacity % C != 0:
                # Padding the last chunk must never spill past capacity
                # (clamped cache writes would silently corrupt the prompt).
                raise ValueError(
                    f"prefill_chunk {C} must divide KV capacity "
                    f"{self.capacity}"
                )
        # Packed multi-admission prefill: up to prefill_batch in-flight
        # admissions' next chunks run as ONE batched forward per tick —
        # the per-chunk weight stream amortizes across admissions the
        # way PR 2's verify amortized decode.  1 (the default) keeps the
        # single-admission pipeline byte-for-byte.
        self._prefill_batch = 1 if prefill_batch is None else int(prefill_batch)
        if self._prefill_batch < 1:
            raise ValueError(
                f"prefill_batch must be >= 1, got {prefill_batch}"
            )
        if self._prefill_batch > 1 and self._prefill_chunk_size is None:
            raise ValueError(
                "prefill_batch > 1 requires chunked prefill: set "
                "prefillChunk (or enable prefixCache, which implies it)"
            )
        # More concurrent admissions than cache rows cannot exist.
        self._prefill_batch = min(self._prefill_batch, self.max_slots)
        self._prefill_token_budget = int(prefill_token_budget or 0)
        if self._prefill_token_budget < 0:
            raise ValueError(
                "prefill_token_budget must be >= 0, got "
                f"{prefill_token_budget}"
            )
        self._packed = self._prefill_batch > 1
        self._on_prefill_batch = on_prefill_batch
        self._on_admission_wait = on_admission_wait
        self._on_ttft = on_ttft
        # Per-request cadence metrics + engine flight recorder.  recorder
        # None (the default) keeps the scheduler loop byte-for-byte: every
        # hook below is guarded, nothing is allocated per tick.
        self._on_itl = on_itl
        self._on_request_tokens = on_request_tokens
        self._on_tick = on_tick
        self._recorder = recorder
        # Device telemetry (HBM ledger + compile observatory + per-tick
        # MFU/bandwidth; spec.tpu.observability.deviceTelemetry).  None
        # — the default — wraps nothing and computes nothing per tick.
        self._telemetry = telemetry
        # JAX dispatch is async: a prefill/seed call returns before the
        # device finishes, and the wait would otherwise be absorbed into
        # the NEXT decode tick's wall — the exact mis-attribution the
        # flight recorder exists to prevent.  With the RECORDER on,
        # non-decode ticks block on their outputs before the wall is
        # read (decode/verify/packed already sync via their np.asarray
        # result reads).  Gated on the recorder ONLY — on_tick (the
        # always-wired tpumlops_tick_seconds metric) must not arm device
        # syncs in the default deployment, or traceRing=0 would no
        # longer be the byte-for-byte unobserved engine loop; without
        # the recorder, non-decode tick-metric walls are dispatch-only.
        # Device telemetry also syncs: a dispatch-only prefill wall would
        # read as an absurd MFU.
        self._sync_ticks = recorder is not None or telemetry is not None
        self._on_prefix_l2 = on_prefix_l2
        if prefix_enabled:
            from .prefix_cache import RadixPrefixCache

            self._prefix_cache = RadixPrefixCache(
                budget_bytes=int(prefix_cache.budget_bytes),
                chunk_tokens=self._prefill_chunk_size,
                on_evict=self._note_prefix_evict,
                l2_budget_bytes=int(
                    getattr(prefix_cache, "l2_budget_bytes", 0) or 0
                ),
                on_l2_event=self._note_prefix_l2,
            )
        # SLO priority classes + mid-decode preemption.  Both default
        # off, and off keeps the scheduler byte-for-byte: no class
        # deques exist, _dequeue IS queue.get, no slot records extra
        # state.  Classes arm when either a default class is configured
        # or preemption is on (preemption needs class ordering to pick
        # victims).
        if slo_class is not None and slo_class not in SLO_CLASSES:
            raise ValueError(
                f"slo_class must be one of {SLO_CLASSES}, got "
                f"{slo_class!r}"
            )
        self._slo_default = slo_class
        self._classes = slo_class is not None or bool(preemption)
        self._class_queues: "dict[str, object] | None" = None
        if self._classes:
            from collections import deque

            self._class_queues = {name: deque() for name in SLO_CLASSES}
        self._preemption = bool(preemption)
        if self._preemption and self._prefix_cache is None:
            # Evicted K/V is written back THROUGH the radix cache (and
            # restore re-seeds through the same chunk layout), so
            # preemption without it has nowhere loss-free to park work.
            raise ValueError(
                "preemption requires the radix prefix cache "
                "(prefixCache.enabled): evicted slots write their K/V "
                "back through it and restore from the same chunks"
            )
        self._on_preempt = on_preempt
        self.preemptions = 0
        self.preempt_restores = 0
        # Tokens a preempted sequence had to RE-generate after restore —
        # zero by construction (the pending token and PRNG carry travel
        # with the eviction record); the bench gate pins it there.
        self.preempt_recomputed_tokens = 0
        # Self-speculative n-gram decoding: disabled (None) = byte-for-byte
        # the plain single-token tick.  Enabled: greedy-only ticks draft up
        # to draft_tokens continuations per slot from the slot's own
        # history and verify them in ONE batched forward (_verify below);
        # any tick with a sampling slot falls back to the plain step —
        # exact acceptance is a greedy-argmax rule.
        self._spec = None
        self._spec_chain: tuple[int, ...] = ()
        self._on_spec = on_spec
        if speculative is not None and speculative.enabled:
            from .speculative import draft_chain

            dt = int(speculative.draft_tokens)
            if dt < 1:
                raise ValueError(
                    f"speculative.draftTokens must be >= 1, got {dt}"
                )
            if not (1 <= int(speculative.ngram_min) <= int(speculative.ngram_max)):
                raise ValueError(
                    "speculative ngram bounds must satisfy "
                    f"1 <= ngramMin <= ngramMax, got "
                    f"[{speculative.ngram_min}, {speculative.ngram_max}]"
                )
            self._spec = speculative
            self._spec_chain = draft_chain(dt)
        # Fused multi-step decode (spec.tpu.decodeSteps): K decode
        # iterations per dispatch as a lax.scan with an on-device
        # sampling chain and EOS latch, paired with lag-1 asynchronous
        # token readback (the scheduler dispatches tick N+1 before
        # blocking on tick N's token block).  1 — the default — keeps
        # the single-step tick loop byte-for-byte: no fused program is
        # built, swept, or consulted.
        self._decode_steps = 1 if decode_steps is None else int(decode_steps)
        if not (1 <= self._decode_steps <= 16):
            raise ValueError(
                f"decode_steps must be in [1, 16], got {decode_steps}"
            )
        self._fused = self._decode_steps > 1
        # Unified ragged super-step (spec.tpu.unifiedStep): ONE program
        # per tick processes a mixed batch of packed-prefill chunk
        # commits, fused-K decode rows, and speculative verify rows —
        # driven by per-row role/offset/budget tensors — so the warmup
        # sweep compiles one variant per (window-bucket x sampling-mode)
        # instead of the decode x verify-chain x multistep x packed-B_p
        # cross-product.  False — the default — builds nothing and keeps
        # the legacy split-program engine byte-for-byte.
        self._unified = bool(unified_step)
        # Static block width of the unified program: wide enough for the
        # largest verify chain (draft_tokens + 1) and the prefill chunk,
        # 1 when neither feature is on.  One width -> one compiled shape.
        sw = 1
        if self._spec is not None:
            sw = max(sw, int(self._spec.draft_tokens) + 1)
        if self._packed:
            sw = max(sw, int(self._prefill_chunk_size))
        self._super_width = sw
        self._on_dispatch = on_dispatch
        # Scheduler-loop watchdog (server/watchdog.py): None — the
        # default — keeps the loop byte-for-byte (every beat below is
        # guarded).  Leader-side only, like the recorder: followers
        # block inside replayed collectives by design and the leader's
        # exit tears the unit down.
        self._watchdog = watchdog
        # An IDLE scheduler blocks in queue.get and beats only once per
        # poll — a deadline below the poll interval would read every
        # quiet second as a stall (readiness flapping, spurious journal
        # events, and with a short grace an exit loop on a healthy idle
        # pod).  Halve the idle poll under the deadline so idle beats
        # always land in time.
        self._idle_poll_s = (
            min(1.0, watchdog.deadline_s / 2.0)
            if watchdog is not None else 1.0
        )
        if watchdog is not None:
            # The engine owns the slot truth; the server owns the
            # readiness/metrics callbacks.  Unconditional: a warm-pool
            # attach/replace hands the SAME watchdog to its new engine,
            # and the inventory must follow.
            watchdog.slot_inventory = self._slot_inventory
        # Poison-request quarantine: prompt fingerprints whose
        # admission/prefill crashed the engine, and the ones past the
        # crash threshold that submit now refuses with a typed 422.
        # Always on — it only changes behavior on the Nth crash of a
        # prompt that already took every in-flight request down twice.
        self._poison_counts: dict[str, int] = {}
        self._quarantined: dict[str, int] = {}
        self._poison_lock = threading.Lock()
        self._on_poison = on_poison  # fed "quarantined" | "rejected"
        self.poison_quarantined_total = 0
        self.poison_rejected_total = 0
        # Engine device dispatches by tick kind (the amortization series:
        # a fused K-step tick is ONE dispatch where the plain loop paid
        # K) — mirrored to tpumlops_engine_dispatches_total{op} via
        # on_dispatch and read by bench.py's multistep scenario.
        self.dispatches_total: dict[str, int] = {}
        self._reset_device_state()

        # Sharding handles for the program signatures below: ``rep`` =
        # replicated (tokens, lengths, keys, sampling params, logits
        # read-backs), ``kvsh`` = the ragged cache repr (heads axis on
        # tp; a (values, scales) pair under int8kv), ``seqsh`` = the
        # batch-1 prefill scratch.  All None without a mesh.
        rep, kvsh, seqsh = self._shard_rep, self._shard_kv, self._shard_seq

        def jit_sharded(fn, donate_argnums=(), static_argnums=(),
                        out_shardings=None):
            """``jax.jit`` with EXPLICIT output shardings when the tp
            mesh is armed — jax.jit with out_shardings IS pjit on every
            jax this repo supports (shard_map_compat stays the escape
            hatch for manually-partitioned kernels; the engine programs
            are GSPMD-partitioned, input shardings propagate from the
            committed param/cache arrays).  Without a mesh this is
            byte-for-byte the plain jax.jit call it replaces: no
            out_shardings kwarg is even passed."""
            kw = {}
            if donate_argnums:
                kw["donate_argnums"] = donate_argnums
            if static_argnums:
                kw["static_argnums"] = static_argnums
            if self._mesh is not None and out_shardings is not None:
                kw["out_shardings"] = out_shardings
            return jax.jit(fn, **kw)

        def make_cache(k, v, lengths):
            """k/v are arrays (bf16 cache) or (values, scales) pairs."""
            if self._kv_quant:
                return llama.QuantRaggedKVCache(k[0], k[1], v[0], v[1], lengths)
            return llama.RaggedKVCache(k, v, lengths)

        def cache_repr(cache):
            if self._kv_quant:
                return (cache.k8, cache.k_scale), (cache.v8, cache.v_scale)
            return cache.k, cache.v

        def _decode(
            params, toks, k, v, lengths, active, keys, temps, tks, tps, window
        ):
            from ..models.sampling import sample_logits, split_keys

            cache = make_cache(k, v, lengths)
            logits, cache = llama.decode_ragged(
                params, toks, cache, cfg, active=active, dtype=dtype,
                window=window,
            )
            keys2, use = split_keys(keys)
            nxt = sample_logits(logits[:, -1, :], use, temps, tks, tps)
            # Finished slots keep their last token so their rows stay inert.
            toks2 = jnp.where(active, nxt, toks[:, 0])[:, None]
            ck, cv = cache_repr(cache)
            return toks2, ck, cv, cache.lengths, keys2

        # ``window`` is static: one compiled program per power-of-two bucket
        # of the longest active sequence (short traffic stops paying
        # full-capacity cache reads — decode's dominant HBM term).
        self._decode = jit_sharded(
            _decode, donate_argnums=(2, 3), static_argnums=(10,),
            out_shardings=(rep, kvsh, kvsh, rep, rep) if rep else None,
        )

        def _decode_greedy(params, toks, k, v, lengths, active, window):
            # Hot path when every occupied slot is greedy (the default):
            # plain argmax — no full-vocab sort/softmax/categorical work.
            cache = make_cache(k, v, lengths)
            logits, cache = llama.decode_ragged(
                params, toks, cache, cfg, active=active, dtype=dtype,
                window=window,
            )
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            toks2 = jnp.where(active, nxt, toks[:, 0])[:, None]
            ck, cv = cache_repr(cache)
            return toks2, ck, cv, cache.lengths

        self._decode_greedy = jit_sharded(
            _decode_greedy, donate_argnums=(2, 3), static_argnums=(6,),
            out_shardings=(rep, kvsh, kvsh, rep) if rep else None,
        )

        def _verify(params, toks, k, v, lengths, active, draft_len, window):
            # Self-speculative verify: toks [B, S] (col 0 = pending token,
            # cols 1.. = draft, padded past draft_len).  ONE forward
            # scores all S positions per slot; acceptance is exact greedy
            # argmax, so emitted tokens are bit-identical to S sequential
            # _decode_greedy steps — but the weight tree streams from HBM
            # once instead of up to S times.  Rejected K/V writes roll
            # back by PER-ROW LENGTH TRUNCATION: lengths advance only by
            # accepted+1, and positions at/beyond the truncated length
            # are never attended before being overwritten (the same
            # invariant that makes slot reuse safe).  One compiled
            # variant per (S, window); K/V donated like _decode.
            from ..models.sampling import speculative_accept

            cache = make_cache(k, v, lengths)
            logits, cache = llama.verify_ragged(
                params, toks, cache, cfg, dtype=dtype, window=window,
                active=active,
            )
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, S]
            accepted, nxt = speculative_accept(toks, greedy, draft_len)
            toks2 = jnp.where(active, nxt, toks[:, 0])[:, None]
            advance = jnp.where(active, accepted + 1, 0).astype(jnp.int32)
            ck, cv = cache_repr(cache)
            return toks2, ck, cv, cache.lengths + advance, greedy, accepted

        self._verify = jit_sharded(
            _verify, donate_argnums=(2, 3), static_argnums=(7,),
            out_shardings=(rep, kvsh, kvsh, rep, rep, rep) if rep else None,
        )

        def _multistep_sampling(
            params, toks, k, v, lengths, active, remaining, eos_ids,
            keys, temps, tks, tps, window, steps,
        ):
            # Fused K-step decode, sampling variant: the scan body is the
            # SAME decode forward as _decode with the on-device sampling
            # chain advancing every row's key once per step — exactly the
            # step-by-step key discipline, so seeded sampling is
            # token-for-token reproducible against K sequential ticks.
            from ..models.sampling import sample_chain_step

            cache = make_cache(k, v, lengths)

            def sample(logits, carry):
                return sample_chain_step(logits, carry, temps, tks, tps)

            tok_block, valid, toks2, cache, active2, remaining2, keys2 = (
                llama.decode_multistep(
                    params, toks, cache, cfg, active, remaining, eos_ids,
                    steps, sample, sample_carry=keys, dtype=dtype,
                    window=window,
                )
            )
            ck, cv = cache_repr(cache)
            return (
                tok_block, valid, toks2, ck, cv, cache.lengths,
                active2, remaining2, keys2,
            )

        def _multistep_greedy(
            params, toks, k, v, lengths, active, remaining, eos_ids,
            window, steps,
        ):
            # Greedy variant: plain argmax per step (no sort/softmax/key
            # work), mirroring _decode_greedy.
            cache = make_cache(k, v, lengths)

            def sample(logits, carry):
                return carry, jnp.argmax(logits, axis=-1).astype(jnp.int32)

            tok_block, valid, toks2, cache, active2, remaining2, _ = (
                llama.decode_multistep(
                    params, toks, cache, cfg, active, remaining, eos_ids,
                    steps, sample, sample_carry=None, dtype=dtype,
                    window=window,
                )
            )
            ck, cv = cache_repr(cache)
            return (
                tok_block, valid, toks2, ck, cv, cache.lengths,
                active2, remaining2,
            )

        if self._fused and not self._unified:
            # One compiled variant per (K, window) pair, like _verify's
            # (S, window) grid; K is fixed per deployment so the warmup
            # sweep is |window buckets| x 2 variants.  The unified
            # engine never builds these: its K steps run inside the
            # super-step program.
            self._multistep = jit_sharded(
                _multistep_sampling, donate_argnums=(2, 3),
                static_argnums=(12, 13),
                out_shardings=(
                    (rep, rep, rep, kvsh, kvsh, rep, rep, rep, rep)
                    if rep else None
                ),
            )
            self._multistep_greedy = jit_sharded(
                _multistep_greedy, donate_argnums=(2, 3),
                static_argnums=(8, 9),
                out_shardings=(
                    (rep, rep, rep, kvsh, kvsh, rep, rep, rep)
                    if rep else None
                ),
            )

        def _prefill_insert(
            params, ids, k, v, lengths, toks, slot, actual_len,
            keys, temps, tks, tps, slot_key, temp, tk, tp,
        ):
            from ..models.sampling import sample_logits

            logits, seq = llama.prefill(params, ids, cfg, dtype=dtype)
            cache = llama.insert_sequence(
                make_cache(k, v, lengths), seq, slot, actual_len
            )
            # Install the slot's sampling state, then draw the first token
            # with the same per-slot key discipline decode uses.
            carry, use = jax.random.split(slot_key)
            keys2 = keys.at[slot].set(carry)
            temps2 = temps.at[slot].set(temp)
            tks2 = tks.at[slot].set(tk)
            tps2 = tps.at[slot].set(tp)
            row = logits[0, actual_len - 1][None]
            first = sample_logits(
                row, use[None], temp[None], tk[None], tp[None]
            )[0]
            toks2 = toks.at[slot, 0].set(first)
            ck, cv = cache_repr(cache)
            return (
                ck, cv, cache.lengths, toks2,
                keys2, temps2, tks2, tps2, first,
            )

        # One compiled program per prompt bucket (jit caches by ids shape).
        self._prefill_insert = jit_sharded(
            _prefill_insert, donate_argnums=(2, 3),
            out_shardings=(
                (kvsh, kvsh, rep, rep, rep, rep, rep, rep, rep)
                if rep else None
            ),
        )

        def _prefill_one_chunk(params, ids, sk, sv, slen):
            seq = llama.KVCache(sk, sv, slen)
            logits, seq = llama.forward(params, ids, seq, cfg, dtype=dtype)
            return logits[0], seq.k, seq.v, seq.length

        self._prefill_one_chunk = jit_sharded(
            _prefill_one_chunk, donate_argnums=(2, 3),
            out_shardings=(rep, seqsh, seqsh, rep) if rep else None,
        )

        from jax.lax import dynamic_slice as lax_ds
        from jax.lax import dynamic_update_slice as lax_dus

        def _seed_chunk(sk, sv, ck, cv, start):
            # Prefix-cache hit: copy one cached chunk's K/V into the
            # in-progress sequence cache at its absolute offset.  ``start``
            # is traced, the chunk shape is fixed — ONE compiled program
            # serves every cached chunk at every offset (vs a forward pass
            # per chunk on the cold path).
            z = jnp.int32(0)
            sk = lax_dus(sk, ck.astype(sk.dtype), (z, z, start, z, z))
            sv = lax_dus(sv, cv.astype(sv.dtype), (z, z, start, z, z))
            return sk, sv

        self._seed_chunk = jit_sharded(
            _seed_chunk, donate_argnums=(0, 1),
            out_shardings=(seqsh, seqsh) if rep else None,
        )

        def _read_chunk(sk, sv, start):
            # Prefix-cache write-back: pull one freshly prefilled chunk's
            # K/V slice off the device.  Traced ``start`` -> one program.
            C = self._prefill_chunk_size
            z = jnp.int32(0)
            size = (sk.shape[0], sk.shape[1], C, sk.shape[3], sk.shape[4])
            return (
                lax_ds(sk, (z, z, start, z, z), size),
                lax_ds(sv, (z, z, start, z, z), size),
            )

        # Chunk read-backs feed the HOST radix cache: replicated outputs
        # (one all-gather at prefill rate, never per tick).
        self._read_chunk = jit_sharded(
            _read_chunk, out_shardings=(rep, rep) if rep else None
        )

        def _insert_only(
            last_logits, k, v, lengths, toks, slot, actual_len,
            keys, temps, tks, tps, slot_key, temp, tk, tp, sk, sv, last_idx,
        ):
            from ..models.sampling import sample_logits

            seq = llama.KVCache(sk, sv, jnp.zeros((), jnp.int32))
            cache = llama.insert_sequence(
                make_cache(k, v, lengths), seq, slot, actual_len
            )
            carry, use = jax.random.split(slot_key)
            keys2 = keys.at[slot].set(carry)
            temps2 = temps.at[slot].set(temp)
            tks2 = tks.at[slot].set(tk)
            tps2 = tps.at[slot].set(tp)
            row = last_logits[last_idx][None]
            first = sample_logits(
                row, use[None], temp[None], tk[None], tp[None]
            )[0]
            toks2 = toks.at[slot, 0].set(first)
            ck, cv = cache_repr(cache)
            return (
                ck, cv, cache.lengths, toks2,
                keys2, temps2, tks2, tps2, first,
            )

        self._insert_only = jit_sharded(
            _insert_only, donate_argnums=(1, 2),
            out_shardings=(
                (kvsh, kvsh, rep, rep, rep, rep, rep, rep, rep)
                if rep else None
            ),
        )

        # Sequence-parallel prefill: the whole padded prompt in ONE
        # ring-attention pass with the sequence split over sp (one
        # compiled variant per prompt bucket >= the threshold's bucket).
        # Stacked K/V lands in the donated seq scratch at origin, and
        # only the last REAL row's logits [1, V] cross the replicated
        # boundary — the insert then rides the existing _insert_only
        # path with last_idx = 0.
        if self._sp > 1:
            sp_mesh = self._mesh

            def _prefill_sp(params, ids, sk, sv, last_idx):
                logits, k_all, v_all = llama.prefill_ring(
                    params, ids, cfg, mesh=sp_mesh, last_idx=last_idx,
                    dtype=dtype,
                )
                z = jnp.int32(0)
                sk = lax_dus(sk, k_all.astype(sk.dtype), (z, z, z, z, z))
                sv = lax_dus(sv, v_all.astype(sv.dtype), (z, z, z, z, z))
                return logits, sk, sv

            self._prefill_sp = jit_sharded(
                _prefill_sp, donate_argnums=(2, 3),
                out_shardings=(rep, seqsh, seqsh) if rep else None,
            )
        else:
            self._prefill_sp = None

        max_slots_static = self.max_slots

        def _prefill_chunks_batched(
            params, ids, k, v, lengths, toks, keys, temps, tks, tps,
            slots, offsets, last_pos, final_lens,
            slot_keys, r_temps, r_tks, r_tps,
        ):
            # Packed admission: B_p in-flight admissions' next chunks in
            # ONE forward (llama.prefill_chunks_ragged), plus the
            # finalize step for rows whose chunk completes the prompt
            # (last_pos >= 0): install the slot's sampling state and
            # sample the first token — the per-sequence _insert_only
            # discipline, batched.  Non-final (and pad) rows scatter to
            # the out-of-range slot index and drop.  One compiled
            # variant per power-of-two B_p bucket (the ids shape).
            from ..models.sampling import sample_logits, split_keys

            cache = make_cache(k, v, lengths)
            logits, cache = llama.prefill_chunks_ragged(
                params, ids, cache, slots, offsets, cfg, dtype=dtype
            )
            is_final = last_pos >= 0
            row = jnp.take_along_axis(
                logits, jnp.maximum(last_pos, 0)[:, None, None], axis=1
            )[:, 0]  # [B_p, vocab]
            carry, use = split_keys(slot_keys)
            firsts = sample_logits(row, use, r_temps, r_tks, r_tps)
            tgt = jnp.where(is_final, slots, jnp.int32(max_slots_static))
            kd = jax.random.key_data(keys)
            keys2 = jax.random.wrap_key_data(
                kd.at[tgt].set(jax.random.key_data(carry), mode="drop")
            )
            temps2 = temps.at[tgt].set(r_temps, mode="drop")
            tks2 = tks.at[tgt].set(r_tks, mode="drop")
            tps2 = tps.at[tgt].set(r_tps, mode="drop")
            lengths2 = cache.lengths.at[tgt].set(final_lens, mode="drop")
            toks2 = toks.at[tgt, 0].set(firsts, mode="drop")
            ck, cv = cache_repr(cache)
            return ck, cv, lengths2, toks2, keys2, temps2, tks2, tps2, firsts

        self._prefill_chunks = jit_sharded(
            _prefill_chunks_batched, donate_argnums=(2, 3),
            out_shardings=(
                (kvsh, kvsh, rep, rep, rep, rep, rep, rep, rep)
                if rep else None
            ),
        )

        def _seed_chunk_slot(k, v, ck, cv, slot, start):
            # Packed-mode prefix-cache hit: copy one cached chunk's K/V
            # straight into the reserved cache row at its absolute
            # offset (the scratch-path _seed_chunk, retargeted at a slot
            # of the ragged cache).  ck/cv arrive position-major
            # [L, 1, C, NKV, D] — the radix cache's storage layout, so
            # entries stay interchangeable between modes.
            z = jnp.int32(0)
            ckh = jnp.swapaxes(ck, 2, 3)  # -> head-major [L,1,NKV,C,D]
            cvh = jnp.swapaxes(cv, 2, 3)
            if self._kv_quant:
                from ..models.llama import _quant_kv

                k8, ksc = _quant_kv(ckh.astype(dtype))
                v8, vsc = _quant_kv(cvh.astype(dtype))
                kb, ks = k
                vb, vs = v
                at = (z, slot, z, start, z)
                return (
                    (lax_dus(kb, k8, at), lax_dus(ks, ksc, at)),
                    (lax_dus(vb, v8, at), lax_dus(vs, vsc, at)),
                )
            at = (z, slot, z, start, z)
            return (
                lax_dus(k, ckh.astype(k.dtype), at),
                lax_dus(v, cvh.astype(v.dtype), at),
            )

        self._seed_slot = jit_sharded(
            _seed_chunk_slot, donate_argnums=(0, 1),
            out_shardings=(kvsh, kvsh) if rep else None,
        )

        def _read_chunk_slot(k, v, slot, start):
            # Packed-mode prefix-cache write-back: pull one freshly
            # prefilled chunk's K/V off the reserved cache row, returned
            # position-major (the radix cache's storage layout).  An
            # int8kv cache dequantizes on the way out — lossless round
            # trip: re-quantizing q8*scale reproduces q8 and scale
            # exactly (the per-head max is preserved).
            C = self._prefill_chunk_size
            z = jnp.int32(0)
            at = (z, slot, z, start, z)

            def pull(buf, width):
                size = (buf.shape[0], 1, buf.shape[2], C, width)
                return lax_ds(buf, at, size)

            if self._kv_quant:
                kb, ks = k
                vb, vs = v
                ck = pull(kb, kb.shape[4]).astype(dtype) * pull(ks, 1)
                cv = pull(vb, vb.shape[4]).astype(dtype) * pull(vs, 1)
            else:
                ck = pull(k, k.shape[4])
                cv = pull(v, v.shape[4])
            return (
                jnp.swapaxes(ck, 2, 3).astype(dtype),
                jnp.swapaxes(cv, 2, 3).astype(dtype),
            )

        self._read_slot = jit_sharded(
            _read_chunk_slot, out_shardings=(rep, rep) if rep else None
        )

        def _insert_restore(
            lengths, toks, keys, temps, tks, tps,
            slot, length, pending, slot_key, temp, tk, tp,
        ):
            # Preemption restore: re-install an evicted sequence's slot
            # bookkeeping after its K/V chunks were re-seeded.  The
            # mirror of _insert_only's finalize step with two deliberate
            # differences that make restore+resume token-for-token
            # identical to never having been evicted: the PRNG carry is
            # installed AS CAPTURED (no split — the split already
            # happened in the sequence's own history), and no token is
            # sampled (the pending token was sampled before eviction
            # and travels with the record).  Touches no cache buffers.
            lengths2 = lengths.at[slot].set(length)
            toks2 = toks.at[slot, 0].set(pending)
            kd = jax.random.key_data(keys)
            keys2 = jax.random.wrap_key_data(
                kd.at[slot].set(jax.random.key_data(slot_key))
            )
            temps2 = temps.at[slot].set(temp)
            tks2 = tks.at[slot].set(tk)
            tps2 = tps.at[slot].set(tp)
            return lengths2, toks2, keys2, temps2, tks2, tps2

        self._insert_restore = jit_sharded(
            _insert_restore,
            out_shardings=(
                (rep, rep, rep, rep, rep, rep) if rep else None
            ),
        )

        def _superstep(
            params, ids, k, v, lengths, toks, keys, temps, tks, tps,
            roles, offsets, counts, draft_len, act_in, remaining, eos_in,
            last_pos, final_lens, slot_keys, r_temps, r_tks, r_tps,
            window, steps, sampling,
        ):
            # The whole tick as ONE program: mixed decode/verify/prefill
            # rows through llama.super_step_ragged, then the packed
            # finalize step (rows whose chunk completes the prompt
            # install their sampling state and sample the first token —
            # _prefill_chunks_batched's tail, reading the same wide
            # logits).  ``sampling`` is static like window/steps: the
            # greedy variant compiles without the chain-sampling work
            # but keeps the full signature (finalize still installs
            # per-request sampling state), so the warmup sweep is
            # |window buckets| x 2 — full stop.
            from ..models.sampling import (
                sample_chain_step, sample_logits, split_keys,
            )

            cache = make_cache(k, v, lengths)
            if sampling:
                def sample(lg, carry):
                    return sample_chain_step(lg, carry, temps, tks, tps)

                carry0 = keys
            else:
                def sample(lg, carry):
                    return carry, jnp.argmax(lg, axis=-1).astype(jnp.int32)

                carry0 = None
            (
                logits, tok_block, valid, greedy, accepted,
                toks2, cache, _act2, _rem2, carry2,
            ) = llama.super_step_ragged(
                params, ids, cache, cfg,
                roles=roles, offsets=offsets, counts=counts,
                draft_len=draft_len, active=act_in, remaining=remaining,
                eos_ids=eos_in, steps=steps, sample_fn=sample,
                sample_carry=carry0, dtype=dtype, window=window,
            )
            keys_run = carry2 if sampling else keys
            is_final = last_pos >= 0
            row = jnp.take_along_axis(
                logits, jnp.maximum(last_pos, 0)[:, None, None], axis=1
            )[:, 0]  # [B, vocab]
            f_carry, use = split_keys(slot_keys)
            firsts = sample_logits(row, use, r_temps, r_tks, r_tps)
            tgt = jnp.where(
                is_final,
                jnp.arange(max_slots_static, dtype=jnp.int32),
                jnp.int32(max_slots_static),
            )
            kd = jax.random.key_data(keys_run)
            keys2 = jax.random.wrap_key_data(
                kd.at[tgt].set(jax.random.key_data(f_carry), mode="drop")
            )
            temps2 = temps.at[tgt].set(r_temps, mode="drop")
            tks2 = tks.at[tgt].set(r_tks, mode="drop")
            tps2 = tps.at[tgt].set(r_tps, mode="drop")
            lengths2 = cache.lengths.at[tgt].set(final_lens, mode="drop")
            toks3 = toks2.at[tgt, 0].set(firsts, mode="drop")
            ck, cv = cache_repr(cache)
            return (
                tok_block, valid, greedy, accepted, firsts,
                toks3, ck, cv, lengths2, keys2, temps2, tks2, tps2,
            )

        if self._unified:
            self._superstep = jit_sharded(
                _superstep, donate_argnums=(2, 3),
                static_argnums=(23, 24, 25),
                out_shardings=(
                    (rep, rep, rep, rep, rep, rep, kvsh, kvsh,
                     rep, rep, rep, rep, rep)
                    if rep else None
                ),
            )

        if telemetry is not None:
            # Compile observatory: every engine jit dispatch is wrapped so
            # XLA compilations attribute to the op that triggered them
            # (decode buckets x verify variants x prefill B_p buckets x
            # seed ops).  The wrapper is a thread-local set/unset around
            # the call — no per-dispatch device work.
            obs = telemetry.observatory
            self._decode = obs.wrap_jit("decode", self._decode)
            self._decode_greedy = obs.wrap_jit("decode", self._decode_greedy)
            self._verify = obs.wrap_jit("verify", self._verify)
            if self._fused and not self._unified:
                self._multistep = obs.wrap_jit("multistep", self._multistep)
                self._multistep_greedy = obs.wrap_jit(
                    "multistep", self._multistep_greedy
                )
            if self._unified:
                self._superstep = obs.wrap_jit("superstep", self._superstep)
            self._prefill_insert = obs.wrap_jit("prefill", self._prefill_insert)
            self._prefill_one_chunk = obs.wrap_jit(
                "prefill", self._prefill_one_chunk
            )
            self._insert_only = obs.wrap_jit("prefill", self._insert_only)
            if self._prefill_sp is not None:
                self._prefill_sp = obs.wrap_jit(
                    "sp-prefill", self._prefill_sp
                )
            self._prefill_chunks = obs.wrap_jit(
                "packed-prefill", self._prefill_chunks
            )
            self._seed_chunk = obs.wrap_jit("seed", self._seed_chunk)
            self._read_chunk = obs.wrap_jit("seed", self._read_chunk)
            self._seed_slot = obs.wrap_jit("seed", self._seed_slot)
            self._read_slot = obs.wrap_jit("seed", self._read_slot)
            prefix_budget = (
                int(prefix_cache.budget_bytes) if prefix_enabled else 0
            )
            telemetry.attach_model(
                params, cfg, self.max_slots,
                kv_quant=self._kv_quant,
                dtype_bytes=jnp.dtype(dtype).itemsize,
                prefix_cache_budget_bytes=prefix_budget,
                mesh_shape=mesh_shape,
            )

        self._slots: list[_Slot | None] = [None] * self.max_slots
        self._pending: list[_PrefillProgress] = []
        # Packed mode: cache rows reserved by in-flight admissions (their
        # chunks are being written there; decode must not hand them out).
        self._reserved: set[int] = set()
        # Single-admission chunked-prefill scratch (leader and follower
        # both thread the in-progress sequence cache through here; it is
        # what serializes that mode to one admission at a time — packed
        # mode writes straight into reserved cache rows and never uses it).
        self._seq_state = None  # (last_logits, seq_k, seq_v, seq_len)
        # Engine-assigned sampling keys: fold a per-boot nonce so unseeded
        # requests never collide with the user-visible seed space (and never
        # replay the same streams after a pod restart).  NOT reset by
        # _reset_device_state: streams stay distinct across a recovery.
        import os as _os

        self._boot_key = jax.random.key(int.from_bytes(_os.urandom(7), "little"))
        self._seed_counter = 0
        # Constant pad-row key material for packed calls, computed ONCE:
        # rebuilding it per tick would put a device dispatch + D2H sync
        # on the scheduler thread ahead of every packed dispatch.
        self._zero_kd = np.asarray(jax.random.key_data(jax.random.key(0)))
        self._queue: queue.Queue[_Request | None] = queue.Queue()
        # Control operations (KV export/import, fleet introspection):
        # closures any thread may enqueue that MUST run on the scheduler
        # thread — the radix prefix cache and slot truth are
        # single-threaded by design.  Drained at the top of every
        # admission phase; one empty get_nowait per tick when idle.
        self._control_ops: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Admission control (the data-plane half of the autoscaling
        # subsystem): a token-denominated bound on queued-but-unadmitted
        # work.  0 (default) = unbounded, the old admission behavior
        # byte-for-byte — submit still takes the lock, but only to read
        # a flag that is always False.
        self._admission_budget = int(admission_queue_budget or 0)
        if self._admission_budget < 0:
            raise ValueError(
                "admission_queue_budget must be >= 0, got "
                f"{admission_queue_budget}"
            )
        self._adm_lock = threading.Lock()
        self._queued_est_tokens = 0
        # Per-model fairness ledger (multiplexed warm pool): estimated
        # tokens outstanding per attached-model id, HTTP-request scoped
        # (reserved in reserve_admission, returned by
        # release_model_admission when the carrying request finishes).
        # Empty — and every branch reading it dead — unless a caller
        # passes model=, so single-model admission is byte-identical.
        self._model_est: dict[str, int] = {}
        self._inflight_reqs = 0  # submitted futures not yet done
        self._draining = False
        self._on_shed = on_shed
        self.shed_total = 0  # sheds by any reason (bench/metrics mirror)
        self.tokens_generated = 0
        # Prefix-cache observability (also read by bench.py's shared-prefix
        # scenario and the Prometheus hookups in app.make_gen_engine).
        self.prefix_hits = 0
        self.prefix_cached_tokens = 0
        self.prefix_evictions = 0
        self.prefill_chunks_dispatched = 0
        # Weight-streaming prefill dispatches (fused prefills, serial
        # chunk forwards, packed batched calls each count 1): the
        # packed_prefill_serving bench reads the packed-vs-serial drop
        # here — every dispatch avoided is a full HBM weight stream
        # the admissions shared instead of re-paying.
        self.prefill_forwards = 0
        # Speculative/fused observability (also read by bench.py's
        # speculative_serving and multistep_serving scenarios):
        # decode_forwards counts every decode/verify/multistep DISPATCH,
        # decode_tokens every token those dispatches emitted.  In the
        # single-step loop a dispatch is one weight stream and the ratio
        # is exactly 1/(active slots); speculative acceptance drives it
        # lower per weight stream, while a fused K-step dispatch streams
        # the weights K times under ONE dispatch — so this ratio is the
        # per-DISPATCH amortization (host/tunnel overhead), not
        # weight-streams-per-token, once decodeSteps > 1.
        self.decode_forwards = 0
        self.decode_tokens = 0
        self.spec_verify_ticks = 0
        self.spec_proposed_tokens = 0
        self.spec_accepted_tokens = 0

    def _reset_device_state(self) -> None:
        """(Re)allocate the KV cache and token buffers.

        Also the recovery path after a failed jitted step: donation has
        already invalidated the old buffers, so continuing with them would
        raise "Array has been deleted" on every subsequent request."""
        import jax
        import jax.numpy as jnp

        from ..models import llama

        if getattr(self, "_kv_quant", False):
            cache = llama.QuantRaggedKVCache.create(self._cfg, self.max_slots)
            self._cache_k = (cache.k8, cache.k_scale)
            self._cache_v = (cache.v8, cache.v_scale)
        else:
            cache = llama.RaggedKVCache.create(
                self._cfg, self.max_slots, self._dtype
            )
            self._cache_k, self._cache_v = cache.k, cache.v
        self._lengths = cache.lengths
        self._tokens = jnp.zeros((self.max_slots, 1), jnp.int32)
        # Per-slot sampling state (arrays so one compiled decode serves any
        # mix of greedy and sampled requests).
        self._keys = jax.random.split(jax.random.key(0), self.max_slots)
        self._temps = jnp.zeros((self.max_slots,), jnp.float32)
        self._topk = jnp.zeros((self.max_slots,), jnp.int32)
        self._topp = jnp.ones((self.max_slots,), jnp.float32)
        if getattr(self, "_mesh", None) is not None:
            # Commit the state to its mesh shardings up front (cache
            # heads on tp, everything else replicated): the programs'
            # explicit out shardings keep them there, so donation reuses
            # the sharded buffers and no tick ever re-lays-out.
            self._cache_k = jax.device_put(self._cache_k, self._shard_kv)
            self._cache_v = jax.device_put(self._cache_v, self._shard_kv)
            put = lambda x: jax.device_put(x, self._shard_rep)
            self._lengths = put(self._lengths)
            self._tokens = put(self._tokens)
            self._keys = put(self._keys)
            self._temps = put(self._temps)
            self._topk = put(self._topk)
            self._topp = put(self._topp)
        # Fused-decode chain state (device-resident active mask / budgets
        # / EOS ids): valid only WITHIN one fused burst — every burst
        # re-seeds it from host slot truth, so a recovery reset needs no
        # special handling beyond dropping the stale references.
        self._ms_active = None
        self._ms_remaining = None
        self._ms_eos = None

    def _put_seq(self, buf):
        """Commit a fresh batch-1 prefill scratch buffer to the seq-cache
        sharding (no-op without a mesh)."""
        if self._mesh is None:
            return buf
        import jax

        return jax.device_put(buf, self._shard_seq)

    # -- lifecycle -----------------------------------------------------------

    def start(self, warmup: bool = True) -> None:
        if warmup:
            self._warmup()
        if self._watchdog is not None:
            # Arm AFTER warmup: the compile sweep legitimately blocks
            # far past any sane tick deadline.
            self._watchdog.arm()
            self._watchdog.start()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="generation-scheduler"
        )
        self._thread.start()

    def _beat(self, kind: str | None = None) -> None:
        """One scheduler heartbeat (no-op without a watchdog — the
        default keeps the loop byte-for-byte)."""
        if self._watchdog is not None:
            self._watchdog.beat(kind)

    def _slot_inventory(self) -> list:
        """Best-effort in-flight snapshot for the watchdog's stall event
        (called from the MONITOR thread while the scheduler is wedged —
        reads race its last mutation by design; the watchdog tolerates
        raises)."""
        inv = []
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            inv.append({
                "slot": i,
                "request_id": slot.request_id,
                "prompt_len": int(slot.prompt_len),
                "generated": len(slot.generated),
                "remaining": int(slot.remaining),
            })
        for prog in list(self._pending):
            inv.append({
                "slot": int(getattr(prog, "slot", -1)),
                "request_id": prog.req.request_id,
                "prompt_len": int(prog.req.prompt.size),
                "generated": 0,
                "remaining": int(prog.req.max_new_tokens),
                "admitting": True,
            })
        return inv

    def _warmup(self) -> None:
        """Compile every decode program before readiness, so no live request
        pays an XLA compile (the persistent compile cache makes this
        near-instant on a warm node).

        "Every" means both decode variants (greedy / sampling) at EVERY
        power-of-two attention-window bucket up to capacity — window is a
        static jit arg, so each bucket is its own executable and a lazily
        compiled one would stall the single scheduler thread (and every
        in-flight stream) for seconds the first time traffic crosses a
        bucket boundary."""
        import jax
        import jax.numpy as jnp

        t0 = time.perf_counter()
        self._in_warmup = True
        if self._telemetry is not None:
            # Compile observatory: the sweep's compiles/seconds roll up
            # into a warmup report, warned about when they exceed the
            # readiness budget (the kubelet's probe window).
            self._telemetry.observatory.begin_warmup()
        try:
            if self._prefix_cache is not None:
                # Compile the prefix-cache seed (dispatched: followers
                # must compile it too) and the leader-side chunk read-back
                # before readiness — a lazy compile on the first warm
                # admission would stall the scheduler thread.  Runs FIRST:
                # the admissions below dispatch fresh-chunk + insert ops
                # that drop the seeded scratch buffers on every host.
                C = self._prefill_chunk_size
                shape = (
                    self._cfg.num_layers, 1, C,
                    self._cfg.num_kv_heads, self._cfg.head_dim,
                )
                zk = np.asarray(jnp.zeros(shape, self._dtype))
                if self._packed:
                    # Packed mode seeds/reads the reserved cache row
                    # directly — different executables than the scratch
                    # path (zeros into row 0 == the freshly allocated
                    # state, so nothing to clean up after).
                    self._dispatch_seed_slot([(zk, zk)], 0, C)
                    self._read_slot(
                        self._cache_k, self._cache_v,
                        jnp.int32(0), jnp.int32(0),
                    )
                else:
                    self._dispatch_seed([(zk, zk)], C)
                    _, sk, sv, _slen = self._seq_state
                    self._read_chunk(sk, sv, jnp.int32(0))
                if self._preemption:
                    # Evict/restore path: the slot-targeted read/seed
                    # pair (packed mode compiled them above) plus the
                    # restore finalize — all dispatched or leader-cheap,
                    # so the first live eviction never compiles on the
                    # scheduler thread.
                    if not self._packed:
                        self._dispatch_seed_slot([(zk, zk)], 0, C)
                        self._read_slot(
                            self._cache_k, self._cache_v,
                            jnp.int32(0), jnp.int32(0),
                        )
                    self._dispatch_restore(
                        0, C, 1, np.asarray(jax.random.key_data(
                            jax.random.key(0))),
                        0.0, 0, 1.0,
                    )
            if self._packed and not self._unified:
                # Packed-prefill variants: one executable per B_p bucket
                # (the ids shape is what jit caches on).  Dispatched, not
                # raw: followers of a multihost unit must compile the
                # same buckets.  The fully parked batch shares the live
                # path's construction site, so warmed shapes cannot
                # drift from what _packed_tick dispatches.  The unified
                # engine has no packed program: chunks ride the
                # super-step variants swept below.
                for bucket in self._pack_buckets():
                    self._dispatch_chunks(*self._parked_batch(bucket))
            self._admit_now(
                _Request(
                    prompt=np.array([1], np.int32),
                    max_new_tokens=2,
                    eos_id=None,
                    future=Future(),
                )
            )
            self._step()  # greedy decode variant, smallest window
            self._slots = [None] * self.max_slots
            self._admit_now(
                _Request(
                    prompt=np.array([1], np.int32),
                    max_new_tokens=2,
                    eos_id=None,
                    future=Future(),
                    temperature=1.0,
                    seed=0,
                )
            )
            self._step()  # sampling decode variant, smallest window
            # Remaining window buckets, both variants, on inert state
            # (active all-False advances nothing; warmup resets state
            # after).  Dispatched, not raw: followers of a multihost unit
            # must compile the same buckets or the first bucket crossing
            # stalls the whole slice.
            inactive = np.zeros((self.max_slots,), bool)
            smallest = decode_window_bucket(1, self.capacity)
            if self._unified:
                # THE K-fold collapse: one super-step variant per
                # (window bucket x sampling mode) covers what the split
                # engine sweeps as decode x 2 + verify x |chain| +
                # multistep x 2 + packed B_p buckets.  Every window is
                # swept (the dummy admits above may land on a larger
                # bucket when decode_steps pushes length + K - 1 over
                # the smallest); re-dispatching a compiled variant is a
                # jit cache hit.  Parked batches (all-idle roles, zero
                # counts) advance nothing, exactly like the inactive
                # decode sweeps.
                for window in decode_window_buckets(self.capacity):
                    self._dispatch_superstep(
                        *self._parked_superstep(), window, False
                    )
                    self._dispatch_superstep(
                        *self._parked_superstep(), window, True
                    )
            else:
                for window in decode_window_buckets(self.capacity):
                    if window == smallest:
                        continue  # both variants already compiled above
                    self._dispatch_step(inactive, window, False)
                    self._dispatch_step(inactive, window, True)
            if self._spec is not None and not self._unified:
                # Verify variants: one executable per (draft length,
                # window) pair — draft lengths are capped to the halving
                # chain so this sweep stays |chain| x |buckets|, not
                # draftTokens x |buckets|.  Dispatched (not raw): lazy
                # compiles on a follower would stall the whole slice at
                # the first live verify.
                zero_draft = np.zeros((self.max_slots,), np.int32)
                for window in decode_window_buckets(self.capacity):
                    for s_draft in self._spec_chain:
                        toks = np.zeros(
                            (self.max_slots, s_draft + 1), np.int32
                        )
                        self._dispatch_verify(
                            toks, inactive, zero_draft, window
                        )
            if self._fused and not self._unified:
                # Fused multi-step variants: one executable per
                # (K, window) pair, both token rules — K is fixed per
                # deployment so the sweep is |buckets| x 2.  Dispatched,
                # not raw: followers must compile the same variants or
                # the first live fused tick stalls the whole slice.
                # All-inactive, zero-budget rows advance nothing.
                zero_rem = np.zeros((self.max_slots,), np.int32)
                no_eos = np.full((self.max_slots,), -1, np.int32)
                for window in decode_window_buckets(self.capacity):
                    self._dispatch_multistep(
                        inactive, zero_rem, no_eos, window, False
                    )
                    self._dispatch_multistep(
                        inactive, zero_rem, no_eos, window, True
                    )
            # Fused-prefill buckets: each power-of-two prompt bucket is its
            # own executable (the padded ids shape is static), so admit one
            # dummy prompt per bucket — otherwise the first live request at
            # a larger bucket pays the XLA compile on the single scheduler
            # thread and stalls every in-flight stream.  Chunked prefill
            # runs one fixed-size program per chunk; no sweep needed there.
            if self._prefill_chunk_size is None:
                bucket = _MIN_BUCKET
                while bucket < self.capacity:
                    bucket = min(bucket * 2, self.capacity)
                    # max_new_tokens=1 resolves at admission, so the slot
                    # frees itself inside _admit — no cleanup needed.
                    self._admit_now(
                        _Request(
                            prompt=np.ones((bucket,), np.int32),
                            max_new_tokens=1,
                            eos_id=None,
                            future=Future(),
                        )
                    )
            if self._sp > 1 and self._sp_threshold <= self.capacity:
                # sp ring-prefill variants: one executable per power-of-
                # two prompt bucket at or above the routing threshold
                # (plus the [1, V] insert variant, shared across
                # buckets).  Dispatched via _admit_now -> _admit_sp so
                # followers of a multihost unit compile the same ring
                # programs.  The prompt length >= threshold guarantees
                # the sp route fires regardless of chunked/fused mode.
                bucket = prefill_bucket(self._sp_threshold, self.capacity)
                while True:
                    self._admit_now(
                        _Request(
                            prompt=np.ones((bucket,), np.int32),
                            max_new_tokens=1,
                            eos_id=None,
                            future=Future(),
                        )
                    )
                    if bucket >= self.capacity:
                        break
                    bucket = min(bucket * 2, self.capacity)
        finally:
            self._in_warmup = False
            if self._telemetry is not None:
                self._telemetry.observatory.end_warmup()
        # Reset state so warmup tokens never leak into a real response.
        slot = self._slots[0]
        if slot is not None:
            slot.future.cancel()
        self._slots = [None] * self.max_slots
        _log.info("generation warmup in %.1fs", time.perf_counter() - t0)

    def shutdown(self) -> None:
        if self._watchdog is not None:
            # Disarm BEFORE the join: teardown legitimately stops
            # beating, and an escalation mid-shutdown would turn a clean
            # drain into an os._exit.
            self._watchdog.disarm()
            self._watchdog.stop()
        self._stop.set()
        self._queue.put(None)  # unblock the scheduler
        if self._thread is not None:
            self._thread.join(timeout=30)
        for prog in self._pending:
            # A chunked admission in flight is in neither the queue nor a
            # slot; fail it LOUDLY or its client awaits forever.
            self._abort_trace(prog.req.trace, "shutdown")
            if not prog.req.future.done():
                _safe_fail(
                    prog.req.future,
                    EngineShutdown(
                        "engine shut down mid-prefill; retry on another "
                        "replica"
                    ),
                )
        self._pending = []
        self._reserved.clear()
        self._seq_state = None
        for slot in self._slots:
            if slot is not None and not slot.future.done():
                self._abort_trace(slot.trace, "shutdown")
                slot.future.cancel()
        while True:
            try:
                fn_fut = self._control_ops.get_nowait()
            except queue.Empty:
                break
            _safe_fail(
                fn_fut[1],
                EngineShutdown("engine shut down before the control op ran"),
            )
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if isinstance(req, _Wake):
                continue
            if req is not None:
                self._release_queued(req)
            if req is not None and not req.future.done():
                # Queued-but-unadmitted: a clear EngineShutdown beats a
                # bare CancelledError — callers can distinguish "the
                # server is going away, retry elsewhere" from a client-
                # side cancel.
                self._abort_trace(req.trace, "shutdown")
                _safe_fail(
                    req.future,
                    EngineShutdown(
                        "engine shut down before admission; retry on "
                        "another replica"
                    ),
                )
        if self._class_queues is not None:
            # Class deques hold dequeued-but-unadmitted requests AND
            # evicted sequences awaiting restore — fail both loudly.
            for dq in self._class_queues.values():
                while dq:
                    item = dq.popleft()
                    if isinstance(item, _Request):
                        self._release_queued(item)
                    if not item.future.done():
                        self._abort_trace(item.trace, "shutdown")
                        _safe_fail(
                            item.future,
                            EngineShutdown(
                                "engine shut down before admission; "
                                "retry on another replica"
                            ),
                        )

    def _abort_trace(self, trace, reason: str) -> None:
        """Finish a request trace off the normal token path (shutdown /
        engine failure) so its span still closes in the recorder."""
        if trace is None:
            return
        trace.finish(reason)
        if self._recorder is not None:
            self._recorder.event(trace.request_id, "finish", slot=trace.slot)
            self._recorder.complete(trace)

    # -- admission control / drain (client-facing) ---------------------------

    def reserve_admission(
        self,
        est_tokens: int,
        slo_class: str | None = None,
        model: str | None = None,
    ) -> None:
        """Reserve queue room for ``est_tokens`` or shed.

        Raises :class:`EngineOverloaded` when the engine is draining, or
        when the reservation would push queued-but-unadmitted estimated
        tokens past the admission budget; otherwise the tokens are
        counted (released exactly once when the scheduler dequeues the
        carrying request).  Callers batching several prompts into one
        HTTP request reserve the TOTAL up front, so a request is
        admitted whole or shed whole — never half-admitted with
        siblings generating into abandoned futures.

        With SLO classes armed, each class sheds at its own fraction of
        the budget (``_CLASS_BUDGET_FACTOR``): a best-effort request
        refused at half-full queue sheds with reason
        ``class_best-effort`` — distinguishable on dashboards from the
        full-budget ``budget`` overload interactive traffic hits.

        ``model`` (multiplexed warm pool: the model id the router
        addressed) arms per-model fairness: with two or more models
        holding outstanding work, each is bounded by an equal SHARE of
        the budget instead of the whole budget — a flooded hot model
        sheds with reason ``model_budget`` at its share while a tail
        model with nothing outstanding is still admitted, so the shared
        queue cannot starve cold models.  The caller returns the
        reservation via :meth:`release_model_admission` when the
        carrying HTTP request finishes.
        """
        cls = None
        if self._classes:
            cls = slo_class or self._slo_default or "interactive"
        with self._adm_lock:
            if self._draining:
                self._note_shed("draining")
                raise EngineOverloaded(
                    "engine is draining; retry on another replica",
                    reason="draining",
                    retry_after_s=1,
                    slo_class=cls,
                )
            budget = self._admission_budget
            eff_budget, reason = budget, "budget"
            if cls is not None and budget:
                factor = _CLASS_BUDGET_FACTOR.get(cls, 1.0)
                if factor < 1.0:
                    eff_budget = int(budget * factor)
                    reason = f"class_{cls}"
            fair_share = None
            if eff_budget and model is not None:
                active = {m for m, v in self._model_est.items() if v > 0}
                active.add(model)
                if len(active) >= 2:
                    # Two or more models contending: this model's bound
                    # becomes budget/n INSTEAD of the global backlog
                    # check below — the global check would let a hot
                    # model's backlog shed the tail model's first
                    # request, the exact starvation fairness exists to
                    # prevent.
                    fair_share = max(1, eff_budget // len(active))
                    mine = self._model_est.get(model, 0)
                    if mine > 0 and mine + est_tokens > fair_share:
                        self._note_shed("model_budget")
                        raise EngineOverloaded(
                            f"model {model!r} admission share full: "
                            f"{mine} estimated tokens outstanding + "
                            f"{est_tokens} requested > share "
                            f"{fair_share} ({eff_budget} budget / "
                            f"{len(active)} active models); retry "
                            "after the share drains",
                            reason="model_budget",
                            retry_after_s=1,
                            slo_class=cls,
                        )
            # The budget bounds the BACKLOG, not request size: with the
            # queue empty, any request validate() allowed is admitted —
            # otherwise a single request whose estimate alone exceeds
            # the budget would shed identically on every replica, a
            # deterministic fleet-wide 429 outage for work the engine
            # could run directly.
            if (
                fair_share is None
                and eff_budget
                and self._queued_est_tokens > 0
                and self._queued_est_tokens + est_tokens > eff_budget
            ):
                self._note_shed(reason)
                raise EngineOverloaded(
                    f"admission queue full: {self._queued_est_tokens} "
                    f"estimated tokens queued + {est_tokens} requested "
                    f"> budget {eff_budget}; retry on another replica",
                    reason=reason,
                    retry_after_s=1,
                    slo_class=cls,
                )
            self._queued_est_tokens += est_tokens
            if model is not None:
                self._model_est[model] = (
                    self._model_est.get(model, 0) + est_tokens
                )

    def _note_shed(self, reason: str) -> None:
        # _adm_lock held: counter mutations stay consistent with the
        # decision that produced them.
        self.shed_total += 1
        if self._on_shed is not None:
            self._on_shed(reason)

    def release_model_admission(self, model: str | None, est_tokens: int) -> None:
        """Return a per-model fairness reservation (HTTP-request scoped
        counterpart of the ``model=`` arm of :meth:`reserve_admission`)."""
        if not model or not est_tokens:
            return
        with self._adm_lock:
            left = self._model_est.get(model, 0) - est_tokens
            if left > 0:
                self._model_est[model] = left
            else:
                self._model_est.pop(model, None)

    def _release_queued(self, req: _Request) -> None:
        """Return a dequeued request's reservation (idempotent)."""
        if req.est_tokens:
            with self._adm_lock:
                self._queued_est_tokens -= req.est_tokens
            req.est_tokens = 0

    def begin_drain(self) -> None:
        """Stop admissions: every later submit sheds with 429-mapped
        :class:`EngineOverloaded`; already-queued and in-flight
        sequences run to completion (that is what makes the drain
        lossless).  The scheduler loop keeps ticking until
        :meth:`shutdown`."""
        with self._adm_lock:
            self._draining = True

    def cancel_drain(self) -> None:
        """Reopen admissions (an operator cancelled the drain); nothing
        in flight was disturbed, so this is just the flag."""
        with self._adm_lock:
            self._draining = False

    @property
    def draining(self) -> bool:
        return self._draining

    def inflight(self) -> int:
        """Submitted sequences whose futures are not yet done.

        Counted at the future boundary, NOT by summing queue + pending +
        slots: a request being moved between those structures on the
        scheduler thread would transiently vanish from a structural sum,
        and a drain waiter hitting that gap would tear the server down
        with work in flight — the one request the drain exists to save.
        """
        with self._adm_lock:
            return self._inflight_reqs

    def drained(self) -> bool:
        return self._draining and self.inflight() == 0

    # -- client API ----------------------------------------------------------

    def validate(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        seed: int | None = None,
    ) -> np.ndarray:
        """Check a request without admitting it; returns the int32 prompt.

        Callers batching several prompts into one HTTP request validate ALL
        of them first, so a bad one rejects the request before any sibling
        has been admitted and left generating into an abandoned future.
        """
        try:
            # int64 first: ids >= 2**31 would raise OverflowError straight
            # from an int32 asarray, and that escaped to clients as a 500.
            prompt = np.asarray(prompt_ids, np.int64).reshape(-1)
        except (OverflowError, ValueError, TypeError) as e:
            raise ValueError(f"prompt ids must be integers: {e}") from None
        if prompt.size == 0:
            raise ValueError("empty prompt")
        vocab = int(getattr(self._cfg, "vocab_size", 0))
        if int(prompt.min()) < 0 or (vocab and int(prompt.max()) >= vocab):
            # Out-of-range ids would silently clamp in jnp.take and return
            # garbage completions as 200s; reject at the door instead.
            raise ValueError(
                f"prompt ids must be in [0, {vocab}), got range "
                f"[{int(prompt.min())}, {int(prompt.max())}]"
            )
        prompt = prompt.astype(np.int32)
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        total = prompt.size + max_new_tokens
        if total > self.capacity:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new_tokens}) "
                f"= {total} exceeds KV-cache capacity {self.capacity}"
            )
        if not (0.0 <= float(temperature) <= 100.0):
            raise ValueError(f"temperature must be in [0, 100], got {temperature}")
        if not (0 <= int(top_k) < 2**31):
            # top_k is lowered to jnp.int32 in _admit; an out-of-range value
            # passing validation would raise OverflowError inside the jitted
            # step and _fail_all_and_recover would kill every in-flight
            # request over one malformed one.
            raise ValueError(f"top_k must be in [0, 2**31), got {top_k}")
        if not (0.0 < float(top_p) <= 1.0):
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if seed is not None and not (0 <= int(seed) < 2**63):
            # jax.random.key takes an int64; reject before admission so one
            # bad request can't poison the scheduler for everyone else.
            raise ValueError(f"seed must be in [0, 2**63), got {seed}")
        # Poison quarantine: a prompt whose admission crashed the engine
        # twice is refused at the door (typed 422 upstream) instead of
        # getting a third shot at crash-looping the replica.  The dict
        # gate keeps the hot path hash-free until a crash ever happens.
        if self._quarantined:
            fp = self._fingerprint(prompt)
            with self._poison_lock:
                crashes = self._quarantined.get(fp)
            if crashes is not None:
                self.poison_rejected_total += 1
                if self._on_poison is not None:
                    self._on_poison("rejected")
                raise PoisonRequest(fp, crashes)
        return prompt

    # -- poison-request quarantine -------------------------------------------

    # Crashes of the same prompt fingerprint before submits refuse it:
    # the first crash could be anything (device wedge, OOM race), the
    # second with every OTHER request meanwhile fine is the prompt.
    POISON_CRASH_THRESHOLD = 2

    @staticmethod
    def _fingerprint(prompt: np.ndarray) -> str:
        import hashlib

        return hashlib.sha256(
            np.ascontiguousarray(prompt, np.int64).tobytes()
        ).hexdigest()[:16]

    def _note_admission_crash(self, reqs) -> None:
        """Attribute an admission/prefill crash to the implicated
        request(s) by prompt fingerprint; quarantine at the threshold.

        Called from the scheduler thread's crash handlers only — decode
        crashes are NOT attributed (every slot was in flight; blaming
        any of them would quarantine innocents).  In packed mode all
        batched admissions are implicated: the poison one accumulates
        toward the threshold on every retry while innocents' counts
        only grow if they keep co-batching with it."""
        for req in reqs:
            if req is None:
                continue
            try:
                fp = self._fingerprint(req.prompt)
            except Exception:
                continue
            newly = False
            with self._poison_lock:
                n = self._poison_counts.get(fp, 0) + 1
                self._poison_counts[fp] = n
                if n >= self.POISON_CRASH_THRESHOLD and fp not in self._quarantined:
                    self._quarantined[fp] = n
                    newly = True
            if newly:
                self.poison_quarantined_total += 1
                _log.error(
                    "poison quarantine: prompt fingerprint %s crashed "
                    "admission %d times; further submits are refused "
                    "with a typed 422",
                    fp, n,
                )
                if self._on_poison is not None:
                    self._on_poison("quarantined")
                if self._recorder is not None:
                    self._recorder.event(
                        req.request_id or "", "poison-quarantine",
                        fingerprint=fp, crashes=n,
                    )

    def submit(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        eos_id: int | None = None,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        seed: int | None = None,
        on_token: Callable[[int], None] | None = None,
        request_id: str = "",
        trace=None,  # flight_recorder.RequestTrace | None
        est_reserved: bool = False,
        slo_class: str | None = None,
    ) -> Future:
        prompt = self.validate(
            prompt_ids, max_new_tokens, temperature, top_k, top_p, seed
        )
        # Per-request class overrides the engine default (one engine
        # serves mixed traffic); meaningless when classes are unarmed.
        if slo_class is not None and slo_class not in SLO_CLASSES:
            raise ValueError(
                f"slo_class must be one of {SLO_CLASSES}, got "
                f"{slo_class!r}"
            )
        cls = slo_class or self._slo_default or "interactive"
        # Admission control: shed BEFORE anything is enqueued (429 at
        # the door, never a half-admitted request).  est_reserved=True
        # means the caller already took the whole multi-prompt request's
        # reservation through reserve_admission.
        est = int(prompt.size) + int(max_new_tokens)
        if not est_reserved:
            self.reserve_admission(est, slo_class=cls)
        fut: Future = Future()
        # None means "use the engine default"; 0 is a legitimate eos token.
        eos = self._eos_default if eos_id is None else eos_id
        t_submit = time.perf_counter()
        if trace is not None:
            trace.t_submit = t_submit
            trace.prompt_tokens = int(prompt.size)
            if not trace.request_id:
                trace.request_id = request_id
            if self._recorder is not None:
                self._recorder.event(trace.request_id, "enqueued")
        with self._adm_lock:
            self._inflight_reqs += 1
        fut.add_done_callback(self._note_request_done)
        self._queue.put(
            _Request(
                prompt,
                int(max_new_tokens),
                eos,
                fut,
                temperature=float(temperature),
                top_k=int(top_k),
                top_p=float(top_p),
                seed=seed,
                on_token=on_token,
                t_submit=t_submit,
                request_id=request_id,
                trace=trace,
                # Always the reservation size: every submit reserved
                # (itself or via the caller's batch reserve_admission),
                # and the dequeue-side release must mirror it exactly.
                est_tokens=est,
                slo_class=cls,
            )
        )
        return fut

    def _note_request_done(self, _fut: Future) -> None:
        # Fires exactly once per submitted future (result, exception, or
        # cancel) — the drain waiter's in-flight count lives here.
        with self._adm_lock:
            self._inflight_reqs -= 1

    def generate(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        eos_id: int | None = None,
        timeout: float | None = 120.0,
        **sampling,
    ) -> np.ndarray:
        """Blocking convenience wrapper around :meth:`submit`."""
        return self.submit(
            prompt_ids, max_new_tokens, eos_id, **sampling
        ).result(timeout)

    # -- scheduler -----------------------------------------------------------

    def _free_slot(self) -> int | None:
        free = [
            i for i, s in enumerate(self._slots)
            if s is None and i not in self._reserved
        ]
        if not free:
            return None
        if self._dp <= 1:
            return free[0]
        # dp > 1: the cache's row axis shards in contiguous blocks of
        # max_slots/dp, so slot index // rows IS the dp shard.  Admit
        # into the least-loaded shard (ties -> lowest index) — filling
        # slots 0..k-1 first would park every active row on shard 0 and
        # idle the rest of the dp axis.
        rows = self.max_slots // self._dp

        def shard_load(shard: int) -> int:
            return sum(
                1 for i in range(shard * rows, (shard + 1) * rows)
                if self._slots[i] is not None or i in self._reserved
            )

        return min(free, key=lambda i: (shard_load(i // rows), i))

    # -- SLO classes / preemption --------------------------------------------

    def _queued_work(self) -> bool:
        """True when any submission waits — transport queue OR class
        deques (a drained-but-unadmitted request must still break a
        fused burst / keep the fused-prefill gate closed)."""
        if not self._queue.empty():
            return True
        return self._class_queues is not None and any(
            self._class_queues[name] for name in SLO_CLASSES
        )

    def _drain_to_classes(self) -> None:
        """Route every immediately available submission from the
        transport queue into its class deque (classes armed only).  The
        None shutdown sentinel and _Wake are pushed back for the
        blocking path — they must be observed in the admission loop,
        not swallowed here."""
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return
            if item is None or isinstance(item, _Wake):
                self._queue.put(item)
                return
            self._class_queues[item.slo_class].append(item)

    def _dequeue(self, block: bool, timeout: float):
        """``self._queue.get`` with class priority.

        Unarmed classes make this EXACTLY the plain ``get`` call it
        replaces.  Armed: drain the transport queue into the per-class
        deques and pop the highest class first (FIFO within a class;
        evicted sequences re-enter at the front of theirs), falling
        back to a blocking get only when every deque is empty."""
        if self._class_queues is None:
            return self._queue.get(block=block, timeout=timeout)
        self._drain_to_classes()
        for name in SLO_CLASSES:
            dq = self._class_queues[name]
            if dq:
                return dq.popleft()
        item = self._queue.get(block=block, timeout=timeout)
        if item is None or isinstance(item, _Wake):
            return item
        # A burst may have landed while we blocked: route through the
        # deques so it is admitted in class order, not arrival order.
        self._class_queues[item.slo_class].append(item)
        self._drain_to_classes()
        for name in SLO_CLASSES:
            dq = self._class_queues[name]
            if dq:
                return dq.popleft()
        raise AssertionError("unreachable: item was just enqueued")

    def _maybe_preempt(self) -> None:
        """Tick-boundary preemption: when a strictly higher-class
        request waits with no free slot, evict the lowest-class active
        slot (least progress, then lowest index breaks ties) so the
        waiting work admits next iteration.  At most one eviction per
        scheduler iteration — preemption tracks demand, it never
        flushes the batch."""
        if self._free_slot() is not None:
            return
        self._drain_to_classes()
        waiting = next(
            (n for n in SLO_CLASSES if self._class_queues[n]), None
        )
        if waiting is None:
            return
        wprio = _CLASS_PRIORITY[waiting]
        victim = None
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            prio = _CLASS_PRIORITY.get(slot.slo_class, wprio)
            if prio >= wprio:
                continue
            key = (prio, slot.prompt_len + len(slot.generated), i)
            if victim is None or key < victim[0]:
                victim = (key, i)
        if victim is not None:
            self._evict_slot(victim[1])

    def _evict_slot(self, idx: int) -> None:
        """Evict one active slot at a tick boundary, losing no work.

        The committed K/V (positions 0..hist-1, where hist = prompt +
        generated - 1: the pending token has not been fed yet) is read
        off the device with the SAME slot-chunk program the prefix
        cache uses, so full chunks re-enter the radix cache — siblings
        reuse them, and restore re-seeds from whichever tier they
        landed in.  Host copies of every chunk ride the eviction record
        as the fallback (interleaved admissions may evict the cache
        entries before restore).  The PRNG carry and the pending token
        complete the record: restore resumes the sequence exactly.

        Leader-only, NO broadcast: ``_read_slot`` is a pure read (no
        donation), followers' device state is untouched, and the
        freed slot's stale rows are exactly the normal slot-reuse case
        every program already tolerates."""
        import jax
        import jax.numpy as jnp

        slot = self._slots[idx]
        assert slot is not None and slot.prompt is not None
        t0 = time.perf_counter()
        self._beat("preempt")
        C = self._prefill_chunk_size
        hist = slot.prompt_len + len(slot.generated) - 1
        full = np.empty((hist,), np.int32)
        full[: slot.prompt_len] = slot.prompt
        if len(slot.generated) > 1:
            full[slot.prompt_len:] = np.asarray(
                slot.generated[:-1], np.int32
            )
        n_chunks = -(-hist // C)
        chunks = []
        for ci in range(n_chunks):
            ck, cv = self._read_slot(
                self._cache_k, self._cache_v,
                jnp.int32(idx), jnp.int32(ci * C),
            )
            chunks.append((np.asarray(ck), np.asarray(cv)))
        # Whole-token chunks only: the tail chunk holds garbage past
        # hist and must never be shared under a token-byte key.
        for ci in range(hist // C):
            if not self._prefix_cache.has_chunk(full, ci):
                if not self._prefix_cache.insert_chunk(
                    full, ci, *chunks[ci]
                ):
                    break  # parent path evicted mid-insert: stop here
        key_data = np.asarray(jax.random.key_data(self._keys))[idx].copy()
        rec = _Preempted(
            future=slot.future,
            remaining=slot.remaining,
            eos_id=slot.eos_id,
            sampling=slot.sampling,
            on_token=slot.on_token,
            prompt=slot.prompt,
            generated=slot.generated,
            t_start=slot.t_start,
            request_id=slot.request_id,
            trace=slot.trace,
            slo_class=slot.slo_class,
            temperature=slot.temperature,
            top_k=slot.top_k,
            top_p=slot.top_p,
            key_data=key_data,
            chunks=chunks,
            hist=hist,
            history=slot.history,
            hist_len=slot.hist_len,
            draft=slot.draft,
        )
        self._slots[idx] = None
        # FRONT of its own class: the evicted sequence outranks newer
        # work of the same class on re-admission.
        self._class_queues[slot.slo_class].appendleft(rec)
        self.preemptions += 1
        if not self._in_warmup:
            self._record_tick(
                "preempt-evict", t0, time.perf_counter() - t0,
                active_slots=sum(s is not None for s in self._slots),
                tokens=hist,
            )
            self._trace_event(slot.trace, "preempt-evict", slot=idx)
            if self._on_preempt is not None:
                self._on_preempt("evict")

    def _admit_restore(self, rec: _Preempted) -> None:
        """Re-admit an evicted sequence into a free slot, resuming it
        token-for-token where the eviction cut it.

        K/V re-seeds through the radix cache where its chunks survived
        (counting prefix hits — an L2-spilled chunk promotes on the
        way), falling back to the record's host copies; then ONE
        restore dispatch re-installs the slot's lengths row, pending
        token, sampling params, and the PRNG carry AS CAPTURED.  No
        token is re-generated: ``preempt_recomputed_tokens`` stays 0
        by construction."""
        slot_idx = self._free_slot()
        assert slot_idx is not None
        t0 = time.perf_counter()
        self._beat("restore")
        C = self._prefill_chunk_size
        hist = rec.hist
        full = np.empty((hist,), np.int32)
        pl = int(rec.prompt.size)
        full[:pl] = rec.prompt
        if hist > pl:
            full[pl:] = np.asarray(
                rec.generated[: hist - pl], np.int32
            )
        matched, cached = self._prefix_cache.lookup(full)
        matched = min(matched, (hist // C) * C)
        seed_chunks = list(cached[: matched // C])
        seed_chunks.extend(rec.chunks[matched // C:])
        self._dispatch_seed_slot(seed_chunks, slot_idx, hist)
        if matched:
            self.prefix_hits += 1
            self.prefix_cached_tokens += matched
            if not self._in_warmup and self._on_prefix_hit is not None:
                self._on_prefix_hit(matched)
        self._dispatch_restore(
            slot_idx, hist, int(rec.generated[-1]), rec.key_data,
            rec.temperature, rec.top_k, rec.top_p,
        )
        self._slots[slot_idx] = _Slot(
            future=rec.future,
            remaining=rec.remaining,
            eos_id=rec.eos_id,
            sampling=rec.sampling,
            on_token=rec.on_token,
            prompt_len=pl,
            generated=rec.generated,
            t_start=rec.t_start,
            history=rec.history,
            hist_len=rec.hist_len,
            draft=rec.draft,
            request_id=rec.request_id,
            trace=rec.trace,
            slo_class=rec.slo_class,
            prompt=rec.prompt,
            temperature=rec.temperature,
            top_k=rec.top_k,
            top_p=rec.top_p,
        )
        self.preempt_restores += 1
        if not self._in_warmup:
            self._record_tick(
                "preempt-restore", t0, time.perf_counter() - t0,
                active_slots=sum(s is not None for s in self._slots),
                tokens=hist,
                cost=self._cost_seed(hist),
            )
            self._trace_event(rec.trace, "preempt-restore", slot=slot_idx)
            if self._on_preempt is not None:
                self._on_preempt("restore")

    def _dispatch_restore(
        self, slot, length, pending, key_data, temp, tk, tp
    ):
        """Broadcast (multihost) then run the restore finalize — a
        stateful GEN op: every host re-installs the same slot
        bookkeeping or later replayed ticks diverge."""
        if self._channel is None:
            self._device_restore(
                slot, length, pending, key_data, temp, tk, tp
            )
            return
        from .multihost import OP_GEN_RESTORE, encode_message

        payload = encode_message(
            OP_GEN_RESTORE,
            {
                "slot": int(slot),
                "length": int(length),
                "pending": int(pending),
                "key_data": np.asarray(key_data),
                "temp": float(temp),
                "tk": int(tk),
                "tp": float(tp),
            },
        )
        self._channel.run(
            payload,
            lambda: self._device_restore(
                slot, length, pending, key_data, temp, tk, tp
            ),
        )

    def _device_restore(
        self, slot, length, pending, key_data, temp, tk, tp
    ):
        import jax
        import jax.numpy as jnp

        slot_key = jax.random.wrap_key_data(jnp.asarray(key_data))
        (
            self._lengths,
            self._tokens,
            self._keys,
            self._temps,
            self._topk,
            self._topp,
        ) = self._insert_restore(
            self._lengths,
            self._tokens,
            self._keys,
            self._temps,
            self._topk,
            self._topp,
            jnp.int32(slot),
            jnp.int32(length),
            jnp.int32(pending),
            slot_key,
            jnp.float32(temp),
            jnp.int32(tk),
            jnp.float32(tp),
        )

    def replay_restore(
        self, slot, length, pending, key_data, temp, tk, tp
    ) -> None:
        """Follower side of :meth:`_dispatch_restore`."""
        self._device_restore(
            int(slot), int(length), int(pending), np.asarray(key_data),
            float(temp), int(tk), float(tp),
        )

    def _admit(self, req: _Request) -> None:
        import jax

        slot_idx = self._free_slot()
        assert slot_idx is not None
        L = int(req.prompt.size)
        bucket = prefill_bucket(L, self.capacity)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :L] = req.prompt

        # Engine-assigned keys are distinct per request and disjoint from
        # any user-specified jax.random.key(seed) stream (see _slot_key_for).
        slot_key = self._slot_key_for(req)
        t0 = time.perf_counter()
        self._beat("admit")
        first = self._dispatch_admit(
            ids, slot_idx, L, slot_key, req.temperature, req.top_k, req.top_p
        )
        if not self._in_warmup:
            self.prefill_forwards += 1
            if self._sync_ticks:
                first = int(first)  # sync: the wall must cover device time
            self._record_tick(
                "prefill", t0, time.perf_counter() - t0,
                active_slots=sum(s is not None for s in self._slots),
                batch_fill=1, tokens=1,
                cost=self._cost_prefill(1, bucket),
            )
        if req.trace is not None:
            req.trace.slot = slot_idx
            req.trace.prefill_chunks += 1  # fused: the whole prompt at once
        slot = _Slot(
            future=req.future,
            remaining=req.max_new_tokens,
            eos_id=req.eos_id,
            sampling=req.temperature > 0,
            on_token=req.on_token,
            prompt_len=L,
            t_start=t0,
            request_id=req.request_id,
            trace=req.trace,
            **self._spec_slot_state(req),
            **self._class_slot_state(req),
        )
        self._slots[slot_idx] = slot
        self._note_ttft(req)
        self._record_token(slot_idx, int(first))

    def _sync_seq_state(self) -> None:
        """Journaling only: wait for the in-flight scratch-cache op so
        the tick wall about to be recorded covers the device time, not
        just the async dispatch (see ``_sync_ticks``)."""
        if self._sync_ticks and self._seq_state is not None:
            import jax

            jax.block_until_ready(self._seq_state[1])

    def _record_tick(
        self, kind: str, t0: float, wall_s: float, *,
        active_slots: int = 0, batch_fill: int = 0, tokens: int = 0,
        spec_accepted: int = 0, cost=None, steps: int = 0,
        roles: dict | None = None,
    ) -> None:
        """Journal one engine device dispatch (tick-kind metric + flight
        recorder + the dispatches-by-op counter).  Callers skip warmup
        themselves; every sink is optional and the default costs one
        dict update + branch per tick.

        ``cost`` is the tick's analytic ``(flops, hbm_bytes)`` (device
        telemetry only, None otherwise): joined with the wall into MFU /
        bandwidth utilization — gauges plus extra recorder-tick fields.
        ``steps`` > 0 marks a fused multi-step tick (K scan iterations
        in the one dispatch this record covers); ``roles`` is a unified
        super-step tick's per-row role breakdown ({prefill, decode,
        verify} counts in the one dispatch)."""
        self.dispatches_total[kind] = self.dispatches_total.get(kind, 0) + 1
        if self._on_dispatch is not None:
            self._on_dispatch(kind)
        util = None
        if self._telemetry is not None and cost is not None:
            util = self._telemetry.tick_util(kind, wall_s, *cost)
        if self._on_tick is not None:
            self._on_tick(kind, wall_s)
        if self._recorder is not None:
            self._recorder.tick(
                kind, t0, wall_s,
                active_slots=active_slots,
                queue_depth=self._queue.qsize(),
                batch_fill=batch_fill,
                tokens=tokens,
                spec_accepted=spec_accepted,
                util=util,
                steps=steps,
                roles=roles,
            )

    def _cost_decode(self, window: int, s: int = 1, steps: int = 1):
        """Analytic (flops, bytes) of one decode/verify tick — the
        program computes EVERY cache row (inactive rows too; the MXU
        does not care), so the cost counts ``max_slots``.  ``steps`` > 1
        scales for a fused multi-step tick: K scan iterations each pay
        the full weight stream and (conservatively, at the pre-picked
        window) the cache read."""
        if self._telemetry is None or self._telemetry.cost is None:
            return None
        flops, nbytes = self._telemetry.cost.decode(self.max_slots, window, s)
        if steps > 1:
            flops, nbytes = flops * steps, nbytes * steps
        return flops, nbytes

    def _cost_superstep(self, window: int, s: int, steps: int):
        """Analytic (flops, bytes) of one unified super-step dispatch:
        the S-wide forward plus ``steps - 1`` single-token fused
        iterations, all at the pre-picked window."""
        if self._telemetry is None or self._telemetry.cost is None:
            return None
        return self._telemetry.cost.superstep(
            self.max_slots, window, s, steps
        )

    def _cost_prefill(self, rows: int, chunk: int, attended=None):
        if self._telemetry is None or self._telemetry.cost is None:
            return None
        return self._telemetry.cost.prefill(rows, chunk, attended)

    def _cost_seed(self, tokens: int):
        if self._telemetry is None or self._telemetry.cost is None:
            return None
        return self._telemetry.cost.seed(tokens)

    def _trace_event(self, trace, name: str, slot: int = -1) -> None:
        if (
            self._recorder is not None
            and trace is not None
            and not self._in_warmup
        ):
            self._recorder.event(trace.request_id, name, slot=slot)

    def _note_ttft(self, req: _Request) -> None:
        """First token produced for ``req``: record submit->token wall."""
        if self._in_warmup or req.t_submit <= 0.0:
            return
        if req.trace is not None:
            req.trace.t_first = time.perf_counter()
            self._trace_event(req.trace, "first_token", slot=req.trace.slot)
        if self._on_ttft is not None:
            self._on_ttft(time.perf_counter() - req.t_submit)

    def _note_admission_wait(self, req: _Request) -> None:
        """``req`` left the submission queue and its admission began."""
        if self._in_warmup or req.t_submit <= 0.0:
            return
        if req.trace is not None:
            req.trace.t_admit = time.perf_counter()
            self._trace_event(req.trace, "admission")
        if self._on_admission_wait is not None:
            self._on_admission_wait(time.perf_counter() - req.t_submit)

    def _spec_slot_state(self, req: _Request) -> dict:
        """Per-slot speculative state (empty when speculation is off)."""
        if self._spec is None:
            return {}
        from .speculative import DraftState

        # validate() caps prompt + max_new_tokens at capacity, so the
        # buffer never overflows; generated tokens append in
        # _record_token.
        history = np.empty((self.capacity,), np.int64)
        L = int(req.prompt.size)
        history[:L] = req.prompt
        return {
            "history": history,
            "hist_len": L,
            "draft": DraftState(
                self._spec.draft_tokens, adaptive=self._spec.adaptive
            ),
        }

    def _class_slot_state(self, req: _Request) -> dict:
        """Per-slot SLO-class / preemption state (empty when classes are
        unarmed — default _Slot fields keep the old layout exactly)."""
        if not self._classes:
            return {}
        out: dict = {"slo_class": req.slo_class}
        if self._preemption:
            # Eviction needs the prompt (to key the radix write-back and
            # rebuild the committed token sequence) and the sampling
            # params (to refill the slot's device rows on restore —
            # another admission may reuse the row meanwhile).
            out.update(
                prompt=req.prompt,
                temperature=req.temperature,
                top_k=req.top_k,
                top_p=req.top_p,
            )
        return out

    def _admit_now(self, req: _Request) -> None:
        """Synchronous admission (warmup): runs the whole chunked pipeline
        at once when chunking is enabled, else the fused path."""
        if self._sp_eligible(req):
            # Warmup prompts are cold by construction — same routing the
            # live admission phases apply.
            self._admit_sp(req)
            return
        if self._prefill_chunk_size is None:
            self._admit(req)
            return
        prog = self._make_progress(req)
        if self._packed:
            slot = self._free_slot()
            assert slot is not None
            prog.slot = slot
            self._reserved.add(slot)
        self._pending.append(prog)
        while prog in self._pending:
            if self._packed:
                # Unified engine: packed chunks ride the super-step
                # dispatch — there is no separate packed program to run.
                if self._unified:
                    self._super_tick()
                else:
                    self._packed_tick()
            else:
                self._chunk_tick()

    def _dispatch_admit(self, ids, slot_idx, L, slot_key, temp, tk, tp):
        """Broadcast (multihost) then run the prefill+insert device call."""
        import jax

        if self._channel is None:
            return self._device_admit(ids, slot_idx, L, slot_key, temp, tk, tp)
        from .multihost import OP_GEN_ADMIT, encode_message

        payload = encode_message(
            OP_GEN_ADMIT,
            {
                "ids": ids,
                "slot": int(slot_idx),
                "length": int(L),
                # typed keys don't pickle portably; ship the raw key data
                "key_data": np.asarray(jax.random.key_data(slot_key)),
                "temp": float(temp),
                "tk": int(tk),
                "tp": float(tp),
            },
        )
        return self._channel.run(
            payload,
            lambda: self._device_admit(ids, slot_idx, L, slot_key, temp, tk, tp),
        )

    def _device_admit(self, ids, slot_idx, L, slot_key, temp, tk, tp):
        import jax.numpy as jnp

        (
            self._cache_k,
            self._cache_v,
            self._lengths,
            self._tokens,
            self._keys,
            self._temps,
            self._topk,
            self._topp,
            first,
        ) = self._prefill_insert(
            self._params,
            jnp.asarray(ids),
            self._cache_k,
            self._cache_v,
            self._lengths,
            self._tokens,
            jnp.int32(slot_idx),
            jnp.int32(L),
            self._keys,
            self._temps,
            self._topk,
            self._topp,
            slot_key,
            jnp.float32(temp),
            jnp.int32(tk),
            jnp.float32(tp),
        )
        return first

    def replay_admit(self, ids, slot, length, key_data, temp, tk, tp) -> None:
        """Follower side of :meth:`_dispatch_admit` (multihost lockstep)."""
        import jax

        slot_key = jax.random.wrap_key_data(np.asarray(key_data))
        self._device_admit(ids, slot, length, slot_key, temp, tk, tp)

    def replay_step(self, active, window, sampling) -> None:
        """Follower side of a decode tick (multihost lockstep)."""
        self._device_step(np.asarray(active), int(window), bool(sampling))

    # -- chunked prefill (one compiled chunk shape; decode interleaves) ------

    def _split_chunks(self, prompt: np.ndarray) -> list:
        C = self._prefill_chunk_size
        L = int(prompt.size)
        n = -(-L // C)
        padded = np.zeros((n * C,), np.int32)
        padded[:L] = prompt
        return [padded[i * C : (i + 1) * C][None, :] for i in range(n)]

    def _make_progress(self, req: _Request) -> _PrefillProgress:
        """Chunked-admission plan: longest radix-cached prefix (to seed)
        plus the uncached suffix (to prefill).  Warmup prompts never
        consult or populate the cache."""
        cached_tokens, cached_kv = 0, []
        if self._prefix_cache is not None and not self._in_warmup:
            cached_tokens, cached_kv = self._prefix_cache.lookup(req.prompt)
        if req.trace is not None:
            req.trace.cached_tokens = cached_tokens
        return _PrefillProgress(
            req=req,
            chunks=self._split_chunks(req.prompt[cached_tokens:]),
            cached_tokens=cached_tokens,
            cached_kv=cached_kv,
        )

    def _note_prefix_evict(self, nbytes: int) -> None:
        self.prefix_evictions += 1
        if self._on_prefix_evict is not None and not self._in_warmup:
            self._on_prefix_evict()

    def _note_prefix_l2(self, kind: str) -> None:
        """Second-tier prefix-cache event (``hit``/``spill``/``evict``)
        — mirrored to the tpumlops_prefix_cache_l2_* counters."""
        if self._on_prefix_l2 is not None and not self._in_warmup:
            self._on_prefix_l2(kind)

    # -- KV handoff (disaggregated prefill/decode fleets) --------------------

    def run_control(self, fn: Callable[[], object]) -> Future:
        """Run ``fn`` on the scheduler thread at the next admission phase
        (thread-safe); returns a Future with its result.  Control ops
        never occupy a cache slot and run even when every slot is busy —
        they exist for state that is single-threaded by design (the
        radix prefix cache, slot truth)."""
        fut: Future = Future()
        if self._stop.is_set():
            # Shut down (or shutting down): the scheduler will never pop
            # this op — fail typed NOW instead of letting the caller
            # block out its timeout.
            _safe_fail(
                fut,
                EngineShutdown("engine shut down before the control op ran"),
            )
            return fut
        self._control_ops.put((fn, fut))
        self._queue.put(_WAKE)  # unblock an idle scheduler promptly
        if self._stop.is_set():
            # Raced stop(): its queue drain may already have missed this
            # op, so drain ourselves.  Both drains use get_nowait and
            # _safe_fail is idempotent, so double-draining is harmless.
            while True:
                try:
                    _fn2, fut2 = self._control_ops.get_nowait()
                except queue.Empty:
                    break
                _safe_fail(
                    fut2,
                    EngineShutdown(
                        "engine shut down before the control op ran"
                    ),
                )
        return fut

    def _drain_control_ops(self) -> None:
        while True:
            try:
                fn, fut = self._control_ops.get_nowait()
            except queue.Empty:
                return
            try:
                _safe_resolve(fut, fn())
            except Exception as exc:
                _safe_fail(fut, exc)

    def _require_prefix_cache(self):
        if self._prefix_cache is None:
            raise RuntimeError(
                "KV handoff requires the radix prefix cache: enable "
                "spec.tpu.prefixCache (--prefix-cache 1)"
            )
        return self._prefix_cache

    def exportable_prefix_tokens(self, prompt: np.ndarray) -> int:
        """Whole-chunk token count of ``prompt`` a handoff can cover
        (the radix lookup's strict cap below the prompt length)."""
        cache = self._require_prefix_cache()
        C = cache.chunk_tokens
        return ((int(np.asarray(prompt).size) - 1) // C) * C

    def export_prefix_kv(
        self, prompt: np.ndarray, timeout: float | None = 60.0
    ) -> tuple[int, list]:
        """Committed prefix K/V of ``prompt`` as host chunk pairs —
        ``(matched_tokens, [(k, v), ...])`` in radix storage layout.
        Thread-safe: the lookup (an LRU-touching radix walk) runs as a
        control op on the scheduler thread; the returned host arrays are
        immutable snapshots safe to serialize from any thread."""
        cache = self._require_prefix_cache()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        return self.run_control(lambda: cache.lookup(prompt)).result(timeout)

    def import_prefix_kv(
        self,
        prompt: np.ndarray,
        chunks: list,
        timeout: float | None = 60.0,
    ) -> int:
        """Install handed-off prefix chunks into the radix cache; returns
        the tokens now covered.  Runs on the scheduler thread and
        journals one ``kv-import`` tick so a relayed request is
        reconstructable from ``/debug/trace`` — the import is the tick
        between the router's handoff and the request's seed."""
        cache = self._require_prefix_cache()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        C = cache.chunk_tokens
        if len(chunks) * C > prompt.size:
            raise ValueError(
                f"{len(chunks)} chunks of {C} tokens exceed the "
                f"{prompt.size}-token prompt"
            )

        def op() -> int:
            t0 = time.perf_counter()
            installed = 0
            for idx, (k, v) in enumerate(chunks):
                if not cache.insert_chunk(prompt, idx, k, v):
                    break  # parent path evicted mid-walk: stop cleanly
                installed += 1
            self._record_tick(
                "kv-import", t0, time.perf_counter() - t0,
                active_slots=sum(s is not None for s in self._slots),
                batch_fill=installed, tokens=installed * C,
            )
            return installed * C

        return int(self.run_control(op).result(timeout))

    def _maybe_cache_chunk(self, prog: _PrefillProgress) -> None:
        """Write the chunk just prefilled (index ``prog.next_idx``) back
        into the radix cache — leader-side only (the scheduler thread),
        full real-token chunks only (a padded tail carries pad-garbage
        K/V that must never be reused).

        The ``np.asarray`` is a device sync: the scheduler waits for the
        chunk's forward pass before dispatching the next decode tick, so
        it is paid at most ONCE per unique chunk — ``has_chunk`` skips
        both the transfer and the sync for chunks already cached (the
        steady state for shared-prefix traffic)."""
        if self._prefix_cache is None or self._in_warmup:
            return
        import jax.numpy as jnp

        C = self._prefill_chunk_size
        L = int(prog.req.prompt.size)
        start = prog.cached_tokens + prog.next_idx * C
        if start + C > L:
            return
        chunk_idx = start // C
        if self._prefix_cache.has_chunk(prog.req.prompt, chunk_idx):
            return
        _, sk, sv, _slen = self._seq_state
        ck, cv = self._read_chunk(sk, sv, jnp.int32(start))
        self._prefix_cache.insert_chunk(
            prog.req.prompt, chunk_idx, np.asarray(ck), np.asarray(cv)
        )

    def _dispatch_chunk(self, ids: np.ndarray, fresh: bool) -> None:
        if self._channel is None:
            self._device_chunk(ids, fresh)
            return
        from .multihost import OP_GEN_CHUNK, encode_message

        payload = encode_message(OP_GEN_CHUNK, {"ids": ids, "fresh": bool(fresh)})
        self._channel.run(payload, lambda: self._device_chunk(ids, fresh))

    def _device_chunk(self, ids: np.ndarray, fresh: bool) -> None:
        import jax.numpy as jnp

        from ..models import llama

        if fresh:
            seq = llama.KVCache.create(self._cfg, 1, self._dtype)
            sk0, sv0 = self._put_seq(seq.k), self._put_seq(seq.v)
            self._seq_state = (None, sk0, sv0, seq.length)
        _, sk, sv, slen = self._seq_state
        logits0, sk, sv, slen = self._prefill_one_chunk(
            self._params, jnp.asarray(ids), sk, sv, slen
        )
        self._seq_state = (logits0, sk, sv, slen)

    def replay_chunk(self, ids, fresh) -> None:
        self._device_chunk(np.asarray(ids), bool(fresh))

    def _dispatch_seed(self, cached_kv: list, length: int) -> None:
        """Broadcast (multihost) then seed the sequence cache from the
        radix-cached prefix chunks.  The payload carries the host K/V so
        followers stay in lockstep without their own cache.

        Known multihost cost: the payload scales with the cached prefix
        (MBs at large geometries) and rides the serialized unit channel,
        where chunk ops are ~KBs.  Follower-local cache replicas (replay
        the write-back index instead of the bytes; eviction is already
        deterministic) would shrink the seed op to a scalar — future
        work, single-host serving is unaffected."""
        if self._channel is None:
            self._device_seed(cached_kv, length)
            return
        from .multihost import OP_GEN_SEED, encode_message

        payload = encode_message(
            OP_GEN_SEED,
            {
                "ks": [np.asarray(k) for k, _ in cached_kv],
                "vs": [np.asarray(v) for _, v in cached_kv],
                "length": int(length),
            },
        )
        self._channel.run(payload, lambda: self._device_seed(cached_kv, length))

    def _device_seed(self, cached_kv: list, length: int) -> None:
        import jax.numpy as jnp

        from ..models import llama

        seq = llama.KVCache.create(self._cfg, 1, self._dtype)
        sk, sv = self._put_seq(seq.k), self._put_seq(seq.v)
        C = self._prefill_chunk_size
        off = 0
        for ck, cv in cached_kv:
            sk, sv = self._seed_chunk(
                sk, sv, jnp.asarray(ck), jnp.asarray(cv), jnp.int32(off)
            )
            off += C
        # No last_logits yet: at least one real suffix chunk ALWAYS follows
        # (lookup caps the match strictly below the prompt length), and its
        # prefill provides the logits the insert samples from.
        self._seq_state = (None, sk, sv, jnp.asarray(int(length), jnp.int32))

    def replay_seed(self, ks, vs, length) -> None:
        """Follower side of :meth:`_dispatch_seed` (multihost lockstep)."""
        self._device_seed(list(zip(ks, vs)), int(length))

    def _dispatch_insert(self, slot_idx, L, slot_key, temp, tk, tp, last_idx):
        import jax

        if self._channel is None:
            return self._device_insert(
                slot_idx, L, slot_key, temp, tk, tp, last_idx
            )
        from .multihost import OP_GEN_INSERT, encode_message

        payload = encode_message(
            OP_GEN_INSERT,
            {
                "slot": int(slot_idx),
                "length": int(L),
                "key_data": np.asarray(jax.random.key_data(slot_key)),
                "temp": float(temp),
                "tk": int(tk),
                "tp": float(tp),
                "last_idx": int(last_idx),
            },
        )
        return self._channel.run(
            payload,
            lambda: self._device_insert(
                slot_idx, L, slot_key, temp, tk, tp, last_idx
            ),
        )

    def _device_insert(self, slot_idx, L, slot_key, temp, tk, tp, last_idx):
        import jax.numpy as jnp

        last_logits, sk, sv, _slen = self._seq_state
        self._seq_state = None
        (
            self._cache_k,
            self._cache_v,
            self._lengths,
            self._tokens,
            self._keys,
            self._temps,
            self._topk,
            self._topp,
            first,
        ) = self._insert_only(
            last_logits,
            self._cache_k,
            self._cache_v,
            self._lengths,
            self._tokens,
            jnp.int32(slot_idx),
            jnp.int32(L),
            self._keys,
            self._temps,
            self._topk,
            self._topp,
            slot_key,
            jnp.float32(temp),
            jnp.int32(tk),
            jnp.float32(tp),
            sk,
            sv,
            jnp.int32(last_idx),
        )
        return first

    def replay_insert(self, slot, length, key_data, temp, tk, tp, last_idx):
        import jax

        slot_key = jax.random.wrap_key_data(np.asarray(key_data))
        self._device_insert(slot, length, slot_key, temp, tk, tp, last_idx)

    # -- sequence-parallel prefill (meshShape sp > 1) ------------------------

    def _sp_eligible(self, req: _Request) -> bool:
        """Long cold prompts ride the ring: one sequence-parallel pass
        instead of L/C serial chunk forwards.  Short prompts and warm
        prefixes keep their existing paths — a radix-cached prefix
        already skips the prefill the ring would parallelize."""
        return (
            self._sp > 1
            and int(req.prompt.size) >= self._sp_threshold
        )

    def _admit_sp(self, req: _Request) -> None:
        """Admit ``req`` through the sequence-parallel prefill: one ring
        pass over the bucket-padded prompt, prefix-cache write-back of
        its full chunks, then the standard scratch insert."""
        slot_idx = self._free_slot()
        assert slot_idx is not None
        L = int(req.prompt.size)
        bucket = prefill_bucket(L, self.capacity)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :L] = req.prompt
        self._beat("prefill")
        ts = time.perf_counter()
        self._dispatch_sp_prefill(ids, L)
        if not self._in_warmup:
            self.prefill_forwards += 1
            self._sync_seq_state()
            self._record_tick(
                "sp-prefill", ts, time.perf_counter() - ts,
                active_slots=sum(s is not None for s in self._slots),
                batch_fill=1,
                cost=self._cost_sp_prefill(bucket),
            )
            self._trace_event(req.trace, "sp_prefill")
        self._cache_sp_chunks(req)
        slot_key = self._slot_key_for(req)
        t0 = time.perf_counter()
        # The ring pass already selected the final real row: last_idx 0
        # indexes the [1, V] logits it returned.
        first = self._dispatch_insert(
            slot_idx, L, slot_key, req.temperature, req.top_k, req.top_p,
            last_idx=0,
        )
        if not self._in_warmup:
            if self._sync_ticks:
                first = int(first)
            self._record_tick(
                "prefill", t0, time.perf_counter() - t0,
                active_slots=sum(s is not None for s in self._slots),
                batch_fill=1, tokens=1,
            )
        if req.trace is not None:
            req.trace.slot = slot_idx
        self._slots[slot_idx] = _Slot(
            future=req.future,
            remaining=req.max_new_tokens,
            eos_id=req.eos_id,
            sampling=req.temperature > 0,
            on_token=req.on_token,
            prompt_len=L,
            t_start=t0,
            request_id=req.request_id,
            trace=req.trace,
            **self._spec_slot_state(req),
            **self._class_slot_state(req),
        )
        self._note_ttft(req)
        self._record_token(slot_idx, int(first))

    def _cache_sp_chunks(self, req: _Request) -> None:
        """Radix write-back after a ring prefill: every FULL chunk of the
        prompt (pad-garbage tails never enter the cache), read from the
        freshly filled scratch — future shared-prefix requests seed from
        these exactly as if the chunked path had prefilled them."""
        if self._prefix_cache is None or self._in_warmup:
            return
        import jax.numpy as jnp

        C = self._prefill_chunk_size
        if C is None:
            return
        L = int(req.prompt.size)
        _, sk, sv, _slen = self._seq_state
        for chunk_idx in range(L // C):
            if self._prefix_cache.has_chunk(req.prompt, chunk_idx):
                continue
            ck, cv = self._read_chunk(sk, sv, jnp.int32(chunk_idx * C))
            self._prefix_cache.insert_chunk(
                req.prompt, chunk_idx, np.asarray(ck), np.asarray(cv)
            )

    def _dispatch_sp_prefill(self, ids: np.ndarray, length: int) -> None:
        if self._channel is None:
            self._device_sp_prefill(ids, length)
            return
        from .multihost import OP_GEN_SP_PREFILL, encode_message

        payload = encode_message(
            OP_GEN_SP_PREFILL, {"ids": ids, "length": int(length)}
        )
        self._channel.run(
            payload, lambda: self._device_sp_prefill(ids, length)
        )

    def _device_sp_prefill(self, ids: np.ndarray, length: int) -> None:
        import jax.numpy as jnp

        from ..models import llama

        seq = llama.KVCache.create(self._cfg, 1, self._dtype)
        sk0, sv0 = self._put_seq(seq.k), self._put_seq(seq.v)
        last_row, sk, sv = self._prefill_sp(
            self._params, jnp.asarray(ids), sk0, sv0,
            jnp.int32(int(length) - 1),
        )
        self._seq_state = (
            last_row, sk, sv, jnp.asarray(int(length), jnp.int32)
        )

    def replay_sp_prefill(self, ids, length) -> None:
        """Follower side of :meth:`_dispatch_sp_prefill` (lockstep)."""
        self._device_sp_prefill(np.asarray(ids), int(length))

    def _cost_sp_prefill(self, tokens: int):
        if self._telemetry is None or self._telemetry.cost is None:
            return None
        return self._telemetry.cost.sp_prefill(tokens)

    # -- packed multi-admission prefill (prefillBatch > 1) -------------------

    def _pack_buckets(self) -> list[int]:
        """Power-of-two B_p buckets up to ``prefill_batch`` (which caps
        the set even when it is not itself a power of two), ascending —
        one compiled packed-call variant each, all swept at warmup."""
        out, b = [], 1
        while b < self._prefill_batch:
            out.append(b)
            b *= 2
        out.append(self._prefill_batch)
        return out

    def _pack_bucket(self, n: int) -> int:
        for b in self._pack_buckets():
            if b >= n:
                return b
        return self._prefill_batch

    def _parked_batch(self, bucket: int) -> tuple:
        """A fully PARKED packed-call argument set — (ids, slots,
        offsets, last_pos, final_lens, key_data, temps, tks, tps) where
        every row writes nothing (offset == capacity drops), finalizes
        nothing (last_pos == -1), and carries neutral sampling params.
        The warmup bucket sweep dispatches it as-is; :meth:`_packed_tick`
        overwrites rows ``[0, n)`` with the real admissions — ONE
        construction site, so the warmed shapes can never drift from the
        live call's.  Pad slots are pairwise distinct (and their parked
        positions start at capacity, so equality with a REAL row's
        reserved slot cannot collide index tuples — see
        llama._commit_chunk_at's unique-indices contract)."""
        C = self._prefill_chunk_size
        return (
            np.zeros((bucket, C), np.int32),
            np.arange(bucket, dtype=np.int32),
            np.full((bucket,), self.capacity, np.int32),
            np.full((bucket,), -1, np.int32),
            np.zeros((bucket,), np.int32),
            np.broadcast_to(
                self._zero_kd, (bucket,) + self._zero_kd.shape
            ).copy(),
            np.zeros((bucket,), np.float32),
            np.zeros((bucket,), np.int32),
            np.ones((bucket,), np.float32),
        )

    def _packed_tick(self) -> None:
        """Advance up to ``prefill_batch`` in-flight admissions by one
        chunk each — ONE batched device call (plus one seed op per
        admission entering with a radix-cached prefix).  The token-budget
        knob caps the chunks packed per tick, Sarathi-style: decode ticks
        interleave every tick regardless, so bounding prefill work per
        tick bounds the decode-cadence jitter long prompts can inject."""
        self._beat("packed-prefill")
        C = self._prefill_chunk_size
        max_chunks = self._prefill_batch
        if self._prefill_token_budget:
            max_chunks = min(
                max_chunks, max(1, self._prefill_token_budget // C)
            )
        take = self._pending[:max_chunks]
        chunk_progs = []
        for prog in take:
            if prog.cached_tokens and not prog.seeded:
                # Cached-prefix hit: seed the radix K/V straight into the
                # reserved cache row; those tokens never re-prefill.
                ts = time.perf_counter()
                self._dispatch_seed_slot(
                    prog.cached_kv, prog.slot, prog.cached_tokens
                )
                prog.seeded = True
                prog.cached_kv = []
                self.prefix_hits += 1
                self.prefix_cached_tokens += prog.cached_tokens
                if not self._in_warmup:
                    if self._on_prefix_hit is not None:
                        self._on_prefix_hit(prog.cached_tokens)
                    if self._sync_ticks:
                        import jax

                        jax.block_until_ready(self._cache_k)
                    self._record_tick(
                        "seed", ts, time.perf_counter() - ts,
                        active_slots=sum(s is not None for s in self._slots),
                        batch_fill=1,
                        cost=self._cost_seed(prog.cached_tokens),
                    )
                    self._trace_event(prog.req.trace, "seed", slot=prog.slot)
            else:
                chunk_progs.append(prog)
        if not chunk_progs:
            return
        import jax

        n = len(chunk_progs)
        bucket = self._pack_bucket(n)
        (
            ids, slots, offsets, last_pos, final_lens,
            key_data, r_temps, r_tks, r_tps,
        ) = self._parked_batch(bucket)
        for i, prog in enumerate(chunk_progs):
            req = prog.req
            ids[i] = prog.chunks[prog.next_idx][0]
            slots[i] = prog.slot
            offsets[i] = prog.cached_tokens + prog.next_idx * C
            if prog.next_idx == len(prog.chunks) - 1:
                L = int(req.prompt.size)
                last_pos[i] = (L - 1) - int(offsets[i])
                final_lens[i] = L
                r_temps[i] = req.temperature
                r_tks[i] = req.top_k
                r_tps[i] = req.top_p
                key_data[i] = np.asarray(
                    jax.random.key_data(self._slot_key_for(req))
                )
        t0 = time.perf_counter()
        firsts = self._dispatch_chunks(
            ids, slots, offsets, last_pos, final_lens,
            key_data, r_temps, r_tks, r_tps,
        )
        if not self._in_warmup:
            self.prefill_chunks_dispatched += n
            self.prefill_forwards += 1
            if self._on_prefill_batch is not None:
                self._on_prefill_batch(n)
            finals = sum(
                1 for prog in chunk_progs
                if prog.next_idx == len(prog.chunks) - 1
            )
            # The compiled program computes every row of the B_p bucket
            # (parked pad rows included); the mean attended span is over
            # the REAL chunks' offsets.
            attended = (
                sum(float(offsets[i]) for i in range(n)) / n + C / 2
            )
            self._record_tick(
                "packed-prefill", t0, time.perf_counter() - t0,
                active_slots=sum(s is not None for s in self._slots),
                batch_fill=n, tokens=finals,
                cost=self._cost_prefill(bucket, C, attended=attended),
            )
        for i, prog in enumerate(chunk_progs):
            if prog.req.trace is not None:
                prog.req.trace.slot = prog.slot
                prog.req.trace.prefill_chunks += 1
                self._trace_event(
                    prog.req.trace, "prefill_chunk", slot=prog.slot
                )
            self._maybe_cache_chunk_slot(prog)
            prog.next_idx += 1
            if prog.next_idx < len(prog.chunks):
                continue
            # Final chunk landed: the packed call already installed the
            # slot's device state and sampled its first token.
            self._pending.remove(prog)
            self._reserved.discard(prog.slot)
            req = prog.req
            self._slots[prog.slot] = _Slot(
                future=req.future,
                remaining=req.max_new_tokens,
                eos_id=req.eos_id,
                sampling=req.temperature > 0,
                on_token=req.on_token,
                prompt_len=int(req.prompt.size),
                t_start=t0,
                request_id=req.request_id,
                trace=req.trace,
                **self._spec_slot_state(req),
                **self._class_slot_state(req),
            )
            self._note_ttft(req)
            self._record_token(prog.slot, int(firsts[i]))

    def _maybe_cache_chunk_slot(self, prog: _PrefillProgress) -> None:
        """Packed-mode prefix write-back: like :meth:`_maybe_cache_chunk`
        but the freshly prefilled chunk is read from the reserved cache
        row, not the batch-1 scratch."""
        if self._prefix_cache is None or self._in_warmup:
            return
        import jax.numpy as jnp

        C = self._prefill_chunk_size
        L = int(prog.req.prompt.size)
        start = prog.cached_tokens + prog.next_idx * C
        if start + C > L:
            return
        chunk_idx = start // C
        if self._prefix_cache.has_chunk(prog.req.prompt, chunk_idx):
            return
        ck, cv = self._read_slot(
            self._cache_k, self._cache_v,
            jnp.int32(prog.slot), jnp.int32(start),
        )
        self._prefix_cache.insert_chunk(
            prog.req.prompt, chunk_idx, np.asarray(ck), np.asarray(cv)
        )

    def _dispatch_chunks(
        self, ids, slots, offsets, last_pos, final_lens,
        key_data, r_temps, r_tks, r_tps,
    ):
        """Broadcast (multihost) then run the packed prefill call."""
        args = (
            ids, slots, offsets, last_pos, final_lens,
            key_data, r_temps, r_tks, r_tps,
        )
        if self._channel is None:
            return self._device_chunks(*args)
        from .multihost import OP_GEN_CHUNKS, encode_message

        payload = encode_message(
            OP_GEN_CHUNKS,
            {
                "ids": ids,
                "slots": slots,
                "offsets": offsets,
                "last_pos": last_pos,
                "final_lens": final_lens,
                "key_data": key_data,
                "temps": r_temps,
                "tks": r_tks,
                "tps": r_tps,
            },
        )
        return self._channel.run(payload, lambda: self._device_chunks(*args))

    def _device_chunks(
        self, ids, slots, offsets, last_pos, final_lens,
        key_data, r_temps, r_tks, r_tps,
    ):
        import jax
        import jax.numpy as jnp

        slot_keys = jax.random.wrap_key_data(jnp.asarray(key_data))
        (
            self._cache_k,
            self._cache_v,
            self._lengths,
            self._tokens,
            self._keys,
            self._temps,
            self._topk,
            self._topp,
            firsts,
        ) = self._prefill_chunks(
            self._params,
            jnp.asarray(ids),
            self._cache_k,
            self._cache_v,
            self._lengths,
            self._tokens,
            self._keys,
            self._temps,
            self._topk,
            self._topp,
            jnp.asarray(slots),
            jnp.asarray(offsets),
            jnp.asarray(last_pos),
            jnp.asarray(final_lens),
            slot_keys,
            jnp.asarray(r_temps),
            jnp.asarray(r_tks),
            jnp.asarray(r_tps),
        )
        return np.asarray(firsts)

    def replay_chunks(
        self, ids, slots, offsets, last_pos, final_lens,
        key_data, temps, tks, tps,
    ) -> None:
        """Follower side of :meth:`_dispatch_chunks` (multihost lockstep)."""
        self._device_chunks(
            np.asarray(ids), np.asarray(slots), np.asarray(offsets),
            np.asarray(last_pos), np.asarray(final_lens),
            np.asarray(key_data), np.asarray(temps), np.asarray(tks),
            np.asarray(tps),
        )

    def _dispatch_seed_slot(self, cached_kv: list, slot: int, length: int):
        """Broadcast (multihost) then seed a reserved cache row from the
        radix-cached prefix chunks (packed-mode sibling of
        :meth:`_dispatch_seed`; same payload-size caveat)."""
        if self._channel is None:
            self._device_seed_slot(cached_kv, slot, length)
            return
        from .multihost import OP_GEN_SEED_SLOT, encode_message

        payload = encode_message(
            OP_GEN_SEED_SLOT,
            {
                "ks": [np.asarray(k) for k, _ in cached_kv],
                "vs": [np.asarray(v) for _, v in cached_kv],
                "slot": int(slot),
                "length": int(length),
            },
        )
        self._channel.run(
            payload, lambda: self._device_seed_slot(cached_kv, slot, length)
        )

    def _device_seed_slot(self, cached_kv: list, slot: int, length: int):
        import jax.numpy as jnp

        C = self._prefill_chunk_size
        off = 0
        for ck, cv in cached_kv:
            self._cache_k, self._cache_v = self._seed_slot(
                self._cache_k, self._cache_v,
                jnp.asarray(ck), jnp.asarray(cv),
                jnp.int32(slot), jnp.int32(off),
            )
            off += C

    def replay_seed_slot(self, ks, vs, slot, length) -> None:
        """Follower side of :meth:`_dispatch_seed_slot`."""
        self._device_seed_slot(list(zip(ks, vs)), int(slot), int(length))

    def _slot_key_for(self, req: _Request):
        import jax

        if req.seed is None:
            self._seed_counter += 1
            return jax.random.fold_in(self._boot_key, self._seed_counter)
        return jax.random.key(int(req.seed))

    def _chunk_tick(self) -> None:
        """Advance the in-flight chunked admission by ONE device op (a
        prefix-cache seed or one prefill chunk); on the final chunk,
        install the sequence into its slot.  Single-admission mode only
        (the batch-1 scratch cache serializes admissions); packed mode
        advances through :meth:`_packed_tick`."""
        assert self._pending
        self._beat("prefill")
        prog = self._pending[0]
        if prog.cached_tokens and not prog.seeded:
            # Cached-prefix hit: one seed op copies the radix-cached K/V
            # into a fresh sequence cache — those tokens never re-prefill.
            ts = time.perf_counter()
            self._dispatch_seed(prog.cached_kv, prog.cached_tokens)
            prog.seeded = True
            prog.cached_kv = []  # host copies handed off; free the refs
            self.prefix_hits += 1
            self.prefix_cached_tokens += prog.cached_tokens
            if not self._in_warmup:
                if self._on_prefix_hit is not None:
                    self._on_prefix_hit(prog.cached_tokens)
                self._sync_seq_state()
                self._record_tick(
                    "seed", ts, time.perf_counter() - ts,
                    active_slots=sum(s is not None for s in self._slots),
                    batch_fill=1,
                    cost=self._cost_seed(prog.cached_tokens),
                )
                self._trace_event(prog.req.trace, "seed")
            return  # suffix chunks start next tick (decode cadence kept)
        ids = prog.chunks[prog.next_idx]
        offset = prog.cached_tokens + prog.next_idx * self._prefill_chunk_size
        ts = time.perf_counter()
        self._dispatch_chunk(ids, fresh=prog.next_idx == 0 and not prog.seeded)
        if not self._in_warmup:
            self.prefill_chunks_dispatched += 1
            self.prefill_forwards += 1
            self._sync_seq_state()
            C = self._prefill_chunk_size
            self._record_tick(
                "prefill", ts, time.perf_counter() - ts,
                active_slots=sum(s is not None for s in self._slots),
                batch_fill=1,
                cost=self._cost_prefill(1, C, attended=offset + C / 2),
            )
        if prog.req.trace is not None:
            prog.req.trace.prefill_chunks += 1
            self._trace_event(prog.req.trace, "prefill_chunk")
        self._maybe_cache_chunk(prog)
        prog.next_idx += 1
        if prog.next_idx < len(prog.chunks):
            return
        req = prog.req
        self._pending.pop(0)
        slot_idx = self._free_slot()
        assert slot_idx is not None  # reserved by the admission policy
        L = int(req.prompt.size)
        C = self._prefill_chunk_size
        slot_key = self._slot_key_for(req)
        t0 = time.perf_counter()
        first = self._dispatch_insert(
            slot_idx, L, slot_key, req.temperature, req.top_k, req.top_p,
            last_idx=(L - 1) - prog.cached_tokens - C * (len(prog.chunks) - 1),
        )
        if not self._in_warmup:
            if self._sync_ticks:
                first = int(first)  # sync: the wall must cover device time
            self._record_tick(
                "prefill", t0, time.perf_counter() - t0,
                active_slots=sum(s is not None for s in self._slots),
                batch_fill=1, tokens=1,
            )
        if req.trace is not None:
            req.trace.slot = slot_idx
        self._slots[slot_idx] = _Slot(
            future=req.future,
            remaining=req.max_new_tokens,
            eos_id=req.eos_id,
            sampling=req.temperature > 0,
            on_token=req.on_token,
            prompt_len=L,
            t_start=t0,
            request_id=req.request_id,
            trace=req.trace,
            **self._spec_slot_state(req),
            **self._class_slot_state(req),
        )
        self._note_ttft(req)
        self._record_token(slot_idx, int(first))

    def replay_reset(self) -> None:
        """Follower side of :meth:`_fail_all_and_recover`'s device reset."""
        self._reset_device_state()

    def _record_token(
        self, slot_idx: int, token: int, t: float | None = None
    ) -> None:
        """Credit one emitted token to a slot.  ``t`` overrides the
        token's wall timestamp (fused multi-step harvests reconstruct
        per-token instants across the tick wall — K tokens landing on
        one perf_counter() read would zero every ITL observation and
        stack the Perfetto token instants on one point)."""
        slot = self._slots[slot_idx]
        assert slot is not None
        if slot.future.cancelled():
            # Client gone (stream disconnect / shutdown): free the slot
            # instead of decoding tokens nobody will read.
            self._finish_trace(slot, "cancelled")
            self._slots[slot_idx] = None
            return
        slot.generated.append(token)
        if slot.history is not None and slot.hist_len < slot.history.size:
            slot.history[slot.hist_len] = token
            slot.hist_len += 1
        slot.remaining -= 1
        if not self._in_warmup:
            now = time.perf_counter() if t is None else t
            if slot.t_last_token > 0.0 and self._on_itl is not None:
                self._on_itl(now - slot.t_last_token)
            slot.t_last_token = now
            if slot.trace is not None:
                slot.trace.note_token(now)
            self.tokens_generated += 1
            if self._on_tokens is not None:
                self._on_tokens(1)
            if slot.on_token is not None:
                try:
                    slot.on_token(token)
                except Exception:
                    # ONE line, then disarm: a broken streaming client
                    # would otherwise log a full stack per token at
                    # decode rate for the rest of the request.
                    _log.exception(
                        "on_token callback failed; disabling streaming "
                        "callback for this request"
                    )
                    slot.on_token = None
        done = slot.remaining <= 0 or (
            slot.eos_id is not None and token == slot.eos_id
        )
        if done:
            reason = (
                "eos"
                if slot.eos_id is not None and token == slot.eos_id
                else "length"
            )
            self._finish_trace(slot, reason)
            _safe_resolve(slot.future, np.asarray(slot.generated, np.int32))
            self._slots[slot_idx] = None

    def _finish_trace(self, slot: _Slot, reason: str) -> None:
        """Close a slot's request trace: finish reason, completion event,
        per-request token-count histogram, and hand the trace to the
        flight recorder's completed-request ring."""
        if self._in_warmup:
            return
        if self._on_request_tokens is not None:
            self._on_request_tokens(len(slot.generated))
        if slot.trace is None:
            return
        slot.trace.finish(reason)
        self._trace_event(slot.trace, "finish", slot=slot.trace.slot)
        if self._recorder is not None:
            self._recorder.complete(slot.trace)

    def _step(self) -> None:
        """One batched decode tick over every occupied slot.

        With speculation enabled and every occupied slot greedy, the tick
        tries a draft+verify (multi-token) pass first; a tick with no
        drafts anywhere — or any sampling slot — runs the original
        single-token step unchanged.

        The unified engine routes EVERY tick through the super-step
        assembler instead: one dispatch carries the tick's decode,
        verify, and packed-prefill work together."""
        if self._unified:
            self._super_tick()
            return
        active_np = np.array([s is not None for s in self._slots])
        if not active_np.any():
            # Still report occupancy: without this the gauges freeze at
            # their last busy values and an idle server reads as loaded.
            # (observe_decode_step skips its histograms at 0 active.)
            if self._on_step is not None and not self._in_warmup:
                self._on_step(0, 0.0, self._queue.qsize(), len(self._pending))
            return
        # Attention window: smallest bucket covering every active row's
        # next write position (prompt + tokens emitted so far).
        needed = max(
            s.prompt_len + len(s.generated)
            for s in self._slots
            if s is not None
        )
        window = decode_window_bucket(needed, self.capacity)
        sampling = any(s is not None and s.sampling for s in self._slots)
        if self._spec is not None and not sampling and not self._in_warmup:
            drafts = self._collect_drafts()
            if any(drafts):
                # Speculative slots fall back to verify ticks (a draft in
                # hand amortizes the weight stream by acceptance, which a
                # fixed-K scan cannot beat on draftable text); ticks with
                # no drafts anywhere fuse below like plain traffic.
                self._verify_tick(active_np, window, drafts)
                return
        if (
            self._fused
            and not self._in_warmup
            and not self._pending
            and not self._queued_work()
        ):
            # Fused multi-step decode engages only when the scheduler
            # owes nothing else: no queued request waiting on a slot a
            # K-step tick would hold for K tokens, no admission
            # mid-prefill whose chunk cadence a fused tick would stall.
            self._step_fused(active_np, sampling)
            return
        t0 = time.perf_counter()
        self._beat("decode")
        self._dispatch_step(active_np, window, sampling)
        toks = np.asarray(self._tokens)[:, 0]
        self._note_tick(
            active_np, t0, tokens=int(active_np.sum()),
            cost=self._cost_decode(window),
        )
        for i, was_active in enumerate(active_np):
            if was_active and self._slots[i] is not None:
                self._record_token(i, int(toks[i]))
                if not self._in_warmup:
                    self.decode_tokens += 1

    def _note_tick(
        self, active_np, t0: float, kind: str = "decode",
        tokens: int = 0, spec_accepted: int = 0, cost=None,
    ) -> None:
        if self._in_warmup:
            return
        self.decode_forwards += 1
        wall = time.perf_counter() - t0
        self._record_tick(
            kind, t0, wall,
            active_slots=int(active_np.sum()),
            tokens=tokens, spec_accepted=spec_accepted, cost=cost,
        )
        if self._on_step is not None:
            # queue depth counts QUEUED-BUT-UNADMITTED requests only; the
            # in-flight admission count rides separately so saturation
            # and admission-latency alerts stop conflating the two.
            self._on_step(
                int(active_np.sum()),
                wall,
                self._queue.qsize(),
                len(self._pending),
            )

    # -- fused multi-step decode (decodeSteps > 1) ---------------------------

    def _step_fused(self, active_np, sampling: bool) -> None:
        """A fused-decode BURST with lag-1 asynchronous readback.

        Each iteration dispatches ONE jitted program that runs K decode
        steps as a ``lax.scan`` (on-device sampling feeds each step's
        token into the next; an on-device EOS latch freezes finished
        rows mid-scan), then harvests the PREVIOUS dispatch's token
        block — so the host-side work of tick N (sync, SSE emission,
        recorder feed) overlaps tick N+1's device execution and the
        dispatch bubble between ticks disappears.  Chained dispatches
        pass NO host arrays: the active mask, per-row budgets, tokens,
        keys, lengths, and the donated cache buffers all stay device-
        resident between ticks.

        Host knowledge therefore lags the device by one tick: slot
        bookkeeping is exact through tick N-1 when tick N+1 is
        dispatched.  Only two decisions need host state — whether to
        keep the burst going, and the attention window — and both use
        conservative bounds (a row can advance at most K per tick), so
        a mid-scan EOS costs at most one trailing all-inactive dispatch,
        never a wrong result.  The burst exits with every harvest
        drained: the scheduler never leaves ``_step`` holding un-synced
        tokens, so admission and shutdown paths see exact slot truth.
        """
        K = self._decode_steps
        B = self.max_slots
        # Burst-entry device inputs from exact host slot truth.
        remaining = np.zeros((B,), np.int32)
        eos_ids = np.full((B,), -1, np.int32)  # -1: no EOS (ids are >= 0)
        hi = np.zeros((B,), np.int64)  # per-row next-write position bound
        rem_hi = np.zeros((B,), np.int64)  # per-row emit-budget bound
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            remaining[i] = slot.remaining
            if slot.eos_id is not None:
                eos_ids[i] = slot.eos_id
            hi[i] = slot.prompt_len + len(slot.generated)
            rem_hi[i] = slot.remaining
        pending = None  # (tok_block_dev, valid_dev, t0, window)
        start = True
        while True:
            # Pre-pick the window for length + K: the scan cannot grow
            # it mid-flight, and the LAST step attends positions up to
            # needed + K - 1 (satellite: a row crossing a bucket edge
            # inside K steps must already be covered).
            needed_hi = int(
                max(
                    hi[i]
                    for i in range(B)
                    if self._slots[i] is not None and rem_hi[i] > 0
                )
            )
            window = decode_window_bucket(
                min(needed_hi + K - 1, self.capacity), self.capacity
            )
            t0 = time.perf_counter()
            self._beat("multistep")
            tok_block, valid = self._dispatch_multistep(
                active_np if start else None,
                remaining if start else None,
                eos_ids if start else None,
                window, sampling,
            )
            for i in range(B):
                emit = min(int(rem_hi[i]), K)
                hi[i] += emit
                rem_hi[i] -= emit
            start = False
            if pending is not None:
                # Lag-1: tick N+1 is in flight; block on tick N now.
                self._harvest_fused(*pending)
            pending = (tok_block, valid, t0, window)
            may_be_active = any(
                self._slots[i] is not None and rem_hi[i] > 0
                for i in range(B)
            )
            if (
                not may_be_active
                or self._stop.is_set()
                or self._pending
                or self._queued_work()
            ):
                break
            if (
                self._spec is not None
                and not sampling
                and any(self._collect_drafts())
            ):
                # Speculative fallback is PER TICK: the harvest above
                # refreshed slot histories, and a draft in hand beats a
                # fixed-K scan on draftable text — end the burst so the
                # next _step runs the verify path.
                break
        if pending is not None:
            self._harvest_fused(*pending)

    def _harvest_fused(self, tok_block_dev, valid_dev, t0, window) -> None:
        """Block on one fused tick's outputs and credit its tokens.

        ``valid[i]`` counts the scan steps row ``i`` was active for —
        token columns at/after it are frozen copies the latch never
        emitted (and whose K/V was never committed: the in-scan active
        gate parks those writes, so no host-side truncation is needed).
        Per-token timestamps are reconstructed by spacing the row's
        valid tokens across the tick wall (clamped monotone against the
        row's previous token): K tokens on one instant would zero every
        ITL observation and stack the Perfetto instants."""
        toks = np.asarray(tok_block_dev)  # the deferred device sync
        valid = np.asarray(valid_dev)
        end = time.perf_counter()
        wall = end - t0
        K = self._decode_steps
        active_slots = int((valid > 0).sum())
        total = int(valid.sum())
        self.decode_forwards += 1
        self._record_tick(
            "multistep", t0, wall,
            active_slots=active_slots, tokens=total, steps=K,
            cost=self._cost_decode(window, steps=K),
        )
        if self._on_step is not None:
            self._on_step(
                active_slots, wall, self._queue.qsize(), len(self._pending)
            )
        for i in range(self.max_slots):
            n = int(valid[i])
            if n <= 0 or self._slots[i] is None:
                continue
            base = max(t0, self._slots[i].t_last_token)
            span = max(end - base, 0.0)
            for j in range(n):
                self._record_token(
                    i, int(toks[i, j]), t=base + span * (j + 1) / n
                )
                self.decode_tokens += 1
                if self._slots[i] is None:
                    break  # finished (eos/length) or cancelled mid-block

    def _dispatch_multistep(self, active_np, remaining, eos_ids, window,
                            sampling):
        """Broadcast (multihost) then run one fused K-step decode.

        ``active_np``/``remaining``/``eos_ids`` are host arrays on the
        first tick of a burst and ``None`` on chained ticks — chained
        state (mask, budgets, EOS ids) lives on device from the previous
        fused tick, on followers exactly as on the leader."""
        if self._channel is None:
            return self._device_multistep(
                active_np, remaining, eos_ids, window, sampling
            )
        from .multihost import OP_GEN_MULTISTEP, encode_message

        payload = encode_message(
            OP_GEN_MULTISTEP,
            {
                "active": active_np,
                "remaining": remaining,
                "eos_ids": eos_ids,
                "window": int(window),
                "sampling": bool(sampling),
            },
        )
        return self._channel.run(
            payload,
            lambda: self._device_multistep(
                active_np, remaining, eos_ids, window, sampling
            ),
        )

    def _device_multistep(self, active_np, remaining, eos_ids, window,
                          sampling):
        import jax.numpy as jnp

        if active_np is None:
            act, rem, eos = self._ms_active, self._ms_remaining, self._ms_eos
        else:
            act = jnp.asarray(np.asarray(active_np, bool))
            rem = jnp.asarray(np.asarray(remaining, np.int32))
            eos = jnp.asarray(np.asarray(eos_ids, np.int32))
            self._ms_eos = eos
        if sampling:
            (
                tok_block, valid, self._tokens,
                self._cache_k, self._cache_v, self._lengths,
                self._ms_active, self._ms_remaining, self._keys,
            ) = self._multistep(
                self._params, self._tokens,
                self._cache_k, self._cache_v, self._lengths,
                act, rem, eos,
                self._keys, self._temps, self._topk, self._topp,
                int(window), self._decode_steps,
            )
        else:
            (
                tok_block, valid, self._tokens,
                self._cache_k, self._cache_v, self._lengths,
                self._ms_active, self._ms_remaining,
            ) = self._multistep_greedy(
                self._params, self._tokens,
                self._cache_k, self._cache_v, self._lengths,
                act, rem, eos,
                int(window), self._decode_steps,
            )
        return tok_block, valid

    def replay_multistep(self, active, remaining, eos_ids, window,
                         sampling) -> None:
        """Follower side of a fused multi-step tick (multihost lockstep).
        ``active`` None = chained tick: the follower's own device-resident
        chain state (maintained by its previous replay) is used, exactly
        as on the leader."""
        self._device_multistep(
            None if active is None else np.asarray(active),
            None if remaining is None else np.asarray(remaining),
            None if eos_ids is None else np.asarray(eos_ids),
            int(window), bool(sampling),
        )

    # -- unified ragged super-step (unifiedStep) -----------------------------

    def _parked_superstep(self) -> tuple:
        """A fully PARKED unified-dispatch argument set: every row idle
        (zero counts park all K/V writes, inactive rows emit nothing,
        ``last_pos == -1`` finalizes nothing) with neutral sampling
        params.  The warmup window sweep dispatches it as-is;
        :meth:`_super_tick` overwrites rows with the tick's real roles —
        ONE construction site, so warmed shapes can never drift from
        the live call's (the `_parked_batch` discipline)."""
        B, S = self.max_slots, self._super_width
        return (
            np.zeros((B, S), np.int32),   # ids
            np.zeros((B,), np.int32),     # roles (all ROLE_IDLE)
            np.zeros((B,), np.int32),     # offsets
            np.zeros((B,), np.int32),     # counts
            np.zeros((B,), np.int32),     # draft_len
            np.zeros((B,), bool),         # active
            np.zeros((B,), np.int32),     # remaining
            np.full((B,), -1, np.int32),  # eos_ids
            np.full((B,), -1, np.int32),  # last_pos
            np.zeros((B,), np.int32),     # final_lens
            np.broadcast_to(
                self._zero_kd, (B,) + self._zero_kd.shape
            ).copy(),                     # key_data
            np.zeros((B,), np.float32),   # r_temps
            np.zeros((B,), np.int32),     # r_tks
            np.ones((B,), np.float32),    # r_tps
        )

    def _super_tick(self) -> None:
        """ONE dispatch per tick: assemble every occupied slot (decode
        or, on an all-greedy tick with drafts in hand, verify) and up to
        the packed budget of pending admissions' next chunks (prefill)
        into per-row role/offset/budget tensors, run the unified
        super-step program, and harvest all three roles' results from
        the one readback.  This is `_step` + `_verify_tick` +
        `_packed_tick` + `_step_fused` collapsed: the split engine's
        per-tick-kind programs (and their warmup cross-product)
        disappear, and prefill chunks interleave with decode inside the
        dispatch instead of between dispatches."""
        import jax

        from ..models import llama

        B = self.max_slots
        occupied = np.array([s is not None for s in self._slots])
        # Packed-admission chunk work riding this tick (seeds stay their
        # own op: a radix copy is not a forward).
        chunk_progs: list = []
        if self._packed and self._pending:
            C = self._prefill_chunk_size
            max_chunks = self._prefill_batch
            if self._prefill_token_budget:
                max_chunks = min(
                    max_chunks, max(1, self._prefill_token_budget // C)
                )
            for prog in self._pending[:max_chunks]:
                if prog.cached_tokens and not prog.seeded:
                    ts = time.perf_counter()
                    self._dispatch_seed_slot(
                        prog.cached_kv, prog.slot, prog.cached_tokens
                    )
                    prog.seeded = True
                    prog.cached_kv = []
                    self.prefix_hits += 1
                    self.prefix_cached_tokens += prog.cached_tokens
                    if not self._in_warmup:
                        if self._on_prefix_hit is not None:
                            self._on_prefix_hit(prog.cached_tokens)
                        if self._sync_ticks:
                            jax.block_until_ready(self._cache_k)
                        self._record_tick(
                            "seed", ts, time.perf_counter() - ts,
                            active_slots=int(occupied.sum()),
                            batch_fill=1,
                            cost=self._cost_seed(prog.cached_tokens),
                        )
                        self._trace_event(
                            prog.req.trace, "seed", slot=prog.slot
                        )
                else:
                    chunk_progs.append(prog)
        if not occupied.any() and not chunk_progs:
            # Still report occupancy: without this the gauges freeze at
            # their last busy values and an idle server reads as loaded.
            if self._on_step is not None and not self._in_warmup:
                self._on_step(0, 0.0, self._queue.qsize(), len(self._pending))
            return
        self._beat("superstep")
        K = self._decode_steps
        sampling = any(s is not None and s.sampling for s in self._slots)
        drafts: list[list[int]] = [[] for _ in range(B)]
        if (
            self._spec is not None
            and not sampling
            and not self._in_warmup
            and occupied.any()
        ):
            drafts = self._collect_drafts()
        (
            ids, roles, offsets, counts, draft_len, active, remaining,
            eos_ids, last_pos, final_lens, key_data, r_temps, r_tks, r_tps,
        ) = self._parked_superstep()
        decode_hi = other_hi = 0
        n_dec = n_ver = 0
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            pos = slot.prompt_len + len(slot.generated)
            ids[i, 0] = slot.generated[-1]  # pending (emitted, unfed) token
            active[i] = True
            d = drafts[i]
            if d:
                roles[i] = llama.ROLE_VERIFY
                ids[i, 1 : 1 + len(d)] = d
                draft_len[i] = len(d)
                counts[i] = len(d) + 1
                other_hi = max(other_hi, pos)
                n_ver += 1
            else:
                roles[i] = llama.ROLE_DECODE
                counts[i] = 1
                remaining[i] = slot.remaining
                if slot.eos_id is not None:
                    eos_ids[i] = slot.eos_id
                decode_hi = max(decode_hi, pos)
                n_dec += 1
        C = self._prefill_chunk_size
        for prog in chunk_progs:
            i, req = prog.slot, prog.req
            roles[i] = llama.ROLE_PREFILL
            off = prog.cached_tokens + prog.next_idx * C
            offsets[i] = off
            counts[i] = C
            ids[i, :C] = prog.chunks[prog.next_idx][0]
            other_hi = max(other_hi, off)
            if prog.next_idx == len(prog.chunks) - 1:
                L = int(req.prompt.size)
                last_pos[i] = (L - 1) - off
                final_lens[i] = L
                r_temps[i] = req.temperature
                r_tks[i] = req.top_k
                r_tps[i] = req.top_p
                key_data[i] = np.asarray(
                    jax.random.key_data(self._slot_key_for(req))
                )
        window = superstep_window(decode_hi, other_hi, K, self.capacity)
        n_pre = len(chunk_progs)
        t0 = time.perf_counter()
        tok_block, valid, greedy, accepted, firsts = self._dispatch_superstep(
            ids, roles, offsets, counts, draft_len, active, remaining,
            eos_ids, last_pos, final_lens, key_data, r_temps, r_tks, r_tps,
            window, sampling,
        )
        end = time.perf_counter()
        finals = sum(
            1 for prog in chunk_progs
            if prog.next_idx == len(prog.chunks) - 1
        )
        acc_total = int(accepted[occupied].sum()) if n_ver else 0
        if not self._in_warmup:
            self.decode_forwards += 1
            if n_pre:
                self.prefill_chunks_dispatched += n_pre
                self.prefill_forwards += 1
                if self._on_prefill_batch is not None:
                    self._on_prefill_batch(n_pre)
            if n_ver:
                self.spec_verify_ticks += 1
            wall = end - t0
            self._record_tick(
                "superstep", t0, wall,
                active_slots=int(occupied.sum()),
                batch_fill=n_pre,
                tokens=int(valid.sum()) + n_ver + acc_total + finals,
                spec_accepted=acc_total,
                steps=K,
                cost=self._cost_superstep(window, self._super_width, K),
                roles={"prefill": n_pre, "decode": n_dec, "verify": n_ver},
            )
            if self._on_step is not None:
                self._on_step(
                    int(occupied.sum()), wall,
                    self._queue.qsize(), len(self._pending),
                )
        # Prefill harvest: the _packed_tick bookkeeping, minus the
        # dispatch it no longer owns.
        for i, prog in enumerate(chunk_progs):
            if prog.req.trace is not None:
                prog.req.trace.slot = prog.slot
                prog.req.trace.prefill_chunks += 1
                self._trace_event(
                    prog.req.trace, "prefill_chunk", slot=prog.slot
                )
            self._maybe_cache_chunk_slot(prog)
            prog.next_idx += 1
            if prog.next_idx < len(prog.chunks):
                continue
            self._pending.remove(prog)
            self._reserved.discard(prog.slot)
            req = prog.req
            self._slots[prog.slot] = _Slot(
                future=req.future,
                remaining=req.max_new_tokens,
                eos_id=req.eos_id,
                sampling=req.temperature > 0,
                on_token=req.on_token,
                prompt_len=int(req.prompt.size),
                t_start=t0,
                request_id=req.request_id,
                trace=req.trace,
                **self._spec_slot_state(req),
                **self._class_slot_state(req),
            )
            self._note_ttft(req)
            self._record_token(prog.slot, int(firsts[prog.slot]))
        # Decode/verify harvest from the same readback.
        for i in range(B):
            if not occupied[i] or self._slots[i] is None:
                continue
            slot = self._slots[i]
            if roles[i] == llama.ROLE_VERIFY:
                n_prop, n_acc = int(draft_len[i]), int(accepted[i])
                if slot.draft is not None:
                    slot.draft.observe(n_prop, n_acc)
                if n_prop and not self._in_warmup:
                    self.spec_proposed_tokens += n_prop
                    self.spec_accepted_tokens += n_acc
                    if slot.trace is not None:
                        slot.trace.spec_proposed += n_prop
                        slot.trace.spec_accepted += n_acc
                    if self._on_spec is not None:
                        self._on_spec(n_prop, n_acc)
                # Emit the accepted draft prefix plus the bonus token;
                # stop early if the slot finishes (eos/budget/cancel).
                for j in range(n_acc + 1):
                    self._record_token(i, int(greedy[i, j]))
                    if not self._in_warmup:
                        self.decode_tokens += 1
                    if self._slots[i] is None:
                        break
            else:
                n = int(valid[i])
                if n <= 0:
                    continue
                # Per-token timestamps spaced across the tick wall (the
                # _harvest_fused discipline): K tokens on one instant
                # would zero every ITL observation.
                base = max(t0, slot.t_last_token)
                span = max(end - base, 0.0)
                for j in range(n):
                    self._record_token(
                        i, int(tok_block[i, j]), t=base + span * (j + 1) / n
                    )
                    if not self._in_warmup:
                        self.decode_tokens += 1
                    if self._slots[i] is None:
                        break

    def _dispatch_superstep(
        self, ids, roles, offsets, counts, draft_len, active, remaining,
        eos_ids, last_pos, final_lens, key_data, r_temps, r_tks, r_tps,
        window, sampling,
    ):
        """Broadcast (multihost) then run one unified super-step tick.
        Unlike the fused multistep burst, every input is a HOST array
        (the assembler rebuilds role truth each tick), so the replay
        payload is self-contained — followers keep no chained device
        state for this op."""
        args = (
            ids, roles, offsets, counts, draft_len, active, remaining,
            eos_ids, last_pos, final_lens, key_data, r_temps, r_tks, r_tps,
            window, sampling,
        )
        if self._channel is None:
            return self._device_superstep(*args)
        from .multihost import OP_GEN_SUPERSTEP, encode_message

        payload = encode_message(
            OP_GEN_SUPERSTEP,
            {
                "ids": ids,
                "roles": roles,
                "offsets": offsets,
                "counts": counts,
                "draft_len": draft_len,
                "active": active,
                "remaining": remaining,
                "eos_ids": eos_ids,
                "last_pos": last_pos,
                "final_lens": final_lens,
                "key_data": key_data,
                "temps": r_temps,
                "tks": r_tks,
                "tps": r_tps,
                "window": int(window),
                "sampling": bool(sampling),
            },
        )
        return self._channel.run(
            payload, lambda: self._device_superstep(*args)
        )

    def _device_superstep(
        self, ids, roles, offsets, counts, draft_len, active, remaining,
        eos_ids, last_pos, final_lens, key_data, r_temps, r_tks, r_tps,
        window, sampling,
    ):
        import jax
        import jax.numpy as jnp

        slot_keys = jax.random.wrap_key_data(jnp.asarray(key_data))
        (
            tok_block,
            valid,
            greedy,
            accepted,
            firsts,
            self._tokens,
            self._cache_k,
            self._cache_v,
            self._lengths,
            self._keys,
            self._temps,
            self._topk,
            self._topp,
        ) = self._superstep(
            self._params,
            jnp.asarray(ids),
            self._cache_k,
            self._cache_v,
            self._lengths,
            self._tokens,
            self._keys,
            self._temps,
            self._topk,
            self._topp,
            jnp.asarray(roles),
            jnp.asarray(offsets),
            jnp.asarray(counts),
            jnp.asarray(draft_len),
            jnp.asarray(active),
            jnp.asarray(remaining),
            jnp.asarray(eos_ids),
            jnp.asarray(last_pos),
            jnp.asarray(final_lens),
            slot_keys,
            jnp.asarray(r_temps),
            jnp.asarray(r_tks),
            jnp.asarray(r_tps),
            int(window),
            self._decode_steps,
            bool(sampling),
        )
        return (
            np.asarray(tok_block), np.asarray(valid), np.asarray(greedy),
            np.asarray(accepted), np.asarray(firsts),
        )

    def replay_superstep(
        self, ids, roles, offsets, counts, draft_len, active, remaining,
        eos_ids, last_pos, final_lens, key_data, temps, tks, tps,
        window, sampling,
    ) -> None:
        """Follower side of a unified super-step tick (multihost
        lockstep).  Every input arrives in the payload; no device-
        resident chain state is consulted."""
        self._device_superstep(
            np.asarray(ids), np.asarray(roles), np.asarray(offsets),
            np.asarray(counts), np.asarray(draft_len), np.asarray(active),
            np.asarray(remaining), np.asarray(eos_ids),
            np.asarray(last_pos), np.asarray(final_lens),
            np.asarray(key_data), np.asarray(temps), np.asarray(tks),
            np.asarray(tps), int(window), bool(sampling),
        )

    # -- self-speculative decoding (n-gram draft + batched verify) -----------

    def _collect_drafts(self) -> list[list[int]]:
        """Per-slot draft proposals for this tick (``[]`` = no draft).

        The budget is the slot's adaptive draft length capped at
        ``remaining - 1``: acceptance emits up to budget+1 tokens and a
        slot must never be asked to emit past its request."""
        drafts: list[list[int]] = []
        for slot in self._slots:
            if slot is None or slot.draft is None:
                drafts.append([])
                continue
            budget = min(slot.draft.budget(), slot.remaining - 1)
            if budget < 1:
                drafts.append([])
                continue
            drafts.append(self._propose(slot, budget))
        return drafts

    def _propose(self, slot: _Slot, budget: int) -> list[int]:
        """N-gram ("prompt lookup") draft from the slot's own history.
        Separate method so tests can swap in an oracle drafter."""
        from .speculative import propose_ngram

        return propose_ngram(
            slot.history[: slot.hist_len], budget,
            self._spec.ngram_min, self._spec.ngram_max,
        )

    def _verify_tick(self, active_np, window: int, drafts) -> None:
        """One draft+verify pass: k+1 positions per slot under ONE weight
        stream; per-slot greedy acceptance decides how many emit."""
        from .speculative import pad_to_chain

        s_draft = pad_to_chain(
            max(len(d) for d in drafts), self._spec_chain
        )
        toks = np.zeros((self.max_slots, s_draft + 1), np.int32)
        draft_len = np.zeros((self.max_slots,), np.int32)
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            toks[i, 0] = slot.generated[-1]  # pending (emitted, unfed) token
            d = drafts[i]
            toks[i, 1 : 1 + len(d)] = d
            draft_len[i] = len(d)
        t0 = time.perf_counter()
        self._beat("verify")
        greedy, accepted = self._dispatch_verify(
            toks, active_np, draft_len, window
        )
        acc_total = int(np.asarray(accepted)[active_np].sum())
        self._note_tick(
            active_np, t0, kind="verify",
            tokens=int(active_np.sum()) + acc_total,
            spec_accepted=acc_total,
            cost=self._cost_decode(window, s_draft + 1),
        )
        if not self._in_warmup:
            self.spec_verify_ticks += 1
        for i, was_active in enumerate(active_np):
            if not was_active or self._slots[i] is None:
                continue
            slot = self._slots[i]
            n_prop, n_acc = int(draft_len[i]), int(accepted[i])
            if slot.draft is not None:
                slot.draft.observe(n_prop, n_acc)
            if n_prop and not self._in_warmup:
                self.spec_proposed_tokens += n_prop
                self.spec_accepted_tokens += n_acc
                if slot.trace is not None:
                    slot.trace.spec_proposed += n_prop
                    slot.trace.spec_accepted += n_acc
                if self._on_spec is not None:
                    self._on_spec(n_prop, n_acc)
            # Emit the accepted draft prefix plus the bonus token; stop
            # early if the slot finishes (eos / budget) or cancels.
            for j in range(n_acc + 1):
                self._record_token(i, int(greedy[i, j]))
                if not self._in_warmup:
                    self.decode_tokens += 1
                if self._slots[i] is None:
                    break

    def _dispatch_verify(self, toks, active_np, draft_len, window):
        if self._channel is None:
            return self._device_verify(toks, active_np, draft_len, window)
        from .multihost import OP_GEN_VERIFY, encode_message

        payload = encode_message(
            OP_GEN_VERIFY,
            {
                "toks": toks,
                "active": active_np,
                "draft_len": draft_len,
                "window": int(window),
            },
        )
        return self._channel.run(
            payload,
            lambda: self._device_verify(toks, active_np, draft_len, window),
        )

    def _device_verify(self, toks, active_np, draft_len, window):
        import jax.numpy as jnp

        (
            self._tokens,
            self._cache_k,
            self._cache_v,
            self._lengths,
            greedy,
            accepted,
        ) = self._verify(
            self._params,
            jnp.asarray(toks),
            self._cache_k,
            self._cache_v,
            self._lengths,
            jnp.asarray(active_np),
            jnp.asarray(draft_len),
            int(window),
        )
        return np.asarray(greedy), np.asarray(accepted)

    def replay_verify(self, toks, active, draft_len, window) -> None:
        """Follower side of a verify tick (multihost lockstep)."""
        self._device_verify(
            np.asarray(toks), np.asarray(active),
            np.asarray(draft_len), int(window),
        )

    def _dispatch_step(self, active_np, window, sampling) -> None:
        if self._channel is None:
            self._device_step(active_np, window, sampling)
            return
        from .multihost import OP_GEN_STEP, encode_message

        payload = encode_message(
            OP_GEN_STEP,
            {"active": active_np, "window": int(window), "sampling": bool(sampling)},
        )
        self._channel.run(
            payload, lambda: self._device_step(active_np, window, sampling)
        )

    def _device_step(self, active_np, window, sampling) -> None:
        import jax.numpy as jnp

        if sampling:
            (
                self._tokens,
                self._cache_k,
                self._cache_v,
                self._lengths,
                self._keys,
            ) = self._decode(
                self._params,
                self._tokens,
                self._cache_k,
                self._cache_v,
                self._lengths,
                jnp.asarray(active_np),
                self._keys,
                self._temps,
                self._topk,
                self._topp,
                window,
            )
        else:
            (
                self._tokens,
                self._cache_k,
                self._cache_v,
                self._lengths,
            ) = self._decode_greedy(
                self._params,
                self._tokens,
                self._cache_k,
                self._cache_v,
                self._lengths,
                jnp.asarray(active_np),
                window,
            )

    def _loop(self) -> None:
        while not self._stop.is_set():
            # Heartbeat: the idle stamp is overwritten by the dispatch
            # sites below just before they block on a device call, so a
            # wedged tick is attributed to its kind, not to "idle".
            self._beat("idle")
            if not self._admit_phase():
                return  # shutdown sentinel
            try:
                self._step()
            except Exception:
                _log.exception("decode step failed")
                self._fail_all_and_recover()

    def _admit_phase(self) -> bool:
        """Admission work for one scheduler iteration.

        Fused mode drains every free slot; single-admission chunked mode
        advances the in-flight admission by ONE chunk (or starts a new
        one); packed mode tops up the admission queue (one reserved cache
        row each) and advances up to ``prefill_batch`` of them with ONE
        batched call.  In every mode the decode tick that follows is
        never more than one prefill tick away — in-flight streams keep
        their token cadence under long prompts.  Returns False on the
        shutdown sentinel."""
        self._drain_control_ops()
        if self._preemption:
            self._maybe_preempt()
        if self._packed:
            return self._admit_phase_packed()
        if self._pending:
            prog = self._pending[0]  # _chunk_tick pops it on finish
            try:
                self._chunk_tick()
            except Exception as exc:
                _log.exception("chunked prefill failed")
                self._note_admission_crash([prog.req])
                self._pending = []
                self._seq_state = None
                if not prog.req.future.done():
                    _safe_fail(prog.req.future, exc)
                self._fail_all_and_recover()
            return True
        while self._free_slot() is not None:
            try:
                idle = all(s is None for s in self._slots)
                req = self._dequeue(idle, self._idle_poll_s)
            except queue.Empty:
                break
            if isinstance(req, _Wake):
                self._drain_control_ops()
                continue
            if req is not None:
                self._release_queued(req)  # left the admission queue
            if req is None or self._stop.is_set():
                # A real request dequeued during shutdown is in neither
                # the queue nor a slot — fail it here or its client
                # awaits a future nobody will ever resolve.
                if req is not None and not req.future.done():
                    _safe_fail(
                        req.future,
                        EngineShutdown(
                            "engine shut down before admission; retry on "
                            "another replica"
                        ),
                    )
                return False
            if isinstance(req, _Preempted):
                # An evicted sequence re-admits straight into the free
                # slot the loop condition guarantees — no prefill.
                try:
                    self._admit_restore(req)
                except Exception as exc:
                    _log.exception("preemption restore failed")
                    if not req.future.done():
                        self._abort_trace(req.trace, "error")
                        _safe_fail(req.future, exc)
                    self._fail_all_and_recover()
                continue
            self._note_admission_wait(req)
            if self._prefill_chunk_size is not None:
                prog = self._make_progress(req)
                if self._sp_eligible(req) and not prog.cached_tokens:
                    # Long cold prompt: one ring pass now instead of
                    # queuing L/C serial chunk ticks.  Warm prefixes
                    # keep the seed + suffix-chunk path — the cache
                    # already skips the work sp would parallelize.
                    try:
                        self._admit_sp(req)
                    except Exception as exc:
                        _log.exception("sp prefill failed")
                        self._note_admission_crash([req])
                        self._seq_state = None
                        if not req.future.done():
                            _safe_fail(req.future, exc)
                        self._fail_all_and_recover()
                    continue
                self._pending.append(prog)
                return True  # first chunk runs next iteration's admit phase
            try:
                if self._sp_eligible(req):
                    self._admit_sp(req)
                else:
                    self._admit(req)
            except Exception as exc:  # keep the scheduler alive
                _log.exception("admit failed")
                self._note_admission_crash([req])
                self._seq_state = None  # a failed sp pass left it stale
                if not req.future.done():
                    _safe_fail(req.future, exc)
                self._fail_all_and_recover()
        return True

    def _admit_phase_packed(self) -> bool:
        """Packed-mode admission: top up the in-flight queue (each new
        admission reserves a free cache row), then advance up to
        ``prefill_batch`` admissions with one batched call."""
        popped = False
        while True:
            slot = self._free_slot()
            if slot is None:
                break
            idle = not self._pending and all(s is None for s in self._slots)
            try:
                req = self._dequeue(
                    idle and not popped, self._idle_poll_s
                )
            except queue.Empty:
                break
            if isinstance(req, _Wake):
                self._drain_control_ops()
                continue
            if req is not None:
                self._release_queued(req)  # left the admission queue
            if req is None or self._stop.is_set():
                if req is not None and not req.future.done():
                    _safe_fail(
                        req.future,
                        EngineShutdown(
                            "engine shut down before admission; retry on "
                            "another replica"
                        ),
                    )
                return False
            if isinstance(req, _Preempted):
                try:
                    self._admit_restore(req)
                except Exception as exc:
                    _log.exception("preemption restore failed")
                    if not req.future.done():
                        self._abort_trace(req.trace, "error")
                        _safe_fail(req.future, exc)
                    self._fail_all_and_recover()
                popped = True
                continue
            self._note_admission_wait(req)
            prog = self._make_progress(req)
            if self._sp_eligible(req) and not prog.cached_tokens:
                # Long cold prompt: the ring pass (batch-1 scratch, no
                # reserved row needed) beats packing its L/C chunks
                # into the batched program one budget at a time.
                try:
                    self._admit_sp(req)
                except Exception as exc:
                    _log.exception("sp prefill failed")
                    self._note_admission_crash([req])
                    self._seq_state = None
                    if not req.future.done():
                        _safe_fail(req.future, exc)
                    self._fail_all_and_recover()
                popped = True
                continue
            prog.slot = slot
            self._reserved.add(slot)
            self._pending.append(prog)
            popped = True
        if not self._pending:
            return True
        if self._unified:
            # Chunks ride the NEXT super-step dispatch (_super_tick
            # consumes up to the packed budget of pending admissions as
            # prefill rows); a failure there runs _loop's recovery,
            # which fails pending packed admissions too.
            return True
        try:
            self._packed_tick()
        except Exception as exc:
            _log.exception("packed prefill failed")
            self._note_admission_crash([p.req for p in self._pending])
            for prog in self._pending:
                if not prog.req.future.done():
                    _safe_fail(prog.req.future, exc)
            self._pending = []
            self._reserved.clear()
            self._fail_all_and_recover()
        return True

    def _fail_all_and_recover(self) -> None:
        """Fail every in-flight sequence and reallocate device state.

        A failed jitted call poisons all slots (their K/V history is part of
        the donated buffers), and donation has ALREADY invalidated those
        buffers — reusing them would raise "Array has been deleted" on every
        later request, bricking the engine while /ready stays green.  Fresh
        buffers restore service for subsequent requests."""
        for i, slot in enumerate(self._slots):
            if slot is not None and not slot.future.done():
                self._abort_trace(slot.trace, "error")
                _safe_fail(
                    slot.future,
                    RuntimeError("generation step failed; see server log"),
                )
            self._slots[i] = None
        if self._packed:
            # Packed admissions prefill STRAIGHT into the donated cache
            # rows, so the reset below destroys their half-written
            # prompts (single-mode admissions live in the untouched
            # batch-1 scratch and survive).  Fail them — continuing over
            # zeroed K/V would stream corrupted completions as 200s.
            for prog in self._pending:
                if not prog.req.future.done():
                    self._abort_trace(prog.req.trace, "error")
                    _safe_fail(
                        prog.req.future,
                        RuntimeError(
                            "generation step failed; see server log"
                        ),
                    )
            self._pending = []
            self._reserved.clear()
        if self._channel is not None:
            # Followers replayed the op that just failed here; their buffers
            # are invalidated (or their state now diverges).  Broadcast the
            # reset so every host drops to the same fresh state — otherwise
            # each subsequent replayed step runs with disagreeing
            # lengths/cache shards and silently corrupts tokens.
            from .multihost import OP_GEN_RESET, encode_message

            try:
                self._channel.run(
                    encode_message(OP_GEN_RESET, {}),
                    self._reset_device_state,
                )
                return
            except Exception:
                _log.exception("broadcasting gen reset failed")
        try:
            self._reset_device_state()
        except Exception:
            _log.exception("device state reallocation failed")
