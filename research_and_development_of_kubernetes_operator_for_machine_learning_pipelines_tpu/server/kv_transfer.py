"""KV handoff wire format: committed prefix K/V rows between replicas.

Disaggregated prefill/decode fleets (docs/SCALE.md) split the engine's
two phases across pools: prefill-heavy replicas compute prompt K/V,
decode-heavy replicas stream tokens.  λScale's observation (PAPERS.md)
is the economics: moving serialized K/V state between instances is far
cheaper than recomputing it — a 512-token prefix is a few MB of int8kv
bytes on the wire vs a full weight-streaming forward pass per replica.

The transfer unit is the radix prefix cache's chunk (PR 1): host copies
of one prefill chunk's K/V in the seq-prefill layout ``[L, 1, C, NKV,
D]``, exactly what ``GenerationEngine._read_slot`` produces and
``_seed_slot`` consumes — so an imported prefix re-enters the device
cache through the same seed program a local radix hit uses, and the
int8kv round trip stays lossless (PR 3's dequant/requant identity).

Wire layout (one blob per handoff)::

    MAGIC (6 bytes: b"TPKV1\\n")
    header length (8 bytes, little-endian uint64)
    JSON header:
        format_version, chunk_tokens, dtype, kv_shape,
        total_tokens, chunks: [
            {tokens, k_offset, k_nbytes, k_crc32,
                     v_offset, v_nbytes, v_crc32}, ...]
    raw payload (concatenated k/v bytes at the indexed offsets)

Every chunk's K and V carry their own CRC32 — a truncated or bit-flipped
blob raises the typed :class:`KvTransferError` at import instead of
splicing corrupt K/V into a request (the same contract as
``snapshot.py``'s per-leaf CRCs).  Token ids ride IN the manifest: the
radix cache keys chunks by exact token bytes, so the importer re-derives
the cumulative keys without trusting the sender's hashing.
"""

from __future__ import annotations

import binascii
import json
from typing import Any

import numpy as np

MAGIC = b"TPKV1\n"

# Bump when the wire layout changes; a mismatch is a typed error — the
# router falls back to unified serving, never to garbage K/V.
FORMAT_VERSION = 1

# A handoff blob is one prompt's prefix, not a checkpoint: cap it well
# below anything a misbehaving peer could use to balloon the importer.
MAX_BLOB_BYTES = 1 << 30


class KvTransferError(Exception):
    """Typed failure of a KV handoff blob: bad magic, truncation, CRC
    mismatch, malformed manifest, or a geometry that does not match the
    importing engine.  Callers treat it as 'this handoff is unusable'
    and fall back to local prefill (unified serving)."""


def _dtype_from_name(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def serialize_chunks(
    chunk_tokens: int,
    prompt: np.ndarray,
    chunks: list,
) -> bytes:
    """Pack ``chunks`` — ``[(k, v), ...]`` host pairs in radix storage
    layout ``[L, 1, C, NKV, D]``, one per matched chunk of ``prompt`` —
    into one handoff blob.  ``len(chunks) * chunk_tokens`` leading tokens
    of ``prompt`` are the covered prefix; their ids ride in the manifest
    so the importer rebuilds the exact radix keys."""
    if not chunks:
        raise KvTransferError("no chunks to serialize")
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    C = int(chunk_tokens)
    if len(chunks) * C > prompt.size:
        raise KvTransferError(
            f"{len(chunks)} chunks of {C} tokens exceed the "
            f"{prompt.size}-token prompt"
        )
    k0 = np.ascontiguousarray(np.asarray(chunks[0][0]))
    header: dict[str, Any] = {
        "format_version": FORMAT_VERSION,
        "chunk_tokens": C,
        "dtype": k0.dtype.name,
        "kv_shape": list(k0.shape),
        "total_tokens": len(chunks) * C,
        "chunks": [],
    }
    payload = bytearray()
    for idx, (k, v) in enumerate(chunks):
        k = np.ascontiguousarray(np.asarray(k))
        v = np.ascontiguousarray(np.asarray(v))
        if k.shape != k0.shape or v.shape != k0.shape or k.dtype != k0.dtype:
            raise KvTransferError(
                f"chunk {idx} geometry {k.shape}/{k.dtype} differs from "
                f"chunk 0 {k0.shape}/{k0.dtype}"
            )
        kraw, vraw = k.tobytes(), v.tobytes()
        header["chunks"].append(
            {
                "tokens": prompt[idx * C : (idx + 1) * C].tolist(),
                "k_offset": len(payload),
                "k_nbytes": len(kraw),
                "k_crc32": binascii.crc32(kraw) & 0xFFFFFFFF,
                "v_offset": len(payload) + len(kraw),
                "v_nbytes": len(vraw),
                "v_crc32": binascii.crc32(vraw) & 0xFFFFFFFF,
            }
        )
        payload += kraw
        payload += vraw
    head = json.dumps(header).encode()
    return (
        MAGIC
        + len(head).to_bytes(8, "little")
        + head
        + bytes(payload)
    )


def deserialize_chunks(blob: bytes) -> tuple[dict[str, Any], list]:
    """Unpack a handoff blob into ``(header, [(k, v), ...])``.

    Every chunk's CRC is verified before its bytes are trusted; any
    structural problem raises :class:`KvTransferError`."""
    if len(blob) > MAX_BLOB_BYTES:
        raise KvTransferError(
            f"handoff blob of {len(blob)} bytes exceeds the "
            f"{MAX_BLOB_BYTES}-byte cap"
        )
    if not blob.startswith(MAGIC):
        raise KvTransferError("bad magic: not a KV handoff blob")
    if len(blob) < len(MAGIC) + 8:
        raise KvTransferError("truncated handoff blob: no header length")
    head_len = int.from_bytes(blob[len(MAGIC) : len(MAGIC) + 8], "little")
    head_start = len(MAGIC) + 8
    if head_start + head_len > len(blob):
        raise KvTransferError("truncated handoff blob: header cut short")
    try:
        header = json.loads(blob[head_start : head_start + head_len])
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise KvTransferError(f"malformed handoff header: {e}") from e
    if not isinstance(header, dict) or not isinstance(
        header.get("chunks"), list
    ):
        raise KvTransferError("malformed handoff header: bad shape")
    if int(header.get("format_version", -1)) != FORMAT_VERSION:
        raise KvTransferError(
            f"handoff format v{header.get('format_version')} != "
            f"v{FORMAT_VERSION}"
        )
    try:
        dtype = _dtype_from_name(str(header["dtype"]))
        shape = tuple(int(d) for d in header["kv_shape"])
        C = int(header["chunk_tokens"])
    except (KeyError, TypeError, ValueError) as e:
        raise KvTransferError(f"malformed handoff header: {e}") from e
    payload = blob[head_start + head_len :]
    chunks: list = []
    expected_off = 0
    for idx, entry in enumerate(header["chunks"]):
        try:
            tokens = entry["tokens"]
            pairs = [
                (entry["k_offset"], entry["k_nbytes"], entry["k_crc32"]),
                (entry["v_offset"], entry["v_nbytes"], entry["v_crc32"]),
            ]
        except (KeyError, TypeError) as e:
            raise KvTransferError(
                f"malformed chunk {idx} manifest: {e}"
            ) from e
        if not isinstance(tokens, list) or len(tokens) != C:
            raise KvTransferError(
                f"chunk {idx} carries {len(tokens) if isinstance(tokens, list) else '?'} "
                f"tokens, expected {C}"
            )
        # The serializer lays chunks out sequentially; require exactly
        # that, so manifest entries cannot alias the same payload bytes
        # — MAX_BLOB_BYTES bounds the wire size, and sequential offsets
        # are what make it also bound the DECODED size (a peer declaring
        # 1000 chunks over one region would otherwise materialize 1000x
        # the payload in host arrays before any geometry check runs).
        (k_off, k_n, _), (v_off, v_n, _) = (
            (int(p[0]), int(p[1]), p[2]) for p in pairs
        )
        if k_off != expected_off or v_off != k_off + k_n:
            raise KvTransferError(
                f"chunk {idx} payload offsets overlap or leave gaps "
                "(sequential layout required)"
            )
        expected_off = v_off + v_n
        arrs = []
        for off, nbytes, crc in pairs:
            off, nbytes = int(off), int(nbytes)
            raw = payload[off : off + nbytes]
            if len(raw) != nbytes:
                raise KvTransferError(
                    f"chunk {idx} truncated: wanted {nbytes} bytes at "
                    f"offset {off}, got {len(raw)}"
                )
            if (binascii.crc32(raw) & 0xFFFFFFFF) != int(crc):
                raise KvTransferError(f"chunk {idx} failed CRC")
            try:
                arrs.append(np.frombuffer(raw, dtype=dtype).reshape(shape))
            except ValueError as e:
                # nbytes disagrees with the manifest's shape x dtype —
                # structural corruption stays TYPED like every other.
                raise KvTransferError(
                    f"chunk {idx} byte count {nbytes} does not fit "
                    f"shape {shape} x {dtype}: {e}"
                ) from e
        chunks.append((arrs[0], arrs[1]))
    return header, chunks


def chunk_token_ids(header: dict[str, Any]) -> np.ndarray:
    """The covered prefix's token ids, concatenated in chunk order —
    the prompt prefix the importer keys the radix inserts by."""
    out: list[int] = []
    for entry in header["chunks"]:
        out.extend(int(t) for t in entry["tokens"])
    return np.asarray(out, np.int32)
