"""Prometheus metrics with Seldon-executor-compatible identity.

The promotion gate queries exactly these series (``mlflow_operator.py``):

- ``seldon_api_executor_client_requests_seconds`` histogram — p95 latency
  (``:367``), mean latency Δsum/Δcount (``:393-404``), request count (``:407``);
- ``seldon_api_executor_server_requests_seconds_count`` with a ``code``
  label — error counting via ``code!="200"`` (``:375``) and a ``service``
  label for feedback requests (``:410``);

all keyed by ``{deployment_name, predictor_name, namespace}`` (``:367``).
Emitting the same names and labels means the reference's PromQL — and our
gate, which preserves it — works against this server unmodified (SURVEY §7
hard part 4: metric identity).

Beyond gate compatibility the server exports first-party TPU series
(``tpumlops_*``): batch sizes, queue latency, compile counts.
"""

from __future__ import annotations

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

# Latency SLOs live in the 1ms-10s range on TPU; buckets chosen to resolve
# p95/p99 there.
_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class ServerMetrics:
    def __init__(
        self,
        deployment_name: str,
        predictor_name: str,
        namespace: str,
        device_telemetry: bool = False,
    ):
        self.registry = CollectorRegistry()
        self.identity = {
            "deployment_name": deployment_name,
            "predictor_name": predictor_name,
            "namespace": namespace,
        }
        ident_labels = list(self.identity)

        self.client_requests = Histogram(
            "seldon_api_executor_client_requests_seconds",
            "Inference request latency (gate-compatible identity)",
            ident_labels,
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )
        # Histogram, NOT Counter: the gate's PromQL reads the ``_count``
        # series (``seldon_api_executor_server_requests_seconds_count``,
        # mlflow_operator.py:375,:383,:410); a Counter would export
        # ``_total`` and every error query would silently read 0 through
        # the ``or on() vector(0)`` fallback.
        self.server_requests = Histogram(
            "seldon_api_executor_server_requests_seconds",
            "Request durations by HTTP code (gate queries _count with code!='200')",
            ident_labels + ["code", "service"],
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )
        self.batch_size = Histogram(
            "tpumlops_batch_size",
            "Dynamic-batcher batch sizes",
            ident_labels,
            buckets=(1, 2, 4, 8, 16, 32, 64, 128),
            registry=self.registry,
        )
        self.queue_seconds = Histogram(
            "tpumlops_queue_seconds",
            "Time requests spend in the batching queue",
            ident_labels,
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )
        # Device dispatch wall per batch: with queue_seconds and the
        # request histogram this decomposes server-observed latency into
        # queue wait + device run + server overhead (JSON, HTTP, glue) —
        # the overhead term is environment-independent and benched
        # (bench.py serve_path server_overhead_ms, VERDICT r2 #7).
        self.batch_run_seconds = Histogram(
            "tpumlops_batch_run_seconds",
            "run_batch (device dispatch) wall time per executed batch",
            ident_labels,
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )
        # Pipelined mode only: time a dispatched batch waited behind its
        # predecessor's device run before its own materialize began.
        # Without this term the wait pools into the residual "overhead"
        # (total - queue - run), misreading pipeline occupancy as server
        # glue cost.
        self.pipeline_wait_seconds = Histogram(
            "tpumlops_pipeline_wait_seconds",
            "Wait behind the previous in-flight batch before materialize",
            ident_labels,
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )
        self.compilations = Counter(
            "tpumlops_compilations_total",
            "XLA compilations triggered (by bucket signature)",
            ident_labels,
            registry=self.registry,
        )
        self.generated_tokens = Counter(
            "tpumlops_generated_tokens_total",
            "Tokens produced by the continuous-batching generation engine",
            ident_labels,
            registry=self.registry,
        )
        self.decode_batch = Histogram(
            "tpumlops_decode_batch_size",
            "Active slots per continuous-batching decode step",
            ident_labels,
            buckets=(1, 2, 4, 8, 16, 32, 64),
            registry=self.registry,
        )
        self.decode_step_seconds = Histogram(
            "tpumlops_decode_step_seconds",
            "Wall time of one batched decode step",
            ident_labels,
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )
        # Prefix KV cache (server/prefix_cache.py): the promotion gate's
        # operator can watch hit rate / cached-token volume per predictor
        # to judge whether a canary inherits the production prefix mix.
        self.prefix_cache_hits = Counter(
            "tpumlops_prefix_cache_hits",
            "Admissions that reused a radix-cached prompt prefix",
            ident_labels,
            registry=self.registry,
        )
        self.prefix_cache_cached_tokens = Counter(
            "tpumlops_prefix_cache_cached_tokens",
            "Prompt tokens served from the prefix KV cache (prefill skipped)",
            ident_labels,
            registry=self.registry,
        )
        self.prefix_cache_evictions = Counter(
            "tpumlops_prefix_cache_evictions",
            "Prefix-cache chunks evicted under the byte budget (LRU)",
            ident_labels,
            registry=self.registry,
        )
        # Second-tier (host-RAM) prefix cache (prefixCache.l2BudgetMB):
        # chunks the first tier evicted that were caught, re-promoted,
        # or aged out of the L2 pool.  Registered unconditionally like
        # the L1 family — children appear only when the tier is on.
        self.prefix_cache_l2_hits = Counter(
            "tpumlops_prefix_cache_l2_hits",
            "Radix-walk misses served by the second-tier host-RAM pool "
            "(chunk promoted back into the tree)",
            ident_labels,
            registry=self.registry,
        )
        self.prefix_cache_l2_spills = Counter(
            "tpumlops_prefix_cache_l2_spills",
            "First-tier evictions caught by the second-tier pool",
            ident_labels,
            registry=self.registry,
        )
        self.prefix_cache_l2_evictions = Counter(
            "tpumlops_prefix_cache_l2_evictions",
            "Chunks aged out of the second-tier pool (LRU byte budget)",
            ident_labels,
            registry=self.registry,
        )
        # Engine occupancy telemetry (fed per decode tick from the
        # engine's on_step callback): lets the operator correlate
        # speculative acceptance — and every other per-tick rate — with
        # batch occupancy and admission backlog.
        self.engine_active_slots = Gauge(
            "tpumlops_engine_active_slots",
            "Occupied decode slots at the most recent engine tick",
            ident_labels,
            registry=self.registry,
        )
        self.engine_queue_depth = Gauge(
            "tpumlops_engine_queue_depth",
            "Requests queued but NOT yet admitted (excludes in-flight "
            "admissions — see tpumlops_engine_admitting)",
            ident_labels,
            registry=self.registry,
        )
        # Separate from queue depth so saturation alerts (queue grows)
        # and admission-latency alerts (admissions in flight pile up
        # behind long prefills) stop conflating the two populations.
        self.engine_admitting = Gauge(
            "tpumlops_engine_admitting",
            "Admissions mid-prefill (dequeued, no first token yet)",
            ident_labels,
            registry=self.registry,
        )
        # Packed multi-admission prefill (server/generation.py
        # prefillBatch): real chunks per batched prefill call.  Mean
        # fill near 1 under light load is expected; under bursts it
        # should track min(concurrent admissions, prefillBatch) — a
        # flat 1 under load means packing is not engaging.
        self.prefill_batch_fill = Histogram(
            "tpumlops_prefill_batch_fill",
            "Admission chunks packed into one batched prefill call",
            ident_labels,
            buckets=(1, 2, 4, 8, 16, 32, 64),
            registry=self.registry,
        )
        self.admission_wait_ms = Histogram(
            "tpumlops_admission_wait_ms",
            "Milliseconds a request waited in the queue before its "
            "admission began",
            ident_labels,
            buckets=(0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000,
                     2500, 5000, 10000),
            registry=self.registry,
        )
        self.ttft_seconds = Histogram(
            "tpumlops_ttft_seconds",
            "Submit-to-first-token latency per generation request",
            ident_labels,
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )
        # Per-request latency decomposition (with ttft_seconds): ITL is
        # the steady-state token cadence a streaming client feels —
        # decode_step_seconds measures the device tick, ITL measures the
        # request (a tick serves many slots; a slot skips ticks while
        # its admission peer prefills).
        self.itl_seconds = Histogram(
            "tpumlops_itl_seconds",
            "Inter-token latency: wall between consecutive tokens of one "
            "request (first token excluded — that is TTFT)",
            ident_labels,
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )
        self.request_tokens = Histogram(
            "tpumlops_request_tokens",
            "Tokens generated per finished request (includes cancelled "
            "requests' partial output)",
            ident_labels,
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096),
            registry=self.registry,
        )
        # Engine tick wall by kind: the aggregate view of the flight
        # recorder's per-tick journal (server/flight_recorder.py) — a
        # decode-cadence regression shows up as the decode kind's
        # distribution shifting while packed-prefill's fattens.  A
        # "multistep" tick covers K decode steps (decodeSteps), so read
        # its wall against tokens, not against single-step decode ticks.
        self.tick_seconds = Histogram(
            "tpumlops_tick_seconds",
            "Engine tick wall time by kind "
            "(decode/verify/multistep/prefill/packed-prefill/seed); "
            "prefill/seed walls are dispatch-only unless the flight "
            "recorder is on (traceRing > 0), which syncs them to cover "
            "device time",
            ident_labels + ["kind"],
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )
        # Engine device dispatches by op: with generated_tokens this is
        # the amortization series of record — dispatches-per-token is
        # what the fused multi-step path (decodeSteps) collapses by ~K,
        # and what prefix-cache/speculative/packed-prefill each already
        # cut on their own axes.  One increment per journaled engine
        # tick (a multi-chunk seed op counts once).  Registered
        # UNCONDITIONALLY like the spec_* families (the series is
        # meaningful for every serving mode, fused or not) — the
        # decodeSteps:1 byte-identity contract covers the engine loop,
        # tick records, and label VALUES (no op="multistep" children
        # ever appear at K=1), not the family's presence; the inventory
        # is pinned in tests/test_metrics_contract.py.
        self.engine_dispatches = Counter(
            "tpumlops_engine_dispatches",
            "Engine device dispatches by tick kind (decode/verify/"
            "multistep/prefill/packed-prefill/seed)",
            ident_labels + ["op"],
            registry=self.registry,
        )
        # Self-speculative decoding (server/speculative.py): proposed vs
        # accepted draft tokens, plus per-verify distributions.  The
        # counters give the exact acceptance rate over any window
        # (rate(accepted)/rate(proposed)); the histograms show its shape
        # — a healthy repetitive workload piles acceptance at the draft
        # cap, adversarial text piles it at 0.
        self.spec_proposed_tokens = Counter(
            "tpumlops_spec_proposed_tokens",
            "Draft tokens proposed by the n-gram speculative drafter",
            ident_labels,
            registry=self.registry,
        )
        self.spec_accepted_tokens = Counter(
            "tpumlops_spec_accepted_tokens",
            "Draft tokens accepted by greedy verification",
            ident_labels,
            registry=self.registry,
        )
        self.spec_accepted_len = Histogram(
            "tpumlops_spec_accepted_len",
            "Accepted draft length per (slot, verify)",
            ident_labels,
            # Top finite bucket matches the draftTokens ceiling (64) so
            # high-draft tunings keep a readable distribution shape.
            buckets=(0, 1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64),
            registry=self.registry,
        )
        self.spec_acceptance_rate = Histogram(
            "tpumlops_spec_acceptance_rate",
            "accepted/proposed per (slot, verify)",
            ident_labels,
            buckets=(0.0, 0.25, 0.5, 0.75, 0.999, 1.0),
            registry=self.registry,
        )
        # Admission control (server/generation.py admission_queue_budget
        # + the drain protocol): requests refused at the door with
        # 429 + Retry-After.  reason="budget" = queued estimated tokens
        # over budget; reason="draining" = scale-down / shutdown drain
        # in progress.  The autoscaler watches this family to confirm
        # shed (not silence) is what a saturated replica produces.
        self.shed = Counter(
            "tpumlops_engine_shed",
            "Generation requests shed at admission (HTTP 429)",
            ident_labels + ["reason"],
            registry=self.registry,
        )
        # Mid-decode preemption (spec.tpu.preemption + spec.sloClass):
        # evictions of lower-class slots to admit higher-class work and
        # the matching restores.  event="evict" | "restore"; restores
        # lag evictions only while the preempted record waits in its
        # class queue, so evict-minus-restore is live preempted backlog.
        self.preempt = Counter(
            "tpumlops_engine_preempt",
            "Slot preemption events (evict = KV written back through "
            "the prefix cache and slot reclaimed; restore = sequence "
            "re-admitted with no lost work)",
            ident_labels + ["event"],
            registry=self.registry,
        )
        # Model-load stage breakdown (server/loader.py load_stats): the
        # bench has measured disk/transfer/quantize/shard for rounds —
        # this makes it a first-party series so a cold-start regression
        # shows on dashboards, not just in bench JSON.  stage="restore"
        # is the snapshot fast path (server/snapshot.py); "total" the
        # load wall.  Registered unconditionally like engine_dispatches:
        # children appear only when a load observes them, and the
        # inventory is pinned in tests/test_metrics_contract.py.
        self.model_load_seconds = Gauge(
            "tpumlops_model_load_seconds",
            "Most recent model load's stage breakdown "
            "(disk/transfer/quantize/shard, or restore for a snapshot "
            "restore; total = wall)",
            ident_labels + ["stage"],
            registry=self.registry,
        )
        # Scale-to-zero cold start ladder (wake -> restore -> compile ->
        # first_token): stamped once per boot/attach so the whole
        # CR-at-zero -> first-token path is observable per stage.
        self.cold_start_seconds = Gauge(
            "tpumlops_cold_start_seconds",
            "Cold-start stage walls of the most recent boot/attach "
            "(wake/load/restore/compile/first_token/total)",
            ident_labels + ["stage"],
            registry=self.registry,
        )
        # Device telemetry layer (server/device_telemetry.py), registered
        # ONLY when spec.tpu.observability.deviceTelemetry is on: even an
        # unobserved labeled family adds HELP/TYPE lines to the
        # exposition, and the disabled contract is byte-for-byte.
        self.device_hbm_bytes = None
        self.device_mfu = None
        self.device_hbm_bw_util = None
        self.engine_collective_seconds = None
        self.compile_seconds = None
        self.compile_cache_hits = None
        self.compile_cache_misses = None
        if device_telemetry:
            self.device_hbm_bytes = Gauge(
                "tpumlops_device_hbm_bytes",
                "Analytic HBM ledger: bytes held on device by component "
                "(weights_<dtype>, kv_cache, sampling_state, total)",
                ident_labels + ["component"],
                registry=self.registry,
            )
            self.device_mfu = Gauge(
                "tpumlops_device_mfu",
                "Model FLOPs utilization of the most recent engine tick "
                "of each kind (analytic cost model / device peak)",
                ident_labels + ["kind"],
                registry=self.registry,
            )
            self.device_hbm_bw_util = Gauge(
                "tpumlops_device_hbm_bw_util",
                "HBM bandwidth utilization of the most recent engine "
                "tick of each kind (analytic bytes / device peak)",
                ident_labels + ["kind"],
                registry=self.registry,
            )
            self.engine_collective_seconds = Counter(
                "tpumlops_engine_collective_seconds",
                "Estimated ICI collective wall seconds per engine "
                "dispatch at tp > 1, by op (all_reduce = the Megatron "
                "o/down psum pair per layer, all_gather = the vocab-"
                "sharded logits gather), from the analytic cost model",
                ident_labels + ["op"],
                registry=self.registry,
            )
            self.compile_seconds = Counter(
                "tpumlops_compile_seconds",
                "XLA backend-compile wall seconds attributed to the "
                "engine op that triggered the compilation",
                ident_labels + ["op"],
                registry=self.registry,
            )
            self.compile_cache_hits = Counter(
                "tpumlops_compile_cache_hits",
                "Persistent compile-cache hits (compile requests served "
                "by deserializing a cached executable)",
                ident_labels,
                registry=self.registry,
            )
            self.compile_cache_misses = Counter(
                "tpumlops_compile_cache_misses",
                "Persistent compile-cache misses (full XLA compilations)",
                ident_labels,
                registry=self.registry,
            )
        self.ready = Gauge(
            "tpumlops_model_ready",
            "1 once the model is loaded and warmed",
            ident_labels,
            registry=self.registry,
        )
        # First-party reward telemetry for the Seldon feedback API
        # (``/api/v1.0/feedback``); the gate-visible count lives in
        # ``server_requests{service="feedback"}`` (``:410-415``).  A
        # Gauge, not a Counter: rewards are arbitrary floats (negative =
        # penalty signal) and the sum must not silently drop them.
        self.feedback_reward = Gauge(
            "tpumlops_feedback_reward_total",
            "Running sum of rewards posted to the feedback endpoint "
            "(may decrease: negative rewards are penalties)",
            ident_labels,
            registry=self.registry,
        )
        # Failure containment (PR 13).  Watchdog families sit at 0 until
        # --watchdog-deadline-s arms the monitor; the poison counters
        # back the always-on quarantine (a prompt whose admission
        # crashed the engine twice is refused with a typed 422).
        self.watchdog_stalls = Counter(
            "tpumlops_engine_watchdog_stalls_total",
            "Scheduler ticks that exceeded the watchdog deadline "
            "(each flips /readyz unready and journals a watchdog event)",
            ident_labels,
            registry=self.registry,
        )
        self.watchdog_tick_age = Gauge(
            "tpumlops_engine_watchdog_last_tick_age_seconds",
            "Age of the scheduler's last heartbeat as seen by the "
            "watchdog monitor (0 while disarmed; climbs during a stall)",
            ident_labels,
            registry=self.registry,
        )
        self.poison_quarantined = Counter(
            "tpumlops_engine_poison_quarantined_total",
            "Prompt fingerprints quarantined after repeated "
            "admission/prefill crashes",
            ident_labels,
            registry=self.registry,
        )
        self.poison_rejected = Counter(
            "tpumlops_engine_poison_rejected_total",
            "Submissions refused (typed 422) because their prompt "
            "fingerprint is quarantined",
            ident_labels,
            registry=self.registry,
        )

    # -- recording helpers ---------------------------------------------------

    def observe_request(self, seconds: float, code: int = 200, service: str = "predictions"):
        # client_requests feeds the gate's latency percentiles
        # (``:367-372``) — inference traffic only; feedback posts land in
        # server_requests under their own ``service`` label so the
        # feedback count query (``:410-415``) sees them without skewing
        # the latency gate.
        if service == "predictions":
            self.client_requests.labels(**self.identity).observe(seconds)
        self.server_requests.labels(
            **self.identity, code=str(code), service=service
        ).observe(seconds)

    def observe_feedback_reward(self, reward: float):
        self.feedback_reward.labels(**self.identity).inc(reward)

    def observe_batch(
        self,
        size: int,
        queue_seconds: float,
        run_seconds: float = 0.0,
        pipeline_wait_seconds: float = 0.0,
    ):
        self.batch_size.labels(**self.identity).observe(size)
        self.queue_seconds.labels(**self.identity).observe(queue_seconds)
        self.batch_run_seconds.labels(**self.identity).observe(run_seconds)
        self.pipeline_wait_seconds.labels(**self.identity).observe(
            pipeline_wait_seconds
        )

    def observe_decode_step(
        self,
        active_slots: int,
        seconds: float,
        queue_depth: int = 0,
        admitting: int = 0,
    ):
        # active_slots == 0 is the engine's idle heartbeat: refresh the
        # occupancy gauges but keep the per-tick histograms tick-only.
        if active_slots > 0:
            self.decode_batch.labels(**self.identity).observe(active_slots)
            self.decode_step_seconds.labels(**self.identity).observe(seconds)
        self.engine_active_slots.labels(**self.identity).set(active_slots)
        self.engine_queue_depth.labels(**self.identity).set(queue_depth)
        self.engine_admitting.labels(**self.identity).set(admitting)

    def inc_watchdog_stall(self):
        self.watchdog_stalls.labels(**self.identity).inc()

    def set_watchdog_tick_age(self, seconds: float):
        self.watchdog_tick_age.labels(**self.identity).set(seconds)

    def inc_poison(self, action: str):
        """``action``: "quarantined" (fingerprint crossed the crash
        threshold) or "rejected" (a submit refused with the typed 422)."""
        if action == "quarantined":
            self.poison_quarantined.labels(**self.identity).inc()
        else:
            self.poison_rejected.labels(**self.identity).inc()

    def inc_shed(self, reason: str):
        self.shed.labels(**self.identity, reason=reason).inc()

    def inc_preempt(self, event: str):
        """``event``: "evict" (slot reclaimed, KV parked in the prefix
        cache) or "restore" (preempted sequence re-admitted)."""
        self.preempt.labels(**self.identity, event=event).inc()

    def observe_prefill_batch(self, fill: int):
        self.prefill_batch_fill.labels(**self.identity).observe(fill)

    def observe_admission_wait(self, seconds: float):
        self.admission_wait_ms.labels(**self.identity).observe(seconds * 1000)

    def observe_ttft(self, seconds: float):
        self.ttft_seconds.labels(**self.identity).observe(seconds)

    def observe_itl(self, seconds: float):
        self.itl_seconds.labels(**self.identity).observe(seconds)

    def observe_request_tokens(self, n: int):
        self.request_tokens.labels(**self.identity).observe(n)

    def observe_tick(self, kind: str, seconds: float):
        self.tick_seconds.labels(**self.identity, kind=kind).observe(seconds)

    def inc_dispatch(self, op: str):
        self.engine_dispatches.labels(**self.identity, op=op).inc()

    def observe_speculative(self, proposed: int, accepted: int):
        self.spec_proposed_tokens.labels(**self.identity).inc(proposed)
        self.spec_accepted_tokens.labels(**self.identity).inc(accepted)
        self.spec_accepted_len.labels(**self.identity).observe(accepted)
        if proposed > 0:
            self.spec_acceptance_rate.labels(**self.identity).observe(
                accepted / proposed
            )

    def observe_prefix_hit(self, cached_tokens: int):
        self.prefix_cache_hits.labels(**self.identity).inc()
        self.prefix_cache_cached_tokens.labels(**self.identity).inc(
            cached_tokens
        )

    def inc_prefix_evictions(self, n: int = 1):
        self.prefix_cache_evictions.labels(**self.identity).inc(n)

    def inc_prefix_l2(self, kind: str):
        counter = {
            "hit": self.prefix_cache_l2_hits,
            "spill": self.prefix_cache_l2_spills,
            "evict": self.prefix_cache_l2_evictions,
        }.get(kind)
        if counter is not None:
            counter.labels(**self.identity).inc()

    # -- device telemetry (families exist only with deviceTelemetry on) ------

    def observe_hbm_component(self, component: str, nbytes: int):
        if self.device_hbm_bytes is not None:
            self.device_hbm_bytes.labels(
                **self.identity, component=component
            ).set(nbytes)

    def observe_collective(self, op: str, seconds: float):
        if self.engine_collective_seconds is not None:
            self.engine_collective_seconds.labels(
                **self.identity, op=op
            ).inc(seconds)

    def observe_device_util(self, kind: str, mfu: float, bw_util: float):
        if self.device_mfu is not None:
            self.device_mfu.labels(**self.identity, kind=kind).set(mfu)
            self.device_hbm_bw_util.labels(**self.identity, kind=kind).set(
                bw_util
            )

    def observe_compile(self, op: str, seconds: float):
        if self.compile_seconds is not None:
            self.compile_seconds.labels(**self.identity, op=op).inc(seconds)

    def observe_compile_cache(self, hit: bool):
        if self.compile_cache_hits is not None:
            (self.compile_cache_hits if hit else self.compile_cache_misses
             ).labels(**self.identity).inc()

    _LOAD_STAGES = {
        "disk_s": "disk",
        "transfer_s": "transfer",
        "quantize_s": "quantize",
        "shard_s": "shard",
        "restore_s": "restore",
        "wall_s": "total",
    }

    def observe_model_load(self, stats: dict):
        """Export a loader ``load_stats`` breakdown (stage keys absent
        from the stats simply don't materialize children)."""
        for key, stage in self._LOAD_STAGES.items():
            if stats.get(key) is not None:
                self.model_load_seconds.labels(
                    **self.identity, stage=stage
                ).set(float(stats[key]))

    def observe_cold_start(self, stage: str, seconds: float):
        self.cold_start_seconds.labels(**self.identity, stage=stage).set(
            max(0.0, float(seconds))
        )

    def inc_generated_tokens(self, n: int = 1):
        # Separate from observe_decode_step: the first token of every
        # sequence comes from prefill, not a decode tick.
        self.generated_tokens.labels(**self.identity).inc(n)

    def exposition(self) -> bytes:
        return generate_latest(self.registry)
