"""HTTP inference server: V2 (kfserving) + Seldon protocol + Prometheus.

Serves the protocols the reference's stack expects — the SeldonDeployment
declares ``protocol: kfserving`` (``mlflow_operator.py:235``), i.e. the V2
dataplane, and Istio routes raw HTTP between predictor versions — while
exporting the gate-compatible metrics (see ``metrics.py``).

Endpoints:
- ``GET  /v2/health/live``, ``GET /v2/health/ready``
- ``GET  /v2/models/{name}``, ``GET /v2/models/{name}/ready``
- ``POST /v2/models/{name}/infer``      (V2 JSON tensors)
- ``POST /api/v1.0/predictions``        (Seldon ndarray compat)
- ``GET  /metrics``                      (Prometheus exposition)

Single-example requests are cross-request batched by the dynamic batcher;
client-batched requests run directly.
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import json
import logging
import os
import re
import time
import uuid
from typing import Any

import numpy as np
from aiohttp import web

from ..utils.config import ServerConfig, TpuSpec
from .batching import DynamicBatcher
from .engine import InferenceEngine
from .generation import EngineOverloaded, PoisonRequest
from .loader import load_predictor
from .metrics import ServerMetrics

_log = logging.getLogger(__name__)
# One structured completion line per generation request (request-id
# correlated; --log-format json emits it as a machine-parseable object).
_req_log = logging.getLogger("tpumlops.request")

# W3C traceparent: version-traceid-spanid-flags; the 32-hex trace id is
# the request identity we adopt (so spans correlate across the mesh) and
# the 16-hex span id is the immediate parent (with the router's journey
# ring on: the router's per-leg span).
_TRACEPARENT = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$"
)


def trace_context_from_headers(headers) -> tuple[str, str]:
    """``(trace_id, parent_span)`` from a well-formed ``traceparent``
    header, or ``("", "")`` — the engine ``RequestTrace`` then carries
    the propagated context so a fleet stitcher can join this replica's
    spans to the router journey that produced them."""
    m = _TRACEPARENT.match(headers.get("traceparent", "").strip().lower())
    if m:
        return m.group(1), m.group(2)
    return "", ""


def request_id_from_headers(headers) -> str:
    """Inbound request identity: ``X-Request-Id`` verbatim, else the W3C
    ``traceparent`` trace id, else a fresh uuid4 hex.  Always echoed back
    as ``X-Request-Id`` so clients (and the router's access logs) can
    correlate a slow response with the server's completion line and the
    flight recorder's span."""
    # Bound + sanitize: the id lands in log lines and trace JSON.  An id
    # that sanitizes to nothing falls through to the next source — an
    # empty identity would make the request uncorrelatable.
    rid = "".join(
        c for c in headers.get("X-Request-Id", "").strip()[:128]
        if c.isprintable()
    )
    if rid:
        return rid
    tp = headers.get("traceparent", "").strip().lower()
    m = _TRACEPARENT.match(tp)
    if m:
        return m.group(1)
    return uuid.uuid4().hex


@web.middleware
async def request_id_middleware(request: web.Request, handler):
    rid = request["request_id"] = request_id_from_headers(request.headers)
    request["trace_id"], request["parent_span"] = trace_context_from_headers(
        request.headers
    )
    try:
        resp = await handler(request)
    except web.HTTPException as exc:
        # Router 404/405 and 413-over-max-size are raised, not returned
        # — exactly the responses a client most needs to correlate.
        exc.headers.setdefault("X-Request-Id", rid)
        raise
    # A streaming response has already sent its status line (its headers
    # carry the id from _stream_generation); everything else gets the
    # echo here, errors included.
    if not getattr(resp, "prepared", False):
        resp.headers.setdefault("X-Request-Id", rid)
    return resp

_V2_TO_NP = {
    "FP32": np.float32,
    "FP64": np.float64,
    "FP16": np.float16,
    "BF16": np.float32,  # JSON carries floats; cast happens model-side
    "INT32": np.int32,
    "INT64": np.int64,
    "UINT8": np.uint8,
    "BOOL": np.bool_,
}
_NP_TO_V2 = {
    np.dtype(np.float32): "FP32",
    np.dtype(np.float64): "FP64",
    np.dtype(np.float16): "FP16",
    np.dtype(np.int32): "INT32",
    np.dtype(np.int64): "INT64",
    np.dtype(np.uint8): "UINT8",
    np.dtype(np.bool_): "BOOL",
}


# Recognized /generate parameters.  Unknown keys 400 instead of being
# silently ignored — a typo'd knob ("max_new_token") quietly generating
# the default is the worst failure mode for a client.  The check itself
# is the CRD-side unknown-key rejection (utils/config), so the error
# contract (key named + allowed set) stays spelled once.
_GEN_PARAM_KEYS = frozenset(
    {"max_new_tokens", "eos_id", "temperature", "top_k", "top_p", "seed",
     "stream", "debug", "slo_class"}
)


def _check_gen_params(params: dict, allowed: frozenset) -> None:
    from ..utils.config import _reject_unknown_keys

    _reject_unknown_keys(params, allowed, "generate parameters")


# Capture directories kept under /tmp/tpumlops-profile: a device trace
# is tens of MB, the endpoint is unauthenticated, and nothing else ever
# cleaned the path — the newest N stay, older ones are deleted after
# each successful capture.
PROFILE_KEEP_DIRS = 8


def _gc_profile_dirs(root: str, keep: int = PROFILE_KEEP_DIRS) -> list:
    """Delete all but the ``keep`` newest capture dirs under ``root``;
    returns the deleted directory names (the ``evicted`` response
    field).  Best-effort: a dir that vanishes mid-walk is skipped, never
    an endpoint error — GC must not fail a successful capture."""
    import shutil

    try:
        entries = [
            e for e in os.scandir(root) if e.is_dir(follow_symlinks=False)
        ]
    except OSError:
        return []
    def _mtime(entry) -> float:
        try:
            return entry.stat().st_mtime
        except OSError:
            return 0.0

    entries.sort(key=_mtime, reverse=True)
    evicted = []
    for entry in entries[keep:]:
        try:
            shutil.rmtree(entry.path)
            evicted.append(entry.name)
        except OSError:
            continue
    return evicted


class TpuInferenceServer:
    def __init__(
        self,
        engine: InferenceEngine | None,
        metrics: ServerMetrics,
        model_name: str,
        max_batch_size: int = 32,
        max_batch_delay_ms: float = 5.0,
        gen_engine=None,
        max_inflight_batches: int = 2,
        recorder=None,
        drain_grace_s: float = 20.0,
        telemetry=None,
        attach_fn=None,
        cold_start_anchor_wall: float | None = None,
        fleet_role: str = "unified",
        snapshot_dir=None,
        timeseries=None,
    ):
        self.engine = engine
        self.metrics = metrics
        self.model_name = model_name
        # Single source of truth for the serving lifecycle: loading ->
        # ready -> draining -> shutdown, plus "warm-pool" — booted,
        # compile-swept, but holding NO weights until /admin/attach.
        # /readyz, /v2/health/ready (the manifest's readiness-probe path
        # — same handler), the drain protocol, and the SIGTERM path all
        # read/write THIS field; there is no second "ready" boolean
        # anywhere to fall out of sync.
        self.lifecycle = "loading"
        self.drain_grace_s = float(drain_grace_s)
        # Set by the SIGTERM path: the process is irrevocably exiting,
        # so a drain can no longer be cancelled (an unauthenticated
        # cancel re-opening admissions on a dying pod would route fresh
        # traffic straight into the teardown's EngineShutdown).
        self.terminating = False
        self.gen_engine = gen_engine  # GenerationEngine for causal-LM flavors
        self.recorder = recorder  # flight_recorder.FlightRecorder | None
        self.telemetry = telemetry  # device_telemetry.DeviceTelemetry | None
        self.timeseries = timeseries  # timeseries.TimeseriesRing | None
        # Warm-pool seam: builds (engine, gen_engine, predictor) for a
        # model URI on demand — None on a normal (model-at-boot) server.
        self.attach_fn = attach_fn
        self.predictor = None  # set by attach (release target on replace)
        # Attached-model identity contract (warm-pool only): what is on
        # the device right now, echoed by /readyz and /admin/attach so a
        # multiplexing bin-packer can prove convergence (and skip swaps
        # that would restore identical weights) without device access.
        self.snapshot_dir = snapshot_dir
        self.attached_model_uri: str | None = None
        self.attached_snapshot_hash: str | None = None
        self._attached_geometry: dict | None = None
        self._batch_geometry = (max_batch_size, max_batch_delay_ms,
                                max_inflight_batches)
        # Wall-clock anchor of the current cold start (wake signal time
        # when known, else boot time); the first token served after it
        # closes the tpumlops_cold_start_seconds ladder.
        self._cold_anchor_wall = cold_start_anchor_wall
        # Disaggregated-fleet role (unified | prefill | decode):
        # advisory identity on /readyz and log lines — the router's
        # role-tagged backend table decides who exports/imports KV.
        self.fleet_role = fleet_role
        import threading

        self._profile_lock = threading.Lock()
        self._attach_lock = asyncio.Lock()
        self.batcher = None
        if engine is not None:
            self._wire_batcher(engine)

    def _wire_batcher(self, engine) -> None:
        # Pipelined when the engine supports async dispatch (the jit
        # tier): batch N+1 stacks/dispatches while N executes on device.
        max_batch_size, max_batch_delay_ms, max_inflight = (
            self._batch_geometry
        )
        has_async = hasattr(engine, "predict_async")
        self.batcher = DynamicBatcher(
            run_batch=engine.predict_async if has_async else engine.predict,
            max_batch_size=max_batch_size,
            max_batch_delay_ms=max_batch_delay_ms,
            on_batch=self.metrics.observe_batch,
            materialize=engine.materialize if has_async else None,
            max_inflight=max_inflight,
        )

    def _not_attached(self, request: web.Request) -> web.Response | None:
        """Typed 503 while a warm-pool replica holds no model (clients
        retry after the operator attaches one).  Carries the request id
        like every typed error body — a shed must stay correlatable
        with the router journey when client stacks drop headers."""
        if self.engine is not None:
            return None
        return web.json_response(
            {
                "error": "no model attached to this warm-pool replica",
                "reason": "warm_pool_empty",
                "retry_after_s": 5,
                "request_id": request.get("request_id", ""),
            },
            status=503,
            headers={"Retry-After": "5"},
        )

    def _snapshot_probe(
        self, model_uri: str
    ) -> tuple[str | None, dict | None]:
        """Best-effort (content_hash, geometry) of ``model_uri``'s
        on-disk snapshot — (None, None) when there is no snapshot yet
        (first attach of a raw model writes one during the load)."""
        if not self.snapshot_dir:
            return None, None
        try:
            from . import snapshot as _snap

            spath = _snap.snapshot_path_for(self.snapshot_dir, model_uri)
            if not (spath / _snap.MANIFEST_NAME).exists():
                return None, None
            manifest = _snap.read_manifest(spath)
            geom = manifest.get("config")
            return (
                manifest.get("content_hash"),
                dict(geom) if isinstance(geom, dict) else None,
            )
        except Exception:
            return None, None

    def note_first_token(self) -> None:
        """First token served since the cold-start anchor: close the
        tpumlops_cold_start_seconds ladder (one-shot per boot/attach)."""
        anchor = self._cold_anchor_wall
        if anchor is None:
            return
        self._cold_anchor_wall = None
        self.metrics.observe_cold_start("first_token", time.time() - anchor)

    # -- lifecycle -----------------------------------------------------------

    @property
    def ready(self) -> bool:
        """Back-compat view of the lifecycle (probes read this)."""
        return self.lifecycle == "ready"

    @ready.setter
    def ready(self, value: bool) -> None:
        # Legacy writers (SIGTERM path, tests) flip a boolean; map it
        # onto the lifecycle without ever resurrecting a shutdown server.
        if value:
            self.lifecycle = "ready"
        elif self.lifecycle == "ready":
            self.lifecycle = "draining"

    def startup(self, warmup: bool = True) -> None:
        if self.engine is None:
            # Warm-pool boot: compile programs are pre-baked (see
            # prewarm_from_snapshot) but there are no weights to serve —
            # readiness stays down until /admin/attach.
            self.lifecycle = "warm-pool"
            self.metrics.ready.labels(**self.metrics.identity).set(0)
            return
        if warmup:
            self.engine.warmup()
        if self.gen_engine is not None:
            self.gen_engine.start(warmup=warmup)
        self.batcher.start()
        self.lifecycle = "ready"
        self.metrics.ready.labels(**self.metrics.identity).set(1)

    def begin_drain(self) -> None:
        """Enter the lossless-drain state: readiness flips (kubelet and
        balancers stop routing here), the generation engine sheds NEW
        submissions with 429 + Retry-After, and everything already
        admitted — queued, mid-prefill, decoding, streaming — runs to
        completion.

        Idempotent, and deliberately NOT guarded on lifecycle ==
        "draining": the SIGTERM path flips ``ready = False`` first (the
        endpoint-removal lag keeps ADMITTING while NotReady), which
        already reads as "draining" — an early-return there would skip
        arming the engine and the drain would never shed or complete.
        Only a shut-down server is past draining."""
        if self.lifecycle == "shutdown":
            return
        self.lifecycle = "draining"
        self.metrics.ready.labels(**self.metrics.identity).set(0)
        if self.gen_engine is not None:
            self.gen_engine.begin_drain()

    def cancel_drain(self) -> bool:
        """Reverse a drain (``POST /admin/drain {"cancel": true}``): the
        engine admits again and readiness returns.  The escape hatch
        that keeps the unauthenticated drain endpoint from being a
        one-way kill switch — a stray or mistaken drain is repairable
        without a pod restart.  Refused (False) once the process is
        terminating (SIGTERM already committed to exit) or shut down."""
        if self.terminating:
            return False
        if self.lifecycle != "draining":
            return self.lifecycle == "ready"
        if self.gen_engine is not None:
            self.gen_engine.cancel_drain()
        self.lifecycle = "ready"
        self.metrics.ready.labels(**self.metrics.identity).set(1)
        return True

    def note_watchdog_stall(self, kind: str, age_s: float, inventory) -> None:
        """Watchdog monitor-thread callback: a scheduler tick exceeded
        the deadline (hung XLA dispatch / wedged device).  Flip
        ``/readyz`` unready so balancers route elsewhere, count the
        stall, and journal the in-flight picture — the flight-recorder
        event is what lets an operator attribute the wedge to a tick
        kind and slot set after the pod restarts."""
        if self.lifecycle == "ready":
            self.lifecycle = "stalled"
            self.metrics.ready.labels(**self.metrics.identity).set(0)
        self.metrics.inc_watchdog_stall()
        if self.recorder is not None:
            self.recorder.event(
                "", "watchdog",
                kind=kind, age_s=round(float(age_s), 3),
                slots=list(inventory),
            )

    def note_watchdog_recover(self) -> None:
        """The stalled tick completed after all (transient contention, a
        pathological compile): re-ready — unless a drain/shutdown landed
        meanwhile, whose state must win."""
        if self.lifecycle == "stalled":
            self.lifecycle = "ready"
            self.metrics.ready.labels(**self.metrics.identity).set(1)

    async def wait_drained(self, grace_s: float | None = None) -> bool:
        """Await in-flight completion (bounded by ``grace_s``); True when
        the engine owes no sequence another token."""
        grace = self.drain_grace_s if grace_s is None else float(grace_s)
        deadline = time.monotonic() + max(0.0, grace)
        while True:
            if self.gen_engine is None or self.gen_engine.drained():
                return True
            if time.monotonic() >= deadline:
                return False
            await asyncio.sleep(0.05)

    def shutdown(self) -> None:
        self.lifecycle = "shutdown"
        if self.telemetry is not None:
            # Stop the process-global compile listeners attributing into
            # this (now retired) server's observatory and metrics.
            from ..utils.compile_cache import detach_observatory

            detach_observatory(self.telemetry.observatory)
        if self.batcher is not None:
            self.batcher.stop()
        if self.gen_engine is not None:
            self.gen_engine.shutdown()
        if hasattr(self.engine, "shutdown"):
            # multi-host leader: release follower processes after the
            # batcher has drained (no more broadcasts can follow)
            self.engine.shutdown()

    # -- request handling ----------------------------------------------------

    async def _run(self, inputs: dict[str, np.ndarray]) -> Any:
        """Dispatch: batch-1 via the dynamic batcher, larger directly —
        but always through the warmed power-of-two buckets, never a raw
        client batch size (each distinct shape is an XLA compile)."""
        seq_pad = getattr(self.engine.predictor, "seq_pad", None)
        if seq_pad:
            from .batching import apply_seq_pad

            inputs = apply_seq_pad(inputs, seq_pad)
        batch = next(iter(inputs.values())).shape[0]
        if batch == 1:
            single = {k: v[0] for k, v in inputs.items()}
            fut = self.batcher.submit(single)
            out = await asyncio.wrap_future(fut)
            return _add_batch_dim(out)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, self._predict_bucketed, inputs)

    def _predict_bucketed(self, inputs: dict[str, np.ndarray]) -> Any:
        """Pad a client batch up to the nearest warmed bucket (chunking
        batches larger than max_batch_size), then slice back."""
        from .batching import next_bucket

        batch = next(iter(inputs.values())).shape[0]
        cap = self.batcher.max_batch_size
        chunks_out = []
        for start in range(0, batch, cap):
            chunk = {k: v[start : start + cap] for k, v in inputs.items()}
            n = next(iter(chunk.values())).shape[0]
            bucket = next_bucket(n, cap)
            if bucket > n:
                chunk = {
                    k: np.concatenate([v, np.repeat(v[-1:], bucket - n, axis=0)])
                    for k, v in chunk.items()
                }
            out = self.engine.predict(chunk)
            chunks_out.append(_slice_batch(out, n))
        return _concat_batches(chunks_out)

    async def handle_v2_infer(self, request: web.Request) -> web.Response:
        err = self._not_attached(request)
        if err is not None:
            return err
        t0 = time.perf_counter()
        code = 200
        try:
            body = await request.json()
            inputs: dict[str, np.ndarray] = {}
            for tensor in body.get("inputs", []):
                dt = _V2_TO_NP.get(tensor.get("datatype", "FP32"))
                if dt is None:
                    raise ValueError(f"unsupported datatype {tensor.get('datatype')}")
                arr = np.asarray(tensor["data"], dtype=dt).reshape(tensor["shape"])
                inputs[tensor["name"]] = arr
            if not inputs:
                raise ValueError("request has no inputs")
            out = await self._run(inputs)
            outputs = _to_v2_outputs(out)
            return web.json_response(
                {
                    "model_name": self.model_name,
                    "id": body.get("id", ""),
                    "outputs": outputs,
                }
            )
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            code = 400
            return web.json_response(
                {"error": str(e), "request_id": request.get("request_id", "")},
                status=400,
            )
        except Exception as e:  # model/runtime failure
            _log.exception("inference failed")
            code = 500
            return web.json_response(
                {"error": str(e), "request_id": request.get("request_id", "")},
                status=500,
            )
        finally:
            self.metrics.observe_request(time.perf_counter() - t0, code=code)

    async def handle_seldon_predict(self, request: web.Request) -> web.Response:
        """Seldon-protocol compatibility (``{"data": {"ndarray": ...}}``)."""
        err = self._not_attached(request)
        if err is not None:
            return err
        t0 = time.perf_counter()
        code = 200
        try:
            body = await request.json()
            data = body.get("data", {})
            if "ndarray" in data:
                arr = np.asarray(data["ndarray"], dtype=np.float32)
            elif "tensor" in data:
                t = data["tensor"]
                arr = np.asarray(t["values"], np.float32).reshape(t["shape"])
            else:
                raise ValueError("data.ndarray or data.tensor required")
            out = await self._run({"x": arr})
            out_arr = np.asarray(out if not isinstance(out, tuple) else out[0])
            return web.json_response(
                {"data": {"ndarray": out_arr.tolist()}, "meta": {}}
            )
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            code = 400
            return web.json_response(
                {"error": str(e), "request_id": request.get("request_id", "")},
                status=400,
            )
        except Exception as e:
            _log.exception("inference failed")
            code = 500
            return web.json_response(
                {"error": str(e), "request_id": request.get("request_id", "")},
                status=500,
            )
        finally:
            self.metrics.observe_request(time.perf_counter() - t0, code=code)

    async def handle_feedback(self, request: web.Request) -> web.Response:
        """Seldon feedback API (``/api/v1.0/feedback``).

        The reference's metric collector counts these per predictor
        (``mlflow_operator.py:410-415``, ``service="feedback"``) — in the
        reference stack Seldon's executor serves the route; here the
        first-party data plane does.  The body is the Seldon shape
        ``{"request": .., "response": .., "reward": r, "truth": ..}``;
        the count (and reward sum) is the product — feedback is reward
        signal, not inference, so nothing is recomputed.
        """
        t0 = time.perf_counter()
        code = 200
        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise ValueError("feedback body must be a JSON object")
            reward = body.get("reward", 0.0)
            if not isinstance(reward, (int, float)):
                raise ValueError("reward must be a number")
            self.metrics.observe_feedback_reward(float(reward))
            return web.json_response({"meta": {}})
        except (ValueError, TypeError, json.JSONDecodeError) as e:
            code = 400
            return web.json_response(
                {"error": str(e), "request_id": request.get("request_id", "")},
                status=400,
            )
        except Exception as e:
            _log.exception("feedback handling failed")
            code = 500
            return web.json_response(
                {"error": str(e), "request_id": request.get("request_id", "")},
                status=500,
            )
        finally:
            self.metrics.observe_request(
                time.perf_counter() - t0, code=code, service="feedback"
            )

    async def handle_generate(self, request: web.Request) -> web.Response:
        """Text generation with continuous batching (causal-LM flavors only).

        Accepts either the simple form ``{"prompt_ids": [[...]], "max_new_tokens": N,
        "eos_id": E?}`` (``prompt_ids`` may be one sequence or a list of
        sequences) or a V2-style tensor ``{"inputs": [{"name": "prompt_ids",
        ...}], "parameters": {"max_new_tokens": N}}``.  Sequences in one
        request are scheduled independently — they share decode steps with
        every other in-flight request, not just each other.
        """
        err = self._not_attached(request)
        if err is not None:
            return err
        t0 = time.perf_counter()
        code = 200
        # Multiplexed warm pool: the wildcard route carries the model id
        # the router addressed; it keys the per-model admission share so
        # a flooded hot model sheds at its share instead of filling the
        # whole queue against the tail models.  The literal (boot-name)
        # route has no mux_model — the ledger stays untouched there.
        mux_model = request.match_info.get("mux_model")
        mux_reserved = 0
        try:
            if self.gen_engine is None:
                code = 400
                return web.json_response(
                    {"error": f"model {self.model_name} is not a causal LM"},
                    status=400,
                )
            body = await request.json()
            if "inputs" in body:
                tensors = {
                    t["name"]: np.asarray(t["data"], np.int32).reshape(t["shape"])
                    for t in body["inputs"]
                }
                if "prompt_ids" not in tensors:
                    raise ValueError('missing input tensor "prompt_ids"')
                rows = tensors["prompt_ids"]
                if "lengths" in tensors:
                    # Explicit per-row lengths disambiguate right-padding
                    # from legitimate trailing 0 tokens.
                    lens = tensors["lengths"].reshape(-1)
                    if lens.size != rows.shape[0]:
                        raise ValueError(
                            f'"lengths" has {lens.size} entries for '
                            f"{rows.shape[0]} prompt rows"
                        )
                    prompts = [row[: int(n)] for row, n in zip(rows, lens)]
                else:
                    # Fallback: strip trailing zeros (document: send
                    # "lengths" if 0 is a real token in your vocabulary).
                    prompts = [np.trim_zeros(row, "b") for row in rows]
                params = body.get("parameters", {})
                _check_gen_params(params, _GEN_PARAM_KEYS)
            else:
                raw = body["prompt_ids"]
                prompts = [raw] if raw and np.isscalar(raw[0]) else list(raw)
                params = body
                _check_gen_params(
                    params, _GEN_PARAM_KEYS | {"prompt_ids", "id"}
                )
            if not prompts:  # covers both forms (zero-row tensor, empty list)
                raise ValueError("prompt_ids is empty")
            max_new = int(params.get("max_new_tokens", 16))
            eos_id = params.get("eos_id")
            eos_id = int(eos_id) if eos_id is not None else None
            seed = params.get("seed")
            sampling = {
                "temperature": float(params.get("temperature", 0.0)),
                "top_k": int(params.get("top_k", 0)),
                "top_p": float(params.get("top_p", 1.0)),
                "seed": int(seed) if seed is not None else None,
            }
            # Per-request SLO class override (falls back to the engine's
            # --slo-class default when absent).  Validated here so a typo
            # 400s before any sibling is admitted.
            slo_class = params.get("slo_class")
            if slo_class is not None:
                slo_class = str(slo_class)
                from .generation import SLO_CLASSES

                if slo_class not in SLO_CLASSES:
                    raise ValueError(
                        f"slo_class {slo_class!r} not in {SLO_CLASSES}"
                    )
            # Validate every prompt BEFORE admitting any: a bad sibling must
            # not leave earlier ones generating into abandoned futures.
            prompts = [
                self.gen_engine.validate(
                    p,
                    max_new,
                    sampling["temperature"],
                    sampling["top_k"],
                    sampling["top_p"],
                    sampling["seed"],
                )
                for p in prompts
            ]

            def row_seed(i: int) -> int | None:
                # Distinct stream per row, reproducible from the request
                # seed: identical prompts sampled in one batch must differ.
                base = sampling["seed"]
                return None if base is None else (base + i) % (2**63)

            rid = request.get("request_id") or request_id_from_headers(
                request.headers
            )
            debug = bool(params.get("debug", False))
            if params.get("stream"):
                if len(prompts) != 1:
                    raise ValueError("stream=true supports exactly one prompt")
                codebox = {"code": 200}
                try:
                    return await self._stream_generation(
                        request, prompts[0], max_new, eos_id, sampling,
                        codebox, rid, slo_class=slo_class,
                    )
                finally:
                    code = codebox["code"]
            from .flight_recorder import RequestTrace

            # Admission control: reserve the WHOLE request's estimated
            # tokens up front, so it is admitted whole or shed whole —
            # a 429 must never leave earlier siblings generating into
            # abandoned futures.  Raises EngineOverloaded (-> 429 below)
            # before anything is enqueued.
            est_total = sum(int(p.size) + max_new for p in prompts)
            self.gen_engine.reserve_admission(
                est_total, slo_class=slo_class, model=mux_model,
            )
            if mux_model:
                mux_reserved = est_total
            traces = [
                RequestTrace(
                    request_id=rid if len(prompts) == 1 else f"{rid}/{i}",
                    trace_id=request.get("trace_id", ""),
                    parent_span=request.get("parent_span", ""),
                )
                for i in range(len(prompts))
            ]
            _stamp_handoff(request, traces)
            futures = [
                self.gen_engine.submit(
                    p, max_new, eos_id,
                    **{**sampling, "seed": row_seed(i)},
                    request_id=traces[i].request_id,
                    trace=traces[i],
                    est_reserved=True,
                    slo_class=slo_class,
                )
                for i, p in enumerate(prompts)
            ]
            outs = await asyncio.gather(
                *(asyncio.wrap_future(f) for f in futures)
            )
            self.note_first_token()
            summary = _timing_summary(rid, traces)
            self._log_completion(summary, code=200)
            payload = {
                "model_name": self.model_name,
                "id": body.get("id", ""),
                "outputs": [
                    {
                        "name": f"output_ids_{i}",
                        "datatype": "INT32",
                        "shape": [int(o.size)],
                        "data": o.tolist(),
                    }
                    for i, o in enumerate(outs)
                ],
            }
            if debug:
                payload["timing"] = summary
            return web.json_response(payload)
        except EngineOverloaded as e:
            # Shed contract: 429 + Retry-After, body naming the typed
            # reason ("budget" under load, "draining" during scale-down
            # / shutdown) AND the request id — a shed body must be
            # correlatable with the router journey / access-log line
            # without header access (many client stacks drop headers on
            # error paths).  Nothing reached the engine — clients retry
            # verbatim on another replica.
            code = 429
            body = {
                "error": str(e),
                "reason": e.reason,
                "retry_after_s": e.retry_after_s,
                "request_id": request.get("request_id", ""),
            }
            # Per-class sheds name the class so dashboards (and clients)
            # can tell best-effort load-shedding from real overload.
            if e.slo_class is not None:
                body["slo_class"] = e.slo_class
            return web.json_response(
                body,
                status=429,
                headers={"Retry-After": str(e.retry_after_s)},
            )
        except PoisonRequest as e:
            # Quarantine contract: 422, NOT 4xx-retryable — the prompt
            # itself crashes admission, so a retry (here or on any other
            # replica) would crash it too.  No Retry-After on purpose.
            code = 422
            return web.json_response(
                {
                    "error": str(e),
                    "reason": "poison_quarantined",
                    "fingerprint": e.fingerprint,
                    "crashes": e.crashes,
                    "request_id": request.get("request_id", ""),
                },
                status=422,
            )
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            code = 400
            return web.json_response(
                {"error": str(e), "request_id": request.get("request_id", "")},
                status=400,
            )
        except Exception as e:
            _log.exception("generation failed")
            code = 500
            return web.json_response(
                {"error": str(e), "request_id": request.get("request_id", "")},
                status=500,
            )
        finally:
            if mux_reserved and self.gen_engine is not None:
                self.gen_engine.release_model_admission(
                    mux_model, mux_reserved
                )
            self.metrics.observe_request(time.perf_counter() - t0, code=code)

    async def _stream_generation(
        self, request, prompt, max_new, eos_id, sampling, codebox,
        request_id: str = "", slo_class: str | None = None,
    ) -> web.StreamResponse:
        """SSE token stream: one ``data:`` event per token, then a final
        event with the full sequence.  Client disconnect cancels the
        request's future, which frees its engine slot at the next tick.

        The HTTP status line is committed as 200 before the outcome is
        known, so the gate-visible request metric takes ``codebox["code"]``
        instead (500 on engine failure, 499 on cancel/disconnect): a broken
        engine serving only streams must still trip the canary gate's
        error-rate query."""
        from .flight_recorder import RequestTrace

        loop = asyncio.get_running_loop()
        tokens: asyncio.Queue = asyncio.Queue()

        def on_token(t: int) -> None:  # scheduler thread -> event loop
            loop.call_soon_threadsafe(tokens.put_nowait, int(t))

        trace = RequestTrace(
            request_id=request_id,
            trace_id=request.get("trace_id", ""),
            parent_span=request.get("parent_span", ""),
        )
        _stamp_handoff(request, [trace])
        fut = self.gen_engine.submit(
            prompt, max_new, eos_id, **sampling, on_token=on_token,
            request_id=request_id, trace=trace, slo_class=slo_class,
        )
        fut.add_done_callback(
            lambda f: loop.call_soon_threadsafe(tokens.put_nowait, None)
        )
        resp = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
                # The status line commits before the middleware could add
                # the echo, so the stream carries it itself.
                "X-Request-Id": request_id,
            }
        )
        await resp.prepare(request)
        emitted: list[int] = []
        try:
            while True:
                item = await tokens.get()
                if item is None:
                    break
                emitted.append(item)
                if len(emitted) == 1:
                    self.note_first_token()
                payload = json.dumps({"index": len(emitted) - 1, "token": item})
                await resp.write(f"data: {payload}\n\n".encode())
            if fut.cancelled():
                codebox["code"] = 499
                await _write_sse_error(
                    resp, request_id, "cancelled", "generation cancelled"
                )
            elif fut.exception() is not None:
                codebox["code"] = 500
                await _write_sse_error(
                    resp, request_id, "engine_failed", str(fut.exception())
                )
            else:
                final = {"done": True, "output_ids": fut.result().tolist()}
                await resp.write(f"data: {json.dumps(final)}\n\n".encode())
        except (ConnectionError, OSError):
            # Client/transport went away mid-stream: free the engine slot
            # and end quietly (the outer handler must not try to write JSON
            # to a response that already started streaming).
            fut.cancel()
            codebox["code"] = 499
        except asyncio.CancelledError:
            fut.cancel()  # frees the slot at the next scheduler tick
            codebox["code"] = 499
            raise
        except Exception as e:
            # Anything else: still cancel (or the slot decodes to
            # max_new_tokens for nobody) — the status line is out, so a
            # JSON error body can't be started, but a terminal SSE
            # ``error`` event usually still can: without it the client
            # sees a dropped connection and cannot tell truncation from
            # completion.
            _log.exception("stream failed mid-generation")
            fut.cancel()
            codebox["code"] = 500
            with contextlib.suppress(Exception):
                await _write_sse_error(
                    resp, request_id, "stream_failed", str(e)
                )
        finally:
            # A cancel frees the engine slot only at the NEXT scheduler
            # tick — finish the trace here (first writer wins: the
            # engine's own later finish becomes a no-op) so the 499/500
            # completion line never reports "in-flight" for exactly the
            # requests an operator most needs to attribute.
            if codebox["code"] != 200:
                trace.finish(
                    "cancelled" if codebox["code"] == 499 else "error"
                )
            self._log_completion(
                _timing_summary(request_id, [trace]), code=codebox["code"]
            )
            with contextlib.suppress(Exception):
                await resp.write_eof()
        return resp

    def _log_completion(self, summary: dict, code: int) -> None:
        """One structured completion line per generation request (the
        request-scoped counterpart of the aggregate histograms; carries
        ``request_id`` as a record attribute for the JSON log format)."""
        _req_log.info(
            "generate done request_id=%s code=%d rows=%d tokens=%d "
            "queue_ms=%s ttft_ms=%s prefill_chunks=%d cached_tokens=%d "
            "spec_accepted=%d/%d finish=%s",
            summary["request_id"],
            code,
            len(summary["rows"]),
            summary["tokens"],
            summary["queue_ms"],
            summary["ttft_ms"],
            summary["prefill_chunks"],
            summary["cached_tokens"],
            summary["spec_accepted"],
            summary["spec_proposed"],
            ",".join(summary["finish_reasons"]),
            extra={"request_id": summary["request_id"]},
        )

    async def handle_profile(self, request: web.Request) -> web.Response:
        """Capture a JAX/XLA device trace (SURVEY §5: the reference has no
        profiling anywhere; the TPU data plane gets ``jax.profiler``).

        ``POST /debug/profile {"duration_s": 3}`` records device + host
        activity for the window and returns the trace directory (TensorBoard
        / xprof readable; always under ``/tmp/tpumlops-profile`` — the
        endpoint is unauthenticated, so no caller-chosen paths).  One
        capture at a time.  After a successful capture only the newest
        :data:`PROFILE_KEEP_DIRS` capture directories are kept — older
        ones are deleted (the dir used to grow without bound across
        calls) and returned as ``evicted``."""
        import math

        import jax

        try:
            body = await request.json() if request.can_read_body else {}
            if not isinstance(body, dict):
                raise ValueError("request body must be a JSON object")
            duration = float(body.get("duration_s", 3.0))
            if not math.isfinite(duration):
                raise ValueError(f"duration_s must be finite, got {duration}")
            duration = min(max(duration, 0.1), 60.0)
            out_dir = f"/tmp/tpumlops-profile/{self.model_name}-{int(time.time())}"
            if not self._profile_lock.acquire(blocking=False):
                return web.json_response(
                    {"error": "a profile capture is already running"}, status=409
                )
            try:
                try:
                    jax.profiler.start_trace(out_dir)
                    await asyncio.sleep(duration)
                finally:
                    with contextlib.suppress(Exception):
                        # raises "no session" when start_trace itself failed
                        jax.profiler.stop_trace()
                evicted = _gc_profile_dirs("/tmp/tpumlops-profile")
            finally:
                self._profile_lock.release()
            return web.json_response(
                {
                    "trace_dir": out_dir,
                    "duration_s": duration,
                    "evicted": evicted,
                }
            )
        except (ValueError, TypeError, json.JSONDecodeError) as e:
            return web.json_response({"error": str(e)}, status=400)
        except Exception as e:
            _log.exception("profile capture failed")
            return web.json_response({"error": str(e)}, status=500)

    async def handle_metrics(self, request: web.Request) -> web.Response:
        return web.Response(
            body=self.metrics.exposition(),
            content_type="text/plain",
            charset="utf-8",
        )

    # -- flight recorder / span debug endpoints ------------------------------

    def _recorder_or_none(self) -> web.Response | None:
        if self.recorder is not None:
            return None
        return web.json_response(
            {
                "error": "flight recorder disabled; set "
                "spec.tpu.observability.traceRing (--trace-ring) > 0"
            },
            status=404,
        )

    async def _debug_json(self, build) -> web.Response:
        """Build + serialize a debug payload OFF the event loop: a full
        ring renders to megabytes of JSON, and a synchronous dumps here
        would stall /generate, health probes, and SSE mid-debugging —
        observation must not perturb serving."""
        loop = asyncio.get_running_loop()
        text = await loop.run_in_executor(None, lambda: json.dumps(build()))
        return web.Response(text=text, content_type="application/json")

    async def handle_debug_engine(self, request: web.Request) -> web.Response:
        """Live engine snapshot: tick/event/trace rings verbatim."""
        err = self._recorder_or_none()
        if err is not None:
            return err
        return await self._debug_json(self.recorder.snapshot)

    async def handle_debug_trace(self, request: web.Request) -> web.Response:
        """Chrome trace-event export (open in Perfetto: ui.perfetto.dev)."""
        err = self._recorder_or_none()
        if err is not None:
            return err
        fmt = request.query.get("format", "chrome")
        if fmt == "chrome":
            return await self._debug_json(self.recorder.chrome_trace)
        if fmt == "json":
            return await self._debug_json(self.recorder.snapshot)
        return web.json_response(
            {"error": f"unknown format {fmt!r}; use chrome or json"},
            status=400,
        )

    async def handle_debug_device(self, request: web.Request) -> web.Response:
        """Device telemetry snapshot: HBM ledger vs measured memory,
        per-tick-kind utilization, compile observatory (spec.tpu.
        observability.deviceTelemetry; 404 names the knob when off)."""
        if self.telemetry is None:
            return web.json_response(
                {
                    "error": "device telemetry disabled; set "
                    "spec.tpu.observability.deviceTelemetry "
                    "(--device-telemetry 1)"
                },
                status=404,
            )
        return await self._debug_json(self.telemetry.snapshot)

    async def handle_debug_timeseries(
        self, request: web.Request
    ) -> web.Response:
        """Per-second serving time-series ring (the anomaly detector's
        input plane; spec.tpu.observability.timeseriesRing; 404 names
        the knob when off)."""
        if self.timeseries is None:
            return web.json_response(
                {
                    "error": "timeseries ring disabled; set "
                    "spec.tpu.observability.timeseriesRing "
                    "(--timeseries-ring) > 0"
                },
                status=404,
            )
        return await self._debug_json(self.timeseries.snapshot)

    async def handle_debug_spans(self, request: web.Request) -> web.Response:
        """GLOBAL_TRACER span stats (count/mean/max per name) — the
        control-plane tracer finally readable off the data plane too."""
        from ..utils.tracing import GLOBAL_TRACER

        return web.json_response({"spans": GLOBAL_TRACER.as_dict()})

    async def handle_live(self, request: web.Request) -> web.Response:
        # Live through loading AND draining: kubelet must not kill a pod
        # that is busy finishing its in-flight request tail.
        return web.json_response(
            {"live": self.lifecycle != "shutdown", "lifecycle": self.lifecycle},
            status=200 if self.lifecycle != "shutdown" else 503,
        )

    async def handle_ready(self, request: web.Request) -> web.Response:
        """The lifecycle endpoint (``/readyz``; ``/v2/health/ready`` is
        the same handler, which is what the builder's readiness-probe
        stanza points at): 200 only in the ``ready`` state — loading,
        draining, and shutdown all 503 so balancers route elsewhere —
        with the state named in the body either way."""
        status = 200 if self.lifecycle == "ready" else 503
        body = {"ready": self.lifecycle == "ready", "lifecycle": self.lifecycle}
        if self.fleet_role != "unified":
            body["fleetRole"] = self.fleet_role
        if self.lifecycle == "draining" and self.gen_engine is not None:
            body["inFlight"] = self.gen_engine.inflight()
        if self.attach_fn is not None:
            # Attached-model report (warm-pool replicas only): the
            # multiplexer's bin-packer and the router's known-model sets
            # read WHAT is on the device, not just whether something is.
            body["model"] = self.attached_model_uri
            if self.attached_snapshot_hash is not None:
                body["snapshotHash"] = self.attached_snapshot_hash
        return web.json_response(body, status=status)

    async def handle_admin_drain(self, request: web.Request) -> web.Response:
        """``POST /admin/drain``: the lossless scale-down protocol.

        Stops admissions (new /generate requests shed 429 + Retry-After),
        flips ``/readyz`` to draining, then waits — bounded by
        ``grace_s`` (default ``--drain-grace-seconds``) — for every
        admitted sequence, SSE streams included, to finish.  Returns the
        final state; the caller (autoscaler teardown, preStop hook, an
        operator's kubectl) deletes the pod only after ``drained`` is
        true.  SIGTERM runs the same protocol.
        """
        try:
            body = await request.json() if request.can_read_body else {}
            if not isinstance(body, dict):
                raise ValueError("drain body must be a JSON object")
            grace = float(body.get("grace_s", self.drain_grace_s))
            if not (0.0 <= grace <= 3600.0):
                raise ValueError(
                    f"grace_s must be in [0, 3600], got {grace}"
                )
            cancel = bool(body.get("cancel", False))
        except (ValueError, TypeError, json.JSONDecodeError) as e:
            return web.json_response({"error": str(e)}, status=400)
        if cancel:
            restored = self.cancel_drain()
            return web.json_response(
                {"lifecycle": self.lifecycle, "cancelled": restored},
                status=200 if restored else 409,
            )
        self.begin_drain()
        drained = await self.wait_drained(grace)
        inflight = (
            self.gen_engine.inflight() if self.gen_engine is not None else 0
        )
        return web.json_response(
            {
                "lifecycle": self.lifecycle,
                "drained": drained,
                "inFlight": inflight,
            }
        )

    async def handle_admin_attach(self, request: web.Request) -> web.Response:
        """``POST /admin/attach``: snapshot-restore a model into a
        warm-pool replica (or swap the attached one with ``replace``).

        The warm-pool replica booted with the compile sweep already run
        against the persistent cache, so the attach path is: restore the
        pre-baked device tree (zero transform work) + deserialize the
        pre-baked executables + flip ``/readyz`` — the whole
        ``tpumlops_cold_start_seconds`` ladder minus the pod boot.

        Body: ``{"model_uri": "...", "replace": false,
        "wake_start_wall": <unix-seconds>?}`` — ``wake_start_wall`` is
        stamped by whoever decided to wake the CR, so the ladder's
        ``wake`` stage measures decision → attach receipt.
        """
        if self.attach_fn is None:
            return web.json_response(
                {
                    "error": "not a warm-pool server (boot with "
                    "--warm-pool 1 to attach models at runtime)"
                },
                status=400,
            )
        try:
            body = await request.json() if request.can_read_body else {}
            if not isinstance(body, dict):
                raise ValueError("attach body must be a JSON object")
            model_uri = body.get("model_uri")
            if not model_uri or not isinstance(model_uri, str):
                raise ValueError('attach requires "model_uri"')
            replace = bool(body.get("replace", False))
            wake_start = body.get("wake_start_wall")
            wake_start = float(wake_start) if wake_start is not None else None
        except (ValueError, TypeError, json.JSONDecodeError) as e:
            return web.json_response({"error": str(e)}, status=400)
        if self.terminating or self.lifecycle == "shutdown":
            return web.json_response(
                {"error": "server is terminating"}, status=409
            )
        async with self._attach_lock:
            req_hash, req_geom = self._snapshot_probe(model_uri)
            if (
                self.engine is not None
                and self.attached_model_uri == model_uri
                and req_hash is not None
                and self.attached_snapshot_hash == req_hash
            ):
                # Idempotent no-op: same uri AND same snapshot hash as
                # what is already on the device — a replace here would
                # drain in-flight work to restore identical weights,
                # a pointless swap the bin-packer would otherwise pay
                # on every convergence pass.
                return web.json_response(
                    {
                        "lifecycle": self.lifecycle,
                        "model_uri": model_uri,
                        "snapshot_hash": req_hash,
                        "noop": True,
                    }
                )
            if self.engine is not None and not replace:
                return web.json_response(
                    {
                        "error": "a model is already attached; pass "
                        '"replace": true to swap it',
                        "lifecycle": self.lifecycle,
                    },
                    status=409,
                )
            if (
                self.engine is not None
                and req_geom is not None
                and self._attached_geometry is not None
                and req_geom != self._attached_geometry
            ):
                # Geometry-incompatible replace: the incoming snapshot's
                # model dims differ from what this replica's compile
                # sweep was baked for — an attach would stall in a full
                # recompile, exactly what the warm pool exists to avoid.
                # Typed 409 BEFORE the quiesce: the attached model keeps
                # serving, and the bin-packer routes the swap to a
                # compatible (or empty) replica instead.
                return web.json_response(
                    {
                        "error": (
                            f"snapshot geometry of {model_uri} does not "
                            "match the attached model's compiled "
                            "programs"
                        ),
                        "reason": "geometry_incompatible",
                        "attached_model_uri": self.attached_model_uri,
                        "lifecycle": self.lifecycle,
                    },
                    status=409,
                )
            t_receipt = time.time()
            if wake_start is not None:
                self.metrics.observe_cold_start(
                    "wake", t_receipt - wake_start
                )
            # Local anchor for THIS attach's arithmetic: a request served
            # during the startup await below one-shots (and nulls) the
            # instance field via note_first_token — the ladder's "total"
            # must not race it.
            anchor = wake_start if wake_start is not None else t_receipt
            self._cold_anchor_wall = anchor
            loop = asyncio.get_running_loop()
            old_predictor = self.predictor
            if self.engine is not None:
                # Replace: quiesce the old engine before its tree is
                # freed (attach_fn releases the device buffers).
                if self.batcher is not None:
                    self.batcher.stop()
                if self.gen_engine is not None:
                    self.gen_engine.shutdown()
                self.lifecycle = "loading"
                self.metrics.ready.labels(**self.metrics.identity).set(0)
                self.engine = None
                self.gen_engine = None
                self.attached_model_uri = None
                self.attached_snapshot_hash = None
                self._attached_geometry = None
            try:
                load_stats: dict = {}
                attached = await loop.run_in_executor(
                    None,
                    lambda: self.attach_fn(
                        model_uri, old_predictor, load_stats
                    ),
                )
                self.predictor = attached["predictor"]
                self.gen_engine = attached.get("gen_engine")
                engine = attached["engine"]
                self._wire_batcher(engine)
                self.metrics.observe_model_load(load_stats)
                restored = load_stats.get("restore_s") is not None
                self.metrics.observe_cold_start(
                    "restore" if restored else "load",
                    load_stats.get("restore_s")
                    or load_stats.get("wall_s")
                    or 0.0,
                )
                t_warm = time.time()
                # startup() runs the warmup sweep — against the compile
                # cache the warm-pool boot already primed, so this is
                # executable deserialization, not compilation.
                self.engine = engine
                await loop.run_in_executor(
                    None, lambda: self.startup(warmup=True)
                )
                self.metrics.observe_cold_start(
                    "compile", time.time() - t_warm
                )
                self.metrics.observe_cold_start(
                    "total", time.time() - anchor
                )
                # Re-probe AFTER the load: a first attach of a raw
                # model writes its snapshot during load_predictor, so
                # the identity contract is complete from attach one.
                self.attached_model_uri = model_uri
                (
                    self.attached_snapshot_hash,
                    self._attached_geometry,
                ) = self._snapshot_probe(model_uri)
                if self.timeseries is not None:
                    # Baseline-reset stamp for the anomaly detector:
                    # drift is measured against the post-attach window.
                    self.timeseries.mark("attach")
            except Exception as e:
                _log.exception("attach of %s failed", model_uri)
                # Quiesce whatever got wired before the failure — a
                # half-attached engine left running would leak its
                # worker thread and device tree.
                if self.batcher is not None:
                    with contextlib.suppress(Exception):
                        self.batcher.stop()
                    self.batcher = None
                if self.gen_engine is not None:
                    with contextlib.suppress(Exception):
                        self.gen_engine.shutdown()
                self.engine = None
                self.gen_engine = None
                self.attached_model_uri = None
                self.attached_snapshot_hash = None
                self._attached_geometry = None
                self.lifecycle = "warm-pool"
                return web.json_response(
                    {"error": f"attach failed: {e}"}, status=500
                )
        return web.json_response(
            {
                "lifecycle": self.lifecycle,
                "model_uri": model_uri,
                "snapshot_hash": self.attached_snapshot_hash,
                "restored": restored,
                "load_breakdown_s": load_stats,
            }
        )

    # -- KV handoff (disaggregated prefill/decode fleets) --------------------

    def _kv_engine_or_error(
        self, request: web.Request
    ) -> tuple[object | None, web.Response | None]:
        """Common gating for the KV endpoints: attached causal-LM engine
        with the radix prefix cache on (the handoff unit IS its chunk)."""
        err = self._not_attached(request)
        if err is not None:
            return None, err
        if self.gen_engine is None:
            return None, web.json_response(
                {"error": f"model {self.model_name} is not a causal LM"},
                status=400,
            )
        if getattr(self.gen_engine, "_prefix_cache", None) is None:
            return None, web.json_response(
                {
                    "error": "KV handoff requires the radix prefix cache; "
                    "enable spec.tpu.prefixCache (--prefix-cache 1)",
                    "reason": "prefix_cache_disabled",
                },
                status=409,
            )
        return self.gen_engine, None

    async def handle_admin_kv_export(self, request: web.Request) -> web.Response:
        """``POST /admin/kv/export``: serialize a prompt's committed
        prefix K/V for handoff to a decode replica.

        Body is the generate shape (``{"prompt_ids": [...]}``); the
        response is one ``application/octet-stream`` handoff blob
        (``server/kv_transfer.py`` wire format) covering the prompt's
        whole-chunk prefix.  A prefix not yet in this replica's radix
        cache is prefilled first (one max_new_tokens=1 admission whose
        write-backs populate the cache) — that forward pass is the work
        the decode pool is NOT doing, which is the point."""
        from . import kv_transfer
        from .flight_recorder import RequestTrace

        engine, err = self._kv_engine_or_error(request)
        if err is not None:
            return err
        t0 = time.perf_counter()
        code = 200
        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise ValueError("export body must be a JSON object")
            raw = body.get("prompt_ids")
            if raw is None:
                raise ValueError('export requires "prompt_ids"')
            if raw and not np.isscalar(raw[0]):
                if len(raw) != 1:
                    raise ValueError(
                        "export supports exactly one prompt sequence"
                    )
                raw = raw[0]
            prompt = engine.validate(raw, 1)
            covered = engine.exportable_prefix_tokens(prompt)
            if covered <= 0:
                code = 400
                return web.json_response(
                    {
                        "error": f"prompt of {prompt.size} tokens has no "
                        "whole-chunk prefix to export",
                        "reason": "prompt_too_short",
                    },
                    status=400,
                )
            loop = asyncio.get_running_loop()
            matched, chunks = await loop.run_in_executor(
                None, engine.export_prefix_kv, prompt
            )
            if matched < covered:
                # Cold prefix: prefill it here (write-backs land the
                # chunks in the radix cache), then re-read.  Sheds and
                # validation errors surface as their usual statuses —
                # the router treats any non-200 as "fall back".
                rid = request.get("request_id") or request_id_from_headers(
                    request.headers
                )
                trace = RequestTrace(
                    request_id=rid,
                    trace_id=request.get("trace_id", ""),
                    parent_span=request.get("parent_span", ""),
                )
                fut = engine.submit(
                    prompt, 1, request_id=rid, trace=trace
                )
                await asyncio.wrap_future(fut)
                matched, chunks = await loop.run_in_executor(
                    None, engine.export_prefix_kv, prompt
                )
            if matched <= 0 or not chunks:
                code = 503
                return web.json_response(
                    {
                        "error": "prefix did not land in the radix cache "
                        "(budget too small for the prompt?)",
                        "reason": "export_unavailable",
                        "retry_after_s": 1,
                    },
                    status=503,
                    headers={"Retry-After": "1"},
                )
            blob = await loop.run_in_executor(
                None,
                lambda: kv_transfer.serialize_chunks(
                    engine._prefill_chunk_size, prompt, chunks
                ),
            )
            return web.Response(
                body=blob,
                content_type="application/octet-stream",
                headers={"X-Tpumlops-Kv-Tokens": str(matched)},
            )
        except EngineOverloaded as e:
            code = 429
            body = {
                "error": str(e),
                "reason": e.reason,
                "retry_after_s": e.retry_after_s,
            }
            if e.slo_class is not None:
                body["slo_class"] = e.slo_class
            return web.json_response(
                body,
                status=429,
                headers={"Retry-After": str(e.retry_after_s)},
            )
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            code = 400
            return web.json_response({"error": str(e)}, status=400)
        except Exception as e:
            _log.exception("kv export failed")
            code = 500
            return web.json_response({"error": str(e)}, status=500)
        finally:
            self.metrics.observe_request(
                time.perf_counter() - t0, code=code, service="kv-export"
            )

    async def handle_admin_kv_import(self, request: web.Request) -> web.Response:
        """``POST /admin/kv/import``: install a handoff blob into this
        replica's radix prefix cache.

        The blob's geometry (chunk size, K/V shape, dtype) must match
        this engine exactly — a mismatch is a typed 409, never a silent
        cast that would blur the token-for-token handoff parity.  The
        import journals a ``kv-import`` engine tick, so the relayed
        request that follows is reconstructable from ``/debug/trace``."""
        from . import kv_transfer

        engine, err = self._kv_engine_or_error(request)
        if err is not None:
            return err
        t0 = time.perf_counter()
        code = 200
        try:
            blob = await request.read()
            loop = asyncio.get_running_loop()
            try:
                header, chunks = await loop.run_in_executor(
                    None, kv_transfer.deserialize_chunks, blob
                )
            except kv_transfer.KvTransferError as e:
                code = 400
                return web.json_response(
                    {"error": str(e), "reason": "bad_blob"}, status=400
                )
            C = engine._prefill_chunk_size
            cfg = engine._cfg
            expected_shape = [
                cfg.num_layers, 1, C, cfg.num_kv_heads, cfg.head_dim,
            ]
            if int(header["chunk_tokens"]) != C or list(
                header["kv_shape"]
            ) != expected_shape:
                code = 409
                return web.json_response(
                    {
                        "error": f"handoff geometry {header['kv_shape']} "
                        f"@ {header['chunk_tokens']} tokens does not "
                        f"match this engine ({expected_shape} @ {C})",
                        "reason": "geometry_mismatch",
                    },
                    status=409,
                )
            import jax.numpy as jnp

            if kv_transfer._dtype_from_name(
                header["dtype"]
            ) != jnp.dtype(engine._dtype):
                code = 409
                return web.json_response(
                    {
                        "error": f"handoff dtype {header['dtype']} does "
                        f"not match engine dtype "
                        f"{jnp.dtype(engine._dtype).name}",
                        "reason": "dtype_mismatch",
                    },
                    status=409,
                )
            prompt = kv_transfer.chunk_token_ids(header)
            imported = await loop.run_in_executor(
                None, engine.import_prefix_kv, prompt, chunks
            )
            return web.json_response(
                {"imported_tokens": int(imported), "chunks": len(chunks)}
            )
        except (ValueError, KeyError, TypeError) as e:
            code = 400
            return web.json_response({"error": str(e)}, status=400)
        except Exception as e:
            _log.exception("kv import failed")
            code = 500
            return web.json_response({"error": str(e)}, status=500)
        finally:
            self.metrics.observe_request(
                time.perf_counter() - t0, code=code, service="kv-import"
            )

    async def handle_model_metadata(self, request: web.Request) -> web.Response:
        err = self._not_attached(request)
        if err is not None:
            return err
        p = self.engine.predictor
        return web.json_response(
            {
                "name": self.model_name,
                "platform": "tpumlops-jax",
                "flavor": p.name,
                "jittable": p.jittable,
                "metadata": p.metadata,
            }
        )

    # -- app wiring ----------------------------------------------------------

    def build_app(self) -> web.Application:
        app = web.Application(
            client_max_size=256 * 1024 * 1024,
            middlewares=[request_id_middleware],
        )
        name = self.model_name
        app.router.add_get("/v2/health/live", self.handle_live)
        app.router.add_get("/v2/health/ready", self.handle_ready)
        # Canonical lifecycle endpoint — same handler as the V2 ready
        # route above, so the manifest probe and the drain protocol read
        # one truth.
        app.router.add_get("/readyz", self.handle_ready)
        # The router's half-open recovery probes GET /healthz; same
        # handler as /readyz, so a draining/stalled replica (503) is
        # never re-admitted by a probe.
        app.router.add_get("/healthz", self.handle_ready)
        app.router.add_get("/livez", self.handle_live)
        app.router.add_post("/admin/drain", self.handle_admin_drain)
        app.router.add_post("/admin/attach", self.handle_admin_attach)
        app.router.add_get(f"/v2/models/{name}", self.handle_model_metadata)
        app.router.add_get(f"/v2/models/{name}/ready", self.handle_ready)
        app.router.add_post(f"/v2/models/{name}/infer", self.handle_v2_infer)
        if self.gen_engine is not None or self.attach_fn is not None:
            # Warm-pool servers register the generate route up front: the
            # attached model may be a causal LM, and routes cannot be
            # added after the app starts (pre-attach requests get the
            # typed warm_pool_empty 503).
            app.router.add_post(f"/v2/models/{name}/generate", self.handle_generate)
            # KV handoff endpoints (disaggregated fleets): export on
            # prefill replicas, import on decode replicas — registered
            # on every role (the router's role table decides who is
            # asked what; a unified replica can do both).
            app.router.add_post("/admin/kv/export", self.handle_admin_kv_export)
            app.router.add_post("/admin/kv/import", self.handle_admin_kv_import)
        if self.attach_fn is not None:
            # Multiplexed warm pool: the router addresses requests by the
            # CR's model id, which is NOT this replica's boot name — the
            # wildcard routes catch any model id (the router only sends
            # ids whose attachment it has confirmed; the server cannot
            # map CR id -> uri and stays permissive).  Literal routes
            # above win exact matches, so single-model wire behavior is
            # unchanged.  {mux_model} keys the per-model admission share.
            app.router.add_post(
                "/v2/models/{mux_model}/generate", self.handle_generate
            )
            app.router.add_post(
                "/v2/models/{mux_model}/infer", self.handle_v2_infer
            )
            app.router.add_get(
                "/v2/models/{mux_model}/ready", self.handle_ready
            )
        app.router.add_post("/api/v1.0/predictions", self.handle_seldon_predict)
        app.router.add_post("/api/v1.0/feedback", self.handle_feedback)
        app.router.add_get("/metrics", self.handle_metrics)
        app.router.add_post("/debug/profile", self.handle_profile)
        app.router.add_get("/debug/engine", self.handle_debug_engine)
        app.router.add_get("/debug/trace", self.handle_debug_trace)
        app.router.add_get("/debug/spans", self.handle_debug_spans)
        app.router.add_get("/debug/device", self.handle_debug_device)
        app.router.add_get("/debug/timeseries", self.handle_debug_timeseries)

        async def on_shutdown(_app):
            self.shutdown()

        app.on_shutdown.append(on_shutdown)
        return app


async def _write_sse_error(
    resp: web.StreamResponse, request_id: str, reason: str, message: str
) -> None:
    """Terminal SSE ``error`` event: a stream that dies mid-generation
    must end with a typed event (request_id + reason) — a bare dropped
    connection leaves the client unable to distinguish truncation from
    completion.  ``done: true``/``error`` keys are kept so pre-existing
    data-event consumers still terminate cleanly."""
    payload = {
        "done": True,
        "error": message,
        "request_id": request_id,
        "reason": reason,
    }
    await resp.write(
        f"event: error\ndata: {json.dumps(payload)}\n\n".encode()
    )


def _stamp_handoff(request: web.Request, traces) -> None:
    """Relayed-request stamp: the router forwards a request AFTER a
    prefill→decode KV handoff with ``X-Tpumlops-Handoff: <ms>`` (the
    handoff wall it measured).  ``t_handoff`` anchors the relay in this
    process's perf_counter domain; ``handoff_ms`` carries the router's
    cross-process measurement verbatim."""
    hdr = request.headers.get("X-Tpumlops-Handoff")
    if not hdr:
        return
    try:
        hms = float(hdr)
    except ValueError:
        return  # malformed stamp: treat as not relayed, never half-mark
    now = time.perf_counter()
    for tr in traces:
        tr.t_handoff = now
        tr.handoff_ms = hms


def _add_batch_dim(out: Any) -> Any:
    if isinstance(out, tuple):
        return tuple(_add_batch_dim(o) for o in out)
    if isinstance(out, dict):
        return {k: _add_batch_dim(v) for k, v in out.items()}
    return np.asarray(out)[None, ...]


def _slice_batch(out: Any, n: int) -> Any:
    if isinstance(out, tuple):
        return tuple(_slice_batch(o, n) for o in out)
    if isinstance(out, dict):
        return {k: _slice_batch(v, n) for k, v in out.items()}
    return np.asarray(out)[:n]


def _concat_batches(chunks: list[Any]) -> Any:
    if len(chunks) == 1:
        return chunks[0]
    first = chunks[0]
    if isinstance(first, tuple):
        return tuple(
            _concat_batches([c[i] for c in chunks]) for i in range(len(first))
        )
    if isinstance(first, dict):
        return {k: _concat_batches([c[k] for c in chunks]) for k in first}
    return np.concatenate([np.asarray(c) for c in chunks], axis=0)


def _timing_summary(request_id: str, traces) -> dict:
    """Aggregate per-sequence :class:`RequestTrace` blocks into the one
    request-level timing object (``"debug": true`` response field and the
    completion log line).  Totals agree with the Prometheus counters the
    request incremented — asserted in tests/test_server.py."""
    rows = [t.timing_block() for t in traces]
    queue = [r["queue_ms"] for r in rows if r["queue_ms"] is not None]
    ttft = [r["ttft_ms"] for r in rows if r["ttft_ms"] is not None]
    return {
        "request_id": request_id,
        "tokens": sum(r["tokens"] for r in rows),
        "prefill_chunks": sum(r["prefill_chunks"] for r in rows),
        "cached_tokens": sum(r["cached_tokens"] for r in rows),
        "spec_proposed": sum(r["spec_proposed"] for r in rows),
        "spec_accepted": sum(r["spec_accepted"] for r in rows),
        # Worst row's queue wait, best row's TTFT: the spread between
        # them is the packing/admission story for a multi-row request.
        "queue_ms": max(queue) if queue else None,
        "ttft_ms": min(ttft) if ttft else None,
        "finish_reasons": sorted({r["finish_reason"] for r in rows}),
        "rows": rows,
    }


def _to_v2_outputs(out: Any) -> list[dict]:
    if isinstance(out, dict):
        items = list(out.items())
    elif isinstance(out, tuple):
        items = [(f"output_{i}", o) for i, o in enumerate(out)]
    else:
        items = [("output_0", out)]
    v2 = []
    for name, arr in items:
        arr = np.asarray(arr)
        v2.append(
            {
                "name": name,
                "shape": list(arr.shape),
                "datatype": _NP_TO_V2.get(arr.dtype, "FP32"),
                "data": arr.ravel().tolist(),
            }
        )
    return v2


# ---------------------------------------------------------------------------
# CLI (the container entrypoint generated by the manifest builder)
# ---------------------------------------------------------------------------


def _fan(*fns):
    """Chain observer callbacks onto ONE engine hook (the timeseries
    ring rides the metrics callbacks instead of new instrumentation
    points).  None entries drop out; a single survivor is returned
    unwrapped so the common no-ring path stays the bare bound method."""
    live = [f for f in fns if f is not None]
    if not live:
        return None
    if len(live) == 1:
        return live[0]

    def fanned(*args, **kwargs):
        for f in live:
            f(*args, **kwargs)

    return fanned


def make_gen_engine(
    predictor, config: ServerConfig, channel=None, metrics=None,
    recorder=None, telemetry=None, watchdog=None, timeseries=None,
):
    """Construct the GenerationEngine for a causal-LM predictor.

    ONE construction site for leader and followers: lockstep replay needs
    bit-identical slot counts / dtype / kv_quant on every host, so the
    shared knobs must never be spelled twice.
    """
    from .generation import GenerationEngine

    ts = timeseries  # per-second ring: fans onto the metric callbacks

    prefix_cache = None
    if config.tpu.prefix_cache.enabled:
        from .prefix_cache import PrefixCacheConfig

        # Same spec on leader and followers (this one construction site):
        # the derived prefill-chunk size must agree or lockstep replay
        # runs mismatched chunk shapes.
        prefix_cache = PrefixCacheConfig(
            enabled=True,
            budget_bytes=config.tpu.prefix_cache.budget_mb * 2**20,
            chunk_tokens=config.tpu.prefix_cache.chunk_tokens,
            l2_budget_bytes=config.tpu.prefix_cache.l2_budget_mb * 2**20,
        )
    speculative = None
    if config.tpu.speculative.enabled:
        from .speculative import SpeculativeConfig

        # Same draft geometry on leader and followers (this one
        # construction site): a verify tick is replayed in lockstep, so
        # the compiled (draft length, window) variants must agree.
        speculative = SpeculativeConfig(
            enabled=True,
            draft_tokens=config.tpu.speculative.draft_tokens,
            ngram_min=config.tpu.speculative.ngram_min,
            ngram_max=config.tpu.speculative.ngram_max,
            adaptive=config.tpu.speculative.adaptive,
        )
    return GenerationEngine(
        predictor.causal_lm["params"],
        predictor.causal_lm["cfg"],
        # Default stays latency-first; spec.tpu.maxSlots raises it for
        # throughput (decode re-reads all weights per step — slots
        # amortize that; see bench.py slot ladder).
        max_slots=config.tpu.max_slots or min(config.tpu.max_batch_size, 8),
        eos_id=predictor.causal_lm.get("eos_id"),
        on_step=_fan(
            metrics.observe_decode_step if metrics else None,
            ts.observe_decode_step if ts else None,
        ),
        on_tokens=metrics.inc_generated_tokens if metrics else None,
        channel=channel,
        kv_quant=config.tpu.quantize == "int8kv",
        prefill_chunk=config.tpu.prefill_chunk,
        prefix_cache=prefix_cache,
        on_prefix_hit=metrics.observe_prefix_hit if metrics else None,
        on_prefix_evict=metrics.inc_prefix_evictions if metrics else None,
        on_prefix_l2=metrics.inc_prefix_l2 if metrics else None,
        speculative=speculative,
        on_spec=metrics.observe_speculative if metrics else None,
        # Fused multi-step decode: same K on leader and followers (this
        # one construction site) — the compiled (K, window) variants
        # must agree for lockstep replay.  1 = single-step loop.
        decode_steps=config.tpu.decode_steps,
        # Unified ragged super-step: same engine kind on leader and
        # followers (this one construction site) — the one-per-tick
        # superstep program must exist on both for lockstep replay.
        unified_step=config.tpu.unified_step,
        on_dispatch=metrics.inc_dispatch if metrics else None,
        # Packed multi-admission prefill: same batch geometry on leader
        # and followers (this one construction site) — the compiled B_p
        # bucket variants must agree for lockstep replay.
        prefill_batch=config.tpu.prefill_batch,
        prefill_token_budget=config.tpu.prefill_token_budget,
        on_prefill_batch=metrics.observe_prefill_batch if metrics else None,
        on_admission_wait=metrics.observe_admission_wait if metrics else None,
        on_ttft=metrics.observe_ttft if metrics else None,
        on_itl=_fan(
            metrics.observe_itl if metrics else None,
            ts.observe_itl if ts else None,
        ),
        on_request_tokens=metrics.observe_request_tokens if metrics else None,
        on_tick=_fan(
            metrics.observe_tick if metrics else None,
            ts.observe_tick if ts else None,
        ),
        # Leader-side only: the scheduler (and so the journal) runs on
        # the leader; follower processes replay device ops blind.
        recorder=recorder,
        # Admission control (leader-side: followers never take
        # submissions): shed past the queued-token budget, 429 upstream.
        admission_queue_budget=config.tpu.admission_queue_budget,
        on_shed=_fan(
            metrics.inc_shed if metrics else None,
            ts.inc_shed if ts else None,
        ),
        # Leader-side only, like the recorder: the ledger/observatory
        # describe the scheduling process; followers replay blind.
        telemetry=telemetry,
        # Leader-side only: the scheduler heartbeat the watchdog
        # monitors runs on the leader; followers block inside replayed
        # collectives by design.
        watchdog=watchdog,
        on_poison=_fan(
            metrics.inc_poison if metrics else None,
            ts.inc_poison if ts else None,
        ),
        # Tensor-parallel mesh: same shape on leader and followers (this
        # one construction site) — sharded programs must agree for
        # lockstep replay.  {"dp": 1, "tp": 1} (the default) arms
        # nothing; the loader already sharded the params over the same
        # device prefix the engine's mesh covers.
        mesh_shape=dict(config.tpu.mesh_shape),
        # sp > 1: cold prompts at/over this length prefill through the
        # ring-attention pass instead of serial chunks.
        sp_prefill_threshold=config.tpu.sp_prefill_threshold,
        # SLO classes + mid-decode preemption: the default class every
        # submit inherits (per-request slo_class overrides) and whether
        # a waiting higher class may evict a lower-class slot at a tick
        # boundary.  Leader-side scheduling, but preemption=True also on
        # followers so the restore program exists for lockstep replay.
        slo_class=config.tpu.slo_class,
        preemption=config.tpu.preemption,
        on_preempt=metrics.inc_preempt if metrics else None,
    )


def prewarm_from_snapshot(config: ServerConfig) -> float | None:
    """Warm-pool boot sweep: compile every engine program from the
    snapshot manifest's *geometry* — a zero-filled tree of the exact
    dtypes/shapes the real weights will have — so the XLA executables
    land in the (persistent) compile cache before any model is attached.
    The zero tree is released afterwards: the replica holds compiled
    programs, not weights.  Best-effort; returns the sweep wall seconds
    or None when there is no snapshot to read geometry from."""
    import numpy as np

    from ..models.registry import get_builder
    from . import snapshot as _snap
    from .loader import (
        _build_config,
        _unflatten,
        release_predictor,
    )

    if not config.tpu.snapshot.enabled:
        return None
    spath = _snap.snapshot_path_for(
        config.tpu.snapshot.dir, config.model_uri
    )
    if not (spath / _snap.MANIFEST_NAME).exists():
        _log.info(
            "warm-pool prewarm skipped: no snapshot at %s yet", spath
        )
        return None
    t0 = time.perf_counter()
    try:
        manifest = _snap.read_manifest(spath)
        if manifest["flavor"] != "llama-generate":
            return None
        flat = {
            leaf["key"]: np.zeros(
                leaf["shape"], dtype=_snap._dtype_from_name(leaf["dtype"])
            )
            for leaf in manifest["leaves"]
        }
        cfg = _build_config(manifest["flavor"], manifest.get("config", {}))
        pred = get_builder(manifest["flavor"])(
            _unflatten(flat),
            **{
                **manifest.get("builder_kwargs", {}),
                **({"cfg": cfg} if cfg is not None else {}),
            },
        )
        gen = make_gen_engine(pred, config)
        try:
            gen.start(warmup=True)
        finally:
            gen.shutdown()
        release_predictor(pred)
        wall = time.perf_counter() - t0
        _log.info(
            "warm-pool prewarm: compile sweep over snapshot geometry "
            "done in %.1fs (programs pre-baked for attach)",
            wall,
        )
        return wall
    except Exception as e:
        _log.warning("warm-pool prewarm failed (attach still works): %s", e)
        return None


def build_server(
    config: ServerConfig,
    warmup: bool = True,
    transport=None,
    wake_start_wall: float | None = None,
) -> TpuInferenceServer:
    """Build the leader-side server.

    ``transport`` (a ``multihost.GroupTransport``) makes this process the
    leader of a multi-host predictor unit: every engine call is broadcast
    so follower processes execute it in lockstep (SURVEY §7 hard part 5).
    Single-host units pass None and run the engine directly.

    ``config.warm_pool`` boots the server with NO weights: the compile
    sweep runs against the snapshot manifest's geometry (persistent
    cache primed), and ``POST /admin/attach`` snapshot-restores a model
    on demand.  ``wake_start_wall`` (unix seconds) is the instant the
    controller decided to wake this replica — it anchors the
    ``tpumlops_cold_start_seconds`` ladder's ``wake`` stage.
    """
    boot_wall = time.time()
    mesh_shape = dict(config.tpu.mesh_shape)
    snapshot_dir = (
        config.tpu.snapshot.dir if config.tpu.snapshot.enabled else None
    )
    telemetry = None
    if config.tpu.observability.device_telemetry:
        from .device_telemetry import DeviceTelemetry

        # Before load_predictor so even the loader-phase compiles (the
        # streamed quantizer) land in the observatory's journal.
        telemetry = DeviceTelemetry()
    metrics = ServerMetrics(
        deployment_name=config.deployment_name or config.model_name,
        predictor_name=config.predictor_name,
        namespace=config.namespace,
        device_telemetry=telemetry is not None,
    )
    if telemetry is not None:
        telemetry.bind_metrics(metrics)
    recorder = None
    if config.tpu.observability.trace_ring > 0:
        from .flight_recorder import FlightRecorder

        recorder = FlightRecorder(config.tpu.observability.trace_ring)
    timeseries = None
    if config.tpu.observability.timeseries_ring > 0:
        from .timeseries import TimeseriesRing

        # Leader-side only, like the recorder: the callback stream it
        # distills runs on the scheduling leader; followers replay blind.
        timeseries = TimeseriesRing(config.tpu.observability.timeseries_ring)
        if telemetry is not None:
            # MFU / HBM-bandwidth per bucket come from the telemetry
            # layer's existing last_util gauge — no new hook.
            timeseries.bind_telemetry(telemetry)
    watchdog = None
    if config.watchdog_deadline_s > 0:
        from .watchdog import EngineWatchdog

        # Leader-side only, like the recorder: followers block inside
        # replayed collectives by design, and the leader's escalation
        # (process exit -> pod restart) tears the whole unit down.
        watchdog = EngineWatchdog(
            deadline_s=config.watchdog_deadline_s,
            grace_s=config.watchdog_grace_s,
            on_age=metrics.set_watchdog_tick_age,
        )

    def _build_engines(predictor, channel=None):
        engine = InferenceEngine(
            predictor,
            max_batch_size=config.tpu.max_batch_size,
            on_compile=lambda: metrics.compilations.labels(
                **metrics.identity
            ).inc(),
            warmup_full_grid=config.tpu.warmup_full_grid,
        )
        gen_engine = None
        if predictor.causal_lm is not None:
            # On a multi-host unit the scheduler runs leader-side only;
            # every device call is broadcast on the unit's channel so
            # followers replay it in lockstep (their GenerationEngine is
            # built in main()'s follower path, driven by follower_loop).
            gen_engine = make_gen_engine(
                predictor, config, channel=channel, metrics=metrics,
                recorder=recorder, telemetry=telemetry, watchdog=watchdog,
                timeseries=timeseries,
            )
        return engine, gen_engine

    if config.warm_pool:
        if transport is not None:
            raise ValueError(
                "--warm-pool is single-host only (a multi-host unit "
                "cannot attach weights after its process group formed)"
            )

        def attach_fn(model_uri, old_predictor, load_stats):
            predictor = load_predictor(
                model_uri,
                mesh_shape=mesh_shape,
                quantize=config.tpu.quantize,
                load_stats=load_stats,
                snapshot_dir=snapshot_dir,
                release_first=old_predictor,
            )
            engine, gen_engine = _build_engines(predictor)
            return {
                "predictor": predictor,
                "engine": engine,
                "gen_engine": gen_engine,
            }

        server = TpuInferenceServer(
            None,
            metrics,
            model_name=config.model_name,
            max_batch_size=config.tpu.max_batch_size,
            max_batch_delay_ms=config.tpu.max_batch_delay_ms,
            max_inflight_batches=config.tpu.max_inflight_batches,
            recorder=recorder,
            drain_grace_s=config.tpu.drain_grace_s,
            telemetry=telemetry,
            attach_fn=attach_fn,
            fleet_role=config.fleet_role,
            snapshot_dir=snapshot_dir,
            timeseries=timeseries,
        )
        if watchdog is not None:
            watchdog.on_stall = server.note_watchdog_stall
            watchdog.on_recover = server.note_watchdog_recover
        if warmup:
            prewarm_from_snapshot(config)
        server.startup(warmup=False)  # lifecycle -> "warm-pool"
        return server

    load_stats: dict = {}
    predictor = load_predictor(
        config.model_uri,
        mesh_shape=mesh_shape,
        quantize=config.tpu.quantize,
        load_stats=load_stats,
        snapshot_dir=snapshot_dir,
    )
    engine = InferenceEngine(
        predictor,
        max_batch_size=config.tpu.max_batch_size,
        on_compile=lambda: metrics.compilations.labels(
            **metrics.identity
        ).inc(),
        warmup_full_grid=config.tpu.warmup_full_grid,
    )
    channel = None
    if transport is not None:
        from .multihost import MultihostEngine

        engine = MultihostEngine(engine, transport)
        channel = engine.channel
    gen_engine = None
    if predictor.causal_lm is not None:
        gen_engine = make_gen_engine(
            predictor, config, channel=channel, metrics=metrics,
            recorder=recorder, telemetry=telemetry, watchdog=watchdog,
            timeseries=timeseries,
        )
    metrics.observe_model_load(load_stats)
    restored = load_stats.get("restore_s") is not None
    anchor = wake_start_wall if wake_start_wall is not None else boot_wall
    if wake_start_wall is not None:
        metrics.observe_cold_start("wake", boot_wall - wake_start_wall)
    if load_stats:
        metrics.observe_cold_start(
            "restore" if restored else "load",
            load_stats.get("restore_s") or load_stats.get("wall_s") or 0.0,
        )
    server = TpuInferenceServer(
        engine,
        metrics,
        model_name=config.model_name,
        max_batch_size=config.tpu.max_batch_size,
        max_batch_delay_ms=config.tpu.max_batch_delay_ms,
        gen_engine=gen_engine,
        max_inflight_batches=config.tpu.max_inflight_batches,
        recorder=recorder,
        drain_grace_s=config.tpu.drain_grace_s,
        telemetry=telemetry,
        cold_start_anchor_wall=anchor,
        fleet_role=config.fleet_role,
        timeseries=timeseries,
    )
    server.predictor = predictor
    if watchdog is not None:
        # Wire the readiness/journal callbacks BEFORE startup arms the
        # monitor — a stall must never fire into unassigned hooks.
        watchdog.on_stall = server.note_watchdog_stall
        watchdog.on_recover = server.note_watchdog_recover
    t_warm = time.time()
    server.startup(warmup=warmup)
    metrics.observe_cold_start("compile", time.time() - t_warm)
    metrics.observe_cold_start("total", time.time() - anchor)
    if timeseries is not None:
        # Baseline anchor for the anomaly detector: samples before this
        # mark are warmup noise, not serving behavior.
        timeseries.mark("warmup")
    return server


def _serve_follower_health(host: str, port: int) -> None:
    """Minimal live/ready listener for follower pods (daemon thread).

    The StatefulSet template shares one readinessProbe across the unit;
    followers answer it here so they don't sit NotReady forever."""
    import threading

    def run() -> None:
        async def ok(_request: web.Request) -> web.Response:
            return web.json_response({"role": "follower", "ok": True})

        app = web.Application()
        app.router.add_get("/v2/health/live", ok)
        app.router.add_get("/v2/health/ready", ok)
        app.router.add_get("/healthz", ok)
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        loop.run_until_complete(web.TCPSite(runner, host, port).start())
        loop.run_forever()

    threading.Thread(target=run, daemon=True, name="follower-health").start()


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser("tpumlops-server")
    ap.add_argument("--model-uri", required=True)
    ap.add_argument("--model-name", default="model")
    ap.add_argument("--predictor-name", default="v1")
    ap.add_argument("--deployment-name", default="")
    ap.add_argument("--namespace", default="default")
    ap.add_argument("--mesh-shape", default='{"dp": 1, "tp": 1}')
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--max-batch-size", type=int, default=32)
    ap.add_argument("--max-batch-delay-ms", type=float, default=5.0)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=9000)
    ap.add_argument(
        "--metrics-port",
        type=int,
        default=6000,
        help="dedicated /metrics listener (matches the manifest's metrics "
        "containerPort); 0 disables the second listener",
    )
    ap.add_argument(
        "--drain-s",
        type=float,
        default=3.0,
        help="seconds to keep serving (NotReady) after SIGTERM before "
        "the in-flight drain begins, so rolling steps don't 503 the "
        "request tail still being routed here",
    )
    ap.add_argument(
        "--admission-queue-budget",
        type=int,
        default=0,
        help="estimated-token bound (prompt + max_new) on queued-but-"
        "unadmitted generation work; beyond it /generate sheds with "
        "429 + Retry-After (tpumlops_engine_shed_total counts them). "
        "0 = unbounded (the pre-admission-control behavior)",
    )
    ap.add_argument(
        "--drain-grace-seconds",
        type=float,
        default=20.0,
        help="lossless-drain window: seconds SIGTERM / POST /admin/drain "
        "waits for in-flight sequences (SSE streams included) to finish "
        "after admissions stop, before teardown",
    )
    ap.add_argument(
        "--prefill-chunk",
        type=int,
        default=0,
        help="chunked prefill size (0 = whole-prompt); long prompts stop "
        "stalling in-flight decode streams",
    )
    ap.add_argument(
        "--prefill-batch",
        type=int,
        default=1,
        help="concurrent admissions whose next prompt chunks batch into "
        "ONE prefill call per tick (amortizes the weight stream under "
        "bursty load; 1 = single-admission pipeline, requires "
        "--prefill-chunk or --prefix-cache when > 1)",
    )
    ap.add_argument(
        "--prefill-token-budget",
        type=int,
        default=0,
        help="prompt tokens prefilled per engine tick, Sarathi-style "
        "(0 = uncapped); bounds decode-cadence jitter under long-prompt "
        "bursts",
    )
    ap.add_argument(
        "--sp-prefill-threshold",
        type=int,
        default=1024,
        help="prompt length at/over which a cold prompt prefills via the "
        "sequence-parallel ring-attention pass (effective only when "
        "meshShape carries sp > 1)",
    )
    ap.add_argument(
        "--prefix-cache",
        type=int,
        default=0,
        help="1 enables the radix prefix KV cache (shared prompt prefixes "
        "prefill once and are copied thereafter)",
    )
    ap.add_argument(
        "--prefix-cache-budget-mb",
        type=int,
        default=256,
        help="host-memory byte budget for cached prefix K/V (LRU eviction)",
    )
    ap.add_argument(
        "--prefix-cache-chunk",
        type=int,
        default=0,
        help="prefix reuse unit in tokens (0 = follow --prefill-chunk, or "
        "64 when that is unset too); an explicit mismatch with "
        "--prefill-chunk is rejected at startup",
    )
    ap.add_argument(
        "--prefix-cache-l2-budget-mb",
        type=int,
        default=0,
        help="second-tier host-RAM pool for evicted prefix chunks (LRU "
        "under this budget, promoted back on a radix-walk miss); 0 "
        "(default) = single-tier behavior byte-for-byte",
    )
    ap.add_argument(
        "--fleet-role",
        default="unified",
        choices=["unified", "prefill", "decode"],
        help="disaggregated-fleet role of this replica (advisory: "
        "surfaced on /readyz and logs; the router's role-tagged backend "
        "table decides who is asked to export/import KV)",
    )
    ap.add_argument(
        "--speculative",
        type=int,
        default=0,
        help="1 enables self-speculative n-gram decoding (draft from the "
        "sequence's own history, verify k+1 positions per weight stream; "
        "greedy-exact output)",
    )
    ap.add_argument(
        "--speculative-draft-tokens",
        type=int,
        default=4,
        help="max draft tokens per slot per verify tick",
    )
    ap.add_argument(
        "--speculative-ngram-min",
        type=int,
        default=1,
        help="shortest history suffix the n-gram drafter may match",
    )
    ap.add_argument(
        "--speculative-ngram-max",
        type=int,
        default=4,
        help="longest history suffix tried first",
    )
    ap.add_argument(
        "--speculative-adaptive",
        type=int,
        default=1,
        help="1: per-slot draft length halves on consecutive zero-accept "
        "verifies and regrows on success; 0: fixed draft length",
    )
    ap.add_argument(
        "--decode-steps",
        type=int,
        default=1,
        help="decode iterations fused into ONE device dispatch per tick "
        "(lax.scan with on-device sampling + EOS latch, lag-1 async "
        "token readback; engages only when no admissions or drafts are "
        "pending).  1 = the single-step tick loop; max 16",
    )
    ap.add_argument(
        "--unified-step",
        type=int,
        default=0,
        help="1: unified ragged super-step engine — ONE jit program per "
        "tick covers packed-prefill chunks, fused-K decode, and "
        "speculative verify via per-row role tensors, collapsing the "
        "warmup sweep to (window-bucket x sampling-mode) variants; "
        "0 (default) keeps the split-program engine byte-for-byte",
    )
    ap.add_argument(
        "--quantize",
        default="none",
        choices=["none", "int8", "int8kv"],
        help="int8: weight-only; int8kv: weights + KV cache "
        "(halves decode HBM traffic twice over)",
    )
    ap.add_argument(
        "--snapshot-dir",
        default="",
        help="pre-baked weight snapshot directory (server/snapshot.py): "
        "the post-shard, post-quantize device tree is baked here after "
        "the first cold load and restored on later boots/attaches with "
        "zero transform work (scale-to-zero fast path); empty disables",
    )
    ap.add_argument(
        "--warm-pool",
        type=int,
        default=0,
        help="1 boots a warm-pool replica: no weights, compile sweep run "
        "against the snapshot manifest's geometry (persistent cache "
        "primed), POST /admin/attach snapshot-restores a model on "
        "demand; requires --snapshot-dir",
    )
    ap.add_argument(
        "--compile-cache-dir",
        default=os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_compile_cache"),
        help="persistent XLA compile cache (SURVEY §7 hard part 3); "
        "empty string disables",
    )
    ap.add_argument(
        "--trace-ring",
        type=int,
        default=0,
        help="engine flight-recorder ring size (ticks/events/requests "
        "kept in memory, served at /debug/engine and /debug/trace); "
        "0 disables recording entirely (the default — zero overhead)",
    )
    ap.add_argument(
        "--timeseries-ring",
        type=int,
        default=0,
        help="per-second serving time-series ring size (seconds of "
        "history kept: tick-wall quantiles, ITL, queue depth, MFU/HBM "
        "bandwidth, shed/poison counts; served at /debug/timeseries — "
        "the operator anomaly detector's input plane); 0 disables the "
        "ring entirely (the default — zero overhead)",
    )
    ap.add_argument(
        "--device-telemetry",
        type=int,
        default=0,
        help="1 enables the device telemetry layer: analytic HBM ledger "
        "(GET /debug/device, tpumlops_device_hbm_bytes), per-op compile "
        "observatory (tpumlops_compile_*), and per-tick MFU/HBM-bandwidth "
        "utilization gauges + recorder fields; 0 (default) constructs "
        "none of it",
    )
    ap.add_argument(
        "--watchdog-deadline-s",
        type=float,
        default=0.0,
        help="scheduler-tick watchdog deadline: a device dispatch "
        "blocking past this flips /readyz unready and journals a "
        "watchdog event (tpumlops_engine_watchdog_stalls_total); armed "
        "only after warmup.  0 (default) disables the monitor entirely",
    )
    ap.add_argument(
        "--watchdog-grace-s",
        type=float,
        default=30.0,
        help="grace past the watchdog deadline before the process exits "
        "non-zero so Kubernetes restarts the pod (a restart is the only "
        "remedy for a wedged device)",
    )
    ap.add_argument(
        "--slo-class",
        default="",
        help="default SLO class for requests that don't carry one "
        "(interactive | batch | best-effort); arms the priority "
        "admission queues — higher classes drain first and lower "
        "classes shed at a fraction of the admission budget",
    )
    ap.add_argument(
        "--preemption",
        type=int,
        default=0,
        help="1: a waiting higher-class request may evict a lower-class "
        "slot at a tick boundary (KV parked in the prefix cache, "
        "restored on re-admission with no lost work); requires "
        "--prefix-cache 1",
    )
    ap.add_argument(
        "--log-format",
        default="text",
        choices=["text", "json"],
        help="json: one JSON object per log line carrying request_id, so "
        "per-request completion lines are machine-parseable",
    )
    args = ap.parse_args(argv)
    from ..utils.logging import configure as configure_logging

    configure_logging(json_format=args.log_format == "json")

    from ..parallel.distributed import maybe_initialize_distributed
    from ..utils.compile_cache import enable_persistent_compile_cache

    maybe_initialize_distributed()
    # Before any jit trace (warmup included), so even the first-ever
    # compile of each batch bucket is persisted for the next pod.
    enable_persistent_compile_cache(args.compile_cache_dir)

    config = ServerConfig(
        model_name=args.model_name,
        model_uri=args.model_uri,
        predictor_name=args.predictor_name,
        deployment_name=args.deployment_name or args.model_name,
        namespace=args.namespace,
        host=args.host,
        port=args.port,
        tpu=TpuSpec.from_spec(
            {
                "meshShape": json.loads(args.mesh_shape),
                "dtype": args.dtype,
                "maxBatchSize": args.max_batch_size,
                "maxBatchDelayMs": args.max_batch_delay_ms,
                "quantize": args.quantize,
                "prefillChunk": args.prefill_chunk or None,
                "prefillBatch": args.prefill_batch,
                "prefillTokenBudget": args.prefill_token_budget,
                "spPrefillThreshold": args.sp_prefill_threshold,
                "prefixCache": {
                    "enabled": bool(args.prefix_cache),
                    "budgetMB": args.prefix_cache_budget_mb,
                    "chunkTokens": args.prefix_cache_chunk or None,
                    "l2BudgetMB": args.prefix_cache_l2_budget_mb,
                },
                "speculative": {
                    "enabled": bool(args.speculative),
                    "draftTokens": args.speculative_draft_tokens,
                    "ngramMin": args.speculative_ngram_min,
                    "ngramMax": args.speculative_ngram_max,
                    "adaptive": bool(args.speculative_adaptive),
                },
                "decodeSteps": args.decode_steps,
                "unifiedStep": bool(args.unified_step),
                "observability": {
                    "traceRing": args.trace_ring,
                    "deviceTelemetry": bool(args.device_telemetry),
                    "timeseriesRing": args.timeseries_ring,
                },
                "admissionQueueBudget": args.admission_queue_budget,
                "drainGraceSeconds": args.drain_grace_seconds,
                **({"sloClass": args.slo_class} if args.slo_class else {}),
                "preemption": bool(args.preemption),
                "snapshot": {
                    "enabled": bool(args.snapshot_dir),
                    **(
                        {"dir": args.snapshot_dir}
                        if args.snapshot_dir
                        else {}
                    ),
                },
            }
        ),
        warm_pool=bool(args.warm_pool),
        fleet_role=args.fleet_role,
        watchdog_deadline_s=args.watchdog_deadline_s,
        watchdog_grace_s=args.watchdog_grace_s,
    )
    if config.warm_pool and not config.tpu.snapshot.enabled:
        ap.error("--warm-pool requires --snapshot-dir")
    if config.fleet_role != "unified" and not config.tpu.prefix_cache.enabled:
        ap.error(
            "--fleet-role prefill/decode requires --prefix-cache 1 "
            "(KV handoff moves radix prefix-cache chunks)"
        )

    import jax  # deferred: process topology is meaningful only after init

    if jax.process_count() > 1:
        from .multihost import JaxProcessTransport, follower_loop

        transport = JaxProcessTransport()
        if not transport.is_leader:
            # Follower pod of a multi-host predictor unit: no inference
            # frontend, but it must still answer the unit's shared
            # readiness probe — joining the process group (init returned)
            # IS follower-readiness.  Then execute the leader's broadcast
            # steps until it shuts the unit down.
            _serve_follower_health(config.host, config.port)
            predictor = load_predictor(
                args.model_uri,
                mesh_shape=dict(config.tpu.mesh_shape),
                quantize=config.tpu.quantize,
            )
            engine = InferenceEngine(
                predictor,
                max_batch_size=config.tpu.max_batch_size,
                warmup_full_grid=config.tpu.warmup_full_grid,
            )
            gen_engine = None
            if predictor.causal_lm is not None:
                # Not started: driven entirely by replayed leader ops.
                gen_engine = make_gen_engine(predictor, config)
            _log.info("follower process %d ready", jax.process_index())
            follower_loop(engine, transport, gen_engine=gen_engine)
            return
    else:
        transport = None

    # Stamped by whoever decided to wake this replica (the operator's
    # scale-from-zero path / LocalReplicaSet): anchors the
    # tpumlops_cold_start_seconds ladder's "wake" stage.
    wake_env = os.environ.get("TPUMLOPS_WAKE_START_WALL")
    server = build_server(
        config,
        transport=transport,
        wake_start_wall=float(wake_env) if wake_env else None,
    )

    async def _serve() -> None:
        runner = web.AppRunner(server.build_app())
        await runner.setup()
        await web.TCPSite(runner, config.host, config.port).start()
        if args.metrics_port:
            # Dedicated /metrics listener on the manifest's metrics port.
            metrics_app = web.Application()
            metrics_app.router.add_get("/metrics", server.handle_metrics)
            mrunner = web.AppRunner(metrics_app)
            await mrunner.setup()
            await web.TCPSite(mrunner, config.host, args.metrics_port).start()
        _log.info(
            "serving on %s:%d (metrics on %s)",
            config.host,
            config.port,
            args.metrics_port or f"{config.port}/metrics",
        )
        # Kubernetes terminates pods with SIGTERM, not Ctrl-C: without a
        # handler the multi-host leader would die before broadcasting
        # OP_SHUTDOWN and its followers would block out their whole grace
        # period in a dead collective.
        import signal

        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stop.set)
            except (NotImplementedError, RuntimeError):  # non-main thread
                pass
        await stop.wait()
        # Lossless drain before teardown, in two phases.
        #
        # Phase 1 (--drain-s): keep ADMITTING while NotReady.  Kubernetes
        # removes a Terminating pod from endpoints asynchronously, so for
        # a short window traffic is still routed here; without accepting
        # that tail every rolling canary step 503s it, which the gate
        # reads as an error-rate spike on whichever version was being
        # replaced.
        server.ready = False
        _log.info(
            "termination signal; endpoint lag %.1fs before drain",
            args.drain_s,
        )
        await asyncio.sleep(max(0.0, args.drain_s))
        # Phase 2 (--drain-grace-seconds): stop admissions — new
        # /generate requests shed 429 + Retry-After so clients go to
        # another replica — and wait for every admitted sequence (SSE
        # streams included) to finish.  Scale-down and rollout teardown
        # never drop a request.
        server.terminating = True  # a committed exit: cancel refused
        server.begin_drain()
        drained = await server.wait_drained(args.drain_grace_seconds)
        if not drained and server.gen_engine is not None:
            _log.warning(
                "drain grace %.1fs expired with %d sequence(s) in flight",
                args.drain_grace_seconds,
                server.gen_engine.inflight(),
            )
        await runner.cleanup()  # fires on_shutdown -> server.shutdown()

    try:
        asyncio.run(_serve())
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()  # idempotent; covers non-signal exits


if __name__ == "__main__":  # pragma: no cover
    main()
