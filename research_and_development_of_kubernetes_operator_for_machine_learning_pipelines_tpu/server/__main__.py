"""``python -m <package>.server`` — container entrypoint for the TPU
inference server (the builder's generated manifests invoke this)."""

from .app import main

if __name__ == "__main__":
    main()
