"""Pre-baked weight snapshots: the device-resident tree on disk, restorable
with zero transform work.

Why: the measured 7B cold path (BENCH_7B_FULL.json) spends 102 s to
first-servable — 92 s of it reading 12.55 GiB of bf16 from disk only to
quantize it down to 6.4 GiB of int8 on device.  Both λScale and "Breaking
the Ice" (PAPERS.md) locate the scale-to-zero win in the same place:
stop re-deriving the device state on every boot.  A snapshot is the
*exact post-shard, post-quantize* param tree — q8/scale planes included —
written once after the first successful load, so a restore is a straight
disk→device stream: ~2x fewer bytes read than the bf16 artifact and no
``quantize_s`` / reshard stage at all.

Layout (one directory per snapshot)::

    <dir>/<content_hash>/
        SNAPSHOT.json     # manifest: format version, identity, leaf index
        chunk-00000.bin   # concatenated raw leaf bytes (bounded size)
        chunk-00001.bin
        ...

The manifest indexes every leaf as ``(file, offset, nbytes, dtype, shape,
crc32)``; leaves are never split across chunk files, so a restore can
stream file-by-file with a reader thread while the consumer transfers the
previous leaves to the device (same overlap discipline as
``loader._stream_native_params``, minus the transform work).

Tensor-parallel trees (``meshShape`` tp > 1) extend a leaf entry with a
SHARD axis: a partitioned leaf carries ``spec`` (its PartitionSpec as
data) and ``shards`` — one ``(file, offset, nbytes, crc32, start,
shape)`` record per device shard, each written from that device's own
buffer.  A restore rebuilds the mesh from the manifest identity's
``mesh_shape`` and device-puts each shard straight onto its device
(``jax.make_array_from_single_device_arrays``), so at no point does the
full tree — or even a full sharded leaf, beyond the one being assembled
— materialize on one host.  Replicated leaves (norms, scales of
row-split matrices) keep the flat single-copy layout, so a ``tp: 1``
snapshot's manifest and chunks are byte-for-byte the pre-tp format.

The shard plan is spec-driven, not axis-named: a ``{dp: N}`` mesh (PR 17)
replicates every weight leaf over dp while tp still splits heads, and the
plan's slice-start dedup writes each DISTINCT shard block exactly once —
a dp x tp tree snapshots the same bytes as the tp-only tree, and restore
reassembles against whatever mesh ``identity.mesh_shape`` names (dp/sp
axes included) because ``devices_indices_map`` carries the full
placement.  No dp/sp-specific code exists here; the geometry tests in
``tests/test_data_parallel.py`` pin that property.

Identity and invalidation: the snapshot is keyed by a content hash of
``(model version/uri, quantize mode, mesh shape, format version)``.  Any
mismatch — a new model version, a different quantize mode, a resharded
mesh, a format bump — makes the hash differ, so the restore path simply
misses and the caller falls back to the cold load (which then re-bakes).
Corruption (truncated chunk, CRC mismatch, malformed manifest) raises the
typed :class:`SnapshotError` instead of serving garbage weights.
"""

from __future__ import annotations

import binascii
import hashlib
import json
import logging
import os
import shutil
import tempfile
import time
from pathlib import Path
from typing import Any

import numpy as np

_log = logging.getLogger(__name__)

# Bump when the on-disk layout changes; a version mismatch is an ordinary
# cache miss (cold load + re-bake), never an error.
FORMAT_VERSION = 1

MANIFEST_NAME = "SNAPSHOT.json"

# Leaves are packed into chunk files of at most this many bytes (a leaf
# larger than the bound gets its own file).  Bounded chunks keep restore
# read-ahead and CRC verification incremental instead of one giant file.
DEFAULT_CHUNK_BYTES = 256 * 2**20


class SnapshotError(Exception):
    """Typed failure of a snapshot read: corrupt/truncated chunk, CRC
    mismatch, malformed manifest.  Callers treat it as 'this snapshot is
    unusable' and fall back to the cold load path."""


class SnapshotMismatch(SnapshotError):
    """The snapshot on disk was baked for a different identity (model
    version, quantize mode, mesh) or format version — a cache miss, not
    corruption."""


# ---------------------------------------------------------------------------
# Identity
# ---------------------------------------------------------------------------


def snapshot_identity(
    model_uri: str, quantize: str | None, mesh_shape: dict | None
) -> dict[str, Any]:
    """The invalidation key, as data: everything that changes the device
    tree a load produces."""
    return {
        "model_uri": str(model_uri),
        "quantize": quantize or "none",
        "mesh_shape": {k: int(v) for k, v in sorted((mesh_shape or {}).items())},
        "format_version": FORMAT_VERSION,
    }


def content_hash(identity: dict[str, Any]) -> str:
    """Stable short hash of an identity dict (sorted-key JSON, sha256)."""
    blob = json.dumps(identity, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def snapshot_path_for(snapshot_dir: str | Path, model_uri: str) -> Path:
    """Deterministic snapshot location for a model artifact — the operator
    computes the same path to record ``status.snapshot.uri`` on a parked
    CR without ever touching the data plane.

    Keyed by the model URI ONLY (a new model version is a new URI, so it
    bakes beside the old); the quantize/mesh half of the identity lives
    in the manifest's content hash, so flipping those knobs hits the
    same location, mismatches, falls back to the cold load, and re-bakes
    in place — stale state can never be restored, only replaced."""
    tag = hashlib.sha256(str(model_uri).encode()).hexdigest()[:16]
    return Path(snapshot_dir) / tag


# ---------------------------------------------------------------------------
# dtype round-trip (numpy has no native bf16; ml_dtypes supplies it)
# ---------------------------------------------------------------------------


def _dtype_from_name(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def _leaf_to_numpy(leaf: Any) -> np.ndarray:
    """Device array -> host ndarray with its dtype intact (bf16 stays
    bf16 — the whole point is writing the device-resident bytes)."""
    arr = np.asarray(leaf)
    return np.ascontiguousarray(arr)


def _spec_to_data(spec) -> list:
    """PartitionSpec -> JSON-serializable form (axis name, list of
    names, or None per dimension)."""
    out = []
    for p in spec:
        if p is None:
            out.append(None)
        elif isinstance(p, (tuple, list)):
            out.append([str(a) for a in p])
        else:
            out.append(str(p))
    return out


def _spec_from_data(data) -> "Any":
    from jax.sharding import PartitionSpec

    return PartitionSpec(
        *[tuple(p) if isinstance(p, list) else p for p in data]
    )


def _shard_plan(leaf: Any):
    """``None`` for a single-device/replicated leaf (flat layout), else
    ``(spec_data, [(starts, shard_ndarray), ...])`` for a partitioned
    one — each shard the bytes ONE device holds, deduplicated by slice
    start (partial replication writes each distinct block once)."""
    sharding = getattr(leaf, "sharding", None)
    if sharding is None:
        return None
    try:
        from jax.sharding import NamedSharding

        if not isinstance(sharding, NamedSharding):
            return None
        if len(sharding.device_set) <= 1 or sharding.is_fully_replicated:
            return None
    except Exception:  # pragma: no cover - exotic sharding types
        return None
    seen: dict[tuple, np.ndarray] = {}
    for s in leaf.addressable_shards:
        starts = tuple(int(sl.start or 0) for sl in s.index)
        if starts not in seen:
            seen[starts] = np.ascontiguousarray(np.asarray(s.data))
    return _spec_to_data(sharding.spec), sorted(seen.items())


# ---------------------------------------------------------------------------
# Writing
# ---------------------------------------------------------------------------


def write_snapshot(
    snapshot_dir: str | Path,
    params: Any,
    *,
    identity: dict[str, Any],
    flavor: str,
    config: dict | None = None,
    builder_kwargs: dict | None = None,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
) -> Path:
    """Write the device tree as a restorable snapshot; returns its path.

    Atomic: everything is staged in a temp directory next to the target
    and renamed into place, so a crash mid-write can never leave a
    half-snapshot that a later restore would trust (restores also verify
    per-leaf CRCs, but the rename makes the common case clean).  Writing
    over an existing snapshot of the same model URI replaces it whole.
    """
    from .loader import _flatten  # one flattening scheme, spelled once

    target = snapshot_path_for(snapshot_dir, identity["model_uri"])
    target.parent.mkdir(parents=True, exist_ok=True)
    t0 = time.perf_counter()
    # convert=False: leaves keep their device placement so _shard_plan
    # can see a partitioned leaf's sharding and write it per-shard.
    flat = _flatten(params, convert=False)
    staging = Path(
        tempfile.mkdtemp(prefix=".snapshot-", dir=str(target.parent))
    )
    try:
        leaves = []
        state = {
            "idx": -1,
            "f": None,
            # force a fresh chunk on first blob
            "used": chunk_bytes + 1,
            "total": 0,
        }

        def emit(raw: bytes) -> tuple[str, int]:
            """Append one blob to the current (or a fresh) chunk file;
            returns its (file, offset)."""
            if state["used"] + len(raw) > chunk_bytes and state["used"] > 0:
                if state["f"] is not None:
                    state["f"].close()
                state["idx"] += 1
                state["f"] = open(
                    staging / f"chunk-{state['idx']:05d}.bin", "wb"
                )
                state["used"] = 0
            off = state["used"]
            state["f"].write(raw)
            state["used"] += len(raw)
            state["total"] += len(raw)
            return f"chunk-{state['idx']:05d}.bin", off

        try:
            for key in sorted(flat):
                plan = _shard_plan(flat[key])
                if plan is None:
                    # Flat layout — byte-for-byte the pre-tp format for
                    # every single-device/replicated leaf.
                    arr = _leaf_to_numpy(flat[key])
                    raw = arr.tobytes()
                    fname, off = emit(raw)
                    leaves.append(
                        {
                            "key": key,
                            "dtype": arr.dtype.name,
                            "shape": list(arr.shape),
                            "file": fname,
                            "offset": off,
                            "nbytes": len(raw),
                            "crc32": binascii.crc32(raw) & 0xFFFFFFFF,
                        }
                    )
                    continue
                spec_data, shards = plan
                entry = {
                    "key": key,
                    "dtype": shards[0][1].dtype.name,
                    "shape": list(flat[key].shape),
                    "spec": spec_data,
                    "shards": [],
                }
                for starts, sarr in shards:
                    raw = sarr.tobytes()
                    fname, off = emit(raw)
                    entry["shards"].append(
                        {
                            "file": fname,
                            "offset": off,
                            "nbytes": len(raw),
                            "crc32": binascii.crc32(raw) & 0xFFFFFFFF,
                            "start": list(starts),
                            "shape": list(sarr.shape),
                        }
                    )
                leaves.append(entry)
        finally:
            if state["f"] is not None:
                state["f"].close()
        total = state["total"]
        manifest = {
            "format_version": FORMAT_VERSION,
            "identity": identity,
            "content_hash": content_hash(identity),
            "flavor": flavor,
            "config": config or {},
            "builder_kwargs": builder_kwargs or {},
            "total_bytes": total,
            "leaves": leaves,
        }
        (staging / MANIFEST_NAME).write_text(json.dumps(manifest, indent=1))
        if target.exists():
            shutil.rmtree(target)
        os.replace(staging, target)
    except BaseException:
        shutil.rmtree(staging, ignore_errors=True)
        raise
    _log.info(
        "wrote snapshot %s: %d leaves, %.2f GiB in %.1fs",
        target,
        len(leaves),
        total / 2**30,
        time.perf_counter() - t0,
    )
    return target


# ---------------------------------------------------------------------------
# Reading
# ---------------------------------------------------------------------------


def read_manifest(path: str | Path) -> dict[str, Any]:
    """Parse + structurally validate a snapshot manifest.  Raises
    :class:`SnapshotError` on anything malformed."""
    mf = Path(path) / MANIFEST_NAME
    if not mf.exists():
        raise SnapshotError(f"no {MANIFEST_NAME} in {path}")
    try:
        manifest = json.loads(mf.read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise SnapshotError(f"unreadable snapshot manifest {mf}: {e}") from e
    if not isinstance(manifest, dict) or not isinstance(
        manifest.get("leaves"), list
    ):
        raise SnapshotError(f"malformed snapshot manifest {mf}")
    return manifest


def check_identity(manifest: dict, identity: dict[str, Any]) -> None:
    """Raise :class:`SnapshotMismatch` unless the manifest was baked for
    exactly this identity (format version rides inside the identity)."""
    if int(manifest.get("format_version", -1)) != FORMAT_VERSION:
        raise SnapshotMismatch(
            f"snapshot format v{manifest.get('format_version')} != "
            f"v{FORMAT_VERSION}"
        )
    if manifest.get("content_hash") != content_hash(identity):
        raise SnapshotMismatch(
            "snapshot identity mismatch: baked for "
            f"{manifest.get('identity')}, requested {identity}"
        )


def load_snapshot(
    path: str | Path,
    *,
    identity: dict[str, Any] | None = None,
    stats: dict | None = None,
    to_device: bool = True,
) -> tuple[Any, dict[str, Any]]:
    """Restore ``(params, manifest)`` from a snapshot directory.

    Streams leaf-by-leaf with a reader thread so disk read overlaps the
    host→device transfer (the restore is pure I/O: no quantize, no
    reshard — the bytes on disk ARE the device layout).  Each leaf's CRC
    is verified before its bytes are trusted; a truncated chunk or CRC
    mismatch raises :class:`SnapshotError`.  When ``identity`` is given,
    a mismatch raises :class:`SnapshotMismatch` BEFORE any data is read.

    ``stats`` (optional dict) is filled with ``restore_s`` / ``disk_s`` /
    ``transfer_s`` / ``read_gib`` so a slow restore says which stage was
    slow — same shape the cold path's ``load_stats`` uses.

    Per-shard leaves (a tp > 1 bake) restore WITHOUT ever assembling the
    full leaf on host: the mesh is rebuilt from the manifest identity's
    ``mesh_shape`` and each shard device-puts straight onto its device
    (``jax.make_array_from_single_device_arrays``).  Restoring a
    sharded snapshot onto a process with too few devices raises
    :class:`SnapshotError` (the caller cold-loads).
    """
    import queue as _queue
    import threading

    from .loader import _unflatten

    path = Path(path)
    manifest = read_manifest(path)
    if identity is not None:
        check_identity(manifest, identity)

    # Flatten leaves into one read plan: a flat leaf is one record, a
    # sharded leaf one record per shard (written contiguously, so the
    # reader stays sequential per chunk file).
    records: list[dict] = []
    sharded = False
    for leaf in manifest["leaves"]:
        if "shards" in leaf:
            sharded = True
            for i, srec in enumerate(leaf["shards"]):
                records.append(
                    {
                        **srec,
                        "key": leaf["key"],
                        "dtype": leaf["dtype"],
                        "leaf": leaf,
                        "last_shard": i == len(leaf["shards"]) - 1,
                    }
                )
        else:
            records.append({**leaf, "leaf": None})

    mesh = None
    if sharded and to_device:
        mesh_shape = (manifest.get("identity") or {}).get("mesh_shape") or {}
        try:
            from ..models.partition import build_serving_mesh

            mesh = build_serving_mesh(mesh_shape)
        except Exception as e:
            # MISMATCH, not corruption: the snapshot is valid, THIS
            # process just cannot host its mesh (fewer visible devices —
            # a CPU debug run, a degraded slice).  SnapshotError here
            # would make the loader quarantine a perfectly good bake
            # over an environmental condition.
            raise SnapshotMismatch(
                f"sharded snapshot needs mesh {mesh_shape}, which this "
                f"process cannot build: {e}"
            ) from e

    t_wall = time.perf_counter()
    timing = {"disk_s": 0.0, "transfer_s": 0.0, "read_bytes": 0}
    q: _queue.Queue = _queue.Queue(maxsize=4)
    reader_error: list[BaseException] = []
    abort = threading.Event()

    def reader() -> None:
        open_file = None
        open_name = None
        try:
            for rec in records:
                if abort.is_set():
                    return
                t0 = time.perf_counter()
                if rec["file"] != open_name:
                    if open_file is not None:
                        open_file.close()
                    fpath = path / rec["file"]
                    if not fpath.exists():
                        raise SnapshotError(
                            f"snapshot chunk {rec['file']} missing in {path}"
                        )
                    open_file = open(fpath, "rb")
                    open_name = rec["file"]
                open_file.seek(rec["offset"])
                raw = open_file.read(rec["nbytes"])
                if len(raw) != rec["nbytes"]:
                    raise SnapshotError(
                        f"snapshot chunk {rec['file']} truncated at leaf "
                        f"{rec['key']!r}: wanted {rec['nbytes']} bytes, "
                        f"got {len(raw)}"
                    )
                if (binascii.crc32(raw) & 0xFFFFFFFF) != rec["crc32"]:
                    raise SnapshotError(
                        f"snapshot leaf {rec['key']!r} failed CRC in "
                        f"{rec['file']}"
                    )
                arr = np.frombuffer(
                    raw, dtype=_dtype_from_name(rec["dtype"])
                ).reshape(rec["shape"])
                timing["disk_s"] += time.perf_counter() - t0
                timing["read_bytes"] += rec["nbytes"]
                q.put((rec, arr))
        except BaseException as e:
            reader_error.append(e)
        finally:
            if open_file is not None:
                open_file.close()
            q.put(None)

    rthread = threading.Thread(
        target=reader, daemon=True, name="snapshot-reader"
    )
    rthread.start()

    leaves: dict[str, Any] = {}
    pending: dict[str, dict[tuple, np.ndarray]] = {}
    try:
        if to_device:
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec

            rep = (
                NamedSharding(mesh, PartitionSpec())
                if mesh is not None else None
            )

        def place_flat(arr):
            if not to_device:
                return arr
            # Replicated leaves of a sharded tree commit to the mesh so
            # the engine programs see one consistent device set.
            return jnp.asarray(arr) if rep is None else jax.device_put(
                arr, rep
            )

        def assemble(leaf, shard_map):
            shape = tuple(leaf["shape"])
            if not to_device:
                full = np.zeros(shape, _dtype_from_name(leaf["dtype"]))
                for starts, arr in shard_map.items():
                    idx = tuple(
                        slice(st, st + n)
                        for st, n in zip(starts, arr.shape)
                    )
                    full[idx] = arr
                return full
            sh = NamedSharding(mesh, _spec_from_data(leaf["spec"]))
            bufs = []
            for dev, idx in sh.devices_indices_map(shape).items():
                starts = tuple(int(sl.start or 0) for sl in idx)
                arr = shard_map.get(starts)
                if arr is None:
                    raise SnapshotError(
                        f"snapshot leaf {leaf['key']!r} has no shard at "
                        f"offset {starts} for mesh placement"
                    )
                bufs.append(jax.device_put(arr, dev))
            return jax.make_array_from_single_device_arrays(
                shape, sh, bufs
            )

        while True:
            item = q.get()
            if item is None:
                break
            rec, arr = item
            t0 = time.perf_counter()
            if rec["leaf"] is None:
                leaves[rec["key"]] = place_flat(arr)
            else:
                acc = pending.setdefault(rec["key"], {})
                acc[tuple(rec["start"])] = arr
                if rec["last_shard"]:
                    leaves[rec["key"]] = assemble(rec["leaf"], acc)
                    del pending[rec["key"]]
            timing["transfer_s"] += time.perf_counter() - t0
    except BaseException:
        # Same reader-unwedging contract as _stream_native_params: a
        # consumer failure must not strand the reader on the bounded put.
        abort.set()
        while True:
            try:
                if q.get_nowait() is None:
                    break
            except _queue.Empty:
                if not rthread.is_alive():
                    break
                time.sleep(0.01)
        raise
    if reader_error:
        err = reader_error[0]
        if isinstance(err, SnapshotError):
            raise err
        raise SnapshotError(f"snapshot read failed: {err}") from err
    if stats is not None:
        stats.update(
            restore_s=round(time.perf_counter() - t_wall, 3),
            disk_s=round(timing["disk_s"], 3),
            transfer_s=round(timing["transfer_s"], 3),
            read_gib=round(timing["read_bytes"] / 2**30, 3),
        )
    return _unflatten(leaves), manifest
