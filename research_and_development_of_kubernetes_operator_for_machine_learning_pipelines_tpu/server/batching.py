"""Dynamic request batching with power-of-two padding buckets.

TPU serving economics: the MXU wants large batches, XLA wants few distinct
shapes.  The batcher bridges both — requests queue briefly
(``max_batch_delay_ms``), are grouped by trailing shape (so a seq-128 BERT
batch never pads against a seq-32 one), stacked, padded up to the next
power-of-two batch bucket, run once, and split back per caller.  Each bucket
shape compiles exactly once (the engine warms the common ones at startup).
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping

import numpy as np


def next_bucket(n: int, max_batch: int) -> int:
    """Smallest power of two >= n, capped at max_batch."""
    b = 1
    while b < n:
        b <<= 1
    return min(b, max_batch)


def _group_key(inputs: Mapping[str, np.ndarray]) -> tuple:
    return tuple(sorted((k, v.shape[1:], str(v.dtype)) for k, v in inputs.items()))


def seq_buckets(spec: Mapping[str, Any]) -> list[int]:
    """The servable length-bucket ladder for a ``seq_pad`` spec: powers of
    two from ``min_bucket``, topped by ``max_len`` itself (which may not
    be a power of two).  ONE definition — ``apply_seq_pad`` pads onto it
    and the engine warmup compiles it; two copies would let warmed and
    served shapes drift apart."""
    max_len = int(spec.get("max_len") or 0)
    ladder = []
    length = max(int(spec.get("min_bucket", 16)), 1)
    while not max_len or length < max_len:
        ladder.append(length)
        if not max_len and length >= 1 << 20:
            break  # uncapped spec: don't ladder to infinity
        length *= 2
    if max_len:
        ladder.append(max_len)
    return ladder


def apply_seq_pad(
    inputs: Mapping[str, np.ndarray], spec: Mapping[str, Any]
) -> dict[str, np.ndarray]:
    """Pad sequence-shaped inputs to a power-of-two length bucket.

    Without this, every distinct request length is a distinct batch-group
    shape — each one a fresh XLA compile and a batch nothing else can
    join.  With it, lengths collapse into log-many buckets that merge in
    the batcher and compile once each.

    ``spec`` (Predictor.seq_pad) is declarative:

    - ``axis``: the sequence axis (default 1);
    - ``pad_values``: {input_name: fill} — ONLY these inputs are padded,
      with model-correct fills (for BERT: ids 0, attention_mask 0 — the
      mask makes padding mathematically exact for pooled/classification
      outputs; token-level outputs would need slicing and are not
      eligible);
    - ``synthesize``: {input_name: fill} — inputs to create as a full
      ``fill`` array when the request omits them, BEFORE padding.
      Without this a request lacking attention_mask would have its
      padded id positions attended (the model defaults a missing mask
      to all-ones over the PADDED length);
    - ``min_bucket`` (default 16) and ``max_len`` (cap): requests longer
      than ``max_len`` raise ValueError — the HTTP layer turns that into
      a 400.  Letting them through would silently clamp position
      embeddings (garbage 200s) and hand hostile clients a fresh XLA
      compile per distinct over-long length.
    """
    axis = int(spec.get("axis", 1))
    pad_values = spec.get("pad_values") or {}
    out = dict(inputs)
    ref = next((out[k] for k in pad_values if k in out), None)
    if ref is None:
        return out
    for name, fill in (spec.get("synthesize") or {}).items():
        if name not in out:
            out[name] = np.full_like(ref, fill)
    lengths = {k: out[k].shape[axis] for k in pad_values if k in out}
    if len(set(lengths.values())) > 1:
        # Padding each input to the max would silently mask out real
        # tokens (e.g. a short attention_mask zero-extended over live
        # ids) — malformed requests must error, not get "repaired".
        raise ValueError(
            f"sequence inputs disagree on length along axis {axis}: {lengths}"
        )
    length = next(iter(lengths.values()))
    max_len = int(spec.get("max_len") or 0)
    if max_len and length > max_len:
        raise ValueError(
            f"sequence length {length} exceeds the model maximum {max_len}"
        )
    bucket = next((b for b in seq_buckets(spec) if b >= length), None)
    if bucket is None:
        # Uncapped spec past the ladder's safety stop (~1M tokens): a
        # bare StopIteration here would surface as a 500.
        raise ValueError(
            f"sequence length {length} exceeds the bucket ladder "
            f"(declare max_len in seq_pad to raise the cap explicitly)"
        )
    if bucket <= length:
        return out  # already exactly bucket-sized
    for name in pad_values:
        if name not in out:
            continue
        v = out[name]
        widths = [(0, 0)] * v.ndim
        widths[axis] = (0, bucket - v.shape[axis])
        out[name] = np.pad(v, widths, constant_values=pad_values[name])
    return out


@dataclass
class _Item:
    inputs: dict[str, np.ndarray]  # each [1, ...] (single example, batch dim 1)
    future: Future
    enqueued_at: float = field(default_factory=time.perf_counter)


class DynamicBatcher:
    """Collects single-example requests into padded batches.

    ``run_batch(inputs: dict[str, np.ndarray]) -> np.ndarray | tuple`` is
    called with stacked+padded arrays; outputs are split along axis 0 and
    delivered to each request's Future.

    **Pipelined mode** (pass ``materialize``): ``run_batch`` is treated
    as an ASYNC dispatch (XLA returns device-array promises immediately)
    and ``materialize(out)`` as the blocking wait.  The collector then
    stacks, pads, and dispatches batch N+1 while batch N still executes
    on device — double buffering, bounded by ``max_inflight`` dispatched-
    but-unmaterialized batches (the put blocks as backpressure).  Under
    concurrent load this removes the serial wait each request otherwise
    pays for the in-flight batch ahead of it (VERDICT r3 #4: queue wait
    was ~an entire device run at clients=8).  Without ``materialize``
    the batcher runs exactly as before: one synchronous batch at a time.
    """

    def __init__(
        self,
        run_batch: Callable[[dict[str, np.ndarray]], Any],
        max_batch_size: int = 32,
        max_batch_delay_ms: float = 5.0,
        on_batch: Callable[[int, float, float, float], None] | None = None,
        materialize: Callable[[Any], Any] | None = None,
        max_inflight: int = 2,
    ):
        self._run_batch = run_batch
        self._materialize = materialize
        self.max_batch_size = int(max_batch_size)
        self.max_delay_s = float(max_batch_delay_ms) / 1000.0
        self.max_inflight = max(1, int(max_inflight)) if materialize else 1
        self._on_batch = on_batch
        self._queue: queue.Queue[_Item | None] = queue.Queue()
        self._inflight: queue.Queue = queue.Queue(maxsize=self.max_inflight)
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._completer = threading.Thread(
            target=self._completion_worker, daemon=True
        )
        self._started = False
        self._stop = False

    def start(self) -> None:
        if not self._started:
            self._started = True
            self._thread.start()
            self._completer.start()

    def stop(self) -> None:
        self._stop = True
        self._queue.put(None)
        if self._started:
            # A wedged materialize can leave the completer stuck and the
            # in-flight queue full (with the collector blocked on its
            # put) — drain BEFORE joining so the collector unsticks, and
            # never block on the sentinel put: everything here must stay
            # bounded even when the device hangs.
            self._drain_inflight()
            self._thread.join(timeout=5)
            try:
                self._inflight.put_nowait(None)
            except queue.Full:
                self._drain_inflight()
                try:
                    self._inflight.put_nowait(None)
                except queue.Full:
                    pass  # completer is wedged; it's a daemon thread
            self._completer.join(timeout=5)
        # Fail anything still queued (including different-shape items the
        # collector re-queued) so in-flight HTTP requests get an error
        # instead of hanging until the server's shutdown timeout.
        self._drain_inflight()
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not None and not item.future.done():
                item.future.set_exception(RuntimeError("server shutting down"))

    def _drain_inflight(self) -> None:
        while True:
            try:
                entry = self._inflight.get_nowait()
            except queue.Empty:
                return
            if entry is not None:
                for item in entry[0]:
                    if not item.future.done():
                        item.future.set_exception(
                            RuntimeError("server shutting down")
                        )

    # -- client side ---------------------------------------------------------

    def submit(self, inputs: Mapping[str, np.ndarray]) -> Future:
        """Submit one example (arrays WITHOUT batch dim); returns a Future."""
        batched = {k: np.asarray(v)[None, ...] for k, v in inputs.items()}
        fut: Future = Future()
        self._queue.put(_Item(batched, fut))
        return fut

    # -- worker side ---------------------------------------------------------

    def _collect(self) -> list[_Item]:
        first = self._queue.get()
        if first is None:
            return []
        items = [first]
        deadline = time.perf_counter() + self.max_delay_s
        key = _group_key(first.inputs)
        pending: list[_Item] = []
        while len(items) < self.max_batch_size:
            timeout = deadline - time.perf_counter()
            if timeout <= 0:
                break
            try:
                item = self._queue.get(timeout=timeout)
            except queue.Empty:
                break
            if item is None:
                self._stop = True
                break
            if _group_key(item.inputs) == key:
                items.append(item)
            else:
                pending.append(item)  # different shape: next batch
        for p in pending:
            self._queue.put(p)
        return items

    def _worker(self) -> None:
        while not self._stop:
            items = self._collect()
            if not items:
                continue
            self._dispatch(items)

    def _dispatch(self, items: list[_Item]) -> None:
        """Stack, pad, and (async-)dispatch one batch.

        Dispatch errors (bad shapes, XLA compile failures — both raise
        synchronously) fail this batch's futures here; device-side
        runtime errors surface at materialize time in the completer.
        """
        n = len(items)
        bucket = next_bucket(n, self.max_batch_size)
        try:
            stacked = {
                k: np.concatenate([it.inputs[k] for it in items], axis=0)
                for k in items[0].inputs
            }
            if bucket > n:  # pad by repeating the last example (valid data,
                # so no NaN/inf poisoning from zero-padding odd dtypes)
                pad = {k: np.repeat(v[-1:], bucket - n, axis=0) for k, v in stacked.items()}
                stacked = {k: np.concatenate([v, pad[k]], axis=0) for k, v in stacked.items()}
            queue_age = time.perf_counter() - items[0].enqueued_at
            t_run = time.perf_counter()
            out = self._run_batch(stacked)
        except Exception as e:
            for item in items:
                if not item.future.done():
                    item.future.set_exception(e)
            return
        # Blocks once max_inflight batches are dispatched-but-unfinished:
        # backpressure that keeps device memory bounded.
        if self._stop:
            # stop() may already have drained the in-flight queue and let
            # the completer exit on its sentinel (e.g. this dispatch sat
            # in a multi-minute compile past the join timeout).  Putting
            # the entry there now would strand its futures forever — fail
            # them directly, matching what stop()'s drain does to every
            # other in-flight batch.
            for item in items:
                if not item.future.done():
                    item.future.set_exception(
                        RuntimeError("server shutting down")
                    )
            return
        self._inflight.put((items, n, out, queue_age, t_run))

    def _completion_worker(self) -> None:
        t_prev_done = 0.0
        while True:
            entry = self._inflight.get()
            if entry is None:
                return
            items, n, out, queue_age, t_run = entry
            try:
                if self._materialize is not None:
                    out = self._materialize(out)
                done = time.perf_counter()
                # Marginal run time: under pipelining, batch N+1's wait
                # includes batch N's leftover device time; measuring
                # from max(dispatch, previous completion) records the
                # time THIS batch added to the pipeline (steady state =
                # its device time), keeping the queue/run/overhead
                # decomposition additive instead of double-counting.
                # The time spent waiting BEHIND the predecessor is its
                # own term (pipeline_wait) so it doesn't masquerade as
                # server overhead in the residual.
                run_seconds = done - max(t_run, t_prev_done)
                pipeline_wait = max(0.0, t_prev_done - t_run)
                t_prev_done = done
                if self._on_batch:
                    self._on_batch(n, queue_age, run_seconds, pipeline_wait)
                outputs = _split_outputs(out, n)
                for i, item in enumerate(items):
                    if not item.future.done():  # stop() may have failed it
                        item.future.set_result(outputs[i])
            except Exception as e:
                for item in items:
                    if not item.future.done():
                        item.future.set_exception(e)


def _split_outputs(out: Any, n: int) -> list[Any]:
    """Split batch-dim-0 outputs (array or tuple/dict of arrays) into n rows."""
    if isinstance(out, (tuple, list)):
        parts = [_split_outputs(o, n) for o in out]
        return [tuple(p[i] for p in parts) for i in range(n)]
    if isinstance(out, dict):
        parts = {k: _split_outputs(v, n) for k, v in out.items()}
        return [{k: v[i] for k, v in parts.items()} for i in range(n)]
    arr = np.asarray(out)
    return [arr[i] for i in range(n)]
