"""Radix-tree prefix KV cache: cross-request prompt reuse.

The dominant redundant work in the generation data plane is prefill:
thousands of requests share the same system-prompt/chat-template prefix,
and every one of them recomputes its full K/V.  Prompt K/V is a pure
function of the token prefix (causal attention: position ``p`` depends
only on tokens ``<= p``), so K/V computed once for a prefix can be
copied — not recomputed — into every later request that shares it.

Design:

- The reuse unit is the engine's PREFILL CHUNK (``prefill_chunk`` /
  ``spec.tpu.prefixCache.chunkTokens``): prompts are already split into
  fixed-size chunks by the chunked-prefill path, each chunk's K/V spans
  a contiguous cache slice, and one chunk shape means one compiled
  insert program.
- The index is a radix tree over chunks.  Each node is one chunk; the
  path from the root IS the cumulative key (node identity = the entire
  token prefix up to and including its chunk), so two prompts sharing
  ``k`` leading chunks share exactly ``k`` nodes.  Edges are keyed by
  the chunk's exact token bytes rather than a digest — a hash collision
  here would silently splice another prompt's K/V into a request and
  corrupt its logits, and the bytes are small (4 B/token).
- Each node owns host copies of its chunk's K/V (``[L, 1, C, NKV, D]``,
  the seq-prefill layout) written back after the chunk's fresh prefill
  completes.  Only FULL chunks made of real prompt tokens are cached;
  a padded tail chunk carries pad-token garbage K/V.
- Eviction is LRU over leaves under a byte budget.  Interior nodes are
  never evicted (their descendants' keys would dangle); a cold branch
  drains leaf-first, which is also reference-count order.

Thread-safety: all calls happen on the engine's single scheduler
thread; no locking needed.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import numpy as np


@dataclass(frozen=True)
class PrefixCacheConfig:
    """Engine-side knobs (parsed from ``spec.tpu.prefixCache``)."""

    enabled: bool = False
    budget_bytes: int = 256 * 2**20
    # Reuse unit; must equal the engine's prefill chunk (or, when
    # prefillChunk is unset, becomes it — enabling the cache enables
    # chunked prefill).
    chunk_tokens: int = 64


class _Node:
    __slots__ = ("key", "kv", "nbytes", "parent", "children", "last_used")

    def __init__(self, key: bytes, kv, nbytes: int, parent: "_Node | None"):
        self.key = key
        self.kv = kv  # (k, v) host arrays, or None on the root
        self.nbytes = nbytes
        self.parent = parent
        self.children: dict[bytes, _Node] = {}
        self.last_used = 0


def _chunk_key(prompt: np.ndarray, idx: int, chunk_tokens: int) -> bytes:
    chunk = np.asarray(
        prompt[idx * chunk_tokens : (idx + 1) * chunk_tokens], np.int32
    )
    return chunk.tobytes()


class RadixPrefixCache:
    """Radix tree of prompt chunks with an LRU-evicted host K/V pool."""

    def __init__(
        self,
        budget_bytes: int,
        chunk_tokens: int,
        on_evict: Callable[[int], None] | None = None,
    ):
        if budget_bytes <= 0:
            raise ValueError(
                f"prefix cache budget must be positive, got {budget_bytes}"
            )
        if chunk_tokens <= 0:
            raise ValueError(
                f"prefix cache chunk_tokens must be positive, got {chunk_tokens}"
            )
        self.budget_bytes = int(budget_bytes)
        self.chunk_tokens = int(chunk_tokens)
        self._root = _Node(b"", None, 0, None)
        self._on_evict = on_evict
        # Leaves tracked incrementally: eviction runs on the engine's
        # single scheduler thread (between decode ticks), so it must not
        # walk the whole tree per evicted node.
        self._leaves: set[_Node] = set()
        self.bytes = 0
        self.lookups = 0
        self.evictions = 0
        self._tick = 0

    # -- queries -------------------------------------------------------------

    def lookup(self, prompt: np.ndarray) -> tuple[int, list]:
        """Longest cached prefix of ``prompt`` in whole chunks.

        Returns ``(matched_tokens, [(k, v), ...])`` — one host K/V pair
        per matched chunk, in order.  The match is capped STRICTLY below
        the prompt length: at least one token must run real prefill so
        the admission has final-position logits to sample the first
        generated token from (``matched <= ((len - 1) // C) * C``).
        Touches every matched node (LRU recency).
        """
        self.lookups += 1
        self._tick += 1
        C = self.chunk_tokens
        max_chunks = (int(np.asarray(prompt).size) - 1) // C
        node = self._root
        out: list = []
        for i in range(max_chunks):
            child = node.children.get(_chunk_key(prompt, i, C))
            if child is None:
                break
            child.last_used = self._tick
            out.append(child.kv)
            node = child
        return len(out) * C, out

    # -- inserts / eviction --------------------------------------------------

    def has_chunk(self, prompt: np.ndarray, chunk_idx: int) -> bool:
        """Existence probe (no LRU touch): lets the engine skip the
        device-to-host K/V read for chunks already cached — the read is
        a sync on the scheduler thread, so it must only be paid once per
        unique chunk."""
        C = self.chunk_tokens
        node = self._root
        for i in range(chunk_idx + 1):
            node = node.children.get(_chunk_key(prompt, i, C))
            if node is None:
                return False
        return True

    def insert_chunk(
        self, prompt: np.ndarray, chunk_idx: int, k: np.ndarray, v: np.ndarray
    ) -> bool:
        """Attach chunk ``chunk_idx`` of ``prompt`` with its K/V.

        The parent path (chunks ``0..chunk_idx-1``) must already exist —
        admissions insert chunks in order, so it does unless an
        interleaved admission evicted it; in that case the insert is
        dropped (returns False) rather than attaching K/V under a wrong
        cumulative key.  Returns True when the chunk is (now) cached.
        """
        self._tick += 1
        C = self.chunk_tokens
        node = self._root
        for i in range(chunk_idx):
            child = node.children.get(_chunk_key(prompt, i, C))
            if child is None:
                return False
            child.last_used = self._tick
            node = child
        key = _chunk_key(prompt, chunk_idx, C)
        existing = node.children.get(key)
        if existing is not None:
            existing.last_used = self._tick
            return True
        k = np.asarray(k)
        v = np.asarray(v)
        nbytes = k.nbytes + v.nbytes
        if nbytes > self.budget_bytes:
            return False  # one chunk bigger than the whole pool
        child = _Node(key, (k, v), nbytes, node)
        child.last_used = self._tick
        node.children[key] = child
        if node is not self._root:
            self._leaves.discard(node)  # gained a child: interior now
        self._leaves.add(child)
        self.bytes += nbytes
        while self.bytes > self.budget_bytes and self._evict_lru():
            pass
        return key in node.children

    def _evict_lru(self) -> bool:
        """Drop the least-recently-used LEAF (interior nodes anchor their
        descendants' cumulative keys and are never evicted directly)."""
        if not self._leaves:
            return False
        # Tie-break equal recencies on the chunk key: set iteration order
        # varies across processes, and eviction must stay deterministic
        # (multihost follower replicas are future work; determinism now
        # costs nothing and unblocks it).
        victim = min(self._leaves, key=lambda n: (n.last_used, n.key))
        parent = victim.parent
        assert parent is not None
        del parent.children[victim.key]
        self._leaves.discard(victim)
        if not parent.children and parent is not self._root:
            self._leaves.add(parent)  # lost its last child: leaf again
        self.bytes -= victim.nbytes
        self.evictions += 1
        if self._on_evict is not None:
            self._on_evict(victim.nbytes)
        return True

    # -- introspection -------------------------------------------------------

    def __len__(self) -> int:
        n = 0
        stack = [self._root]
        while stack:
            node = stack.pop()
            n += len(node.children)
            stack.extend(node.children.values())
        return n
