"""Radix-tree prefix KV cache: cross-request prompt reuse.

The dominant redundant work in the generation data plane is prefill:
thousands of requests share the same system-prompt/chat-template prefix,
and every one of them recomputes its full K/V.  Prompt K/V is a pure
function of the token prefix (causal attention: position ``p`` depends
only on tokens ``<= p``), so K/V computed once for a prefix can be
copied — not recomputed — into every later request that shares it.

Design:

- The reuse unit is the engine's PREFILL CHUNK (``prefill_chunk`` /
  ``spec.tpu.prefixCache.chunkTokens``): prompts are already split into
  fixed-size chunks by the chunked-prefill path, each chunk's K/V spans
  a contiguous cache slice, and one chunk shape means one compiled
  insert program.
- The index is a radix tree over chunks.  Each node is one chunk; the
  path from the root IS the cumulative key (node identity = the entire
  token prefix up to and including its chunk), so two prompts sharing
  ``k`` leading chunks share exactly ``k`` nodes.  Edges are keyed by
  the chunk's exact token bytes rather than a digest — a hash collision
  here would silently splice another prompt's K/V into a request and
  corrupt its logits, and the bytes are small (4 B/token).
- Each node owns host copies of its chunk's K/V (``[L, 1, C, NKV, D]``,
  the seq-prefill layout) written back after the chunk's fresh prefill
  completes.  Only FULL chunks made of real prompt tokens are cached;
  a padded tail chunk carries pad-token garbage K/V.
- Eviction is LRU over leaves under a byte budget.  Interior nodes are
  never evicted (their descendants' keys would dangle); a cold branch
  drains leaf-first, which is also reference-count order.
- An optional SECOND tier (``l2_budget_bytes`` > 0) catches evicted
  leaves instead of dropping them: the chunk's K/V moves to a flat
  host-RAM pool keyed by its cumulative token bytes, under its own LRU
  byte budget.  A radix-walk miss consults the L2 before giving up;
  a hit promotes the chunk back into the tree (re-seeded into the
  device cache through the existing ``_seed_slot`` path on the next
  admission), extending prefix reuse beyond what the first tier's
  budget — sized against HBM-adjacent copy bandwidth — can hold.

Thread-safety: all calls happen on the engine's single scheduler
thread; no locking needed.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable

import numpy as np


@dataclass(frozen=True)
class PrefixCacheConfig:
    """Engine-side knobs (parsed from ``spec.tpu.prefixCache``)."""

    enabled: bool = False
    budget_bytes: int = 256 * 2**20
    # Reuse unit; must equal the engine's prefill chunk (or, when
    # prefillChunk is unset, becomes it — enabling the cache enables
    # chunked prefill).
    chunk_tokens: int = 64
    # Second-tier host-RAM pool for evicted chunks (0 = off, the
    # single-tier behavior byte-for-byte).
    l2_budget_bytes: int = 0


class _Node:
    __slots__ = ("key", "kv", "nbytes", "parent", "children", "last_used")

    def __init__(self, key: bytes, kv, nbytes: int, parent: "_Node | None"):
        self.key = key
        self.kv = kv  # (k, v) host arrays, or None on the root
        self.nbytes = nbytes
        self.parent = parent
        self.children: dict[bytes, _Node] = {}
        self.last_used = 0


def _chunk_key(prompt: np.ndarray, idx: int, chunk_tokens: int) -> bytes:
    chunk = np.asarray(
        prompt[idx * chunk_tokens : (idx + 1) * chunk_tokens], np.int32
    )
    return chunk.tobytes()


class RadixPrefixCache:
    """Radix tree of prompt chunks with an LRU-evicted host K/V pool."""

    def __init__(
        self,
        budget_bytes: int,
        chunk_tokens: int,
        on_evict: Callable[[int], None] | None = None,
        l2_budget_bytes: int = 0,
        on_l2_event: Callable[[str], None] | None = None,
    ):
        if budget_bytes <= 0:
            raise ValueError(
                f"prefix cache budget must be positive, got {budget_bytes}"
            )
        if chunk_tokens <= 0:
            raise ValueError(
                f"prefix cache chunk_tokens must be positive, got {chunk_tokens}"
            )
        if l2_budget_bytes < 0:
            raise ValueError(
                f"prefix cache L2 budget must be >= 0, got {l2_budget_bytes}"
            )
        self.budget_bytes = int(budget_bytes)
        self.chunk_tokens = int(chunk_tokens)
        self._root = _Node(b"", None, 0, None)
        self._on_evict = on_evict
        # Leaves tracked incrementally: eviction runs on the engine's
        # single scheduler thread (between decode ticks), so it must not
        # walk the whole tree per evicted node.
        self._leaves: set[_Node] = set()
        self.bytes = 0
        self.lookups = 0
        self.evictions = 0
        self._tick = 0
        # Second tier: cumulative-token-bytes -> (k, v, nbytes), LRU via
        # OrderedDict order (hit -> move_to_end).  0 budget = disabled:
        # every L2 code path below is behind `self.l2_budget_bytes`.
        self.l2_budget_bytes = int(l2_budget_bytes)
        self._on_l2_event = on_l2_event
        self._l2: OrderedDict[bytes, tuple] = OrderedDict()
        self.l2_bytes = 0
        self.l2_hits = 0
        self.l2_spills = 0
        self.l2_evictions = 0

    def _note_l2(self, kind: str) -> None:
        if self._on_l2_event is not None:
            self._on_l2_event(kind)

    def _cum_key(self, node: _Node) -> bytes:
        """Cumulative token bytes of ``node``'s whole prefix (root path).
        Walked on demand — only spill/promote pay it, never the hot
        radix walk."""
        parts = []
        while node is not None and node.parent is not None:
            parts.append(node.key)
            node = node.parent
        return b"".join(reversed(parts))

    # -- queries -------------------------------------------------------------

    def lookup(self, prompt: np.ndarray) -> tuple[int, list]:
        """Longest cached prefix of ``prompt`` in whole chunks.

        Returns ``(matched_tokens, [(k, v), ...])`` — one host K/V pair
        per matched chunk, in order.  The match is capped STRICTLY below
        the prompt length: at least one token must run real prefill so
        the admission has final-position logits to sample the first
        generated token from (``matched <= ((len - 1) // C) * C``).
        Touches every matched node (LRU recency).
        """
        self.lookups += 1
        self._tick += 1
        C = self.chunk_tokens
        max_chunks = (int(np.asarray(prompt).size) - 1) // C
        node = self._root
        out: list = []
        for i in range(max_chunks):
            child = node.children.get(_chunk_key(prompt, i, C))
            if child is None and self.l2_budget_bytes:
                # Second tier: an evicted chunk may still be in host RAM
                # — promote it back into the tree so this admission (and
                # every later one) re-seeds it through the device path.
                child = self._promote_from_l2(prompt, i, node)
            if child is None:
                break
            child.last_used = self._tick
            out.append(child.kv)
            node = child
        return len(out) * C, out

    def _promote_from_l2(self, prompt: np.ndarray, idx: int, parent: _Node):
        """L2 hit: move a spilled chunk back under its (present) parent
        path.  Returns the re-attached node, or None on a miss — or when
        the promotion itself was immediately re-evicted (a chunk larger
        than the whole first tier)."""
        C = self.chunk_tokens
        cum = np.asarray(prompt[: (idx + 1) * C], np.int32).tobytes()
        entry = self._l2.pop(cum, None)
        if entry is None:
            return None
        k, v, nbytes = entry
        self.l2_bytes -= nbytes
        self.l2_hits += 1
        self._note_l2("hit")
        key = _chunk_key(prompt, idx, C)
        child = _Node(key, (k, v), nbytes, parent)
        child.last_used = self._tick
        parent.children[key] = child
        if parent is not self._root:
            self._leaves.discard(parent)
        self._leaves.add(child)
        self.bytes += nbytes
        while self.bytes > self.budget_bytes and self._evict_lru():
            pass
        return parent.children.get(key)

    # -- inserts / eviction --------------------------------------------------

    def has_chunk(self, prompt: np.ndarray, chunk_idx: int) -> bool:
        """Existence probe (no LRU touch): lets the engine skip the
        device-to-host K/V read for chunks already cached — the read is
        a sync on the scheduler thread, so it must only be paid once per
        unique chunk."""
        C = self.chunk_tokens
        node = self._root
        for i in range(chunk_idx + 1):
            node = node.children.get(_chunk_key(prompt, i, C))
            if node is None:
                return False
        return True

    def insert_chunk(
        self, prompt: np.ndarray, chunk_idx: int, k: np.ndarray, v: np.ndarray
    ) -> bool:
        """Attach chunk ``chunk_idx`` of ``prompt`` with its K/V.

        The parent path (chunks ``0..chunk_idx-1``) must already exist —
        admissions insert chunks in order, so it does unless an
        interleaved admission evicted it; in that case the insert is
        dropped (returns False) rather than attaching K/V under a wrong
        cumulative key.  Returns True when the chunk is (now) cached.
        """
        self._tick += 1
        C = self.chunk_tokens
        node = self._root
        for i in range(chunk_idx):
            child = node.children.get(_chunk_key(prompt, i, C))
            if child is None:
                return False
            child.last_used = self._tick
            node = child
        key = _chunk_key(prompt, chunk_idx, C)
        existing = node.children.get(key)
        if existing is not None:
            existing.last_used = self._tick
            return True
        k = np.asarray(k)
        v = np.asarray(v)
        nbytes = k.nbytes + v.nbytes
        if nbytes > self.budget_bytes:
            return False  # one chunk bigger than the whole pool
        child = _Node(key, (k, v), nbytes, node)
        child.last_used = self._tick
        if self.l2_budget_bytes:
            # A fresh insert supersedes any spilled copy of the SAME
            # chunk still sitting in L2 (possible when an earlier chunk
            # of the prompt aged out of the flat tier but deeper ones
            # remain): purge it, or the duplicate squats on L2 budget
            # until it ages out as a phantom eviction.
            stale = self._l2.pop(self._cum_key(child), None)
            if stale is not None:
                self.l2_bytes -= stale[2]
        node.children[key] = child
        if node is not self._root:
            self._leaves.discard(node)  # gained a child: interior now
        self._leaves.add(child)
        self.bytes += nbytes
        while self.bytes > self.budget_bytes and self._evict_lru():
            pass
        return key in node.children

    def _evict_lru(self) -> bool:
        """Drop the least-recently-used LEAF (interior nodes anchor their
        descendants' cumulative keys and are never evicted directly)."""
        if not self._leaves:
            return False
        # Tie-break equal recencies on the chunk key: set iteration order
        # varies across processes, and eviction must stay deterministic
        # (multihost follower replicas are future work; determinism now
        # costs nothing and unblocks it).
        victim = min(self._leaves, key=lambda n: (n.last_used, n.key))
        parent = victim.parent
        assert parent is not None
        if self.l2_budget_bytes and victim.kv is not None:
            self._spill_to_l2(victim)
        del parent.children[victim.key]
        self._leaves.discard(victim)
        if not parent.children and parent is not self._root:
            self._leaves.add(parent)  # lost its last child: leaf again
        self.bytes -= victim.nbytes
        self.evictions += 1
        if self._on_evict is not None:
            self._on_evict(victim.nbytes)
        return True

    def _spill_to_l2(self, victim: _Node) -> None:
        """Move an evicted leaf's K/V into the flat second tier (keyed by
        its CUMULATIVE token bytes — the node identity the tree encoded
        positionally), LRU-bounded by its own byte budget."""
        if victim.nbytes > self.l2_budget_bytes:
            return  # one chunk bigger than the whole second tier
        cum = self._cum_key(victim)
        old = self._l2.pop(cum, None)
        if old is not None:
            self.l2_bytes -= old[2]
        self._l2[cum] = (victim.kv[0], victim.kv[1], victim.nbytes)
        self.l2_bytes += victim.nbytes
        self.l2_spills += 1
        self._note_l2("spill")
        while self.l2_bytes > self.l2_budget_bytes:
            _key, (_k, _v, nb) = self._l2.popitem(last=False)
            self.l2_bytes -= nb
            self.l2_evictions += 1
            self._note_l2("evict")

    # -- introspection -------------------------------------------------------

    def __len__(self) -> int:
        n = 0
        stack = [self._root]
        while stack:
            node = stack.pop()
            n += len(node.children)
            stack.extend(node.children.values())
        return n
