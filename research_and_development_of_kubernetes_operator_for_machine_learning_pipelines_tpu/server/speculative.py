"""Self-speculative n-gram decoding: host-side drafter + adaptive control.

Steady-state decode is HBM-bandwidth-bound — every tick streams the full
weight tree to emit ONE token per slot.  Speculative decoding multiplies
tokens per weight stream by the acceptance length: a drafter proposes k
continuation tokens per slot, a single batched verify forward scores all
k+1 positions (``models.llama.verify_ragged``), and the longest draft
prefix agreeing with greedy argmax is accepted — output stays
bit-identical to the non-speculative greedy path because acceptance IS
the argmax chain (``models.sampling.speculative_accept``).

The drafter here is the "prompt lookup" n-gram scheme: no second model —
a slot's own history (prompt + generated tokens) is searched for an
earlier occurrence of its current suffix, and the tokens that followed
that occurrence become the draft.  Free to compute (host-side numpy on
sequences the scheduler already mirrors), and effective exactly on the
traffic where decode dominates: templated/repetitive continuations
(code, JSON, chat templates, extraction tasks that re-emit prompt
spans).  On adversarial (random) text it proposes little or nothing and
the engine falls back to the plain single-token step per slot.

:class:`DraftState` is the per-slot adaptive controller: consecutive
zero-accept verifies halve that slot's draft budget (eventually to 0 =
plain decode for that slot), any acceptance regrows it, and a parked
slot re-probes after a cooldown so a phase change in the stream
(entering a repetitive region) is picked back up.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class SpeculativeConfig:
    """Engine-side knobs (one construction site: ``app.make_gen_engine``
    builds this from ``spec.tpu.speculative`` for leader and followers —
    lockstep replay needs identical draft geometry on every host)."""

    enabled: bool = False
    draft_tokens: int = 4  # max draft length k (verify scores k+1 positions)
    ngram_min: int = 1  # shortest history suffix the drafter may match
    ngram_max: int = 4  # longest history suffix tried first
    adaptive: bool = True  # per-slot halve-on-zero-accept / regrow-on-success


def draft_chain(draft_tokens: int) -> tuple[int, ...]:
    """The static draft lengths the engine compiles: the halving chain
    ``{k, k//2, ..., 1}`` (ascending).  A tick's draft length is padded
    UP to the nearest chain value, so the compiled-variant count stays
    logarithmic in ``draftTokens`` instead of linear — same philosophy
    as the power-of-two decode window buckets."""
    if draft_tokens < 1:
        raise ValueError(f"draft_tokens must be >= 1, got {draft_tokens}")
    chain = set()
    k = int(draft_tokens)
    while k >= 1:
        chain.add(k)
        k //= 2
    return tuple(sorted(chain))


def pad_to_chain(want: int, chain: tuple[int, ...]) -> int:
    """Smallest compiled draft length >= ``want``."""
    for c in chain:
        if c >= want:
            return c
    return chain[-1]


# Trailing-history bound for the n-gram scan (tokens).  Covers typical
# system-prompt + recent-generation reuse while capping per-tick host
# work; matches past the window are simply not found (fallback: plain
# single-token decode).
_SCAN_WINDOW = 2048


def propose_ngram(
    context: np.ndarray,
    max_tokens: int,
    ngram_min: int,
    ngram_max: int,
) -> list[int]:
    """Prompt-lookup draft: longest-suffix match against the sequence's
    own history.

    Tries suffix lengths ``ngram_max`` down to ``ngram_min``; on the
    first (longest) suffix with an earlier occurrence, drafts
    ``max_tokens`` tokens under the copy hypothesis the match implies:
    ``context[j] == context[j - d]`` where ``d`` is the distance between
    the suffix and its MOST RECENT earlier occurrence (recent context
    predicts the continuation best).  For ``d >= max_tokens`` that is
    simply the tokens that followed the match; for shorter distances —
    a period-``d`` repetition, the common shape of greedy loops and
    templated fills — the draft tiles the cycle so short periods still
    fill the whole budget.  Returns ``[]`` when nothing matches — the
    caller falls back to the plain single-token step for that slot.
    """
    arr = np.asarray(context, dtype=np.int64).reshape(-1)
    # Bound the searched history so drafting stays CONSTANT serial work
    # per tick on the scheduler thread regardless of context length
    # (at 8k context x 64 slots an unbounded scan would be millions of
    # comparisons ahead of every dispatch).  Recency also predicts the
    # continuation best, so the truncation costs little acceptance.
    if arr.size > _SCAN_WINDOW:
        arr = arr[-_SCAN_WINDOW:]
    L = int(arr.size)
    if max_tokens < 1 or L < ngram_min + 1:
        return []
    history = arr[:-1]  # candidate windows must END strictly before L-1
    for n in range(min(int(ngram_max), L - 1), int(ngram_min) - 1, -1):
        suffix = arr[L - n :]
        windows = np.lib.stride_tricks.sliding_window_view(history, n)
        hits = np.nonzero((windows == suffix).all(axis=1))[0]
        if hits.size:
            start = int(hits[-1]) + n  # token AFTER the most recent match
            d = L - start
            idx = start + (np.arange(int(max_tokens)) % d)
            return arr[idx].astype(np.int64).tolist()
    return []


class DraftState:
    """Per-slot adaptive draft budget.

    - ``budget()`` is how many tokens the slot may draft this tick
      (0 = parked: the slot rides the plain single-token step).
    - After ``HALVE_AFTER`` CONSECUTIVE zero-accept verifies, the budget
      halves (4 -> 2 -> 1 -> 0): a slot in adversarial text stops paying
      verify compute it never converts.
    - Any acceptance resets the streak and doubles the budget back
      toward the configured maximum.
    - A parked slot re-probes at budget 1 after ``REPROBE_AFTER`` plain
      ticks, so a stream that ENTERS a repetitive region is picked up.

    With ``adaptive=False`` the budget is pinned to the maximum.
    """

    HALVE_AFTER = 2
    REPROBE_AFTER = 16

    def __init__(self, max_draft: int, adaptive: bool = True) -> None:
        self.max = int(max_draft)
        self.adaptive = bool(adaptive)
        self.length = self.max
        self.zero_streak = 0
        self.parked_ticks = 0

    def budget(self) -> int:
        if not self.adaptive:
            return self.max
        if self.length == 0:
            self.parked_ticks += 1
            if self.parked_ticks >= self.REPROBE_AFTER:
                self.parked_ticks = 0
                return 1  # probation draft; observe() decides its fate
            return 0
        return self.length

    def observe(self, proposed: int, accepted: int) -> None:
        """Feed back one verify outcome (no-op when nothing was drafted)."""
        if not self.adaptive or proposed <= 0:
            return
        if accepted > 0:
            self.zero_streak = 0
            self.length = min(self.max, max(1, self.length * 2))
            return
        self.zero_streak += 1
        if self.zero_streak >= self.HALVE_AFTER:
            self.zero_streak = 0
            self.length //= 2  # 1 -> 0 parks the slot
