"""Inference engine: jit compilation, warmup, and dispatch for a Predictor.

TPU cold-start is the canary killer (SURVEY §7 hard part 3): the first
request on a fresh predictor would otherwise pay tens of seconds of XLA
compile and instantly fail the latency gate.  The engine therefore:

- jits jittable predictors once per input-shape signature;
- *warms up* every batch bucket (1, 2, 4, ... max_batch) at startup using
  the flavor's ``example_input`` builder, so steady-state traffic only ever
  hits cached executables;
- honors ``JAX_COMPILATION_CACHE_DIR`` (set by the manifest builder) so
  even process restarts skip recompiles.

Non-jittable (pyfunc) predictors dispatch to the host callable directly —
same interface, same metrics, different tier.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Mapping

import numpy as np

from ..models.registry import Predictor

_log = logging.getLogger(__name__)


class InferenceEngine:
    def __init__(
        self,
        predictor: Predictor,
        max_batch_size: int = 32,
        on_compile: Callable[[], None] | None = None,
    ):
        self.predictor = predictor
        self.max_batch_size = int(max_batch_size)
        self._on_compile = on_compile
        self._seen_signatures: set[tuple] = set()
        self._lock = threading.Lock()
        if predictor.jittable:
            import jax

            self._jitted = jax.jit(self._call_predict)
        else:
            self._jitted = None

    # -- calling conventions -------------------------------------------------

    def _call_predict(self, inputs: Mapping[str, Any]):
        """Single input -> positional call; several -> keyword call."""
        if len(inputs) == 1:
            (value,) = inputs.values()
            return self.predictor.predict(value)
        return self.predictor.predict(**inputs)

    @staticmethod
    def _signature(inputs: Mapping[str, np.ndarray]) -> tuple:
        return tuple(sorted((k, v.shape, str(v.dtype)) for k, v in inputs.items()))

    # -- public API ----------------------------------------------------------

    def predict(self, inputs: Mapping[str, np.ndarray]) -> Any:
        """Run one already-batched input dict; returns numpy outputs."""
        sig = self._signature(inputs)
        with self._lock:
            new_sig = sig not in self._seen_signatures
            if new_sig:
                self._seen_signatures.add(sig)
        if new_sig:
            if self._on_compile:
                self._on_compile()
            _log.info("new input signature %s (compiling)", sig)
        if self._jitted is not None:
            out = self._jitted(dict(inputs))
        else:
            out = self._call_predict(inputs)
        return _to_numpy(out)

    def warmup(self, buckets: list[int] | None = None) -> float:
        """Compile every batch bucket ahead of traffic; returns seconds spent."""
        if self.predictor.example_input is None or self._jitted is None:
            return 0.0
        if buckets is None:
            buckets = []
            b = 1
            while b <= self.max_batch_size:
                buckets.append(b)
                b <<= 1
            # next_bucket() caps at max_batch_size, so a non-power-of-two cap
            # is itself a servable bucket and must be warmed too.
            if buckets[-1] != self.max_batch_size:
                buckets.append(self.max_batch_size)
        t0 = time.perf_counter()
        for b in buckets:
            ex = self.predictor.example_input(b)
            if not isinstance(ex, Mapping):
                ex = {"x": ex}
            self.predict(ex)
        dt = time.perf_counter() - t0
        _log.info("warmup compiled %d buckets in %.1fs", len(buckets), dt)
        return dt


def _to_numpy(out: Any) -> Any:
    if isinstance(out, (tuple, list)):
        return tuple(_to_numpy(o) for o in out)
    if isinstance(out, dict):
        return {k: _to_numpy(v) for k, v in out.items()}
    return np.asarray(out)
