"""Inference engine: jit compilation, warmup, and dispatch for a Predictor.

TPU cold-start is the canary killer (SURVEY §7 hard part 3): the first
request on a fresh predictor would otherwise pay tens of seconds of XLA
compile and instantly fail the latency gate.  The engine therefore:

- jits jittable predictors once per input-shape signature;
- *warms up* every batch bucket (1, 2, 4, ... max_batch) at startup using
  the flavor's ``example_input`` builder, so steady-state traffic only ever
  hits cached executables;
- honors ``JAX_COMPILATION_CACHE_DIR`` (set by the manifest builder) so
  even process restarts skip recompiles.

Non-jittable (pyfunc) predictors dispatch to the host callable directly —
same interface, same metrics, different tier.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Mapping

import numpy as np

from ..models.registry import Predictor

_log = logging.getLogger(__name__)


def warmup_buckets(max_batch_size: int) -> list[int]:
    """Batch buckets to pre-compile: powers of two up to the cap, plus the
    cap itself when it isn't one (``next_bucket()`` clamps there, so a
    non-power-of-two cap is a servable bucket and must be warmed too)."""
    buckets = []
    b = 1
    while b <= max_batch_size:
        buckets.append(b)
        b <<= 1
    if buckets[-1] != max_batch_size:
        buckets.append(max_batch_size)
    return buckets


class InferenceEngine:
    def __init__(
        self,
        predictor: Predictor,
        max_batch_size: int = 32,
        on_compile: Callable[[], None] | None = None,
        warmup_full_grid: bool = False,
    ):
        self.predictor = predictor
        self.max_batch_size = int(max_batch_size)
        # Latency-sensitive deployments (CRD spec.tpu.warmupFullGrid) warm
        # the full batch x length grid: with a cold persistent compile
        # cache, an interior bucket (e.g. batch 4 at a non-base length)
        # otherwise pays its XLA compile on first live traffic.
        self.warmup_full_grid = bool(warmup_full_grid)
        self._on_compile = on_compile
        self._seen_signatures: set[tuple] = set()
        self._lock = threading.Lock()
        if predictor.jittable:
            import jax

            self._jitted = jax.jit(self._call_predict)
        else:
            self._jitted = None

    # -- calling conventions -------------------------------------------------

    def _call_predict(self, inputs: Mapping[str, Any]):
        """Single input -> positional call; several -> keyword call."""
        if len(inputs) == 1:
            (value,) = inputs.values()
            return self.predictor.predict(value)
        return self.predictor.predict(**inputs)

    @staticmethod
    def _signature(inputs: Mapping[str, np.ndarray]) -> tuple:
        return tuple(sorted((k, v.shape, str(v.dtype)) for k, v in inputs.items()))

    # -- public API ----------------------------------------------------------

    @property
    def wants_warmup(self) -> bool:
        """True when warmup would actually compile something (jittable
        predictor with an example-input builder)."""
        return self._jitted is not None and self.predictor.example_input is not None

    def predict(self, inputs: Mapping[str, np.ndarray]) -> Any:
        """Run one already-batched input dict; returns numpy outputs."""
        return self.materialize(self.predict_async(inputs))

    def predict_async(self, inputs: Mapping[str, np.ndarray]) -> Any:
        """Dispatch one already-batched input dict WITHOUT materializing.

        Under ``jit``, XLA dispatch is asynchronous: the returned device
        arrays are promises, so the caller can overlap forming/dispatching
        the NEXT batch with this one's device execution (the
        ``DynamicBatcher``'s pipelined mode).  Pair with
        :meth:`materialize`, which blocks until the device is done.  On
        the non-jittable (pyfunc) tier the call runs synchronously here —
        ``materialize`` is then a cheap identity walk.
        """
        sig = self._signature(inputs)
        with self._lock:
            new_sig = sig not in self._seen_signatures
            if new_sig:
                self._seen_signatures.add(sig)
        if new_sig:
            if self._on_compile:
                self._on_compile()
            _log.info("new input signature %s (compiling)", sig)
        if self._jitted is not None:
            return self._jitted(dict(inputs))
        return self._call_predict(inputs)

    def materialize(self, out: Any) -> Any:
        """Block until ``out``'s device computation finishes; numpy it."""
        return _to_numpy(out)

    def warmup(
        self,
        buckets: list[int] | None = None,
        predict: Callable[[Mapping[str, np.ndarray]], Any] | None = None,
    ) -> float:
        """Compile every batch bucket ahead of traffic; returns seconds spent.

        ``predict`` overrides the dispatch path (the multi-host wrapper
        passes its broadcasting predict so followers warm the same buckets)
        while bucket policy and example building stay in this one place."""
        if not self.wants_warmup:
            return 0.0
        if buckets is None:
            buckets = warmup_buckets(self.max_batch_size)
        predict = predict or self.predict
        t0 = time.perf_counter()
        n_shapes = 0
        for b in buckets:
            ex = self.predictor.example_input(b)
            if not isinstance(ex, Mapping):
                ex = {"x": ex}
            predict(ex)
            n_shapes += 1
        # Sequence-bucketed predictors: also warm the LENGTH buckets at
        # the batch-grid edges (batch 1 and max).  The full batch x length
        # grid would be |buckets|^2 cold compiles; the edges cover lone
        # requests and saturated batches, and the persistent compile
        # cache fills the interior once, fleet-wide.  warmup_full_grid
        # opts into the whole grid for deployments that cannot afford a
        # single cold-cache first-hit compile stall.
        seq_pad = getattr(self.predictor, "seq_pad", None)
        if seq_pad:
            axis = int(seq_pad.get("axis", 1))
            max_len = int(seq_pad.get("max_len") or 0)
            example = self.predictor.example_input(1)
            pad_names = [
                k
                for k in (seq_pad.get("pad_values") or {})
                if isinstance(example, Mapping) and k in example
            ]
            if pad_names and max_len:

                def at_length(b: int, length: int) -> dict:
                    ex = self.predictor.example_input(b)
                    idx = np.zeros(length, np.intp)  # repeat position 0
                    return {
                        k: (np.take(v, idx, axis=axis) if k in pad_names else v)
                        for k, v in ex.items()
                    }

                from .batching import seq_buckets

                base_len = example[pad_names[0]].shape[axis]
                grid_batches = (
                    buckets if self.warmup_full_grid else (1, self.max_batch_size)
                )
                for length in seq_buckets(seq_pad):
                    if length == base_len:
                        continue  # base length covered above
                    for b in grid_batches:
                        predict(at_length(b, length))
                        n_shapes += 1
        dt = time.perf_counter() - t0
        _log.info("warmup compiled %d shapes in %.1fs", n_shapes, dt)
        return dt


def _to_numpy(out: Any) -> Any:
    if isinstance(out, (tuple, list)):
        return tuple(_to_numpy(o) for o in out)
    if isinstance(out, dict):
        return {k: _to_numpy(v) for k, v in out.items()}
    return np.asarray(out)
