"""Engine flight recorder: bounded in-memory journal of the scheduler loop.

Every serving metric this server exports is an aggregate — when one
request's TTFT blows p99 the histograms cannot say whether it lost the
time queued, behind a packed-prefill budget, to a prefix-cache miss, or
to a zero-accept speculative streak.  The recorder keeps the raw
material for that question in three bounded rings:

- **ticks** — one record per engine device dispatch (kind: ``decode`` /
  ``verify`` / ``multistep`` / ``packed-prefill`` / ``prefill`` /
  ``seed`` / ``kv-import`` — the last is a handed-off prefix landing in
  the radix cache, host-side — / ``superstep``, the unified engine's
  one-dispatch-per-tick program) with wall time, batch fill, active
  slots, queue depth,
  tokens emitted, and accepted speculative drafts; fused multi-step
  ticks additionally carry ``steps`` (K scan iterations per dispatch),
  and their per-token instants in the request traces are reconstructed
  across the tick wall, not stacked on the harvest instant; superstep
  ticks carry both ``steps`` and ``roles`` (the {prefill, decode,
  verify} row mix of the dispatch);
- **events** — per-request lifecycle points (``enqueued``, ``admission``,
  ``seed``, ``prefill_chunk``, ``first_token``, ``finish``) with the
  cache row they happened on;
- **traces** — completed :class:`RequestTrace` objects carrying the
  request's whole timing block including per-token timestamps.

``GET /debug/engine`` serves the live snapshot; ``GET
/debug/trace?format=chrome`` renders the rings as Chrome trace-event
JSON (one track for engine ticks, one per cache row; request spans as
async begin/end pairs) viewable in Perfetto or ``chrome://tracing``.

Sized by ``spec.tpu.observability.traceRing`` (CRD -> config -> builder
-> server ``--trace-ring``); 0 — the default — means no recorder object
exists at all, so the engine's hot path stays byte-for-byte what it was.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field


def _ms(a: float, b: float) -> float | None:
    """Wall delta in milliseconds, None when either endpoint is unset."""
    if a <= 0.0 or b <= 0.0:
        return None
    return round((b - a) * 1000.0, 3)


@dataclass
class RequestTrace:
    """Per-request timing, filled in by the engine as the request moves
    queue -> admission -> prefill chunks -> first token -> finish.

    Created by the HTTP layer (one per submitted sequence) regardless of
    whether a recorder is attached: the ``"debug": true`` timing block
    and the per-request completion log line are always available.  All
    timestamps are ``time.perf_counter()`` values; only deltas are ever
    exposed."""

    request_id: str = ""
    # W3C trace context joined from the inbound traceparent (the router
    # mints/propagates one when its journey ring is on): the 32-hex
    # trace id shared by every component this request touched, and the
    # 16-hex span id of the immediate parent (the router's leg span).
    # Empty = the request arrived without a traceparent; the timing
    # block and chrome export then stay byte-for-byte what they were.
    trace_id: str = ""
    parent_span: str = ""
    prompt_tokens: int = 0
    slot: int = -1
    t_submit: float = 0.0
    t_admit: float = 0.0
    t_first: float = 0.0
    t_finish: float = 0.0
    # Disaggregated-fleet relay: stamped at request receipt when the
    # router forwarded this request AFTER a prefill→decode KV handoff
    # (X-Tpumlops-Handoff header); ``handoff_ms`` is the router-measured
    # handoff wall riding the same header.  0.0/None = not relayed.
    t_handoff: float = 0.0
    handoff_ms: float | None = None
    prefill_chunks: int = 0
    cached_tokens: int = 0
    spec_proposed: int = 0
    spec_accepted: int = 0
    tokens: int = 0
    finish_reason: str = ""
    token_times: list = field(default_factory=list)

    def note_token(self, t: float) -> None:
        self.tokens += 1
        self.token_times.append(t)

    def finish(self, reason: str, t: float | None = None) -> None:
        # First writer wins: a client cancel racing the final token must
        # not relabel an already-finished request.
        if not self.finish_reason:
            self.finish_reason = reason
            self.t_finish = time.perf_counter() if t is None else t

    def timing_block(self) -> dict:
        """The JSON shape returned by ``"debug": true`` and logged on
        completion.  Totals here agree with the Prometheus counters the
        same request incremented (asserted in tests/test_server.py)."""
        out = {
            "request_id": self.request_id,
            "prompt_tokens": self.prompt_tokens,
            "queue_ms": _ms(self.t_submit, self.t_admit),
            "ttft_ms": _ms(self.t_submit, self.t_first),
            "total_ms": _ms(self.t_submit, self.t_finish),
            "prefill_chunks": self.prefill_chunks,
            "cached_tokens": self.cached_tokens,
            "spec_proposed": self.spec_proposed,
            "spec_accepted": self.spec_accepted,
            "tokens": self.tokens,
            # KV relay context (None = not a relayed request): the
            # router's measured handoff wall, so /debug/trace alone
            # reconstructs export → import → forward → seed.
            "handoff_ms": self.handoff_ms,
            "finish_reason": self.finish_reason or "in-flight",
        }
        if self.trace_id:
            # Present only for requests that arrived with a traceparent
            # (fleet trace plane on): pre-trace-plane blocks stay
            # byte-for-byte.
            out["trace_id"] = self.trace_id
            out["parent_span"] = self.parent_span
        return out


class FlightRecorder:
    """Bounded ring journal fed from the engine scheduler loop.

    All writers (the scheduler thread, ``submit`` on HTTP threads) and
    readers (the ``/debug/*`` handlers) go through one lock; every write
    is an O(1) deque append, so the recorder's steady-state cost is a
    dict build + append per engine tick (bench scenario
    ``observability_serving`` pins the tok/s overhead).
    """

    # Completed traces carry per-token timestamps (up to max_new_tokens
    # floats each), so their ring is capped independently of the tick
    # ring — traceRing=4096 with 1k-token generations must not pin
    # hundreds of MB of host memory for a debug feature.
    MAX_TRACES = 512
    # Token instants rendered per request span in the Chrome export
    # (stride-sampled beyond this): bounds the /debug/trace payload.
    MAX_TOKEN_INSTANTS = 256

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError(f"trace ring capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._t0_perf = time.perf_counter()
        self._t0_unix = time.time()
        self._lock = threading.Lock()
        self._ticks: deque = deque(maxlen=self.capacity)
        self._events: deque = deque(maxlen=self.capacity)
        self._traces: deque = deque(maxlen=min(self.capacity, self.MAX_TRACES))
        self.ticks_recorded = 0
        self.events_recorded = 0
        self.traces_recorded = 0

    def _us(self, t: float | None = None) -> int:
        """Microseconds since recorder start (the Chrome trace clock)."""
        return int(((time.perf_counter() if t is None else t) - self._t0_perf) * 1e6)

    # -- writers (engine side) ----------------------------------------------

    def tick(
        self,
        kind: str,
        t0: float,
        wall_s: float,
        *,
        active_slots: int = 0,
        queue_depth: int = 0,
        batch_fill: int = 0,
        tokens: int = 0,
        spec_accepted: int = 0,
        util: dict | None = None,
        steps: int = 0,
        roles: dict | None = None,
    ) -> None:
        rec = {
            "ts_us": self._us(t0),
            "dur_us": max(0, int(wall_s * 1e6)),
            "kind": kind,
            "active_slots": int(active_slots),
            "queue_depth": int(queue_depth),
            "batch_fill": int(batch_fill),
            "tokens": int(tokens),
            "spec_accepted": int(spec_accepted),
        }
        if steps:
            # Fused multi-step ticks only (K scan iterations under this
            # one dispatch); absent otherwise so single-step tick
            # records stay byte-for-byte what they were.
            rec["steps"] = int(steps)
        if roles:
            # Unified super-step ticks only: the per-row role breakdown
            # ({prefill, decode, verify} counts) of this one dispatch.
            # Absent for every split-engine tick kind, so the
            # unified-off record shapes stay byte-for-byte.
            rec["roles"] = {k: int(v) for k, v in roles.items()}
        if util:
            # Device telemetry only (spec.tpu.observability.
            # deviceTelemetry): mfu / hbm_bw_util from the analytic cost
            # model joined with this tick's wall.  Absent otherwise, so
            # the telemetry-off tick record stays byte-for-byte.
            rec.update(util)
        with self._lock:
            self.ticks_recorded += 1
            self._ticks.append(rec)

    def event(
        self, request_id: str, name: str, *, slot: int = -1, **fields
    ) -> None:
        rec = {
            "ts_us": self._us(),
            "request_id": request_id,
            "event": name,
            "slot": int(slot),
            **fields,
        }
        with self._lock:
            self.events_recorded += 1
            self._events.append(rec)

    def complete(self, trace: RequestTrace) -> None:
        with self._lock:
            self.traces_recorded += 1
            self._traces.append(trace)

    # -- readers (/debug/* side) --------------------------------------------

    def snapshot(self) -> dict:
        """Live state for ``GET /debug/engine``: the rings verbatim plus
        lifetime totals (so ring rotation is visible as recorded > len).

        The lock is held only for the deque copies: building thousands
        of payload dicts under it would block the scheduler thread's
        ``tick()`` mid-decode — inflating the very tail latency someone
        is scraping this endpoint to debug.  Ring records are immutable
        once appended and traces are completed, so reading them outside
        the lock is safe; the per-record ``dict(...)`` copies keep
        callers from mutating the live journal."""
        with self._lock:
            ticks = list(self._ticks)
            events = list(self._events)
            traces = list(self._traces)
            totals = (
                self.ticks_recorded,
                self.events_recorded,
                self.traces_recorded,
            )
        return {
            "capacity": self.capacity,
            "started_unix": self._t0_unix,
            "ticks_recorded": totals[0],
            "events_recorded": totals[1],
            "traces_recorded": totals[2],
            "ticks": [dict(t) for t in ticks],
            "events": [dict(e) for e in events],
            "requests": [t.timing_block() for t in traces],
        }

    def chrome_trace(self) -> dict:
        """Chrome trace-event JSON (Perfetto / chrome://tracing).

        Track layout: tid 0 carries the engine ticks as complete (``X``)
        events; tid ``row + 1`` is one track per cache row carrying that
        row's request spans (async ``b``/``e`` pairs keyed by request id)
        with per-token instant events and the lifecycle instants between
        them.  A request that never reached a row (shutdown while
        queued) spans on tid 0."""
        with self._lock:
            ticks = [dict(t) for t in self._ticks]
            events = [dict(e) for e in self._events]
            traces = list(self._traces)

        out: list[dict] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": 1,
                "tid": 0,
                "args": {"name": "tpumlops-engine"},
            },
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": 0,
                "args": {"name": "engine ticks"},
            },
        ]
        rows = sorted(
            {t.slot for t in traces if t.slot >= 0}
            | {e["slot"] for e in events if e.get("slot", -1) >= 0}
        )
        for row in rows:
            out.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": 1,
                    "tid": row + 1,
                    "args": {"name": f"cache row {row}"},
                }
            )
        for t in ticks:
            out.append(
                {
                    "name": t["kind"],
                    "cat": "tick",
                    "ph": "X",
                    "ts": t["ts_us"],
                    "dur": t["dur_us"],
                    "pid": 1,
                    "tid": 0,
                    "args": {
                        k: t[k]
                        for k in (
                            "active_slots",
                            "queue_depth",
                            "batch_fill",
                            "tokens",
                            "spec_accepted",
                            "steps",
                            "roles",
                        )
                        if k in t
                    },
                }
            )
            if "roles" in t:
                # Role-fill counter track: Perfetto renders one series
                # per args key, so each unified dispatch's
                # prefill/decode/verify mix reads as a stacked
                # staircase next to the tick track.  Superstep ticks
                # only — the legacy export stays byte-for-byte.
                out.append(
                    {
                        "name": "role_fill",
                        "cat": "roles",
                        "ph": "C",
                        "ts": t["ts_us"],
                        "pid": 1,
                        "args": dict(t["roles"]),
                    }
                )
            if "mfu" in t:
                # Device-telemetry counter tracks: Perfetto renders one
                # counter per name, one series per args key (tick kind)
                # — the utilization staircase next to the tick track.
                # Emitted only for ticks carrying the fields, so the
                # telemetry-off export stays byte-for-byte.
                for counter in ("mfu", "hbm_bw_util"):
                    out.append(
                        {
                            "name": counter,
                            "cat": "utilization",
                            "ph": "C",
                            "ts": t["ts_us"],
                            "pid": 1,
                            "args": {t["kind"]: t[counter]},
                        }
                    )
        for e in events:
            out.append(
                {
                    "name": e["event"],
                    "cat": "lifecycle",
                    "ph": "i",
                    "s": "t",
                    "ts": e["ts_us"],
                    "pid": 1,
                    "tid": e.get("slot", -1) + 1 if e.get("slot", -1) >= 0 else 0,
                    "args": {"request_id": e["request_id"]},
                }
            )
        for tr in traces:
            tid = tr.slot + 1 if tr.slot >= 0 else 0
            begin = self._us(tr.t_submit) if tr.t_submit > 0 else 0
            end = self._us(tr.t_finish) if tr.t_finish > 0 else begin
            end = max(end, begin)  # clock skew must never invert the span
            if tr.t_handoff > 0 and tr.handoff_ms:
                # The router-measured KV handoff, anchored in this
                # process's clock by the receipt stamp: the relay span
                # ENDS at t_handoff and lasted handoff_ms.  Emitted only
                # for relayed requests — the non-fleet export stays
                # byte-for-byte.
                dur_us = int(tr.handoff_ms * 1000.0)
                out.append(
                    {
                        "name": "kv-handoff",
                        "cat": "handoff",
                        "ph": "X",
                        "ts": max(self._us(tr.t_handoff) - dur_us, 0),
                        "dur": dur_us,
                        "pid": 1,
                        "tid": tid,
                        "args": {"request_id": tr.request_id},
                    }
                )
            out.append(
                {
                    "name": "request",
                    "cat": "request",
                    "ph": "b",
                    "id": tr.request_id,
                    "ts": begin,
                    "pid": 1,
                    "tid": tid,
                }
            )
            # Stride-sample long generations: every token of a 1k-token
            # request as its own event would balloon the export without
            # adding readable detail at that zoom level.
            times = tr.token_times
            stride = max(1, -(-len(times) // self.MAX_TOKEN_INSTANTS))
            for tok_t in times[::stride]:
                out.append(
                    {
                        "name": "token",
                        "cat": "token",
                        "ph": "i",
                        "s": "t",
                        "ts": min(max(self._us(tok_t), begin), end),
                        "pid": 1,
                        "tid": tid,
                        "args": {"request_id": tr.request_id},
                    }
                )
            out.append(
                {
                    "name": "request",
                    "cat": "request",
                    "ph": "e",
                    "id": tr.request_id,
                    "ts": end,
                    "pid": 1,
                    "tid": tid,
                    "args": tr.timing_block(),
                }
            )
        # started_unix rides top-level so the fleet stitcher
        # (utils/trace_stitch.py) reads its clock anchor from this
        # payload instead of fetching /debug/engine separately.
        return {
            "traceEvents": out,
            "displayTimeUnit": "ms",
            "started_unix": self._t0_unix,
        }
