"""Fixed-memory 1 s-resolution time-series ring for the serving plane.

Every observability layer before this one is point-in-time or
event-shaped: the Prometheus gauges say what is true NOW, the flight
recorder's rings say what HAPPENED, but neither holds short-horizon
history — so nothing on the server can answer "has ITL drifted since the
last attach?" or give the operator's anomaly detector a window to
compare replicas over.  :class:`TimeseriesRing` closes that gap with a
bounded ring of per-second samples distilled from the SAME callback
stream the metrics layer already consumes (``on_step``/``on_tick``/
``on_itl``/``on_shed``/``on_poison`` out of the engine's
``_record_tick`` funnel) — zero new instrumentation points; the ring's
observer methods are fanned onto the existing metric callbacks at the
one ``make_gen_engine`` wiring site.

Memory is fixed by construction: the open (current-second) bucket keeps
at most :data:`BUCKET_SAMPLE_CAP` raw walls per tick kind (p50/p99 past
the cap are computed over the first CAP observations — an error bar
documented in docs/OBSERVABILITY.md), and a finalized bucket is a small
flat dict of aggregates in a ``deque(maxlen=capacity)``.

Sized by ``spec.tpu.observability.timeseriesRing`` (``--timeseries-ring``);
0 — the default — constructs no ring at all, so the engine callbacks,
``/debug`` routes, and serving behavior stay byte-for-byte.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable

# Raw per-kind tick walls (and ITL samples) kept per open bucket; a
# decode loop can tick thousands of times a second and the ring must
# stay fixed-memory, so quantiles past the cap are over the first CAP
# observations of that second.
BUCKET_SAMPLE_CAP = 256


def _quantile(sorted_vals: list, q: float) -> float:
    """Nearest-rank quantile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return float(sorted_vals[idx])


class TimeseriesRing:
    """Bounded ring of per-second serving samples.

    Observer methods mirror the :class:`ServerMetrics` callback
    signatures exactly, so one fan-out combinator chains both onto the
    engine's existing hooks.  All methods are thread-safe (the engine
    scheduler thread observes; the aiohttp event loop snapshots).
    """

    def __init__(
        self,
        capacity: int,
        clock: Callable[[], float] = time.time,
    ):
        if capacity <= 0:
            raise ValueError(
                f"timeseries ring capacity must be > 0, got {capacity}"
            )
        self.capacity = int(capacity)
        self._clock = clock
        self._lock = threading.Lock()
        self._samples: deque = deque(maxlen=self.capacity)
        self._telemetry = None  # DeviceTelemetry | None (last_util source)
        self._open_t: int | None = None  # unix second of the open bucket
        self._open: dict = {}

    def bind_telemetry(self, telemetry) -> None:
        """Attach the device-telemetry layer as the MFU / HBM-bandwidth
        source: each finalized bucket gauge-samples ``last_util`` (the
        dict ``tick_util`` maintains) instead of adding a new hook."""
        self._telemetry = telemetry

    # -- bucket lifecycle ---------------------------------------------------

    def _fresh_bucket(self) -> dict:
        return {
            "ticks": {},  # kind -> capped list of wall seconds
            "tick_counts": {},  # kind -> total count (cap-independent)
            "itl": [],  # capped list of inter-token latencies (s)
            "itl_count": 0,
            "queue_depth": None,  # last observed this second
            "active_slots": None,
            "shed": 0,
            "poison": 0,
            "marks": [],  # lifecycle marks (e.g. "attach") this second
        }

    def _roll(self, now: float) -> None:
        """Finalize the open bucket if the wall clock left its second.
        Caller holds the lock."""
        sec = int(now)
        if self._open_t is None:
            self._open_t = sec
            self._open = self._fresh_bucket()
            return
        if sec <= self._open_t:
            return
        self._samples.append(self._finalize(self._open_t, self._open))
        self._open_t = sec
        self._open = self._fresh_bucket()

    def _finalize(self, t: int, bucket: dict) -> dict:
        ticks = {}
        for kind, walls in bucket["ticks"].items():
            walls.sort()
            ticks[kind] = {
                "n": bucket["tick_counts"][kind],
                "wall_p50_ms": round(_quantile(walls, 0.50) * 1e3, 4),
                "wall_p99_ms": round(_quantile(walls, 0.99) * 1e3, 4),
            }
        itl = sorted(bucket["itl"])
        sample: dict[str, Any] = {
            "t": t,
            "ticks": ticks,
            "itl": {
                "n": bucket["itl_count"],
                "p50_ms": round(_quantile(itl, 0.50) * 1e3, 4),
                "p99_ms": round(_quantile(itl, 0.99) * 1e3, 4),
            },
            "queue_depth": bucket["queue_depth"],
            "active_slots": bucket["active_slots"],
            "shed": bucket["shed"],
            "poison": bucket["poison"],
        }
        if bucket["marks"]:
            sample["marks"] = list(bucket["marks"])
        util = self._sample_util()
        if util is not None:
            sample["mfu"] = util[0]
            sample["hbm_bw_util"] = util[1]
        return sample

    def _sample_util(self):
        """Busiest program's (mfu, hbm_bw_util) from the telemetry
        layer's ``last_util`` gauge — absent when device telemetry is
        off (the sample simply carries no utilization fields)."""
        if self._telemetry is None:
            return None
        try:
            with self._telemetry._util_lock:
                utils = list(self._telemetry.last_util.values())
        except Exception:
            return None
        if not utils:
            return None
        best = max(utils, key=lambda u: u.get("mfu", 0.0))
        return (
            float(best.get("mfu", 0.0)),
            float(best.get("hbm_bw_util", 0.0)),
        )

    # -- observer methods (ServerMetrics-signature mirrors) -----------------

    def observe_tick(self, kind: str, seconds: float) -> None:
        with self._lock:
            self._roll(self._clock())
            walls = self._open["ticks"].setdefault(kind, [])
            counts = self._open["tick_counts"]
            counts[kind] = counts.get(kind, 0) + 1
            if len(walls) < BUCKET_SAMPLE_CAP:
                walls.append(float(seconds))

    def observe_decode_step(
        self,
        active_slots: int,
        seconds: float,
        queue_depth: int = 0,
        admitting: int = 0,
    ) -> None:
        with self._lock:
            self._roll(self._clock())
            self._open["queue_depth"] = int(queue_depth)
            self._open["active_slots"] = int(active_slots)

    def observe_itl(self, seconds: float) -> None:
        with self._lock:
            self._roll(self._clock())
            self._open["itl_count"] += 1
            if len(self._open["itl"]) < BUCKET_SAMPLE_CAP:
                self._open["itl"].append(float(seconds))

    def inc_shed(self, reason: str = "") -> None:
        with self._lock:
            self._roll(self._clock())
            self._open["shed"] += 1

    def inc_poison(self, action: str = "") -> None:
        with self._lock:
            self._roll(self._clock())
            self._open["poison"] += 1

    def mark(self, event: str) -> None:
        """Stamp a lifecycle mark (e.g. ``"attach"``) into the current
        second — the anomaly detector's baseline-reset signal."""
        with self._lock:
            self._roll(self._clock())
            self._open["marks"].append(str(event))

    # -- export -------------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``GET /debug/timeseries`` payload: finalized samples
        oldest-first, then the open (still-accumulating) bucket."""
        with self._lock:
            self._roll(self._clock())
            samples = list(self._samples)
            if self._open_t is not None:
                open_view = self._finalize(self._open_t, self._open)
                open_view["open"] = True
                samples.append(open_view)
        return {
            "capacity": self.capacity,
            "resolution_s": 1,
            "samples": samples,
        }
