"""Multi-host predictor unit: one predictor = N lockstep server processes.

SURVEY §7 hard part 5: a multi-host TPU slice (e.g. v5e-16 = 4 hosts of 4
chips) means one *predictor* is N pods that must act as a single unit for
traffic and health.  The reference never faces this — Seldon's
``MLFLOW_SERVER`` pods are single-host CPU containers
(``mlflow_operator.py:195-222``) — but a model tensor-sharded across hosts
cannot run any step unless every process joins the same XLA collective.

Design (the standard JAX serving shape — all hosts run the same program):

- process 0 (the **leader**) owns the HTTP frontend; the Service only
  selects the leader pod, so Istio traffic weights keep meaning "percent of
  requests to this *unit*";
- processes 1..N-1 (**followers**) run :func:`follower_loop`: block on a
  broadcast from the leader, execute the same engine call with the same
  inputs, repeat.  Every process therefore enters each jit'd computation
  together and the cross-host collectives line up;
- the broadcast channel is the JAX process group itself
  (:class:`JaxProcessTransport`, ``broadcast_one_to_all`` over DCN), so no
  side-channel (Redis/gRPC) is needed; tests use
  :class:`LocalGroupTransport` (threads + barriers) to run an N-"host"
  unit inside one process.

Health as a unit: ``jax.distributed.initialize`` blocks until all N
processes join, and the leader's readiness endpoint only turns ready after
warmup — so "leader ready" ⇒ "all hosts up and compiled", and the operator
can keep gating on the one readiness probe the builder emits.
"""

from __future__ import annotations

import logging
import pickle
import threading
from typing import Any, Mapping, Protocol

import numpy as np

_log = logging.getLogger(__name__)

OP_PREDICT = "predict"
OP_SHUTDOWN = "shutdown"
OP_GEN_ADMIT = "gen_admit"  # continuous-batching prefill+insert (replayed)
OP_GEN_STEP = "gen_step"  # continuous-batching decode tick (replayed)
OP_GEN_RESET = "gen_reset"  # leader recovered from a failed step: drop state
OP_GEN_CHUNK = "gen_chunk"  # chunked-prefill: one prompt chunk (replayed)
OP_GEN_INSERT = "gen_insert"  # chunked-prefill: install sequence into slot
OP_GEN_SEED = "gen_seed"  # prefix-cache hit: seed seq cache from cached K/V
OP_GEN_VERIFY = "gen_verify"  # speculative draft+verify tick (replayed)
OP_GEN_CHUNKS = "gen_chunks"  # packed prefill: batched multi-admission chunks
OP_GEN_SEED_SLOT = "gen_seed_slot"  # packed prefill: seed a reserved slot row
OP_GEN_MULTISTEP = "gen_multistep"  # fused K-step decode tick (replayed);
#   chained ticks of a burst carry None inputs — the device-resident chain
#   state from each host's OWN previous replay keeps the slice in lockstep
OP_GEN_SP_PREFILL = "gen_sp_prefill"  # sp ring prefill: whole prompt, one pass
OP_GEN_RESTORE = "gen_restore"  # preemption restore: re-install an evicted
# slot's lengths/pending-token/PRNG-carry/sampling rows (the K/V re-seed
# rides OP_GEN_SEED_SLOT)
OP_GEN_SUPERSTEP = "gen_superstep"  # unified ragged super-step tick: every
#   role (prefill chunks / fused-K decode / speculative verify) in ONE
#   dispatch; the payload is self-contained host state — no chained inputs

# Fixed-size round-1 header: payload byte length as uint32.  Round 2 is the
# payload itself.  Two rounds because ``broadcast_one_to_all`` needs every
# process to supply a same-shape buffer, and followers can't know the
# payload size ahead of time.
_LEN_DTYPE = np.uint32


class GroupTransport(Protocol):
    """One-to-all broadcast within the predictor unit."""

    @property
    def is_leader(self) -> bool: ...

    def broadcast(self, payload: bytes | None) -> bytes:
        """Leader passes ``payload``; followers pass ``None``.  Every
        process returns the leader's bytes."""
        ...


class JaxProcessTransport:
    """Broadcast over the JAX process group (DCN collectives).

    Uses ``jax.experimental.multihost_utils.broadcast_one_to_all`` — the
    same channel the model's own cross-host collectives ride, so transport
    liveness and compute liveness fail together (no split-brain where the
    control channel is up but the slice is wedged).
    """

    def __init__(self) -> None:
        import jax

        self._process_index = jax.process_index()

    @property
    def is_leader(self) -> bool:
        return self._process_index == 0

    def broadcast(self, payload: bytes | None) -> bytes:
        from jax.experimental import multihost_utils

        if self.is_leader:
            if payload is None:
                raise ValueError("leader must supply a payload")
            buf = np.frombuffer(payload, dtype=np.uint8)
            n = np.asarray([len(buf)], dtype=_LEN_DTYPE)
        else:
            buf = None
            n = np.zeros(1, dtype=_LEN_DTYPE)
        n = np.asarray(multihost_utils.broadcast_one_to_all(n))
        size = int(n[0])
        if buf is None:
            buf = np.zeros(size, dtype=np.uint8)
        out = np.asarray(multihost_utils.broadcast_one_to_all(buf))
        return out.tobytes()


class LocalGroupTransport:
    """In-process fake: N threads acting as N hosts (tests / docs).

    Construct one :class:`_LocalGroup` and take a transport per "host".
    """

    def __init__(self, group: "_LocalGroup", rank: int) -> None:
        self._group = group
        self._rank = rank

    @property
    def is_leader(self) -> bool:
        return self._rank == 0

    def broadcast(self, payload: bytes | None) -> bytes:
        return self._group.broadcast(self._rank, payload)


class _LocalGroup:
    def __init__(self, size: int) -> None:
        self.size = size
        self._slot: bytes | None = None
        self._fill = threading.Barrier(size)
        self._drain = threading.Barrier(size)

    def broadcast(self, rank: int, payload: bytes | None) -> bytes:
        if rank == 0:
            if payload is None:
                raise ValueError("leader must supply a payload")
            self._slot = payload
        self._fill.wait()
        out = self._slot
        self._drain.wait()
        assert out is not None
        return out

    def transports(self) -> list[LocalGroupTransport]:
        return [LocalGroupTransport(self, r) for r in range(self.size)]


# ---------------------------------------------------------------------------
# Message encoding
# ---------------------------------------------------------------------------


def encode_message(op: str, inputs: Mapping[str, np.ndarray] | None = None) -> bytes:
    """Pickle is safe here: the channel is the slice's own process group —
    every peer already runs the same trusted server image."""
    return pickle.dumps((op, dict(inputs) if inputs is not None else None))


def decode_message(raw: bytes) -> tuple[str, dict[str, np.ndarray] | None]:
    op, inputs = pickle.loads(raw)
    return op, inputs


# ---------------------------------------------------------------------------
# Leader-side engine wrapper + follower loop
# ---------------------------------------------------------------------------


class UnitChannel:
    """Serialized broadcast+execute for every leader-side dispatcher.

    Cross-host collectives only line up if every process enters the same
    jitted programs in the same order.  Follower order is broadcast order,
    so the leader must make (broadcast, execute) atomic — and with BOTH the
    batcher's predict path and the generation scheduler dispatching device
    work, they must share one lock.  ``run`` is that critical section.
    """

    def __init__(self, transport: GroupTransport) -> None:
        self.transport = transport
        self.lock = threading.RLock()
        self.closed = False

    def run(self, payload: bytes, fn):
        with self.lock:
            if self.closed:
                # After OP_SHUTDOWN the followers have exited their loop; a
                # further broadcast would wait on peers that are gone and
                # wedge the leader process instead of letting it terminate.
                raise RuntimeError("multihost unit is shut down")
            self.transport.broadcast(payload)
            return fn()

    def close_with(self, payload: bytes) -> None:
        with self.lock:
            if self.closed:
                return
            self.closed = True
            self.transport.broadcast(payload)


class MultihostEngine:
    """Duck-types :class:`InferenceEngine` for the batcher/app; every
    ``predict`` is first broadcast so followers execute it in lockstep.

    ``warmup`` deliberately routes through ``self.predict`` so followers
    compile the same batch buckets the leader does — otherwise the first
    real request would stall N-1 hosts on an XLA compile.
    """

    def __init__(
        self,
        engine: Any,
        transport: GroupTransport,
        channel: UnitChannel | None = None,
    ) -> None:
        if not transport.is_leader:
            raise ValueError("MultihostEngine is leader-side; followers run follower_loop")
        self._engine = engine
        self._transport = transport
        # Shared with the generation scheduler (see UnitChannel).
        self.channel = channel or UnitChannel(transport)

    # pass-throughs the app/batcher use
    @property
    def predictor(self):
        return self._engine.predictor

    @property
    def max_batch_size(self) -> int:
        return self._engine.max_batch_size

    def predict(self, inputs: Mapping[str, np.ndarray]) -> Any:
        return self.channel.run(
            encode_message(OP_PREDICT, inputs),
            lambda: self._engine.predict(inputs),
        )

    def warmup(self, buckets: list[int] | None = None) -> float:
        # Delegate to the engine's single warmup implementation, routing
        # dispatch through the broadcasting predict so followers compile
        # the same buckets the leader does.
        return self._engine.warmup(buckets, predict=self.predict)

    def shutdown(self) -> None:
        """Release followers; without this they block on broadcast forever
        and the pod unit never terminates cleanly."""
        self.channel.close_with(encode_message(OP_SHUTDOWN))


def follower_loop(engine: Any, transport: GroupTransport, gen_engine: Any = None) -> int:
    """Run on processes 1..N-1: execute broadcast steps until shutdown.

    ``gen_engine`` (a non-started GenerationEngine) replays the leader's
    continuous-batching device calls for causal-LM units.
    Returns the number of steps executed (for tests/metrics).
    """
    if transport.is_leader:
        raise ValueError("follower_loop must not run on the leader")
    steps = 0
    while True:
        op, inputs = decode_message(transport.broadcast(None))
        if op == OP_SHUTDOWN:
            _log.info("follower received shutdown after %d steps", steps)
            return steps
        try:
            if op == OP_PREDICT:
                assert inputs is not None
                engine.predict(inputs)
            elif op == OP_GEN_ADMIT:
                if gen_engine is None:
                    raise RuntimeError("GEN op on a unit without a gen engine")
                gen_engine.replay_admit(**inputs)
            elif op == OP_GEN_STEP:
                if gen_engine is None:
                    raise RuntimeError("GEN op on a unit without a gen engine")
                gen_engine.replay_step(**inputs)
            elif op == OP_GEN_RESET:
                if gen_engine is None:
                    raise RuntimeError("GEN op on a unit without a gen engine")
                gen_engine.replay_reset()
            elif op == OP_GEN_CHUNK:
                if gen_engine is None:
                    raise RuntimeError("GEN op on a unit without a gen engine")
                gen_engine.replay_chunk(**inputs)
            elif op == OP_GEN_INSERT:
                if gen_engine is None:
                    raise RuntimeError("GEN op on a unit without a gen engine")
                gen_engine.replay_insert(**inputs)
            elif op == OP_GEN_SEED:
                if gen_engine is None:
                    raise RuntimeError("GEN op on a unit without a gen engine")
                gen_engine.replay_seed(**inputs)
            elif op == OP_GEN_VERIFY:
                if gen_engine is None:
                    raise RuntimeError("GEN op on a unit without a gen engine")
                gen_engine.replay_verify(**inputs)
            elif op == OP_GEN_CHUNKS:
                if gen_engine is None:
                    raise RuntimeError("GEN op on a unit without a gen engine")
                gen_engine.replay_chunks(**inputs)
            elif op == OP_GEN_SEED_SLOT:
                if gen_engine is None:
                    raise RuntimeError("GEN op on a unit without a gen engine")
                gen_engine.replay_seed_slot(**inputs)
            elif op == OP_GEN_MULTISTEP:
                if gen_engine is None:
                    raise RuntimeError("GEN op on a unit without a gen engine")
                gen_engine.replay_multistep(**inputs)
            elif op == OP_GEN_SP_PREFILL:
                if gen_engine is None:
                    raise RuntimeError("GEN op on a unit without a gen engine")
                gen_engine.replay_sp_prefill(**inputs)
            elif op == OP_GEN_SUPERSTEP:
                if gen_engine is None:
                    raise RuntimeError("GEN op on a unit without a gen engine")
                gen_engine.replay_superstep(**inputs)
            elif op == OP_GEN_RESTORE:
                if gen_engine is None:
                    raise RuntimeError("GEN op on a unit without a gen engine")
                gen_engine.replay_restore(**inputs)
            else:  # unknown op: skip rather than desync the group
                _log.warning("follower ignoring unknown op %r", op)
        except Exception:
            if op in (OP_GEN_ADMIT, OP_GEN_STEP, OP_GEN_RESET, OP_GEN_CHUNK,
                      OP_GEN_INSERT, OP_GEN_SEED, OP_GEN_VERIFY,
                      OP_GEN_CHUNKS, OP_GEN_SEED_SLOT, OP_GEN_MULTISTEP,
                      OP_GEN_SUPERSTEP, OP_GEN_SP_PREFILL, OP_GEN_RESTORE):
                # Generation is STATEFUL: if this host failed a step the
                # leader executed, its cache/lengths shards now disagree
                # with every other host's, and all in-flight sequences
                # would keep streaming silently corrupted tokens as 200s.
                # Fail LOUD instead: exit the loop (the pod terminates,
                # the process group breaks, the leader's next collective
                # errors and fails in-flight requests with a 500, and the
                # unit restarts into a consistent state).
                _log.exception(
                    "follower gen step %r failed; exiting so the unit "
                    "restarts instead of serving corrupted tokens", op
                )
                raise
            # predict is stateless: the leader catches the same model error
            # in its HTTP handler and stays up (app.py returns 500); a
            # follower that dies instead could never rejoin the formed
            # process group.  Same step attempted on every host keeps the
            # group in lockstep whether it raised or not.
            _log.exception("follower step %r failed; continuing", op)
        steps += 1
