"""Device telemetry: HBM ledger, compile observatory, cost model + MFU.

Every perf PR so far justified itself with hand-derived "weight streams
per token" arithmetic; this module makes the hardware story a measured,
served surface instead of a code comment.  Three parts:

- **HBM ledger** — an analytic byte ledger of what the serving process
  holds on device (weight tree by dtype, KV cache incl. the int8kv
  layout's scale planes, per-slot sampling state) cross-checked against
  ``device.memory_stats()`` where the platform provides it.  Served at
  ``GET /debug/device``, exported as ``tpumlops_device_hbm_bytes
  {component}``, and stamped into the model-capacity startup log line
  (``server/loader.py`` emits that line even with telemetry off).
- **Compile observatory** — wraps every engine jit dispatch so each XLA
  compilation is attributed to the op that triggered it (decode buckets,
  verify variants, prefill B_p buckets, seed ops), with wall time and
  persistent-cache hit/miss from ``utils/compile_cache``'s jax
  monitoring hooks.  One structured ``tpumlops.compile`` log line per
  compilation; ``tpumlops_compile_seconds_total{op}`` and
  ``tpumlops_compile_cache_{hits,misses}_total`` series; a warning when
  the warmup sweep exceeds the readiness budget (cold-start is a
  first-class serving cost — "Breaking the Ice", PAPERS.md).
- **Cost model + utilization** — analytic per-program FLOPs / HBM-bytes
  estimates for the llama serving programs, joined with flight-recorder
  tick walls into per-tick-kind MFU and HBM-bandwidth utilization.  The
  ENGINE path is analytic by design: its programs are jit-dispatched
  with donated buffers, so there is no compiled object in hand and an
  AOT re-lower just to ask XLA's opinion would double every compile.
  :func:`cost_from_analysis` is the adapter for contexts that DO hold a
  ``Compiled`` (scripts, notebooks, AOT tooling — ``lower().compile()
  .cost_analysis()``), and the test suite uses it to cross-check the
  analytic numbers against XLA's own count.  Exposed surfaces:
  ``mfu`` / ``hbm_bw_util`` fields on recorder ticks, Perfetto counter
  tracks in ``/debug/trace``, and ``tpumlops_device_{mfu,hbm_bw_util}
  {kind}`` gauges.

Error bars (documented in docs/OBSERVABILITY.md): the analytic FLOPs
count is exact for the matmul tree and counts the attention einsums at
the full padded window, so MFU is a lower bound on "useful" utilization
by at most the padding fraction; HBM bytes assume each weight byte and
each attended cache byte streams exactly once (XLA re-reads under
fusion-decline pathologies, so bw_util can read > 1 of the *model*
while still < 1 of the wire — values are clamped to (0, 1]).

``spec.tpu.observability.deviceTelemetry`` (CRD -> config -> builder
``--device-telemetry`` -> server CLI) gates the whole layer; off — the
default — constructs nothing and every payload stays byte-for-byte.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

_log = logging.getLogger("tpumlops.device_telemetry")
_compile_log = logging.getLogger("tpumlops.compile")

# Warmup sweep budget before a warning fires: the builder's readiness
# probe window is initialDelay 10 + period 5 x failureThreshold 60 =
# 310 s; a sweep past ~300 s risks the kubelet killing the pod
# mid-compile (SURVEY §7 hard part 3).
READINESS_BUDGET_S = 300.0


# ---------------------------------------------------------------------------
# Device facts (peaks the utilization ratios divide by)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class DevicePeaks:
    """Peak rates the utilization ratios are read against.

    ``chips`` is how many chips the numbers cover: the cost model and
    ledger count the WHOLE (possibly sharded) model, so the peaks must
    cover the whole device set holding it — a tp=8 mesh divides by 8x
    the per-chip roofline, or every ratio reads 8x high and clamps."""

    kind: str  # jax device_kind (or the assumed stand-in)
    flops_per_s: float  # dense peak for the serving dtype family
    hbm_bytes_per_s: float
    hbm_bytes: int  # HBM capacity
    source: str  # "detected" | "assumed"
    chips: int = 1
    # Per-chip ICI bandwidth the collective-wall estimates divide by
    # (rough order-of-magnitude constants, marked per ``source`` like
    # the rooflines; stays PER-CHIP under scaled() — a ring all-reduce's
    # wall is set by one link, not the aggregate).
    ici_bytes_per_s: float = 2e11

    def scaled(self, chips: int) -> "DevicePeaks":
        import dataclasses

        n = max(1, int(chips))
        return dataclasses.replace(
            self,
            flops_per_s=self.flops_per_s * n,
            hbm_bytes_per_s=self.hbm_bytes_per_s * n,
            hbm_bytes=self.hbm_bytes * n,
            chips=n,
        )


def param_device_count(params) -> int:
    """Devices the param tree is actually sharded over (1 for the
    default unsharded tree, even when more devices are visible)."""
    try:
        import jax

        leaf = jax.tree.leaves(params)[0]
        return max(1, len(leaf.sharding.device_set))
    except Exception:
        return 1


# v5e: 197 bf16 TFLOP/s, 819 GB/s, 16 GiB HBM (bench.py's constants of
# record).  Matching is by device_kind substring; unknown kinds (the CPU
# dev environment) fall back to the v5e row marked "assumed" so ratios
# stay computable — tiny on CPU, honest on the target part.
_KNOWN_DEVICES = {
    "v5 lite": ("tpu-v5e", 197e12, 819e9, 16 * 2**30),
    "v5e": ("tpu-v5e", 197e12, 819e9, 16 * 2**30),
    "v4": ("tpu-v4", 275e12, 1228e9, 32 * 2**30),
}
_ASSUMED = ("tpu-v5e (assumed)", 197e12, 819e9, 16 * 2**30)


def detect_peaks() -> DevicePeaks:
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        kind = "unknown"
    for marker, (name, fl, bw, hbm) in _KNOWN_DEVICES.items():
        if marker in kind:
            return DevicePeaks(name, fl, bw, hbm, "detected")
    name, fl, bw, hbm = _ASSUMED
    return DevicePeaks(name, fl, bw, hbm, "assumed")


def measured_memory() -> dict | None:
    """``device.memory_stats()`` summed over the ADDRESSABLE devices
    (TPU/GPU runtimes report it; CPU returns None).  ``devices`` counts
    how many reported — on a multi-host unit each process sees only its
    local chips, so the ledger cross-check scales by the addressable
    fraction (see :meth:`HbmLedger.snapshot`)."""
    try:
        import jax

        devs = jax.local_devices()
    except Exception:
        return None
    totals: dict[str, int] = {}
    reporting = 0
    for dev in devs:
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        reporting += 1
        for k, v in stats.items():
            if isinstance(v, (int, float)):
                totals[k] = totals.get(k, 0) + int(v)
    if reporting == 0:
        return None
    totals["devices"] = reporting
    return totals


# ---------------------------------------------------------------------------
# HBM ledger
# ---------------------------------------------------------------------------


def weights_bytes_by_dtype(params, per_chip: bool = False) -> dict[str, int]:
    """Parameter bytes grouped by dtype as stored (int8 leaves count
    1 byte/elem; their f32 scale planes land under float32).

    ``per_chip=True`` counts what ONE device holds — exact, via each
    leaf's shard shape: a tp-sharded matrix counts 1/tp of its bytes,
    a replicated norm counts whole on every chip."""
    import jax

    out: dict[str, int] = {}
    for leaf in jax.tree.leaves(params):
        name = str(leaf.dtype)
        if per_chip:
            try:
                from ..models.partition import shard_bytes

                nbytes = shard_bytes(leaf)
            except Exception:  # host arrays / exotic shardings
                nbytes = int(leaf.size) * leaf.dtype.itemsize
        else:
            nbytes = int(leaf.size) * leaf.dtype.itemsize
        out[name] = out.get(name, 0) + nbytes
    return out


def kv_cache_bytes_per_row(
    cfg, kv_quant: bool, dtype_bytes: int = 2, tp: int = 1
) -> int:
    """Bytes one cache row (slot at full ``max_seq``) holds: k + v across
    all layers, plus the int8kv layout's per-(pos, head) f32 scales.
    ``tp`` > 1 gives the PER-CHIP row (the heads axis is what shards, so
    each chip holds num_kv_heads/tp of every row)."""
    heads = cfg.num_kv_heads // max(1, int(tp))
    elems = cfg.num_layers * heads * cfg.max_seq * cfg.head_dim
    if kv_quant:
        # int8 values + f32 scale per head_dim group, for k and v each.
        return 2 * (elems + (elems // cfg.head_dim) * 4)
    return 2 * elems * dtype_bytes


def sampling_state_bytes(max_slots: int) -> int:
    """Engine per-slot device state outside the cache: token buffer
    (int32), PRNG keys (2x uint32), temps/topk/topp (4 B each)."""
    return max_slots * (4 + 8 + 4 + 4 + 4)


@dataclass
class HbmLedger:
    """Analytic device-byte ledger, cross-checkable against
    ``memory_stats()``.  ``components`` are on-device; ``host_components``
    (the prefix cache's host-RAM budget) ride along for the capacity
    story but never count toward the device total."""

    components: dict[str, int] = field(default_factory=dict)
    host_components: dict[str, int] = field(default_factory=dict)
    kv_bytes_per_row: int = 0
    max_slots: int = 0
    # tp > 1: what ONE chip holds of each component (weights exact via
    # shard shapes, kv/sampling analytic) — the per-chip view the
    # tpumlops_device_hbm_bytes{component="*_per_chip"} gauges export.
    per_chip: dict[str, int] = field(default_factory=dict)
    chips: int = 1

    def device_total(self) -> int:
        return sum(self.components.values())

    def max_cache_rows(self, hbm_bytes: int) -> int:
        """Full-capacity KV rows that fit beside the weights — the
        capacity number the autoscaler/operator plans against."""
        if self.kv_bytes_per_row <= 0:
            return 0
        spare = hbm_bytes - sum(
            v for k, v in self.components.items() if not k.startswith("kv_")
        )
        return max(0, spare // self.kv_bytes_per_row)

    def snapshot(self, peaks: DevicePeaks | None = None) -> dict:
        peaks = peaks or detect_peaks()
        measured = measured_memory()
        out = {
            "components": dict(self.components),
            "host_components": dict(self.host_components),
            "device_total_bytes": self.device_total(),
            "kv_bytes_per_row": self.kv_bytes_per_row,
            "max_slots": self.max_slots,
            "hbm_capacity_bytes": peaks.hbm_bytes,
            "hbm_source": peaks.source,
            "max_cache_rows": self.max_cache_rows(peaks.hbm_bytes),
            "measured": measured,
        }
        if self.per_chip:
            out["per_chip"] = dict(self.per_chip)
            out["chips"] = self.chips
        if measured and measured.get("bytes_in_use"):
            # Multi-host: this process addresses only its local chips,
            # which hold addressable/total of the sharded model — scale
            # the ledger to what THESE chips should hold before
            # comparing.
            frac = min(1.0, measured["devices"] / max(1, peaks.chips))
            expected = self.device_total() * frac
            out["ledger_vs_measured_pct"] = round(
                100.0 * (expected - measured["bytes_in_use"])
                / max(1, measured["bytes_in_use"]),
                1,
            )
        return out


def build_hbm_ledger(
    params,
    cfg,
    max_slots: int,
    kv_quant: bool = False,
    dtype_bytes: int = 2,
    prefix_cache_budget_bytes: int = 0,
    tp: int = 1,
    dp: int = 1,
) -> HbmLedger:
    dp = max(1, int(dp))
    ledger = HbmLedger(
        kv_bytes_per_row=kv_cache_bytes_per_row(cfg, kv_quant, dtype_bytes),
        max_slots=int(max_slots),
        chips=max(1, int(tp)) * dp,
    )
    for dtype, nbytes in weights_bytes_by_dtype(params).items():
        ledger.components[f"weights_{dtype}"] = nbytes
    ledger.components["kv_cache"] = ledger.kv_bytes_per_row * int(max_slots)
    ledger.components["sampling_state"] = sampling_state_bytes(max_slots)
    if prefix_cache_budget_bytes:
        ledger.host_components["prefix_cache_budget"] = int(
            prefix_cache_budget_bytes
        )
    if tp > 1 or dp > 1:
        for dtype, nbytes in weights_bytes_by_dtype(
            params, per_chip=True
        ).items():
            ledger.per_chip[f"weights_{dtype}"] = nbytes
        row_chip = kv_cache_bytes_per_row(cfg, kv_quant, dtype_bytes, tp=tp)
        ledger.per_chip["kv_bytes_per_row"] = row_chip
        # dp shards the ROW axis: one chip holds max_slots/dp rows (of
        # its tp heads-shard of each).
        ledger.per_chip["kv_cache"] = row_chip * (int(max_slots) // dp)
        # Sampling state replicates: every chip holds the whole thing.
        ledger.per_chip["sampling_state"] = sampling_state_bytes(max_slots)
        ledger.per_chip["total"] = sum(
            v for k, v in ledger.per_chip.items()
            if k != "kv_bytes_per_row"
        )
    return ledger


def capacity_log_line(params, cfg, kv_quant: bool) -> str:
    """The model-capacity startup line ``server/loader.py`` stamps (even
    with telemetry off): weights by dtype, KV bytes/row, max cache rows.
    HBM covers the device set the params are sharded over."""
    n_chips = param_device_count(params)
    peaks = detect_peaks().scaled(n_chips)
    by_dtype = weights_bytes_by_dtype(params)
    total = sum(by_dtype.values())
    per_row = kv_cache_bytes_per_row(cfg, kv_quant)
    spare = peaks.hbm_bytes - total
    rows = max(0, spare // per_row) if per_row else 0
    dtypes = ", ".join(
        f"{k}={v / 2**20:.1f}MiB" for k, v in sorted(by_dtype.items())
    )
    chips = f" x{peaks.chips}" if peaks.chips > 1 else ""
    per_chip = ""
    if n_chips > 1:
        # The tp view: what ONE chip actually holds (weights exact via
        # shard shapes, KV row = heads/tp) — the number that fits or
        # OOMs on the hardware.
        chip_w = sum(weights_bytes_by_dtype(params, per_chip=True).values())
        chip_row = kv_cache_bytes_per_row(cfg, kv_quant, tp=n_chips)
        per_chip = (
            f", per-chip weights {chip_w / 2**20:.1f} MiB "
            f"kv {chip_row} B/row"
        )
    return (
        f"model capacity: weights {total / 2**20:.1f} MiB ({dtypes}), "
        f"kv {per_row} B/row (max_seq {cfg.max_seq}"
        f"{', int8kv' if kv_quant else ''}), "
        f"max cache rows {rows} "
        f"(hbm {peaks.hbm_bytes / 2**30:.1f} GiB "
        f"{peaks.source} {peaks.kind}{chips}){per_chip}"
    )


# ---------------------------------------------------------------------------
# Compile observatory
# ---------------------------------------------------------------------------


class CompileObservatory:
    """Attributes every XLA compilation to the engine op that triggered
    it.

    The engine wraps each jitted callable with :meth:`wrap_jit`; the
    wrapper pins the op name in a thread-local for the duration of the
    call, and ``utils/compile_cache``'s jax monitoring hooks deliver
    (compile wall, cache hit/miss) events back through :meth:`on_event`
    — compiles are synchronous inside the triggering dispatch, so the
    attribution is exact.  Each compilation logs one structured
    ``tpumlops.compile`` line (from ``utils/compile_cache``, which asks
    this observatory for the current op)."""

    MAX_EVENTS = 256

    def __init__(self, readiness_budget_s: float = READINESS_BUDGET_S):
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.readiness_budget_s = float(readiness_budget_s)
        # op -> {"compiles", "seconds", "cache_hits", "cache_misses"}
        self.ops: dict[str, dict] = {}
        self.events: list[dict] = []  # newest-last, bounded
        self._in_warmup = False
        self.warmup: dict = {}
        self._on_compile = None  # (op, seconds) -> None (metrics hookup)
        self._on_cache = None  # (hit: bool) -> None

    # -- wiring ---------------------------------------------------------------

    def install(self) -> None:
        """Register with utils/compile_cache's monitoring hooks (idempotent
        there); safe to call before any jit."""
        from ..utils.compile_cache import install_compile_listeners

        install_compile_listeners(observatory=self)

    def set_metrics_hooks(self, on_compile=None, on_cache=None) -> None:
        self._on_compile = on_compile
        self._on_cache = on_cache

    def wrap_jit(self, op: str, fn):
        """Wrap a jitted callable so compiles inside it attribute to
        ``op``.  Transparent otherwise — same args, same returns."""

        def wrapped(*args, **kwargs):
            prev = getattr(self._tls, "op", None)
            self._tls.op = op
            try:
                return fn(*args, **kwargs)
            finally:
                self._tls.op = prev

        wrapped.__name__ = f"observed_{op}"
        return wrapped

    def current_op(self) -> str:
        return getattr(self._tls, "op", None) or "other"

    # -- event sinks (called from utils/compile_cache's listeners) -----------

    def on_event(self, kind: str, seconds: float = 0.0) -> None:
        """``kind``: "compile" (with backend wall) or "cache_hit" /
        "cache_miss" (persistent-cache outcome of the compile request)."""
        op = self.current_op()
        with self._lock:
            rec = self.ops.setdefault(
                op,
                {"compiles": 0, "seconds": 0.0,
                 "cache_hits": 0, "cache_misses": 0},
            )
            if kind == "compile":
                rec["compiles"] += 1
                rec["seconds"] += seconds
                self.events.append(
                    {"op": op, "seconds": round(seconds, 4),
                     "ts": time.time(), "warmup": self._in_warmup}
                )
                del self.events[: -self.MAX_EVENTS]
                if self._in_warmup:
                    self.warmup["compiles"] = self.warmup.get("compiles", 0) + 1
                    self.warmup["seconds"] = (
                        self.warmup.get("seconds", 0.0) + seconds
                    )
            elif kind == "cache_hit":
                rec["cache_hits"] += 1
            elif kind == "cache_miss":
                rec["cache_misses"] += 1
        if kind == "compile" and self._on_compile is not None:
            self._on_compile(op, seconds)
        elif kind in ("cache_hit", "cache_miss") and self._on_cache is not None:
            self._on_cache(kind == "cache_hit")

    # -- warmup sweep ---------------------------------------------------------

    def begin_warmup(self) -> None:
        with self._lock:
            self._in_warmup = True
            self.warmup = {"compiles": 0, "seconds": 0.0}
            # Per-op compile counts at sweep start, so end_warmup can
            # report the variant INVENTORY the sweep itself compiled —
            # not lifetime totals polluted by pre-warmup seeds.
            self._warmup_baseline = {
                op: rec["compiles"] for op, rec in self.ops.items()
            }
            self._t_warmup = time.perf_counter()

    def end_warmup(self) -> dict:
        with self._lock:
            self._in_warmup = False
            self.warmup["wall_s"] = round(
                time.perf_counter() - getattr(self, "_t_warmup", 0.0), 2
            )
            baseline = getattr(self, "_warmup_baseline", {})
            inventory = {
                op: rec["compiles"] - baseline.get(op, 0)
                for op, rec in sorted(self.ops.items())
                if rec["compiles"] - baseline.get(op, 0) > 0
            }
            self.warmup["ops"] = inventory
            report = dict(self.warmup)
            report["ops"] = dict(inventory)
        inv = (
            " ".join(f"{op}={n}" for op, n in report["ops"].items()) or "-"
        )
        if report["wall_s"] > self.readiness_budget_s:
            _log.warning(
                "warmup sweep took %.1fs (> readiness budget %.0fs): "
                "%d compiles [%s], %.1fs of XLA work — the kubelet may "
                "kill this pod mid-compile; pre-seed the persistent "
                "compile cache or raise the readiness window",
                report["wall_s"], self.readiness_budget_s,
                report["compiles"], inv, report["seconds"],
            )
        else:
            # The variant inventory in one structured line: the op ×
            # count breakdown makes a program-space regression (or the
            # unified engine's K-fold collapse) visible without diffing
            # gauge snapshots.
            _compile_log.info(
                "warmup sweep done compiles=%d compile_s=%.2f "
                "wall_s=%.2f ops=[%s]",
                report["compiles"], report["seconds"],
                report["wall_s"], inv,
            )
        return report

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "ops": {k: dict(v) for k, v in self.ops.items()},
                "events": [dict(e) for e in self.events],
                "warmup": dict(self.warmup),
                "readiness_budget_s": self.readiness_budget_s,
            }


# ---------------------------------------------------------------------------
# Cost model
# ---------------------------------------------------------------------------


def cost_from_analysis(analysis) -> tuple[float, float] | None:
    """Parse an XLA ``Compiled.cost_analysis()`` payload into
    ``(flops, hbm_bytes)`` (jax returns a dict, or a 1-list of dicts on
    older versions).  For callers that hold a compiled object — scripts
    / AOT tooling / the cross-check test — NOT the engine hot path,
    which is analytic by design (its programs are jit-dispatched with
    donated buffers; see the module docstring)."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    flops = float(analysis.get("flops", 0.0))
    nbytes = float(analysis.get("bytes accessed", 0.0))
    if flops <= 0.0 and nbytes <= 0.0:
        return None
    return flops, nbytes


@dataclass(frozen=True)
class LlamaCostModel:
    """Analytic per-program FLOPs / HBM-bytes for the llama serving
    programs.  ``matmul_params`` is the weight-matrix element count (the
    2-flops-per-param term); ``weight_bytes`` the tree as stored (int8
    leaves 1 B) — every program streams it once."""

    matmul_params: int
    weight_bytes: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    kv_elem_bytes: float  # bytes per cache element incl. scale overhead
    # Tensor-parallel collective geometry (tp == 1 -> no collectives):
    # hidden/vocab size the per-layer all-reduces and the logits
    # all-gather move, in the serving activation dtype.
    tp: int = 1
    hidden_size: int = 0
    vocab_size: int = 0
    act_bytes: int = 2
    # Batch (row) and sequence parallel degrees — dp shards the cache's
    # row axis (no extra collectives: weights replicate and the logits
    # all-gather already covers the replicated read-back); sp adds the
    # ring-permute K/V rotation costed in :meth:`ring_bytes`.
    dp: int = 1
    sp: int = 1

    @classmethod
    def for_model(cls, params, cfg, kv_quant: bool = False,
                  dtype_bytes: int = 2,
                  mesh_shape=None) -> "LlamaCostModel":
        import jax

        from ..models.llama import matmul_param_count

        wbytes = sum(
            int(leaf.size) * leaf.dtype.itemsize
            for leaf in jax.tree.leaves(params)
        )
        hd = cfg.head_dim
        kv_eb = 1 + 4.0 / hd if kv_quant else float(dtype_bytes)
        # Prefer the declared mesh: under dp the params REPLICATE over
        # dp*tp devices, so the sharded-device count alone would
        # over-report tp by the dp factor.
        if mesh_shape:
            tp = max(1, int(dict(mesh_shape).get("tp", 1)))
            dp = max(1, int(dict(mesh_shape).get("dp", 1)))
            sp = max(1, int(dict(mesh_shape).get("sp", 1)))
        else:
            tp, dp, sp = param_device_count(params), 1, 1
        return cls(
            matmul_params=matmul_param_count(cfg),
            weight_bytes=wbytes,
            num_layers=cfg.num_layers,
            num_heads=cfg.num_heads,
            num_kv_heads=cfg.num_kv_heads,
            head_dim=hd,
            kv_elem_bytes=kv_eb,
            tp=tp,
            hidden_size=int(getattr(cfg, "hidden_size", 0)),
            vocab_size=int(getattr(cfg, "vocab_size", 0)),
            act_bytes=int(dtype_bytes),
            dp=dp,
            sp=sp,
        )

    def collective_bytes(self, rows: int, s: int = 1) -> dict[str, float]:
        """Per-device ICI bytes one forward dispatch moves at tp > 1:

        - ``all_reduce`` — the Megatron pair: 2 psums per layer (after
          the o and down projections) of the ``[rows*s, hidden]``
          activation block; a ring all-reduce moves ``2(tp-1)/tp`` of
          the block per device;
        - ``all_gather`` — the vocab-sharded lm_head product gathered
          for replicated token/logit outputs: ``(tp-1)/tp`` of
          ``[rows*s, vocab]`` f32 once per dispatch.

        Empty at tp == 1 (no collectives exist to estimate)."""
        if self.tp <= 1:
            return {}
        tokens = float(rows) * float(s)
        block = tokens * self.hidden_size * self.act_bytes
        ar = 2.0 * self.num_layers * block * 2.0 * (self.tp - 1) / self.tp
        ag = tokens * self.vocab_size * 4.0 * (self.tp - 1) / self.tp
        return {"all_reduce": ar, "all_gather": ag}

    def _kv_bytes(self, rows: int, positions: float) -> float:
        """k+v cache traffic for ``rows`` rows over ``positions`` each."""
        return (
            2.0 * rows * positions * self.num_layers * self.num_kv_heads
            * self.head_dim * self.kv_elem_bytes
        )

    def decode(self, rows: int, window: int, s: int = 1
               ) -> tuple[float, float]:
        """One decode (``s=1``) or verify (``s`` positions/row) tick over
        ``rows`` cache rows attending ``window`` positions."""
        flops = 2.0 * self.matmul_params * rows * s
        flops += 4.0 * rows * s * window * self.num_heads * self.head_dim
        nbytes = self.weight_bytes + self._kv_bytes(rows, window)
        nbytes += self._kv_bytes(rows, s)  # fresh K/V written
        return flops, nbytes

    def prefill(self, rows: int, chunk: int, attended: float | None = None
                ) -> tuple[float, float]:
        """One prefill call: ``rows`` rows of ``chunk`` tokens each,
        attending ``attended`` mean positions (defaults to the causal
        mean over the chunk itself)."""
        if attended is None:
            attended = chunk / 2.0
        flops = 2.0 * self.matmul_params * rows * chunk
        flops += 4.0 * rows * chunk * attended * self.num_heads * self.head_dim
        nbytes = self.weight_bytes + self._kv_bytes(rows, chunk)
        nbytes += self._kv_bytes(rows, max(0.0, attended - chunk / 2.0))
        return flops, nbytes

    def superstep(self, rows: int, window: int, s: int, steps: int
                  ) -> tuple[float, float]:
        """One unified super-step dispatch: the wide ragged forward
        (``s`` positions/row — the verify-chain / prefill-chunk width)
        plus ``steps - 1`` chained single-position decode iterations
        under the same dispatch.  A composition of :meth:`decode`, so
        the unified engine's cost stays consistent with the split
        programs it replaces."""
        flops, nbytes = self.decode(rows, window, s)
        if steps > 1:
            f1, b1 = self.decode(rows, window, 1)
            flops += (steps - 1) * f1
            nbytes += (steps - 1) * b1
        return flops, nbytes

    def seed(self, tokens: int) -> tuple[float, float]:
        """Prefix-cache seed: a pure K/V copy — read + write, no flops."""
        return 0.0, 2.0 * self._kv_bytes(1, tokens)

    def sp_prefill(self, tokens: int) -> tuple[float, float]:
        """One ring-attention prefill pass over a ``tokens``-long padded
        prompt: same total flops/bytes as a fused prefill of the whole
        prompt (the ring changes WHERE the S x S work runs — S/sp per
        device — not how much exists)."""
        return self.prefill(1, tokens)

    def ring_bytes(self, tokens: int) -> dict[str, float]:
        """Per-device ICI bytes the sp ring rotation moves in one
        prefill pass: each device forwards its K/V shard ``sp - 1``
        times per layer (k and v each, [1, S/sp, NKV, D] blocks).
        Empty at sp == 1 — no ring exists to estimate."""
        if self.sp <= 1:
            return {}
        shard = float(tokens) / self.sp
        per_layer = (
            2.0 * shard * self.num_kv_heads * self.head_dim * self.act_bytes
        )
        return {
            "ring_permute": per_layer * self.num_layers * (self.sp - 1)
        }


# ---------------------------------------------------------------------------
# Facade the server wires together
# ---------------------------------------------------------------------------


class DeviceTelemetry:
    """One object per server process: ledger + observatory + cost model.

    Constructed only when ``spec.tpu.observability.deviceTelemetry`` is
    on; ``None`` everywhere otherwise, so the disabled path allocates
    nothing and every existing payload stays byte-for-byte."""

    def __init__(self, metrics=None,
                 readiness_budget_s: float = READINESS_BUDGET_S):
        # Per-chip until attach_model scales to the param-holding device
        # set; _chip_peaks keeps the pristine base so a rebind/re-attach
        # can never compound the scaling.
        self._chip_peaks = detect_peaks()
        self.peaks = self._chip_peaks
        self.observatory = CompileObservatory(readiness_budget_s)
        self.observatory.install()
        self.ledger: HbmLedger | None = None
        self.cost: LlamaCostModel | None = None
        self._metrics = None
        # Last computed utilization per tick kind (the /debug/device
        # mirror of the gauges).  Written by the engine scheduler
        # thread, read by the /debug/device executor thread — the lock
        # covers the first-tick-of-a-new-kind insert racing a snapshot
        # iteration.
        self._util_lock = threading.Lock()
        self.last_util: dict[str, dict] = {}
        if metrics is not None:
            self.bind_metrics(metrics)

    def bind_metrics(self, metrics) -> None:
        """Hook the Prometheus families (present only when the registry
        was built with ``device_telemetry=True``)."""
        if getattr(metrics, "device_hbm_bytes", None) is None:
            return
        self._metrics = metrics
        self.observatory.set_metrics_hooks(
            on_compile=metrics.observe_compile,
            on_cache=metrics.observe_compile_cache,
        )

    def attach_model(self, params, cfg, max_slots: int,
                     kv_quant: bool = False, dtype_bytes: int = 2,
                     prefix_cache_budget_bytes: int = 0,
                     mesh_shape=None) -> None:
        """Build the ledger + cost model once the engine geometry is
        known; exports the per-component HBM gauges.  Peaks scale to the
        device set actually holding the params (the cost model and
        ledger count the whole sharded model).  ``mesh_shape`` (when
        the engine runs one) disambiguates the axes: params replicated
        over a dp axis span dp*tp devices, which the sharded-device
        count alone would misread as tp."""
        if mesh_shape:
            tp = max(1, int(dict(mesh_shape).get("tp", 1)))
            dp = max(1, int(dict(mesh_shape).get("dp", 1)))
            chips = 1
            for v in dict(mesh_shape).values():
                chips *= max(1, int(v))
        else:
            tp, dp = param_device_count(params), 1
            chips = tp
        self.peaks = self._chip_peaks.scaled(chips)
        self.ledger = build_hbm_ledger(
            params, cfg, max_slots, kv_quant=kv_quant,
            dtype_bytes=dtype_bytes,
            prefix_cache_budget_bytes=prefix_cache_budget_bytes,
            tp=tp, dp=dp,
        )
        self.cost = LlamaCostModel.for_model(
            params, cfg, kv_quant=kv_quant, dtype_bytes=dtype_bytes,
            mesh_shape=mesh_shape,
        )
        if self._metrics is not None:
            for comp, nbytes in self.ledger.components.items():
                self._metrics.observe_hbm_component(comp, nbytes)
            self._metrics.observe_hbm_component(
                "total", self.ledger.device_total()
            )
            # tp > 1: the per-chip view rides the same family under
            # ``<component>_per_chip`` label values — what ONE chip
            # holds, which is what fits-or-OOMs on the hardware.
            for comp, nbytes in self.ledger.per_chip.items():
                if comp == "kv_bytes_per_row":
                    continue
                self._metrics.observe_hbm_component(
                    f"{comp}_per_chip", nbytes
                )

    def tick_util(self, kind: str, wall_s: float, flops: float,
                  hbm_bytes: float) -> dict:
        """Join one tick's wall with its program cost: MFU and HBM-BW
        utilization, clamped to (0, 1] (see the module docstring's error
        bars).  Returns the dict merged onto the recorder tick."""
        wall = max(wall_s, 1e-9)
        mfu = min(1.0, flops / wall / self.peaks.flops_per_s)
        bw = min(1.0, hbm_bytes / wall / self.peaks.hbm_bytes_per_s)
        # 3 significant digits, NOT fixed decimals: a CPU dev tick's
        # 4e-7 MFU must stay > 0 (the in-(0,1] contract), and a real
        # chip's 0.41 needs no more precision.
        util = {
            "mfu": float(f"{mfu:.3g}") if flops > 0 else 0.0,
            "hbm_bw_util": float(f"{bw:.3g}"),
        }
        if (
            self.cost is not None
            and (self.cost.tp > 1 or kind == "sp-prefill")
            and kind in ("decode", "verify", "multistep", "prefill",
                         "packed-prefill", "superstep", "sp-prefill")
        ):
            # Analytic collective walls at tp > 1: one dispatch's ICI
            # traffic over the per-chip link rate, split by op — the
            # tpumlops_engine_collective_seconds{op} feed.  The token
            # count is recovered from the tick's own flops (flops ~=
            # 2 x matmul_params x tokens), so a fused K-step scan, an
            # S-position verify, and a packed chunk call all count
            # their full per-dispatch traffic, not one token-row's.
            tokens = flops / max(1.0, 2.0 * self.cost.matmul_params)
            coll = self.cost.collective_bytes(tokens)
            if kind == "sp-prefill":
                # The ring rotation is the sp axis's collective wall —
                # per-layer K/V shard forwards, costed per device.
                coll = dict(coll)
                coll.update(self.cost.ring_bytes(tokens))
            total_coll = 0.0
            for op, nbytes in coll.items():
                secs = nbytes / self.peaks.ici_bytes_per_s
                total_coll += secs
                if self._metrics is not None:
                    self._metrics.observe_collective(op, secs)
            util["collective_s"] = float(f"{total_coll:.3g}")
        with self._util_lock:
            self.last_util[kind] = util
        if self._metrics is not None:
            self._metrics.observe_device_util(kind, mfu, bw)
        return util

    def snapshot(self) -> dict:
        """The ``GET /debug/device`` payload."""
        with self._util_lock:
            utilization = {k: dict(v) for k, v in self.last_util.items()}
        return {
            "peaks": {
                "device": self.peaks.kind,
                "source": self.peaks.source,
                "chips": self.peaks.chips,
                "flops_per_s": self.peaks.flops_per_s,
                "hbm_bytes_per_s": self.peaks.hbm_bytes_per_s,
                "hbm_bytes": self.peaks.hbm_bytes,
            },
            "hbm": self.ledger.snapshot(self.peaks) if self.ledger else None,
            "utilization": utilization,
            "compile": self.observatory.snapshot(),
        }
