"""The first-party TPU inference server (data plane).

The reference delegates all inference to Seldon's prebuilt ``MLFLOW_SERVER``
container (``mlflow_operator.py:198,:213``) and only manipulates traffic
weights around it.  This package replaces that outsourced data plane:

- ``loader``   — resolve a model URI to a ``Predictor`` (MLmodel-aware,
  tiered: TPU-native JAX flavors vs host pyfunc fallback)
- ``engine``   — jit compilation, batch-bucket warmup, thread-safe dispatch
- ``batching`` — dynamic request batching with power-of-two padding buckets
- ``metrics``  — Prometheus histograms with the exact metric names + identity
  labels the promotion gate queries (``mlflow_operator.py:367-415``)
- ``app``      — V2 (kfserving) + Seldon-protocol HTTP endpoints
- ``flight_recorder`` — bounded engine journal: per-tick records +
  request traces, served at ``/debug/engine`` / ``/debug/trace``
  (Perfetto-viewable Chrome trace export)
"""

from .engine import InferenceEngine
from .metrics import ServerMetrics

__all__ = ["InferenceEngine", "ServerMetrics", "app", "loader", "batching"]


def __getattr__(name):
    if name in ("app", "loader", "batching"):
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
