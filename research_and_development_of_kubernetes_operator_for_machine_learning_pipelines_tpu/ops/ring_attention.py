"""Ring attention: exact attention over a sequence sharded on the ``sp``
mesh axis (long-context serving / context parallelism).

Each device keeps its sequence shard of Q resident and streams K/V shards
around the ICI ring (``ppermute`` to the nearest neighbor — one hop per
step on the v5e torus).  Blockwise online softmax merges each incoming
block into running (acc, max, denom), so the full S x S score matrix never
exists anywhere and per-device memory stays O(S/n * S/n) per step.

This is the TPU-native equivalent of the sequence/context parallelism the
rebuild is mandated to provide first-class (the reference has none —
SURVEY §2.3, §5 long-context row).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec

NEG_INF = -1e30


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
    causal: bool = False,
    scale: float | None = None,
) -> jax.Array:
    """Call INSIDE shard_map: q/k/v are local shards [B, H, S/n, D]."""
    from ..parallel.collectives import axis_size_compat

    n = axis_size_compat(axis_name)
    r = lax.axis_index(axis_name)
    b, h, chunk, d = q.shape
    scale = scale if scale is not None else 1.0 / (d**0.5)
    # Accumulate in at least f32; f64 inputs (the parity-proof harness)
    # keep f64 accumulation so the online softmax matches the dense
    # reference to the last ulp instead of quantizing through f32.
    acc_dtype = jnp.promote_types(q.dtype, jnp.float32)
    qf = q.astype(acc_dtype) * scale

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(t, carry):
        acc, m, l, kk, vv = carry
        # After t shifts, this device holds the block that originated on
        # device (r - t) mod n.
        k_origin = (r - t) % n
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", qf, kk.astype(acc_dtype),
            preferred_element_type=acc_dtype,
        )
        if causal:
            q_global = r * chunk + lax.broadcasted_iota(jnp.int32, (chunk, chunk), 0)
            k_global = k_origin * chunk + lax.broadcasted_iota(
                jnp.int32, (chunk, chunk), 1
            )
            s = jnp.where((k_global <= q_global)[None, None], s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vv.astype(acc_dtype),
            preferred_element_type=acc_dtype,
        )
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return acc_new, m_new, l_new, kk, vv

    acc0 = jnp.zeros((b, h, chunk, d), acc_dtype)
    m0 = jnp.full((b, h, chunk, 1), NEG_INF, acc_dtype)
    l0 = jnp.zeros((b, h, chunk, 1), acc_dtype)
    acc, m, l, _, _ = lax.fori_loop(0, n, step, (acc0, m0, l0, k, v))
    out = acc / jnp.where(l == 0.0, 1.0, l)
    return out.astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = False,
    axis_name: str = "sp",
) -> jax.Array:
    """Convenience wrapper: global [B,H,S,D] arrays, seq sharded over ``sp``."""
    spec = PartitionSpec(None, None, axis_name, None)
    from ..parallel.collectives import shard_map_compat

    f = shard_map_compat(
        functools.partial(ring_attention, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return f(q, k, v)
