"""Flash attention for TPU (Pallas) with an XLA reference fallback.

Blockwise online-softmax attention: each grid program owns one query tile
in VMEM and streams key/value tiles through it, maintaining running max and
denominator — the score matrix never materializes, so memory is O(S) and
the two matmuls per tile run back-to-back on the MXU.

Layout: [batch, heads, seq, head_dim]; grid is (batch*heads, q_tiles).
Tiles default to 128x128 (the MXU native tile).  Causal masking and a
static ``kv_len`` (for padded keys) fold into the tile mask via iota.

Dispatch policy (measured on TPU v5e, 2026-07): standalone, this kernel
beats XLA attention at BERT-base shapes (16.9 us vs 29.9 us per op at
B32/H12/S128/D64).  *Inside* a full encoder forward, however, the XLA
path wins at every shape tried (S=128: 6.1 vs 6.3 ms; B8/S512: 12.4 vs
17.0 ms; B2/S2048: 20.8 vs 35.6 ms per forward) because XLA fuses the
QKV projections, softmax, and context matmul without the layout
transposes the [B,H,S,D] kernel interface forces.  The model zoo
therefore keeps XLA attention; this kernel is the building block for
``ring_attention`` (sequence parallelism), where blockwise
online-softmax structure is required to overlap compute with the ICI
ring permute and XLA has no equivalent fusion.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1e30


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    kv_len: int | None = None,
    scale: float | None = None,
) -> jax.Array:
    """Pure-XLA oracle: [B,H,S,D] x [B,H,T,D] -> [B,H,S,D]."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d**0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    t = k.shape[2]
    if kv_len is not None:
        key_ok = jnp.arange(t) < kv_len
        s = jnp.where(key_ok[None, None, None, :], s, NEG_INF)
    if causal:
        qi = jnp.arange(q.shape[2])
        ki = jnp.arange(t)
        s = jnp.where(ki[None, None, None, :] <= qi[None, None, :, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool, kv_len: int, scale: float
):
    q = q_ref[0].astype(jnp.float32) * scale  # [BQ, D]
    bq = q.shape[0]
    total_k = k_ref.shape[1]
    nk = total_k // block_k
    qi0 = pl.program_id(1) * bq

    def body(j, carry):
        acc, m, l = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)  # [BQ, BK]

        k_idx = j * block_k + lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        mask = k_idx < kv_len
        if causal:
            q_idx = qi0 + lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            mask = mask & (k_idx <= q_idx)
        s = jnp.where(mask, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.dot(p, v_blk, preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((bq, q_ref.shape[2]), jnp.float32)
    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc, m, l = lax.fori_loop(0, nk, body, (acc0, m0, l0))
    # Fully-masked rows (l == 0) produce 0 output instead of NaN.
    out = acc / jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = out.astype(o_ref.dtype)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    kv_len: int | None = None,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Flash attention over [B,H,S,D]; pads S/T internally to tile multiples.

    ``kv_len`` masks trailing (padded) keys; defaults to the true key length.
    """
    b, h, s_q, d = q.shape
    t_k = k.shape[2]
    scale = scale if scale is not None else 1.0 / (d**0.5)
    kv_len = int(kv_len) if kv_len is not None else t_k

    block_q = min(block_q, _round_up(s_q, 8))
    block_k = min(block_k, _round_up(t_k, 8))
    s_pad = _round_up(s_q, block_q)
    t_pad = _round_up(t_k, block_k)
    qp = _pad_seq(q, s_pad)
    kp = _pad_seq(k, t_pad)
    vp = _pad_seq(v, t_pad)

    qf = qp.reshape(b * h, s_pad, d)
    kf = kp.reshape(b * h, t_pad, d)
    vf = vp.reshape(b * h, t_pad, d)

    grid = (b * h, s_pad // block_q)
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal, kv_len=kv_len, scale=scale
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, s_pad, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, t_pad, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, t_pad, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, s_pad, d)[:, :, :s_q, :]


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _pad_seq(x: jax.Array, target: int) -> jax.Array:
    pad = target - x.shape[2]
    if pad == 0:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
