"""Fused RMSNorm (Pallas) with XLA reference.

One VMEM pass: read the row tile, compute the f32 mean-square, rsqrt,
scale — instead of XLA's separate square/reduce/mul HLOs bouncing through
HBM for long rows.  Rows tile the grid; the feature dimension stays whole
(RMSNorm reduces over it).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def rmsnorm_reference(x: jax.Array, scale: jax.Array, eps: float = 1e-5) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32)).astype(x.dtype)


def _rmsnorm_kernel(x_ref, s_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    o_ref[:] = (x * jax.lax.rsqrt(var + eps) * s_ref[:].astype(jnp.float32)).astype(
        o_ref.dtype
    )


def rmsnorm(
    x: jax.Array,
    scale: jax.Array,
    eps: float = 1e-5,
    block_rows: int = 256,
    interpret: bool = False,
) -> jax.Array:
    """RMSNorm over the last axis of [..., rows, features]."""
    orig_shape = x.shape
    features = orig_shape[-1]
    rows = 1
    for dim in orig_shape[:-1]:
        rows *= dim
    x2 = x.reshape(rows, features)

    block_rows = min(block_rows, rows)
    padded = ((rows + block_rows - 1) // block_rows) * block_rows
    if padded != rows:
        x2 = jnp.pad(x2, ((0, padded - rows), (0, 0)))

    out = pl.pallas_call(
        functools.partial(_rmsnorm_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct((padded, features), x.dtype),
        grid=(padded // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, features), lambda i: (i, 0)),
            pl.BlockSpec((features,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, features), lambda i: (i, 0)),
        interpret=interpret,
    )(x2, scale)
    return out[:rows].reshape(orig_shape)
