"""Fused ragged-decode attention over an int8 KV window (Pallas, TPU).

One decode step's attention for one layer is, in XLA, ~15 small ops per
layer: two dequant-scale transposes, two einsums, mask add, self-term
concat, softmax, weighted-sum split.  Each reduce breaks fusion, and at
single-token shapes the per-op latency — not bandwidth — dominates
(round-4 profile: a weights-only decode step ran ~3x the int8 stream
floor with the GEMMs themselves measured at 76-87% of peak, leaving
~90 us/layer of elementwise soup).  This kernel collapses the block to
ONE program per (slot, kv-head): both MXU dots back-to-back over the
VMEM-resident K/V window, the int8 scales folded into score/probability
rows (exact — see below), the mask added in-register, and the current
token's self-term joined into the softmax without a concat.

Exactness of the scale folding (same algebra as ``models.llama._qmatmul``):
the cache scale is per (position, kv-head) over head_dim, so

  q . (k8[w] * ks[w]) == (q . k8[w]) * ks[w]          (score row scale)
  sum_w p[w] * (v8[w] * vs[w]) == (p * vs) @ v8        (prob row scale)

— int8 values convert exactly to f32, so the kernel is bit-compatible
with dequantize-then-attend up to f32 summation order.

Layouts (B slots, W window, NKV kv heads, G = heads/kv_head, D head_dim):

  q       [B, NKV, G, D]   current token's queries, grouped by kv head
  k8, v8  [B, NKV, W, D]   int8 cache window (head-major cache layout —
                           one (slot, head)'s window is contiguous)
  ks, vs  [B, NKV, W, 1]   f32 scales (the cache's window slice as-is;
                           the trailing 1 keeps the block tile-legal)
  k_self  [B, NKV, 1, D]   current token's K/V (exact, never quantized)
  v_self  [B, NKV, 1, D]
  mask    [B, 1, W]        f32 additive bias (0 keep / large negative
                           drop — any magnitude that underflows exp()
                           to 0 in f32; the production caller
                           ``decode_ragged`` passes -1e9),
                           STRICT: position w < lengths[b]
  out     [B, NKV, G, D]   f32

Reference behavior is pinned against the XLA path in
``tests/test_ops.py`` (interpret mode, so the parity runs on CPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

def _decode_attn_kernel_mxu(q_ref, k_ref, ks_ref, v_ref, vs_ref,
                            kself_ref, vself_ref, mask_ref, o_ref,
                            *, scale, bb):
    """MXU decode-attention program over ``bb`` slots of one kv head.

    ``bb == 1`` is the classic one-program-per-(slot, head) shape; the
    slot-batched variant unrolls ``bb`` slots back-to-back in VMEM so
    the grid (and its per-program overhead) shrinks by ``bb``.  Measured
    on a v5e at 1.35B geometry the distinction barely matters — both sit
    ~2.3x above XLA's batched-dot emitter because the cost is the f32
    [G,W]x[W,D] dots at G=1, not the grid (scripts/ab_attention.py;
    PERF.md round 5) — but the two spellings stay A/B-able from ONE
    kernel body so a numerics fix cannot diverge them."""
    for t in range(bb):
        q = q_ref[t, 0].astype(jnp.float32) * scale       # [G, D]
        k = k_ref[t, 0].astype(jnp.float32)               # [W, D]
        ks = ks_ref[t, 0, :, 0].astype(jnp.float32)       # [W]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                 # [G, W]
        s = s * ks[None, :] + mask_ref[t]

        k_self = kself_ref[t, 0].astype(jnp.float32)      # [1, D]
        s_self = jnp.sum(q * k_self, axis=-1, keepdims=True)

        m = jnp.maximum(jnp.max(s, axis=-1, keepdims=True), s_self)
        p = jnp.exp(s - m)
        p_self = jnp.exp(s_self - m)
        denom = jnp.sum(p, axis=-1, keepdims=True) + p_self

        vs = vs_ref[t, 0, :, 0].astype(jnp.float32)
        v = v_ref[t, 0].astype(jnp.float32)
        ctx = jax.lax.dot_general(
            p * vs[None, :], v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        v_self = vself_ref[t, 0].astype(jnp.float32)
        o_ref[t, 0] = (ctx + p_self * v_self) / denom


def _slot_block(b: int) -> int:
    """Largest power-of-two slot block (<=8) dividing ``b``: 8 bounds the
    f32-converted K/V VMEM footprint (~4 MiB at W=512, D=128) and the
    unroll size; smaller b falls back so any slot count lowers."""
    for bb in (8, 4, 2):
        if b % bb == 0:
            return bb
    return 1


def _mxu_decode_call(q, k8, ks, v8, vs, k_self, v_self, mask,
                     *, bb, interpret):
    """Shared pallas_call wrapper for the MXU kernel at block size ``bb``."""
    b, nkv, g, d = q.shape
    w = k8.shape[2]
    scale = 1.0 / (d ** 0.5)
    if not interpret and jax.devices()[0].platform == "cpu":
        # No Mosaic lowering on CPU: interpret transparently so the
        # integrated pallas path stays testable off-chip.
        interpret = True
    kernel = functools.partial(_decode_attn_kernel_mxu, scale=scale, bb=bb)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, nkv, g, d), jnp.float32),
        grid=(b // bb, nkv),
        in_specs=[
            pl.BlockSpec((bb, 1, g, d), lambda i, j: (i, j, 0, 0)),   # q
            pl.BlockSpec((bb, 1, w, d), lambda i, j: (i, j, 0, 0)),   # k8
            pl.BlockSpec((bb, 1, w, 1), lambda i, j: (i, j, 0, 0)),   # ks
            pl.BlockSpec((bb, 1, w, d), lambda i, j: (i, j, 0, 0)),   # v8
            pl.BlockSpec((bb, 1, w, 1), lambda i, j: (i, j, 0, 0)),   # vs
            pl.BlockSpec((bb, 1, 1, d), lambda i, j: (i, j, 0, 0)),   # k_self
            pl.BlockSpec((bb, 1, 1, d), lambda i, j: (i, j, 0, 0)),   # v_self
            pl.BlockSpec((bb, 1, w), lambda i, j: (i, 0, 0)),         # mask
        ],
        out_specs=pl.BlockSpec((bb, 1, g, d), lambda i, j: (i, j, 0, 0)),
        interpret=interpret,
    )(q, k8, ks, v8, vs, k_self, v_self, mask)


def decode_attention(
    q: jax.Array,
    k8: jax.Array,
    ks: jax.Array,
    v8: jax.Array,
    vs: jax.Array,
    k_self: jax.Array,
    v_self: jax.Array,
    mask: jax.Array,
    *,
    interpret: bool = False,
) -> jax.Array:
    """Fused int8-KV decode attention, one program per (slot, kv head);
    see module docstring for layouts."""
    return _mxu_decode_call(
        q, k8, ks, v8, vs, k_self, v_self, mask, bb=1, interpret=interpret)


def decode_attention_batched(
    q: jax.Array,
    k8: jax.Array,
    ks: jax.Array,
    v8: jax.Array,
    vs: jax.Array,
    k_self: jax.Array,
    v_self: jax.Array,
    mask: jax.Array,
    *,
    interpret: bool = False,
) -> jax.Array:
    """Fused int8-KV decode attention, ``_slot_block(b)`` slots per grid
    program (same contract and kernel body as :func:`decode_attention`)."""
    return _mxu_decode_call(
        q, k8, ks, v8, vs, k_self, v_self, mask,
        bb=_slot_block(q.shape[0]), interpret=interpret)


_LANE = 128  # VPU lane width: W is retiled as [W // _LANE, _LANE]


def _decode_attn_kernel_vpu(q_ref, k_ref, ks_ref, v_ref, vs_ref,
                            kself_ref, vself_ref, mask_ref, o_ref,
                            *, scale, bb, wg):
    """VPU formulation for G == 1 (num_heads == num_kv_heads) decode.

    Why not the MXU: with one query row per kv head the score/ctx dots
    are [1,W]x[W,D] matvecs, and the MXU's tiling floor (~512 cycles per
    pass regardless of M) makes attention cost ~0.5 us x slots x heads
    x 2 dots x layers — 24 ms/step at 1.35B/64 slots, ~10x the actual
    HBM traffic cost, capping decode bw_util at ~0.2 (measured: both
    XLA's batched dot emitter and the MXU pallas kernels sit at this
    floor, scripts/ab_attention.py).  Decode attention at G=1 is ~1
    FLOP/byte — bandwidth-bound — so the VPU's elementwise
    multiply+reduce does the EXACT work with no padding waste and can
    keep pace with the DMA stream.  No dot_general appears in this
    kernel: Mosaic lowers the multiply+reduce chains to vector ops,
    which is the point.

    Mosaic constraints shape the spelling: every intermediate stays
    >= 2-D with W retiled as [wg, 128] so softmax runs dense across
    lanes, and every reduction is a keepdims reduction over one axis at
    a time (scalar-form reductions of 1-D vectors fail to lower with
    "Not implemented: Offset change").  The scale/mask operands arrive
    pre-retiled from the wrapper."""
    for t in range(bb):
        q2 = q_ref[t, 0].astype(jnp.float32) * scale       # [1, D]
        d = q2.shape[1]
        k3 = k_ref[t, 0].astype(jnp.float32).reshape(wg, _LANE, d)
        s3 = jnp.sum(k3 * q2[None], axis=-1)               # [Wg, 128]
        s3 = s3 * ks_ref[t, 0].astype(jnp.float32) + mask_ref[t]

        kself2 = kself_ref[t, 0].astype(jnp.float32)       # [1, D]
        s_self = jnp.sum(q2 * kself2, axis=-1, keepdims=True)  # [1, 1]

        m = jnp.max(jnp.max(s3, axis=1, keepdims=True), axis=0, keepdims=True)
        m = jnp.maximum(m, s_self)                         # [1, 1]
        p3 = jnp.exp(s3 - m)                               # [Wg, 128]
        p_self = jnp.exp(s_self - m)                       # [1, 1]
        denom = jnp.sum(
            jnp.sum(p3, axis=1, keepdims=True), axis=0, keepdims=True
        ) + p_self                                         # [1, 1]

        pv3 = p3 * vs_ref[t, 0].astype(jnp.float32)        # [Wg, 128]
        v3 = v_ref[t, 0].astype(jnp.float32).reshape(wg, _LANE, d)
        acc = jnp.sum(pv3[:, :, None] * v3, axis=0)        # [128, D]
        ctx = jnp.sum(acc, axis=0, keepdims=True)          # [1, D]
        vself2 = vself_ref[t, 0].astype(jnp.float32)       # [1, D]
        o_ref[t, 0] = (ctx + p_self * vself2) / denom


def decode_attention_vpu(
    q: jax.Array,
    k8: jax.Array,
    ks: jax.Array,
    v8: jax.Array,
    vs: jax.Array,
    k_self: jax.Array,
    v_self: jax.Array,
    mask: jax.Array,
    *,
    interpret: bool = False,
) -> jax.Array:
    """Fused int8-KV decode attention on the VPU; requires G == 1 and
    W % 128 == 0 (serving windows are powers of two >= 128).

    Same contract as :func:`decode_attention` (see the kernel docstring
    for the roofline argument)."""
    b, nkv, g, d = q.shape
    if g != 1:
        raise ValueError(f"decode_attention_vpu requires G == 1, got {g}")
    w = k8.shape[2]
    if w % _LANE != 0:
        raise ValueError(
            f"decode_attention_vpu requires W % {_LANE} == 0, got {w}")
    wg = w // _LANE
    scale = 1.0 / (d ** 0.5)
    bb = _slot_block(b)
    if not interpret and jax.devices()[0].platform == "cpu":
        interpret = True
    # Retile the per-position vectors [.., W, 1] -> [.., Wg, 128] (and
    # the mask [B, 1, W] -> [B, Wg, 128]) on the XLA side: pure reshapes
    # of tiny arrays, giving the kernel lane-dense softmax layouts.
    ks_t = ks[..., 0].reshape(b, nkv, wg, _LANE)
    vs_t = vs[..., 0].reshape(b, nkv, wg, _LANE)
    mask_t = mask.reshape(b, wg, _LANE)
    kernel = functools.partial(
        _decode_attn_kernel_vpu, scale=scale, bb=bb, wg=wg)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, nkv, g, d), jnp.float32),
        grid=(b // bb, nkv),
        in_specs=[
            pl.BlockSpec((bb, 1, g, d), lambda i, j: (i, j, 0, 0)),    # q
            pl.BlockSpec((bb, 1, w, d), lambda i, j: (i, j, 0, 0)),    # k8
            pl.BlockSpec((bb, 1, wg, _LANE), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((bb, 1, w, d), lambda i, j: (i, j, 0, 0)),    # v8
            pl.BlockSpec((bb, 1, wg, _LANE), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((bb, 1, 1, d), lambda i, j: (i, j, 0, 0)),    # k_self
            pl.BlockSpec((bb, 1, 1, d), lambda i, j: (i, j, 0, 0)),    # v_self
            pl.BlockSpec((bb, wg, _LANE), lambda i, j: (i, 0, 0)),     # mask
        ],
        out_specs=pl.BlockSpec((bb, 1, g, d), lambda i, j: (i, j, 0, 0)),
        interpret=interpret,
    )(q, k8, ks_t, v8, vs_t, k_self, v_self, mask_t)


def decode_attention_reference(
    q, k8, ks, v8, vs, k_self, v_self, mask
) -> jax.Array:
    """Pure-XLA oracle with the identical contract (f32 everywhere)."""
    d = q.shape[-1]
    qf = q.astype(jnp.float32) / (d ** 0.5)
    s = jnp.einsum("bngd,bnwd->bngw", qf, k8.astype(jnp.float32))
    s = s * ks[..., 0][:, :, None, :] + mask[:, :, None, :]
    s_self = jnp.einsum(
        "bngd,bnsd->bngs", qf, k_self.astype(jnp.float32)
    )
    m = jnp.maximum(jnp.max(s, axis=-1, keepdims=True), s_self)
    p = jnp.exp(s - m)
    p_self = jnp.exp(s_self - m)
    denom = jnp.sum(p, axis=-1, keepdims=True) + p_self
    ctx = jnp.einsum("bngw,bnwd->bngd", p * vs[..., 0][:, :, None, :],
                     v8.astype(jnp.float32))
    ctx = ctx + p_self * v_self.astype(jnp.float32)
    return ctx / denom
