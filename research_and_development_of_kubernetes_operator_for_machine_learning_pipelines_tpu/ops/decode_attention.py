"""Fused ragged-decode attention over an int8 KV window (Pallas, TPU).

One decode step's attention for one layer is, in XLA, ~15 small ops per
layer: two dequant-scale transposes, two einsums, mask add, self-term
concat, softmax, weighted-sum split.  Each reduce breaks fusion, and at
single-token shapes the per-op latency — not bandwidth — dominates
(round-4 profile: a weights-only decode step ran ~3x the int8 stream
floor with the GEMMs themselves measured at 76-87% of peak, leaving
~90 us/layer of elementwise soup).  This kernel collapses the block to
ONE program per (slot, kv-head): both MXU dots back-to-back over the
VMEM-resident K/V window, the int8 scales folded into score/probability
rows (exact — see below), the mask added in-register, and the current
token's self-term joined into the softmax without a concat.

Exactness of the scale folding (same algebra as ``models.llama._qmatmul``):
the cache scale is per (position, kv-head) over head_dim, so

  q . (k8[w] * ks[w]) == (q . k8[w]) * ks[w]          (score row scale)
  sum_w p[w] * (v8[w] * vs[w]) == (p * vs) @ v8        (prob row scale)

— int8 values convert exactly to f32, so the kernel is bit-compatible
with dequantize-then-attend up to f32 summation order.

Layouts (B slots, W window, NKV kv heads, G = heads/kv_head, D head_dim):

  q       [B, NKV, G, D]   current token's queries, grouped by kv head
  k8, v8  [B, NKV, W, D]   int8 cache window (head-major cache layout —
                           one (slot, head)'s window is contiguous)
  ks, vs  [B, NKV, W, 1]   f32 scales (the cache's window slice as-is;
                           the trailing 1 keeps the block tile-legal)
  k_self  [B, NKV, 1, D]   current token's K/V (exact, never quantized)
  v_self  [B, NKV, 1, D]
  mask    [B, 1, W]        f32 additive bias (0 keep / large negative
                           drop — any magnitude that underflows exp()
                           to 0 in f32; the production caller
                           ``decode_ragged`` passes -1e9),
                           STRICT: position w < lengths[b]
  out     [B, NKV, G, D]   f32

Reference behavior is pinned against the XLA path in
``tests/test_ops.py`` (interpret mode, so the parity runs on CPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

def _decode_attn_kernel(q_ref, k_ref, ks_ref, v_ref, vs_ref,
                        kself_ref, vself_ref, mask_ref, o_ref, *, scale):
    q = q_ref[0, 0].astype(jnp.float32) * scale          # [G, D]
    k = k_ref[0, 0].astype(jnp.float32)                  # [W, D] (int8 exact)
    ks = ks_ref[0, 0, :, 0].astype(jnp.float32)          # [W]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                    # [G, W]
    s = s * ks[None, :] + mask_ref[0]

    k_self = kself_ref[0, 0].astype(jnp.float32)         # [1, D]
    s_self = jnp.sum(q * k_self, axis=-1, keepdims=True)  # [G, 1]

    m = jnp.maximum(jnp.max(s, axis=-1, keepdims=True), s_self)
    p = jnp.exp(s - m)                                   # [G, W]
    p_self = jnp.exp(s_self - m)                         # [G, 1]
    denom = jnp.sum(p, axis=-1, keepdims=True) + p_self

    vs = vs_ref[0, 0, :, 0].astype(jnp.float32)          # [W]
    v = v_ref[0, 0].astype(jnp.float32)                  # [W, D]
    ctx = jax.lax.dot_general(
        p * vs[None, :], v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                    # [G, D]
    v_self = vself_ref[0, 0].astype(jnp.float32)         # [1, D]
    ctx = (ctx + p_self * v_self) / denom
    o_ref[0, 0] = ctx


def decode_attention(
    q: jax.Array,
    k8: jax.Array,
    ks: jax.Array,
    v8: jax.Array,
    vs: jax.Array,
    k_self: jax.Array,
    v_self: jax.Array,
    mask: jax.Array,
    *,
    interpret: bool = False,
) -> jax.Array:
    """Fused int8-KV decode attention; see module docstring for layouts."""
    b, nkv, g, d = q.shape
    w = k8.shape[2]
    scale = 1.0 / (d ** 0.5)
    if not interpret and jax.devices()[0].platform == "cpu":
        # No Mosaic lowering on CPU: interpret transparently so the
        # integrated pallas path stays testable off-chip.
        interpret = True
    kernel = functools.partial(_decode_attn_kernel, scale=scale)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, nkv, g, d), jnp.float32),
        grid=(b, nkv),
        in_specs=[
            pl.BlockSpec((1, 1, g, d), lambda i, j: (i, j, 0, 0)),   # q
            pl.BlockSpec((1, 1, w, d), lambda i, j: (i, j, 0, 0)),   # k8
            pl.BlockSpec((1, 1, w, 1), lambda i, j: (i, j, 0, 0)),   # ks
            pl.BlockSpec((1, 1, w, d), lambda i, j: (i, j, 0, 0)),   # v8
            pl.BlockSpec((1, 1, w, 1), lambda i, j: (i, j, 0, 0)),   # vs
            pl.BlockSpec((1, 1, 1, d), lambda i, j: (i, j, 0, 0)),   # k_self
            pl.BlockSpec((1, 1, 1, d), lambda i, j: (i, j, 0, 0)),   # v_self
            pl.BlockSpec((1, 1, w), lambda i, j: (i, 0, 0)),         # mask
        ],
        out_specs=pl.BlockSpec((1, 1, g, d), lambda i, j: (i, j, 0, 0)),
        interpret=interpret,
    )(q, k8, ks, v8, vs, k_self, v_self, mask)


def decode_attention_reference(
    q, k8, ks, v8, vs, k_self, v_self, mask
) -> jax.Array:
    """Pure-XLA oracle with the identical contract (f32 everywhere)."""
    d = q.shape[-1]
    qf = q.astype(jnp.float32) / (d ** 0.5)
    s = jnp.einsum("bngd,bnwd->bngw", qf, k8.astype(jnp.float32))
    s = s * ks[..., 0][:, :, None, :] + mask[:, :, None, :]
    s_self = jnp.einsum(
        "bngd,bnsd->bngs", qf, k_self.astype(jnp.float32)
    )
    m = jnp.maximum(jnp.max(s, axis=-1, keepdims=True), s_self)
    p = jnp.exp(s - m)
    p_self = jnp.exp(s_self - m)
    denom = jnp.sum(p, axis=-1, keepdims=True) + p_self
    ctx = jnp.einsum("bngw,bnwd->bngd", p * vs[..., 0][:, :, None, :],
                     v8.astype(jnp.float32))
    ctx = ctx + p_self * v_self.astype(jnp.float32)
    return ctx / denom
