"""TPU kernels (Pallas) with XLA fallbacks.

The reference has no compute kernels at all (its data plane is Seldon's
generic container); these are the hot ops of the rebuild's first-party
data plane:

- ``flash_attention`` — blockwise online-softmax attention: O(S) memory
  instead of the O(S^2) score matrix, VMEM-resident tiles feeding the MXU.
- ``rmsnorm``          — fused normalize+scale in one VMEM pass.
- ``ring_attention``   — sequence parallelism over the ``sp`` mesh axis:
  KV blocks rotate around the ICI ring while each device keeps only its
  sequence shard (long-context serving).

Every op has a pure-XLA reference implementation used as fallback off-TPU
and as the numerical oracle in tests (kernels run in interpret mode on CPU).
"""

from .flash_attention import flash_attention, attention_reference
from .rmsnorm import rmsnorm, rmsnorm_reference
from .ring_attention import ring_attention, ring_attention_sharded

__all__ = [
    "flash_attention",
    "attention_reference",
    "rmsnorm",
    "rmsnorm_reference",
    "ring_attention",
    "ring_attention_sharded",
]
