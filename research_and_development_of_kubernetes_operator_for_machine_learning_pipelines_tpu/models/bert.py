"""BERT-base encoder (baseline config 3: batched sentence classification).

Pure-JAX, post-LayerNorm architecture matching HuggingFace ``BertModel``
semantics exactly (verified by the weight-copy parity test in
``tests/test_models_bert.py``).  Tensor-parallel ready: QKV/O and MLP
weights carry logical axes that TRANSFORMER_RULES maps onto the ``tp`` mesh
axis (Megatron column/row split); under ``jit`` with NamedSharding-placed
params XLA inserts the ICI all-reduces.

The reference serves BERT-class models through Seldon's generic CPU/GPU
``MLFLOW_SERVER`` (``mlflow_operator.py:198``); this module is the
TPU-native predict path behind ``backend: tpu``.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .common import dense, gelu, gelu_tanh, init_dense, layer_norm, take_embedding


def _dense(x, p):
    """Dense dispatch: f32/bf16 weights -> MXU bf16 matmul; int8 leaves
    (quantization.quantize_bert) -> true int8 MXU matmul (dense_q8)."""
    from .quantization import dense_q8, is_quantized

    if is_quantized(p["w"]):
        return dense_q8(x, p["w"], p.get("b"))
    return dense(x, p["w"], p["b"])


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    num_labels: int = 2  # classifier head; 0 disables
    # "gelu" = exact erf (HF/torch parity); "gelu_tanh" = tanh approx,
    # ~1.4x faster end-to-end on v5e (erf is unfused VPU work — see
    # common.gelu_tanh).  The int8 load path defaults to tanh: quantize
    # already opted into larger approximation than tanh-vs-erf.
    hidden_act: str = "gelu"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def base(cls, **kw) -> "BertConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "BertConfig":
        """Small config for tests/CI."""
        defaults = dict(
            vocab_size=512,
            hidden_size=64,
            num_layers=2,
            num_heads=4,
            intermediate_size=128,
            max_position_embeddings=128,
        )
        defaults.update(kw)
        return cls(**defaults)


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def _init_ln(h: int) -> dict:
    return {"scale": jnp.ones((h,)), "bias": jnp.zeros((h,))}


def init(key: jax.Array, cfg: BertConfig) -> dict:
    keys = iter(jax.random.split(key, 8 + 8 * cfg.num_layers))
    h, i = cfg.hidden_size, cfg.intermediate_size
    std = 0.02

    def normal(k, shape):
        return std * jax.random.normal(k, shape, jnp.float32)

    params: dict = {
        "embeddings": {
            "word": normal(next(keys), (cfg.vocab_size, h)),
            "position": normal(next(keys), (cfg.max_position_embeddings, h)),
            "token_type": normal(next(keys), (cfg.type_vocab_size, h)),
            "ln": _init_ln(h),
        },
        "layers": [],
        "pooler": init_dense(next(keys), h, h),
    }
    for _ in range(cfg.num_layers):
        layer = {
            "attn": {
                "q": init_dense(next(keys), h, h),
                "k": init_dense(next(keys), h, h),
                "v": init_dense(next(keys), h, h),
                "o": init_dense(next(keys), h, h),
                "ln": _init_ln(h),
            },
            "mlp": {
                "up": init_dense(next(keys), h, i),
                "down": init_dense(next(keys), i, h),
                "ln": _init_ln(h),
            },
        }
        params["layers"].append(layer)
    if cfg.num_labels:
        params["classifier"] = init_dense(next(keys), h, cfg.num_labels)
    return params


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _self_attention(p: dict, x: jax.Array, mask_bias: jax.Array, cfg: BertConfig):
    b, s, h = x.shape
    nh, hd = cfg.num_heads, cfg.head_dim

    q = _dense(x, p["q"]).reshape(b, s, nh, hd)
    k = _dense(x, p["k"]).reshape(b, s, nh, hd)
    v = _dense(x, p["v"]).reshape(b, s, nh, hd)

    scores = jnp.einsum(
        "bqnd,bknd->bnqk", q, k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.float32(hd))
    scores = scores + mask_bias  # (b, 1, 1, s) additive bias
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    ctx = jnp.einsum("bnqk,bknd->bqnd", probs, v).reshape(b, s, h)
    return _dense(ctx, p["o"])


def encode(
    params: dict,
    input_ids: jax.Array,
    attention_mask: jax.Array | None = None,
    token_type_ids: jax.Array | None = None,
    cfg: BertConfig = BertConfig(),
    dtype=jnp.float32,
) -> tuple[jax.Array, jax.Array]:
    """Return (sequence_output [B,S,H], pooled_output [B,H])."""
    b, s = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s), jnp.int32)
    if token_type_ids is None:
        token_type_ids = jnp.zeros((b, s), jnp.int32)

    emb = params["embeddings"]
    positions = jnp.arange(s)[None, :]
    x = (
        take_embedding(emb["word"], input_ids)
        + take_embedding(emb["position"], positions)
        + take_embedding(emb["token_type"], token_type_ids)
    ).astype(dtype)
    x = layer_norm(x, emb["ln"]["scale"], emb["ln"]["bias"], cfg.layer_norm_eps)

    # Additive attention bias in f32: 0 where attend, -1e9 where masked.
    mask_bias = (1.0 - attention_mask[:, None, None, :].astype(jnp.float32)) * -1e9

    act = gelu_tanh if cfg.hidden_act == "gelu_tanh" else gelu
    for layer in params["layers"]:
        a = _self_attention(layer["attn"], x, mask_bias, cfg)
        x = layer_norm(
            x + a,
            layer["attn"]["ln"]["scale"],
            layer["attn"]["ln"]["bias"],
            cfg.layer_norm_eps,
        )
        m = _dense(x, layer["mlp"]["up"])
        m = act(m)
        m = _dense(m, layer["mlp"]["down"])
        x = layer_norm(
            x + m,
            layer["mlp"]["ln"]["scale"],
            layer["mlp"]["ln"]["bias"],
            cfg.layer_norm_eps,
        )

    pooled = jnp.tanh(dense(x[:, 0], params["pooler"]["w"], params["pooler"]["b"]))
    return x, pooled


def classify(
    params: dict,
    input_ids: jax.Array,
    attention_mask: jax.Array | None = None,
    token_type_ids: jax.Array | None = None,
    cfg: BertConfig = BertConfig(),
    dtype=jnp.float32,
) -> jax.Array:
    """Sentence-classification logits [B, num_labels]."""
    _, pooled = encode(params, input_ids, attention_mask, token_type_ids, cfg, dtype)
    c = params["classifier"]
    return dense(pooled, c["w"], c["b"]).astype(jnp.float32)


# ---------------------------------------------------------------------------
# Sharding
# ---------------------------------------------------------------------------


def param_logical_axes(params: dict) -> dict:
    """Logical-axis pytree matching ``params`` (see parallel.sharding)."""

    def attn_axes():
        return {
            "q": {"w": ("embed", "heads"), "b": ("heads",)},
            "k": {"w": ("embed", "heads"), "b": ("heads",)},
            "v": {"w": ("embed", "heads"), "b": ("heads",)},
            "o": {"w": ("heads", "embed"), "b": None},
            "ln": {"scale": None, "bias": None},
        }

    def mlp_axes():
        return {
            "up": {"w": ("embed", "mlp"), "b": ("mlp",)},
            "down": {"w": ("mlp", "embed"), "b": None},
            "ln": {"scale": None, "bias": None},
        }

    axes: dict = {
        "embeddings": {
            "word": ("vocab", "embed"),
            "position": None,
            "token_type": None,
            "ln": {"scale": None, "bias": None},
        },
        "layers": [
            {"attn": attn_axes(), "mlp": mlp_axes()} for _ in params["layers"]
        ],
        "pooler": {"w": None, "b": None},
    }
    if "classifier" in params:
        axes["classifier"] = {"w": None, "b": None}
    return axes


# ---------------------------------------------------------------------------
# Torch weight import (parity tests / MLflow transformers flavor)
# ---------------------------------------------------------------------------


def from_torch(torch_model, cfg: BertConfig) -> dict:
    """Convert a HuggingFace ``BertModel`` (or ``BertForSequenceClassification``)
    state dict to this module's param tree."""
    sd = {k: v.detach().cpu().numpy() for k, v in torch_model.state_dict().items()}
    prefix = "bert." if any(k.startswith("bert.") for k in sd) else ""

    def t(name):
        return jnp.asarray(sd[prefix + name])

    def lin(name):
        return {"w": t(f"{name}.weight").T, "b": t(f"{name}.bias")}

    def ln(name):
        return {"scale": t(f"{name}.weight"), "bias": t(f"{name}.bias")}

    params = {
        "embeddings": {
            "word": t("embeddings.word_embeddings.weight"),
            "position": t("embeddings.position_embeddings.weight"),
            "token_type": t("embeddings.token_type_embeddings.weight"),
            "ln": ln("embeddings.LayerNorm"),
        },
        "layers": [],
        "pooler": lin("pooler.dense"),
    }
    for i in range(cfg.num_layers):
        base = f"encoder.layer.{i}"
        params["layers"].append(
            {
                "attn": {
                    "q": lin(f"{base}.attention.self.query"),
                    "k": lin(f"{base}.attention.self.key"),
                    "v": lin(f"{base}.attention.self.value"),
                    "o": lin(f"{base}.attention.output.dense"),
                    "ln": ln(f"{base}.attention.output.LayerNorm"),
                },
                "mlp": {
                    "up": lin(f"{base}.intermediate.dense"),
                    "down": lin(f"{base}.output.dense"),
                    "ln": ln(f"{base}.output.LayerNorm"),
                },
            }
        )
    if "classifier.weight" in sd:
        params["classifier"] = {
            "w": jnp.asarray(sd["classifier.weight"]).T,
            "b": jnp.asarray(sd["classifier.bias"]),
        }
    return params
