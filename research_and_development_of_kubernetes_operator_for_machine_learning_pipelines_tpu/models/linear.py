"""Linear / logistic-regression models (baseline config 0: sklearn iris).

The reference serves sklearn models via Seldon's ``MLFLOW_SERVER``
(``mlflow_operator.py:198``); here the fitted coefficients are lifted into a
jittable JAX predict function so even tiny tabular models ride the same
TPU/XLA path and metric surface as the big ones.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class LinearConfig:
    n_features: int
    n_classes: int = 1  # 1 => regression or binary-with-sigmoid
    kind: str = "logistic"  # "logistic" | "linear"


def init(key: jax.Array, cfg: LinearConfig) -> dict:
    k1, _ = jax.random.split(key)
    out = max(cfg.n_classes, 1)
    return {
        "coef": 0.01 * jax.random.normal(k1, (cfg.n_features, out), jnp.float32),
        "intercept": jnp.zeros((out,), jnp.float32),
    }


def decision_function(params: dict, x: jax.Array) -> jax.Array:
    return x @ params["coef"] + params["intercept"]


def predict_proba(params: dict, x: jax.Array) -> jax.Array:
    """Class probabilities; matches sklearn LogisticRegression semantics
    (sigmoid for binary stored as a single column, softmax for multinomial)."""
    z = decision_function(params, x)
    if z.shape[-1] == 1:
        p1 = jax.nn.sigmoid(z)
        return jnp.concatenate([1.0 - p1, p1], axis=-1)
    return jax.nn.softmax(z, axis=-1)


def predict(params: dict, x: jax.Array, cfg: LinearConfig) -> jax.Array:
    if cfg.kind == "linear":
        z = decision_function(params, x)
        return z[..., 0] if z.shape[-1] == 1 else z
    return jnp.argmax(predict_proba(params, x), axis=-1)


def from_sklearn(model) -> tuple[dict, LinearConfig]:
    """Convert a fitted sklearn LogisticRegression / LinearRegression."""
    coef = jnp.asarray(model.coef_, jnp.float32)
    if coef.ndim == 1:
        coef = coef[None, :]
    intercept = jnp.atleast_1d(jnp.asarray(model.intercept_, jnp.float32))
    kind = "logistic" if hasattr(model, "predict_proba") else "linear"
    params = {"coef": coef.T, "intercept": intercept}
    cfg = LinearConfig(
        n_features=params["coef"].shape[0],
        n_classes=params["coef"].shape[1],
        kind=kind,
    )
    return params, cfg
