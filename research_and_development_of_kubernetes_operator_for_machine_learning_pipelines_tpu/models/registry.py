"""Model-family registry: flavor name -> Predictor builder.

The server's loader resolves an MLflow artifact to a *flavor* (sklearn,
forest, bert, llama, resnet, pyfunc, ...) and asks this registry to build a
``Predictor`` — the one interface the data plane serves:

- ``predict``   — batched callable; a pure jittable JAX function for native
  flavors, a host-side Python callable for the pyfunc fallback tier;
- ``jittable``  — selects the engine path (jit+warmup vs host thread pool);
- ``example_input`` — builds a representative batch for warmup compilation
  so the first real request never pays the XLA compile (SURVEY §7 hard
  part 3, TPU cold-start).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np


@dataclass
class Predictor:
    name: str
    predict: Callable[..., Any]
    jittable: bool = True
    example_input: Callable[[int], Any] | None = None  # batch_size -> inputs
    metadata: dict = field(default_factory=dict)
    # Causal-LM handles ({"params", "cfg", "eos_id"?}) for flavors that
    # support autoregressive decoding: the server builds a continuous-
    # batching GenerationEngine from these and exposes /generate.
    causal_lm: dict | None = None
    # Declarative sequence bucketing (server/batching.apply_seq_pad):
    # collapses variable request lengths into power-of-two buckets so the
    # batcher can merge them and XLA compiles log-many shapes.  Only for
    # models whose padding is exact (masked attention, pooled outputs).
    seq_pad: dict | None = None


_BUILDERS: dict[str, Callable[..., Predictor]] = {}


def register(flavor: str):
    def deco(fn: Callable[..., Predictor]):
        _BUILDERS[flavor] = fn
        return fn

    return deco


def get_builder(flavor: str) -> Callable[..., Predictor]:
    try:
        return _BUILDERS[flavor]
    except KeyError:
        raise KeyError(
            f"unknown model flavor {flavor!r}; registered: {sorted(_BUILDERS)}"
        ) from None


def list_flavors() -> list[str]:
    return sorted(_BUILDERS)


# ---------------------------------------------------------------------------
# Built-in flavors
# ---------------------------------------------------------------------------


@register("sklearn-linear")
def _build_sklearn_linear(model: Any, **_kw) -> Predictor:
    from . import linear

    params, cfg = linear.from_sklearn(model)
    n_feat = cfg.n_features

    def predict(x):
        return linear.predict(params, x, cfg)

    return Predictor(
        name="sklearn-linear",
        predict=predict,
        jittable=True,
        example_input=lambda b: np.zeros((b, n_feat), np.float32),
        metadata={"n_features": n_feat, "n_classes": cfg.n_classes},
    )


@register("sklearn-forest")
def _build_sklearn_forest(model: Any, **_kw) -> Predictor:
    from . import tabular

    trees = tabular.from_sklearn_forest(model)
    n_feat = int(model.n_features_in_)
    predict, form = tabular.lower_forest(trees)

    return Predictor(
        name="sklearn-forest",
        predict=predict,
        jittable=True,
        example_input=lambda b: np.zeros((b, n_feat), np.float32),
        metadata={"n_trees": int(trees.feature.shape[0]), "eval_form": form},
    )


@register("xgboost")
def _build_xgboost(model: Any, **_kw) -> Predictor:
    """``model`` is a parsed xgboost JSON dict (or a live Booster).

    Fully TPU-native (baseline config 1): the forest is lowered to the
    MXU matmul form when it fits the budget (tabular.GemmForest; ~11x
    the gather traversal on v5e), else to the flattened gather program
    shared with sklearn forests.  The objective picks the output
    transform: sigmoid for ``binary:*``, softmax/argmax over per-class
    margins for ``multi:*``, identity for regression.  Matches xgboost's
    ``predict`` output shapes: probabilities [B, K] for softprob, class
    ids [B] for softmax.
    """
    from . import tabular

    if isinstance(model, (dict, str, bytes)):
        trees, objective = tabular.from_xgboost_json(model)
    else:
        trees, objective = tabular.from_xgboost(model)
    margins, form = tabular.lower_forest(trees)

    if objective.startswith("binary:"):
        def predict(x):
            import jax

            return jax.nn.sigmoid(margins(x))
    elif objective == "multi:softprob":
        def predict(x):
            import jax

            return jax.nn.softmax(margins(x), axis=-1)
    elif objective == "multi:softmax":
        def predict(x):
            import jax.numpy as jnp

            return jnp.argmax(margins(x), axis=-1).astype(jnp.float32)
    else:
        predict = margins

    n_feat = trees.n_features or int(trees.feature.max()) + 1
    return Predictor(
        name="xgboost",
        predict=predict,
        jittable=True,
        example_input=lambda b: np.zeros((b, n_feat), np.float32),
        metadata={
            "n_trees": int(trees.feature.shape[0]),
            "n_features": n_feat,
            "objective": objective,
            "n_classes": trees.n_groups,
            "eval_form": form,
        },
    )


@register("pyfunc")
def _build_pyfunc(model: Any, **_kw) -> Predictor:
    from .tabular import PyFuncPredictor

    wrapped = model if isinstance(model, PyFuncPredictor) else PyFuncPredictor(
        model.predict if hasattr(model, "predict") else model
    )
    return Predictor(name="pyfunc", predict=wrapped, jittable=False)


@register("bert-classifier")
def _build_bert(
    params: Any,
    cfg: Any = None,
    seq_len: int = 128,
    seq_buckets: bool = True,
    **_kw,
) -> Predictor:
    from . import bert

    cfg = cfg or bert.BertConfig.base()

    def predict(input_ids, attention_mask=None, token_type_ids=None):
        import jax.numpy as jnp

        return bert.classify(
            params,
            input_ids,
            attention_mask,
            token_type_ids,
            cfg=cfg,
            dtype=jnp.bfloat16,
        )

    def example(b):
        return {
            "input_ids": np.ones((b, seq_len), np.int32),
            "attention_mask": np.ones((b, seq_len), np.int32),
        }

    return Predictor(
        name="bert-classifier",
        predict=predict,
        jittable=True,
        example_input=example,
        metadata={
            "seq_len": seq_len,
            "num_labels": cfg.num_labels,
            "hidden_act": cfg.hidden_act,
        },
        # Padding is exact for classification: the attention mask (0 on
        # padded keys) removes them from every softmax, and the CLS
        # pooling position is unaffected.  A request without a mask gets
        # one synthesized BEFORE padding, or the padded ids would be
        # attended.
        # seq_buckets=False pins the model to fixed-length traffic (no
        # length ladder warmed or served) — for controlled benches and
        # pipelines that always send one length.
        seq_pad=None
        if not seq_buckets
        else {
            "axis": 1,
            "pad_values": {
                "input_ids": 0,
                "attention_mask": 0,
                "token_type_ids": 0,
            },
            "synthesize": {"attention_mask": 1},
            "min_bucket": 16,
            "max_len": cfg.max_position_embeddings,
        },
    )


@register("resnet-classifier")
def _build_resnet(params: Any, cfg: Any = None, image_size: int = 224, **_kw) -> Predictor:
    from . import resnet

    cfg = cfg or resnet.ResNetConfig.resnet50()

    def predict(images):
        return resnet.forward(params, images, cfg)

    return Predictor(
        name="resnet-classifier",
        predict=predict,
        jittable=True,
        example_input=lambda b: np.zeros((b, image_size, image_size, 3), np.float32),
        metadata={"image_size": image_size, "num_classes": cfg.num_classes},
    )


@register("llama-generate")
def _build_llama(
    params: Any,
    cfg: Any,
    max_new_tokens: int = 64,
    eos_id: int | None = None,
    **_kw,
) -> Predictor:
    from . import llama

    # The batch predict path pairs a fixed example prompt length with a
    # fixed generation budget; both must fit the KV-cache capacity.
    example_len = min(16, cfg.max_seq // 4)
    max_new_tokens = min(max_new_tokens, cfg.max_seq - example_len)

    def predict(prompt_ids):
        return llama.generate_greedy(params, prompt_ids, max_new_tokens, cfg)

    return Predictor(
        name="llama-generate",
        predict=predict,
        jittable=True,
        example_input=lambda b: np.ones((b, example_len), np.int32),
        metadata={"max_new_tokens": max_new_tokens, "max_seq": cfg.max_seq},
        causal_lm={"params": params, "cfg": cfg, "eos_id": eos_id},
    )
