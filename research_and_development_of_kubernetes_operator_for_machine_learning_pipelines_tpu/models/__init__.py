"""Model zoo backing the five baseline configs (BASELINE.json):

- ``linear``   — sklearn iris logistic-regression (config 0)
- ``tabular``  — gradient-boosted / generic pyfunc tabular models (config 1)
- ``resnet``   — ResNet-50 image classifier (config 2)
- ``bert``     — BERT-base encoder classifier, batched (config 3)
- ``llama``    — Llama-2 decoder, tensor-parallel over v5e-8 (config 4)

All models are pure-JAX functional: a ``Config`` dataclass, ``init(key, cfg)
-> params`` (nested dict of arrays), jittable ``apply``-style functions, a
``param_logical_axes(cfg)`` pytree for mesh sharding, and (where a torch
twin exists) a ``from_torch`` converter used by the parity tests.

The reference contains no model code at all — its data plane is Seldon's
generic ``MLFLOW_SERVER`` image (``mlflow_operator.py:198``); this zoo is
the first-party TPU replacement.
"""

from . import common

__all__ = ["common", "linear", "tabular", "resnet", "bert", "llama", "registry"]


def __getattr__(name):
    if name in __all__:
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
