"""Weight-only int8 quantization (symmetric, per-output-channel).

Why: autoregressive decode is HBM-bandwidth-bound — every generated token
re-reads every weight matrix, so at serving batch sizes the time-per-token
floor is ``bytes(weights) / HBM_bandwidth``, not FLOPs (the bench's BERT
prefill path is the opposite: compute-bound at ~55% MXU, see bench.py).
Storing the matmul weights as int8 halves the bytes read per token, which
halves the decode floor; the dequantize (int8 → bf16 multiply by a
per-channel scale) is elementwise work XLA fuses into the matmul's operand
read, so no bf16 copy of the weight ever lands in HBM.

Scheme: for a weight ``w [..., in, out]`` the scale is
``max|w| / 127`` reduced over the ``in`` axis (per output channel, per
stacked layer), kept at the same rank so sharding specs line up with the
original weight's logical axes.  Symmetric (no zero point): one fused
multiply on the read path, and LLM weight distributions are near-centered.

Quantized leaves are plain dicts ``{"q8": int8, "scale": f32}`` — ordinary
pytree nodes, so they travel through ``lax.scan``, ``jit`` donation, and
checkpointing unchanged.  ``models/llama.py`` consumes either form via its
``_mat`` helper; norms and the embedding table stay full-precision (the
embedding is a gather — only B rows are read per step — and norm vectors
are noise-sensitive and tiny).

Measured on a v5e chip (1.35B-param shape, B=8 slots, capacity 1024):
bf16 13.3 ms/step vs int8 11.5 ms/step — 1.16x.  The gap to the 2x byte
ratio is the KV cache: decode also streams the full static-capacity cache
(~1.6 GiB here) every step, which int8 weights don't shrink.  The speedup
grows with model size (7B: ~13.5 GB weights vs the same cache traffic);
enable per model via the CRD's ``spec.tpu.quantize: int8``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def quantize_tensor(w: jax.Array, axis: int = -2) -> dict[str, jax.Array]:
    """Symmetric int8 with the |max| reduced over ``axis`` (kept at rank).

    ``axis=-2`` (default) is per-output-channel for ``[..., in, out]``
    weights; the KV cache uses ``axis=-1`` (per position+head over
    head_dim).  ONE implementation of the scheme — epsilon, rounding, and
    clip live here only."""
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q8 = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return {"q8": q8, "scale": scale}


def dequantize_tensor(q: dict[str, jax.Array], dtype=jnp.bfloat16) -> jax.Array:
    # Multiply in f32 and round ONCE into the target dtype: casting the
    # scale to bf16 first would round twice (~2x the weight error) for the
    # same fused HBM traffic.
    return (q["q8"].astype(jnp.float32) * q["scale"]).astype(dtype)


def is_quantized(leaf: Any) -> bool:
    return isinstance(leaf, dict) and "q8" in leaf and "scale" in leaf


# Llama matmul weights worth quantizing: everything the decode step streams
# from HBM in full.  Norm vectors and the embedding gather stay as-is.
_LLAMA_LAYER_MATS = ("q", "k", "v", "o", "gate", "up", "down")


def quantize_llama(params: dict) -> dict:
    """Return a params tree with layer matmuls + lm_head as int8 leaves.

    Runs under jit so sharded inputs produce identically-sharded q8/scale
    outputs (the reduction over the ``in`` axis inserts a collective when
    that axis is sharded — correct per-channel scales on every shard).
    """

    @jax.jit
    def _q(params):
        out = dict(params)
        out["layers"] = dict(params["layers"])
        for name in _LLAMA_LAYER_MATS:
            out["layers"][name] = quantize_tensor(params["layers"][name])
        out["lm_head"] = quantize_tensor(params["lm_head"])
        return out

    return _q(params)


def dense_q8(x: jax.Array, qw: dict, b: jax.Array | None = None) -> jax.Array:
    """Dynamic-activation int8 matmul: ``x [..., in] @ q8 [in, out]``.

    Unlike the weight-only scheme above (a bandwidth lever for decode),
    this feeds the MXU actual int8 operands — on v5e the int8 systolic
    path has 2x the bf16 throughput, the lever for a COMPUTE-bound
    workload like BERT prefill.  Activations quantize per row (per
    token): symmetric, scale = max|x| / 127 over the contraction axis,
    computed on the fly — XLA fuses it into the matmul read (round-3
    ablation: the dynamic-quant GEMM ladder runs at 188 TFLOP/s, ~0 cost
    over pre-quantized operands; scripts/profile_bert_int8.py).  The
    int32 accumulator rescales by (a_scale x w_scale) in f32, so the
    only approximation is the two roundings to int8.  End to end the
    int8 path pairs with tanh-GELU (loader default under quantize: int8
    — see common.gelu_tanh) for ~1.4x over bf16-erf at b32/s128.
    """
    qa = quantize_tensor(x, axis=-1)  # per-row (per-token) scales
    x8, a_scale = qa["q8"], qa["scale"]
    y = jax.lax.dot_general(
        x8,
        qw["q8"],
        (((x8.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    # w scale was reduced over axis=-2 with keepdims -> shape [1, out].
    y32 = y.astype(jnp.float32) * a_scale * qw["scale"].reshape(-1)
    if b is not None:
        y32 = y32 + b.astype(jnp.float32)
    return y32.astype(x.dtype)


# BERT dense layers worth int8-ing: the six big matmuls per encoder layer.
# ~97% of classify FLOPs at b32/s128 live here (12*S*H^2 vs 2*S^2*H for the
# attention einsums); pooler/classifier/embeddings are noise-sensitive and
# a rounding error away from flipping a logit, for no measurable FLOPs.
_BERT_LAYER_MATS = (("attn", "q"), ("attn", "k"), ("attn", "v"), ("attn", "o"),
                    ("mlp", "up"), ("mlp", "down"))


def quantize_bert(params: dict) -> dict:
    """Params tree with each encoder layer's dense weights as int8 leaves.

    The per-dense dicts keep their ``b`` (bias) and gain ``{"q8","scale"}``
    in place of ``w``; ``models/bert.py``'s dense dispatch routes such
    layers through :func:`dense_q8`.
    """

    @jax.jit
    def _q(params):
        out = dict(params)
        layers = []
        for layer in params["layers"]:
            new_layer = {k: dict(v) for k, v in layer.items()}
            for group, name in _BERT_LAYER_MATS:
                d = dict(new_layer[group][name])
                d["w"] = quantize_tensor(d["w"])
                new_layer[group][name] = d
            layers.append(new_layer)
        out["layers"] = layers
        return out

    return _q(params)


def quantized_bytes(params: Any) -> int:
    """Total parameter bytes as stored (int8 leaves count 1 byte/elem)."""
    total = 0
    for leaf in jax.tree.leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total
