"""Weight-only int8 quantization (symmetric, per-output-channel).

Why: autoregressive decode is HBM-bandwidth-bound — every generated token
re-reads every weight matrix, so at serving batch sizes the time-per-token
floor is ``bytes(weights) / HBM_bandwidth``, not FLOPs (the bench's BERT
prefill path is the opposite: compute-bound at ~55% MXU, see bench.py).
Storing the matmul weights as int8 halves the bytes read per token, which
halves the decode floor; the dequantize (int8 → bf16 multiply by a
per-channel scale) is elementwise work XLA fuses into the matmul's operand
read, so no bf16 copy of the weight ever lands in HBM.

Scheme: for a weight ``w [..., in, out]`` the scale is
``max|w| / 127`` reduced over the ``in`` axis (per output channel, per
stacked layer), kept at the same rank so sharding specs line up with the
original weight's logical axes.  Symmetric (no zero point): one fused
multiply on the read path, and LLM weight distributions are near-centered.

Quantized leaves are plain dicts ``{"q8": int8, "scale": f32}`` — ordinary
pytree nodes, so they travel through ``lax.scan``, ``jit`` donation, and
checkpointing unchanged.  ``models/llama.py`` consumes either form via its
``_mat`` helper; norms and the embedding table stay full-precision (the
embedding is a gather — only B rows are read per step — and norm vectors
are noise-sensitive and tiny).

Measured on a v5e chip (1.35B-param shape, B=8 slots, capacity 1024):
bf16 13.3 ms/step vs int8 11.5 ms/step — 1.16x.  The gap to the 2x byte
ratio is the KV cache: decode also streams the full static-capacity cache
(~1.6 GiB here) every step, which int8 weights don't shrink.  The speedup
grows with model size (7B: ~13.5 GB weights vs the same cache traffic);
enable per model via the CRD's ``spec.tpu.quantize: int8``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def quantize_tensor(w: jax.Array, axis: int = -2) -> dict[str, jax.Array]:
    """Symmetric int8 with the |max| reduced over ``axis`` (kept at rank).

    ``axis=-2`` (default) is per-output-channel for ``[..., in, out]``
    weights; the KV cache uses ``axis=-1`` (per position+head over
    head_dim).  ONE implementation of the scheme — epsilon, rounding, and
    clip live here only."""
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q8 = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return {"q8": q8, "scale": scale}


def dequantize_tensor(q: dict[str, jax.Array], dtype=jnp.bfloat16) -> jax.Array:
    # Multiply in f32 and round ONCE into the target dtype: casting the
    # scale to bf16 first would round twice (~2x the weight error) for the
    # same fused HBM traffic.
    return (q["q8"].astype(jnp.float32) * q["scale"]).astype(dtype)


def is_quantized(leaf: Any) -> bool:
    return isinstance(leaf, dict) and "q8" in leaf and "scale" in leaf


# Llama matmul weights worth quantizing: everything the decode step streams
# from HBM in full.  Norm vectors and the embedding gather stay as-is.
_LLAMA_LAYER_MATS = ("q", "k", "v", "o", "gate", "up", "down")


def quantize_llama(params: dict) -> dict:
    """Return a params tree with layer matmuls + lm_head as int8 leaves.

    Runs under jit so sharded inputs produce identically-sharded q8/scale
    outputs (the reduction over the ``in`` axis inserts a collective when
    that axis is sharded — correct per-channel scales on every shard).
    """

    @jax.jit
    def _q(params):
        out = dict(params)
        out["layers"] = dict(params["layers"])
        for name in _LLAMA_LAYER_MATS:
            out["layers"][name] = quantize_tensor(params["layers"][name])
        out["lm_head"] = quantize_tensor(params["lm_head"])
        return out

    return _q(params)


def quantized_bytes(params: Any) -> int:
    """Total parameter bytes as stored (int8 leaves count 1 byte/elem)."""
    total = 0
    for leaf in jax.tree.leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total
