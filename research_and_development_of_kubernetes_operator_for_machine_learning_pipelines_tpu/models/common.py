"""Shared building blocks for the pure-JAX model zoo.

Conventions:

- params are nested dicts of ``jnp.ndarray``; layer stacks may carry a
  leading ``layers`` axis consumed by ``lax.scan``.
- every initializer takes and splits an explicit PRNG key;
- compute dtype is a parameter (bfloat16 on TPU to hit the MXU's native
  tile; params may be kept in float32 and cast at use);
- matmuls accumulate in float32 via ``preferred_element_type`` so bf16
  activations do not lose the accumulation precision the MXU provides.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def dense(x: jax.Array, w: jax.Array, b: jax.Array | None = None) -> jax.Array:
    """x @ w (+ b), accumulating in f32 on the MXU regardless of input dtype."""
    y = jnp.matmul(x, w.astype(x.dtype), preferred_element_type=jnp.float32)
    if b is not None:
        y = y + b.astype(jnp.float32)
    return y.astype(x.dtype)


def init_dense(key, d_in: int, d_out: int, dtype=jnp.float32) -> dict[str, jax.Array]:
    scale = 1.0 / jnp.sqrt(d_in)
    w = jax.random.uniform(key, (d_in, d_out), dtype, -scale, scale)
    return {"w": w, "b": jnp.zeros((d_out,), dtype)}


def layer_norm(
    x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float = 1e-12
) -> jax.Array:
    """LayerNorm in f32 (mean/var of bf16 activations overflow/underflow
    easily; normalize in f32, cast back)."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)


def rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-5) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)).astype(x.dtype)


def gelu(x: jax.Array) -> jax.Array:
    """Exact (erf) GELU — matches torch's default, unlike jax.nn.gelu's
    tanh approximation default."""
    return jax.nn.gelu(x, approximate=False)


def gelu_tanh(x: jax.Array) -> jax.Array:
    """Tanh-approximate GELU (max abs error ~1e-3 vs erf, comparable to
    bf16 rounding).  On v5e the erf polynomial is VPU work XLA does not
    fuse into the matmul epilogue — measured ~1.8 ms of a 6.8 ms int8
    BERT-base b32/s128 batch — while the tanh form fuses to ~zero cost;
    the int8 serving path selects this via ``BertConfig.hidden_act``."""
    return jax.nn.gelu(x, approximate=True)


def take_embedding(table: jax.Array, ids: jax.Array, dtype=None) -> jax.Array:
    out = jnp.take(table, ids, axis=0)
    return out.astype(dtype) if dtype is not None else out


def count_params(params: Any) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


def cast_floats(tree: Any, dtype) -> Any:
    """Cast floating-point leaves to ``dtype`` (ints/bools untouched)."""

    def _cast(x):
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(_cast, tree)


def stack_layers(layer_params: list[dict]) -> dict:
    """Stack per-layer param dicts along a new leading axis for lax.scan."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *layer_params)
