"""Tensor-parallel partition rules for the llama serving stack.

One rule table (regex path -> :class:`~jax.sharding.PartitionSpec`, the
SNIPPETS [2]/[3] shape) maps everything the generation engine holds on
device onto a ``{"dp": 1, "tp": N}`` mesh:

- the llama param tree — Megatron column/row splits: q/k/v/gate/up shard
  their OUTPUT axis, o/down their INPUT axis, embed/lm_head the vocab
  axis; norms replicate.  The int8 layout's ``q8`` planes shard exactly
  like the bf16 matrices they quantize; ``scale`` planes shard on their
  OUTPUT axis only (the reduced axis is size 1 — q/k/v/gate/up scales
  follow their weights, o/down scales replicate);
- the :class:`~.llama.RaggedKVCache` (and its int8kv variant) — the
  ``kv_heads`` axis, so each chip holds its heads' K/V window and the
  decode attention einsums never cross chips;
- the per-sequence prefill scratch :class:`~.llama.KVCache` — same
  heads split, position-major layout;
- sampling state (tokens, PRNG keys, temps/topk/topp, lengths, masks) —
  replicated, so host reads and the on-device sampling chain see the
  same values on every chip.

XLA inserts the collectives: one all-reduce after the o and down
projections per layer (the Megatron pair), one all-gather where a
replicated output (sampled tokens, logits read-backs) consumes the
vocab-sharded lm_head product.  Nothing here gathers the cache — K/V
commits scatter into the sharded buffers and stay resident.

``build_serving_mesh`` builds the mesh over a PREFIX of the visible
devices (``jax.devices()[:n]``), not all of them: the 8-device CPU test
environment runs tp in {1, 2, 4} ladders side by side, and a production
slice where the mesh consumes every chip is the n == len(devices)
special case.
"""

from __future__ import annotations

import math
from typing import Any, Mapping

from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..parallel import AXIS_TENSOR, build_mesh, match_partition_rules

P = PartitionSpec
TP = AXIS_TENSOR

# Regex path -> PartitionSpec, first match wins (rule ORDER is load-
# bearing: the quantized scale/q8 rules sit above the bare-matrix rules
# they would otherwise shadow).  Matched by re.search against "/"-joined
# tree paths, e.g. "layers/q/q8".
LLAMA_PARTITION_RULES: tuple[tuple[str, PartitionSpec], ...] = (
    # int8 weight layout: q8 shards like its source matrix; scale is
    # [..., 1, out] so only output-axis-sharded matrices shard it.
    (r"layers/(q|k|v|gate|up)/q8$", P(None, None, TP)),
    (r"layers/(q|k|v|gate|up)/scale$", P(None, None, TP)),
    (r"layers/(o|down)/q8$", P(None, TP, None)),
    (r"layers/(o|down)/scale$", P()),
    (r"lm_head/q8$", P(None, TP)),
    (r"lm_head/scale$", P(None, TP)),
    # bf16/f32 weight matrices (Megatron column/row split).
    (r"layers/(q|k|v|gate|up)$", P(None, None, TP)),
    (r"layers/(o|down)$", P(None, TP, None)),
    (r"embed$", P(TP, None)),
    (r"lm_head$", P(None, TP)),
    # Norms replicate (tiny, consumed by every chip's residual stream).
    (r"(attn_norm|mlp_norm|final_norm)$", P()),
)

# Engine device state outside the param tree.  The ragged cache is
# head-major [L, B, NKV, T, D]; the prefill scratch is position-major
# [L, B, T, NKV, D]; the int8kv scale planes share their buffer's rank.
RAGGED_KV_SPEC = P(None, None, TP, None, None)
SEQ_KV_SPEC = P(None, None, None, TP, None)
REPLICATED = P()


def tp_degree(mesh_shape: Mapping[str, int] | None) -> int:
    """The ``tp`` axis size of a meshShape (1 when absent/empty)."""
    if not mesh_shape:
        return 1
    return int(mesh_shape.get(AXIS_TENSOR, 1))


def mesh_device_count(mesh_shape: Mapping[str, int] | None) -> int:
    n = 1
    for v in (mesh_shape or {}).values():
        n *= int(v)
    return n


def build_serving_mesh(mesh_shape: Mapping[str, int]) -> Mesh:
    """Mesh over the first ``prod(mesh_shape)`` visible devices.

    A prefix, not the full set: parity tests run tp in {1, 2, 4} on one
    8-device CPU process, and on a real slice the CRD's reconcile-time
    ``meshShape x tpuTopology`` check already pins prod == chip count.
    """
    import jax

    n = mesh_device_count(mesh_shape)
    devices = jax.devices()
    if n > len(devices):
        raise ValueError(
            f"meshShape {dict(mesh_shape)} needs {n} devices, "
            f"have {len(devices)}"
        )
    return build_mesh(mesh_shape, devices[:n])


def llama_param_specs(params: Any) -> Any:
    """PartitionSpec pytree for a llama param tree (bf16 or int8)."""
    return match_partition_rules(LLAMA_PARTITION_RULES, params)


def llama_param_shardings(params: Any, mesh: Mesh) -> Any:
    import jax

    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        llama_param_specs(params),
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )


def shard_llama_params(params: Any, mesh: Mesh) -> Any:
    """Device-put a llama param tree sharded per the rule table."""
    import jax

    return jax.tree.map(
        jax.device_put, params, llama_param_shardings(params, mesh)
    )


def validate_llama_mesh(cfg, mesh_shape: Mapping[str, int] | None) -> None:
    """Reject a meshShape the llama geometry cannot shard — typed, with
    the knob named, instead of the opaque XLA shape error the first
    warmup dispatch would otherwise raise (see
    ``utils.config.validate_mesh_for_model``, which this wraps with the
    model's numbers filled in)."""
    from ..utils.config import validate_mesh_for_model

    validate_mesh_for_model(
        mesh_shape,
        num_kv_heads=cfg.num_kv_heads,
        num_heads=cfg.num_heads,
        intermediate_size=cfg.intermediate_size,
        vocab_size=cfg.vocab_size,
    )


def engine_state_shardings(mesh: Mesh, kv_quant: bool):
    """The generation engine's device-state shardings on ``mesh``:
    ``(replicated, ragged_kv, seq_kv)`` where the kv entries mirror the
    engine's cache repr — a bare NamedSharding for the bf16 cache, a
    ``(values, scales)`` pair under int8kv."""
    rep = NamedSharding(mesh, REPLICATED)
    ragged = NamedSharding(mesh, RAGGED_KV_SPEC)
    seq = NamedSharding(mesh, SEQ_KV_SPEC)
    if kv_quant:
        return rep, (ragged, ragged), seq
    return rep, ragged, seq


def shard_bytes(leaf) -> int:
    """Bytes ONE device holds of ``leaf`` (the per-chip HBM ledger's
    exact term — replicated leaves count whole, sharded leaves their
    shard)."""
    shape = leaf.sharding.shard_shape(leaf.shape)
    return math.prod(shape) * leaf.dtype.itemsize
