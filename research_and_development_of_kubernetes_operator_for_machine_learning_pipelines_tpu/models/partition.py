"""Tensor-parallel partition rules for the llama serving stack.

One rule table (regex path -> :class:`~jax.sharding.PartitionSpec`, the
SNIPPETS [2]/[3] shape) maps everything the generation engine holds on
device onto a ``{"dp": 1, "tp": N}`` mesh:

- the llama param tree — Megatron column/row splits: q/k/v/gate/up shard
  their OUTPUT axis, o/down their INPUT axis, embed/lm_head the vocab
  axis; norms replicate.  The int8 layout's ``q8`` planes shard exactly
  like the bf16 matrices they quantize; ``scale`` planes shard on their
  OUTPUT axis only (the reduced axis is size 1 — q/k/v/gate/up scales
  follow their weights, o/down scales replicate);
- the :class:`~.llama.RaggedKVCache` (and its int8kv variant) — the
  ``kv_heads`` axis, so each chip holds its heads' K/V window and the
  decode attention einsums never cross chips;
- the per-sequence prefill scratch :class:`~.llama.KVCache` — same
  heads split, position-major layout;
- sampling state (tokens, PRNG keys, temps/topk/topp, lengths, masks) —
  replicated, so host reads and the on-device sampling chain see the
  same values on every chip.

XLA inserts the collectives: one all-reduce after the o and down
projections per layer (the Megatron pair), one all-gather where a
replicated output (sampled tokens, logits read-backs) consumes the
vocab-sharded lm_head product.  Nothing here gathers the cache — K/V
commits scatter into the sharded buffers and stay resident.

``build_serving_mesh`` builds the mesh over a PREFIX of the visible
devices (``jax.devices()[:n]``), not all of them: the 8-device CPU test
environment runs tp in {1, 2, 4} ladders side by side, and a production
slice where the mesh consumes every chip is the n == len(devices)
special case.
"""

from __future__ import annotations

import math
from typing import Any, Mapping

from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..parallel import (
    AXIS_DATA,
    AXIS_SEQ,
    AXIS_TENSOR,
    build_mesh,
    match_partition_rules,
)

P = PartitionSpec
TP = AXIS_TENSOR
DP = AXIS_DATA
SP = AXIS_SEQ

# Regex path -> PartitionSpec, first match wins (rule ORDER is load-
# bearing: the quantized scale/q8 rules sit above the bare-matrix rules
# they would otherwise shadow).  Matched by re.search against "/"-joined
# tree paths, e.g. "layers/q/q8".
LLAMA_PARTITION_RULES: tuple[tuple[str, PartitionSpec], ...] = (
    # int8 weight layout: q8 shards like its source matrix; scale is
    # [..., 1, out] so only output-axis-sharded matrices shard it.
    (r"layers/(q|k|v|gate|up)/q8$", P(None, None, TP)),
    (r"layers/(q|k|v|gate|up)/scale$", P(None, None, TP)),
    (r"layers/(o|down)/q8$", P(None, TP, None)),
    (r"layers/(o|down)/scale$", P()),
    (r"lm_head/q8$", P(None, TP)),
    (r"lm_head/scale$", P(None, TP)),
    # bf16/f32 weight matrices (Megatron column/row split).
    (r"layers/(q|k|v|gate|up)$", P(None, None, TP)),
    (r"layers/(o|down)$", P(None, TP, None)),
    (r"embed$", P(TP, None)),
    (r"lm_head$", P(None, TP)),
    # Norms replicate (tiny, consumed by every chip's residual stream).
    (r"(attn_norm|mlp_norm|final_norm)$", P()),
)

# Engine device state outside the param tree.  The ragged cache is
# head-major [L, B, NKV, T, D]; the prefill scratch is position-major
# [L, B, T, NKV, D]; the int8kv scale planes share their buffer's rank.
# Under dp > 1 the ragged cache ALSO shards its row (batch) axis — see
# ``ragged_kv_spec`` — so each dp shard holds B/dp cache rows and the
# decode forward partitions on batch with replicated weights.
RAGGED_KV_SPEC = P(None, None, TP, None, None)
RAGGED_KV_SPEC_DP = P(None, DP, TP, None, None)
SEQ_KV_SPEC = P(None, None, None, TP, None)
REPLICATED = P()


def ragged_kv_spec(dp: int) -> PartitionSpec:
    """The ragged cache's PartitionSpec: heads on tp always; the row
    (batch) axis joins dp only when that axis is real — ``dp <= 1``
    keeps the PR 15 spec object byte-for-byte (the ``{dp: 1}`` pin)."""
    return RAGGED_KV_SPEC_DP if int(dp) > 1 else RAGGED_KV_SPEC


def tp_degree(mesh_shape: Mapping[str, int] | None) -> int:
    """The ``tp`` axis size of a meshShape (1 when absent/empty)."""
    if not mesh_shape:
        return 1
    return int(mesh_shape.get(AXIS_TENSOR, 1))


def dp_degree(mesh_shape: Mapping[str, int] | None) -> int:
    """The ``dp`` axis size of a meshShape (1 when absent/empty)."""
    if not mesh_shape:
        return 1
    return int(mesh_shape.get(AXIS_DATA, 1))


def sp_degree(mesh_shape: Mapping[str, int] | None) -> int:
    """The ``sp`` axis size of a meshShape (1 when absent/empty)."""
    if not mesh_shape:
        return 1
    return int(mesh_shape.get(AXIS_SEQ, 1))


def mesh_device_count(mesh_shape: Mapping[str, int] | None) -> int:
    n = 1
    for v in (mesh_shape or {}).values():
        n *= int(v)
    return n


def build_serving_mesh(mesh_shape: Mapping[str, int]) -> Mesh:
    """Mesh over the first ``prod(mesh_shape)`` visible devices.

    A prefix, not the full set: parity tests run tp in {1, 2, 4} on one
    8-device CPU process, and on a real slice the CRD's reconcile-time
    ``meshShape x tpuTopology`` check already pins prod == chip count.
    """
    import jax

    n = mesh_device_count(mesh_shape)
    devices = jax.devices()
    if n > len(devices):
        raise ValueError(
            f"meshShape {dict(mesh_shape)} needs {n} devices, "
            f"have {len(devices)}"
        )
    return build_mesh(mesh_shape, devices[:n])


def llama_param_specs(params: Any) -> Any:
    """PartitionSpec pytree for a llama param tree (bf16 or int8)."""
    return match_partition_rules(LLAMA_PARTITION_RULES, params)


def _spec_on_mesh(spec: PartitionSpec, mesh: Mesh) -> PartitionSpec:
    """Drop axis names the mesh doesn't carry (NamedSharding rejects
    them): an ``{sp: N}``-only mesh has no ``tp`` axis, so the rule
    table's tp entries degrade to replication there, exactly as a
    size-1 tp axis would."""
    names = set(mesh.axis_names)

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in names)
            return kept if kept else None
        return entry if entry in names else None

    return PartitionSpec(*(keep(e) for e in spec))


def llama_param_shardings(params: Any, mesh: Mesh) -> Any:
    import jax

    return jax.tree.map(
        lambda spec: NamedSharding(mesh, _spec_on_mesh(spec, mesh)),
        llama_param_specs(params),
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )


def shard_llama_params(params: Any, mesh: Mesh) -> Any:
    """Device-put a llama param tree sharded per the rule table."""
    import jax

    return jax.tree.map(
        jax.device_put, params, llama_param_shardings(params, mesh)
    )


def validate_llama_mesh(cfg, mesh_shape: Mapping[str, int] | None) -> None:
    """Reject a meshShape the llama geometry cannot shard — typed, with
    the knob named, instead of the opaque XLA shape error the first
    warmup dispatch would otherwise raise (see
    ``utils.config.validate_mesh_for_model``, which this wraps with the
    model's numbers filled in)."""
    from ..utils.config import validate_mesh_for_model

    validate_mesh_for_model(
        mesh_shape,
        num_kv_heads=cfg.num_kv_heads,
        num_heads=cfg.num_heads,
        intermediate_size=cfg.intermediate_size,
        vocab_size=cfg.vocab_size,
    )


def engine_state_shardings(mesh: Mesh, kv_quant: bool):
    """The generation engine's device-state shardings on ``mesh``:
    ``(replicated, ragged_kv, seq_kv)`` where the kv entries mirror the
    engine's cache repr — a bare NamedSharding for the bf16 cache, a
    ``(values, scales)`` pair under int8kv.  When the mesh carries a
    real ``dp`` axis the ragged cache's row axis shards over it (each
    dp shard holds B/dp rows; sampling state and token read-backs stay
    replicated so host slot truth is mesh-shape-independent)."""
    dp = int(dict(mesh.shape).get(DP, 1))
    rep = NamedSharding(mesh, REPLICATED)
    ragged = NamedSharding(mesh, _spec_on_mesh(ragged_kv_spec(dp), mesh))
    seq = NamedSharding(mesh, _spec_on_mesh(SEQ_KV_SPEC, mesh))
    if kv_quant:
        return rep, (ragged, ragged), seq
    return rep, ragged, seq


def shard_bytes(leaf) -> int:
    """Bytes ONE device holds of ``leaf`` (the per-chip HBM ledger's
    exact term — replicated leaves count whole, sharded leaves their
    shard)."""
    shape = leaf.sharding.shard_shape(leaf.shape)
    return math.prod(shape) * leaf.dtype.itemsize
