"""Llama-2 decoder (baseline config 4: text-gen, TP-sharded across v5e-8).

Pure-JAX implementation matching HuggingFace ``LlamaForCausalLM`` semantics
(weight-copy parity test in ``tests/test_models_llama.py``): pre-RMSNorm,
rotate-half RoPE, grouped-query attention, SwiGLU MLP, untied LM head.

TPU-first design decisions:

- layer params are STACKED on a leading axis and consumed by ``lax.scan`` —
  one compiled block instead of ``n_layers`` unrolled copies, keeping
  compile times flat as depth grows;
- a fixed-capacity KV cache (``max_seq``) with a dynamic write index keeps
  every shape static under ``jit`` (no data-dependent shapes, SURVEY §7);
- logical axes put heads/kv_heads/mlp/vocab on the ``tp`` mesh axis
  (Megatron split) so a v5e-8 mesh shards Llama-2-7B ~0.9 GiB/chip in bf16;
  XLA inserts the ICI all-reduces at the o/down projections.

The reference has no model code (SURVEY §2.3); this is the rebuild's
long-context/distributed first-class citizen.
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .common import rms_norm
from .quantization import dequantize_tensor, is_quantized


# Decode attention dispatch: "xla" (einsum chain), "pallas" (fused
# ops/decode_attention kernels), "pallas_single" (one program per
# (slot, head)), or "auto".  "auto" resolves to XLA: measured on a v5e
# chip at 1.35B geometry (scripts/ab_attention.py, in-process A/B), the
# einsum chain beats both pallas kernels at every slot count — 2.80 vs
# 6.99 ms/step at 8 slots, 14.42 vs 34.2 (batched) / 36.4 (per-slot) at
# 32.  The reason is structural, not kernel overhead: with
# num_heads == num_kv_heads (llama-1.35B/7B), G = 1 and each head's
# score/ctx dot is a 1-row matvec, so the MXU's 8-sublane tiling floor
# (~512 cycles per [1,W]x[W,D] pass) dominates — a cost XLA's batched
# dot emitter already sits at, which the extra pallas dispatch and
# VMEM conversions only add to.  The kernels stay selectable for A/B;
# grouped-query geometry does NOT flip the result — measured at G=8
# (nh=16/nkv=2), XLA still wins: 1.99 vs 2.22 ms/step at 8 slots, 3.79
# vs 5.13 at 32 — and GQA decode is near-streaming-bound there
# (8437 tok/s @ 32 slots, ~0.51 bw_util; docs/PERF.md round 5).
# NOTE (pallas_vpu + 1.5x window buckets): the engine's intermediate
# decode windows (96, 192, 384, 768, ... — generation.decode_window_
# bucket) are not all multiples of 128, and the VPU kernel requires
# W % 128 == 0 — so under that opt-in config only the W%128==0 buckets
# run the VPU kernel; the rest warn-and-fall-back to the XLA chain
# (_block_decode_deferred), i.e. the attention impl varies per window
# bucket within one stream.  Harmless for the default ("auto" -> xla);
# A/B runs labeled "pallas_vpu" should pin a 128-multiple window.
# NOTE (speculative verify): the multi-token verify layer
# (_block_verify_deferred) always uses the XLA einsum chain — the
# Pallas kernels are single-query formulations.  No cost under the
# measured default (auto -> xla everywhere), but an opt-in pallas*
# config combined with spec.tpu.speculative runs verify ticks on XLA
# while plain ticks run the kernel; pin one or the other for A/B runs.
_DECODE_ATTN = "auto"

_DECODE_ATTN_IMPLS = ("auto", "xla", "pallas", "pallas_single", "pallas_vpu")


def _decode_attn_impl() -> str:
    if _DECODE_ATTN not in _DECODE_ATTN_IMPLS:
        # Reject, don't reroute: a typo'd variant silently running a
        # DIFFERENT implementation would mislabel A/B benchmark rows.
        raise ValueError(
            f"unknown _DECODE_ATTN {_DECODE_ATTN!r}; "
            f"expected one of {_DECODE_ATTN_IMPLS}"
        )
    if _DECODE_ATTN != "auto":
        return _DECODE_ATTN
    return "xla"


def _mat(w, dtype):
    """Weight leaf -> matmul operand: raw array or int8 {"q8","scale"}.

    Prefer :func:`_qmatmul` on the hot paths — materializing the
    dequantized operand risks XLA writing a full-precision weight copy
    to HBM when the fusion heuristics decline (round-4 profile: a
    "weights-only" decode step cost 3-4x the int8 stream floor).
    """
    return dequantize_tensor(w, dtype) if is_quantized(w) else w.astype(dtype)


def _qmatmul(x, w):
    """``x @ dequantize(w)`` with the scale applied to the OUTPUT.

    The int8 scheme's scale is per-output-channel (``axis=-2`` reduce,
    shape ``[..., 1, out]``), so ``x @ (q8 * scale) == (x @ q8) * scale``
    exactly — the multiply moves from the ``[in, out]`` weight matrix to
    the ``[rows, out]`` result.  That guarantees the GEMM's HBM read is
    the RAW int8 buffer with only a convert on the operand (a fusion XLA
    performs reliably), instead of relying on it fusing a broadcast
    multiply — when that fusion declines, a bf16 copy of every weight
    matrix hits HBM and decode pays ~3x the weight traffic (round-4
    profile, scripts/profile_decode.py).  int8 values are exact in bf16,
    and the f32 scale multiplies the f32 accumulator, so numerics are at
    least as good as dequantize-then-matmul.
    """
    if is_quantized(w):
        y = jnp.matmul(
            x, w["q8"].astype(x.dtype), preferred_element_type=jnp.float32
        )
        return y * w["scale"].astype(jnp.float32)
    return jnp.matmul(x, w.astype(x.dtype), preferred_element_type=jnp.float32)


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    intermediate_size: int = 11008
    max_seq: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def llama2_7b(cls, **kw) -> "LlamaConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        defaults = dict(
            vocab_size=256,
            hidden_size=64,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            intermediate_size=128,
            max_seq=64,
        )
        defaults.update(kw)
        return cls(**defaults)


def matmul_param_count(cfg: LlamaConfig) -> int:
    """Weight-matrix elements one token-position multiplies through in a
    forward pass: q/k/v/o projections, the SwiGLU MLP triple, and the
    untied LM head (embedding lookups move bytes, not FLOPs).  The
    device-telemetry cost model's dominant term — 2 FLOPs per element
    per position — kept HERE so it can never drift from the layer
    geometry it describes."""
    h, i, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    per_layer = (
        h * nh * hd          # q
        + 2 * h * nkv * hd   # k, v
        + nh * hd * h        # o
        + 3 * h * i          # gate, up, down
    )
    return cfg.num_layers * per_layer + h * v


class KVCache(NamedTuple):
    """Static-shape KV cache: (layers, batch, max_seq, kv_heads, head_dim).

    Capacity is fixed at creation (``max_seq``); ``forward`` rejects chunks
    larger than capacity and ``generate_greedy`` rejects prompt+new-token
    totals beyond it.  Writing past capacity via repeated ``decode_step``
    calls is undefined (dynamic_update_slice clamps) — callers track
    ``length`` against capacity (the server engine does).
    """

    k: jax.Array
    v: jax.Array
    length: jax.Array  # int32 scalar: number of valid positions

    @classmethod
    def create(cls, cfg: LlamaConfig, batch: int, dtype=jnp.bfloat16) -> "KVCache":
        shape = (cfg.num_layers, batch, cfg.max_seq, cfg.num_kv_heads, cfg.head_dim)
        return cls(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            length=jnp.zeros((), jnp.int32),
        )


class RaggedKVCache(NamedTuple):
    """Multi-slot KV cache with PER-ROW lengths (continuous batching).

    Shapes match :class:`KVCache` — k/v ``[L, B, T, NKV, D]`` — but
    ``lengths`` is int32 ``[B]``: each batch row ("slot") sits at its own
    sequence position, so requests that arrived at different times decode
    together in one static-shape batched step (``decode_ragged``).  The
    server's :class:`~..server.generation.GenerationEngine` owns slot
    assignment; this type is the pure-JAX state it schedules over.
    """

    k: jax.Array  # [L, B, NKV, T, D] — head-major (see QuantRaggedKVCache)
    v: jax.Array
    lengths: jax.Array  # int32 [B]: valid positions per slot

    @classmethod
    def create(
        cls, cfg: LlamaConfig, batch: int, dtype=jnp.bfloat16
    ) -> "RaggedKVCache":
        shape = (cfg.num_layers, batch, cfg.num_kv_heads, cfg.max_seq, cfg.head_dim)
        return cls(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            lengths=jnp.zeros((batch,), jnp.int32),
        )


class QuantRaggedKVCache(NamedTuple):
    """Int8 variant of :class:`RaggedKVCache` (KV-cache quantization).

    Decode streams the whole attended cache window every step; at long
    context that traffic dwarfs the (already int8-able) weights, so the
    cache itself is the next HBM lever.  K/V are stored int8 with a
    per-(layer, row, position, head) scale over the ``head_dim`` axis —
    written once when the position is produced and consumed WITHOUT a
    dequantized copy (scales factor out of the attention einsums; see
    ``_block_decode_deferred``).  With the round-3 deferred-write decode
    (v5e chip, 1.35B shape, int8 weights, window=512) the int8 cache is
    part of the 1938 tok/s @ 8 slots / 2240 @ 16 ladder (docs/PERF.md);
    numerics are gated by bench.py's teacher-forced logit-parity fixture
    (~3% max rel err, argmax agreement 1.0).  Opt-in:
    ``spec.tpu.quantize: int8kv``.
    """

    k8: jax.Array  # int8   [L, B, NKV, T, D] — head-major: one (slot,
    #   kv-head)'s attended window is CONTIGUOUS, which is both the DMA-
    #   friendly order for decode reads and the block shape the fused
    #   Pallas kernel requires (ops/decode_attention.py; last two block
    #   dims must be the tile-aligned (W, D)).
    k_scale: jax.Array  # f32 [L, B, NKV, T, 1]
    v8: jax.Array
    v_scale: jax.Array
    lengths: jax.Array  # int32 [B]

    @classmethod
    def create(cls, cfg: LlamaConfig, batch: int) -> "QuantRaggedKVCache":
        shape = (cfg.num_layers, batch, cfg.num_kv_heads, cfg.max_seq, cfg.head_dim)
        sshape = shape[:-1] + (1,)
        return cls(
            k8=jnp.zeros(shape, jnp.int8),
            k_scale=jnp.zeros(sshape, jnp.float32),
            v8=jnp.zeros(shape, jnp.int8),
            v_scale=jnp.zeros(sshape, jnp.float32),
            lengths=jnp.zeros((batch,), jnp.int32),
        )


def _quant_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-(…, head) int8 over the trailing head_dim axis."""
    from .quantization import quantize_tensor

    q = quantize_tensor(x, axis=-1)
    return q["q8"], q["scale"]


# ---------------------------------------------------------------------------
# Init / torch import
# ---------------------------------------------------------------------------


def init(key: jax.Array, cfg: LlamaConfig, dtype=jnp.float32) -> dict:
    h, i, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    L = cfg.num_layers
    keys = jax.random.split(key, 9)
    std = 0.02

    def normal(k, shape):
        return (std * jax.random.normal(k, shape, jnp.float32)).astype(dtype)

    return {
        "embed": normal(keys[0], (v, h)),
        "layers": {
            "attn_norm": jnp.ones((L, h), dtype),
            "q": normal(keys[1], (L, h, nh * hd)),
            "k": normal(keys[2], (L, h, nkv * hd)),
            "v": normal(keys[3], (L, h, nkv * hd)),
            "o": normal(keys[4], (L, nh * hd, h)),
            "mlp_norm": jnp.ones((L, h), dtype),
            "gate": normal(keys[5], (L, h, i)),
            "up": normal(keys[6], (L, h, i)),
            "down": normal(keys[7], (L, i, h)),
        },
        "final_norm": jnp.ones((h,), dtype),
        "lm_head": normal(keys[8], (h, v)),
    }


def from_torch(torch_model, cfg: LlamaConfig) -> dict:
    """Convert a HuggingFace ``LlamaForCausalLM`` state dict."""
    import numpy as np

    sd = {k: v.detach().cpu().float().numpy() for k, v in torch_model.state_dict().items()}

    def stack(fmt: str, transpose: bool = False):
        mats = [sd[fmt.format(i)] for i in range(cfg.num_layers)]
        if transpose:
            mats = [m.T for m in mats]
        return jnp.asarray(np.stack(mats, axis=0))

    return {
        "embed": jnp.asarray(sd["model.embed_tokens.weight"]),
        "layers": {
            "attn_norm": stack("model.layers.{}.input_layernorm.weight"),
            "q": stack("model.layers.{}.self_attn.q_proj.weight", transpose=True),
            "k": stack("model.layers.{}.self_attn.k_proj.weight", transpose=True),
            "v": stack("model.layers.{}.self_attn.v_proj.weight", transpose=True),
            "o": stack("model.layers.{}.self_attn.o_proj.weight", transpose=True),
            "mlp_norm": stack("model.layers.{}.post_attention_layernorm.weight"),
            "gate": stack("model.layers.{}.mlp.gate_proj.weight", transpose=True),
            "up": stack("model.layers.{}.mlp.up_proj.weight", transpose=True),
            "down": stack("model.layers.{}.mlp.down_proj.weight", transpose=True),
        },
        "final_norm": jnp.asarray(sd["model.norm.weight"]),
        "lm_head": jnp.asarray(sd["lm_head.weight"].T),
    }


# ---------------------------------------------------------------------------
# RoPE (HF rotate-half convention)
# ---------------------------------------------------------------------------


def rope_cos_sin(positions: jax.Array, cfg: LlamaConfig, dtype=jnp.float32):
    """cos/sin tables for ``positions`` [S] (or [B, S]) -> [..., head_dim]."""
    hd = cfg.head_dim
    inv_freq = 1.0 / (
        cfg.rope_theta ** (jnp.arange(0, hd, 2, dtype=jnp.float32) / hd)
    )
    freqs = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., hd/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # [..., hd]
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rotate_half(x: jax.Array) -> jax.Array:
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: [B, S, N, D]; cos/sin: [S, D] (shared) or [B, S, D] (per-row)."""
    if cos.ndim == 2:
        c = cos[None, :, None, :]
        s = sin[None, :, None, :]
    else:
        c = cos[:, :, None, :]
        s = sin[:, :, None, :]
    return (x * c + _rotate_half(x) * s).astype(x.dtype)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _block(
    x: jax.Array,
    lp: dict,
    cache_k: jax.Array,
    cache_v: jax.Array,
    start: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    mask_bias: jax.Array,
    cfg: LlamaConfig,
    window: int | None = None,
):
    """One decoder layer over a fixed-capacity cache.

    x: [B,S,H]; cache_k/v: [B,max_seq,NKV,D]; start: scalar write offset
    shared by the batch (prefill / chunked prefill).  Per-row ragged
    decode does NOT come through here — see _block_decode_deferred.

    ``window`` (static) restricts ATTENTION to cache positions
    ``[0, window)`` while writes still land in the full buffer — decode's
    HBM floor is dominated by streaming the cache, so reading only a
    bucket that covers every row's current position instead of the full
    static capacity cuts that traffic proportionally.  Callers guarantee
    ``start + s <= window`` for every attended row; ``mask_bias``'s key
    axis must already be ``window``-sized.
    Returns (y, new_cache_k, new_cache_v).
    """
    b, s, h = x.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    xn = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
    q = _qmatmul(xn, lp["q"])
    k = _qmatmul(xn, lp["k"])
    v = _qmatmul(xn, lp["v"])
    q = q.astype(x.dtype).reshape(b, s, nh, hd)
    k = k.astype(x.dtype).reshape(b, s, nkv, hd)
    v = v.astype(x.dtype).reshape(b, s, nkv, hd)

    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    # Write this chunk's K/V into the cache at [start : start+s].
    # A quantized cache layer arrives as pairs (values int8, scales): the
    # chunk is quantized per-(position, head) at write time and dequantized
    # on the (fused) read path — KV-cache HBM traffic halves.
    quant_cache = isinstance(cache_k, tuple)

    def _write_all(buffers_and_vals):
        # Scalar start only: ragged (per-row) decode writes do not come
        # through here — decode_ragged defers them and commits all layers
        # with one scatter after its scan (see _block_decode_deferred).
        out = []
        z = jnp.zeros((), start.dtype) if hasattr(start, "dtype") else 0
        for buf, vals in buffers_and_vals:
            out.append(
                lax.dynamic_update_slice(
                    buf, vals.astype(buf.dtype), (z, start, z, z)
                )
            )
        return out

    if quant_cache:
        k8, ks = cache_k
        v8, vs = cache_v
        kq, kqs = _quant_kv(k)
        vq, vqs = _quant_kv(v)
        k8, ks, v8, vs = _write_all([(k8, kq), (ks, kqs), (v8, vq), (vs, vqs)])
        cache_k = (k8, ks)
        cache_v = (v8, vs)
    else:
        cache_k, cache_v = _write_all([(cache_k, k), (cache_v, v)])

    # GQA via grouped einsum: q reshaped to [B,S,NKV,G,D] contracts directly
    # against the [B,T,NKV,D] cache — no materialized repeat of K/V to all
    # query heads (that broadcast would dominate HBM traffic at decode).
    group = nh // nkv
    qg = q.reshape(b, s, nkv, group, hd)
    if quant_cache:
        # The per-(position, head) scales are CONSTANT over the contracted
        # head_dim axis, so they factor OUT of both einsums: contract the
        # raw int8 cache (the int8->bf16 convert fuses into the operand
        # read like the weight path) and fold K's scale into the scores,
        # V's into the probabilities.  A naive dequantize-then-einsum
        # materializes a full bf16 copy of the cache window per step —
        # measured SLOWER than the bf16 cache it was meant to beat.
        k8, ks = cache_k
        v8, vs = cache_v
        if window is not None:
            k8, ks = k8[:, :window], ks[:, :window]
            v8, vs = v8[:, :window], vs[:, :window]
        scores = jnp.einsum(
            "bqngd,bknd->bngqk",
            qg,
            k8.astype(x.dtype),
            preferred_element_type=jnp.float32,
        ) / jnp.sqrt(jnp.float32(hd))
        # ks: [B, W, NKV, 1] -> [B, NKV, 1, 1, W] broadcast over (G, S)
        kscale = jnp.moveaxis(ks[..., 0], 1, 2)[:, :, None, None, :]
        scores = scores * kscale
        scores = scores + mask_bias[:, None]
        probs = jax.nn.softmax(scores, axis=-1)
        vscale = jnp.moveaxis(vs[..., 0], 1, 2)[:, :, None, None, :]
        probs = (probs * vscale).astype(x.dtype)
        ctx = jnp.einsum(
            "bngqk,bknd->bqngd", probs, v8.astype(x.dtype)
        ).reshape(b, s, nh * hd)
    else:
        kk = cache_k if window is None else cache_k[:, :window]
        vv = cache_v if window is None else cache_v[:, :window]
        kk = kk.astype(x.dtype)
        vv = vv.astype(x.dtype)

        scores = jnp.einsum(
            "bqngd,bknd->bngqk", qg, kk, preferred_element_type=jnp.float32
        ) / jnp.sqrt(jnp.float32(hd))
        scores = scores + mask_bias[:, None]  # [B or 1, 1, 1, S, T]
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        ctx = jnp.einsum("bngqk,bknd->bqngd", probs, vv).reshape(b, s, nh * hd)
    attn_out = _qmatmul(ctx, lp["o"]).astype(x.dtype)
    x = x + attn_out

    xn = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
    gate = _qmatmul(xn, lp["gate"])
    up = _qmatmul(xn, lp["up"])
    act = jax.nn.silu(gate) * up
    down = _qmatmul(act.astype(x.dtype), lp["down"]).astype(x.dtype)
    return x + down, cache_k, cache_v


def _block_decode_deferred(
    x: jax.Array,
    lp: dict,
    cache_k,
    cache_v,
    cos: jax.Array,
    sin: jax.Array,
    mask_bias: jax.Array,
    cfg: LlamaConfig,
    window: int,
):
    """One decoder layer for single-token ragged decode with the cache
    READ-ONLY: returns ``(y, k_new, v_new)`` instead of an updated cache.

    Why: if the layer scan carried an updated cache, the update would ride
    the scan's stacked outputs and XLA materializes that as a full cache
    read + write every step — traffic linear in slots that capped 1.35B
    decode at ~1000 tok/s (round-3 probe: the write path cost 11.7 ms of
    a 17 ms step at 32 slots).  Deferring the write means the scan emits
    only each layer's tiny ``[B,1,NKV,D]`` row and :func:`decode_ragged`
    commits every layer with ONE scatter after the scan, leaving the big
    buffers untouched through the jit body.

    The current token is attended via an exact bf16 self-term concatenated
    before the softmax — ``mask_bias`` must therefore be STRICT
    (``key_pos < position``): the current position's cache row is
    stale/unwritten by design.  On the quant-cache path this also skips a
    quantize round-trip for the newest token (slightly better numerics).
    """
    b, s, h = x.shape  # s == 1 by contract
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    xn = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
    q = _qmatmul(xn, lp["q"])
    k = _qmatmul(xn, lp["k"])
    v = _qmatmul(xn, lp["v"])
    q = q.astype(x.dtype).reshape(b, s, nh, hd)
    k = k.astype(x.dtype).reshape(b, s, nkv, hd)
    v = v.astype(x.dtype).reshape(b, s, nkv, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    group = nh // nkv
    qg = q.reshape(b, s, nkv, group, hd)
    quant_cache = isinstance(cache_k, tuple)
    impl = _decode_attn_impl()
    if impl == "pallas_vpu" and (group != 1 or window % 128 != 0):
        # The VPU kernel is the G == 1 formulation over [W/128, 128]
        # lane tiles; grouped-head models or sub-lane windows take the
        # XLA chain instead of failing at trace time.  LOUDLY: an A/B
        # labeled "pallas_vpu" that silently measured XLA would produce
        # a false "VPU has no benefit" row.
        warnings.warn(
            f"pallas_vpu requires G == 1 and window % 128 == 0 "
            f"(got G={group}, window={window}); falling back to the XLA "
            "decode-attention chain — timings from this trace measure "
            "XLA, not the VPU kernel",
            stacklevel=2,
        )
        impl = "xla"
    if quant_cache and impl.startswith("pallas"):
        # Fused Pallas path: program(s) over (slot-block, kv-head) do both
        # MXU dots over the VMEM-resident int8 window with scales folded
        # into score/prob rows and the self-term joined in-softmax —
        # replacing the ~15-op XLA chain below (ops/decode_attention.py;
        # dispatch measured by scripts/ab_attention.py).  "pallas" is the
        # slot-batched kernel (grid divided by the slot block — the
        # per-program overhead was a ~1 ms/slot linear term at 1.35B);
        # "pallas_single" keeps one program per (slot, head) for A/B.
        from ..ops.decode_attention import (
            decode_attention, decode_attention_batched, decode_attention_vpu)

        attn_fn = {
            "pallas_single": decode_attention,
            "pallas_vpu": decode_attention_vpu,
        }.get(impl, decode_attention_batched)
        k8, ks = cache_k
        v8, vs = cache_v
        ctx4 = attn_fn(
            qg[:, 0],                                   # [B, NKV, G, D]
            k8[:, :, :window],
            ks[:, :, :window],                          # [B, NKV, W, 1]
            v8[:, :, :window],
            vs[:, :, :window],
            k[:, 0][:, :, None, :],                     # [B, NKV, 1, D]
            v[:, 0][:, :, None, :],
            mask_bias[:, 0],                            # [B, 1, W]
        )
        ctx = ctx4[:, None].astype(x.dtype).reshape(b, s, nh * hd)
        attn_out = _qmatmul(ctx, lp["o"]).astype(x.dtype)
        x = x + attn_out
        xn = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        gate = _qmatmul(xn, lp["gate"])
        up = _qmatmul(xn, lp["up"])
        act = jax.nn.silu(gate) * up
        down = _qmatmul(act.astype(x.dtype), lp["down"]).astype(x.dtype)
        return x + down, k, v
    if quant_cache:
        k8, ks = cache_k
        v8, vs = cache_v
        k8, ks = k8[:, :, :window], ks[:, :, :window]
        v8, vs = v8[:, :, :window], vs[:, :, :window]
        scores = jnp.einsum(
            "bqngd,bnkd->bngqk",
            qg,
            k8.astype(x.dtype),
            preferred_element_type=jnp.float32,
        ) / jnp.sqrt(jnp.float32(hd))
        # ks: [B, NKV, W, 1] -> [B, NKV, 1, 1, W] — head-major layout
        # means NO transposed copy, just a reshape of the window slice.
        kscale = ks[..., 0][:, :, None, None, :]
        scores = scores * kscale
    else:
        kk = cache_k[:, :, :window].astype(x.dtype)
        scores = jnp.einsum(
            "bqngd,bnkd->bngqk", qg, kk, preferred_element_type=jnp.float32
        ) / jnp.sqrt(jnp.float32(hd))
    scores = scores + mask_bias[:, None]

    # Exact self-term for the current (not-yet-written) position.
    score_self = (
        jnp.einsum("bqngd,bqnd->bngq", qg, k, preferred_element_type=jnp.float32)
        / jnp.sqrt(jnp.float32(hd))
    )[..., None]
    full = jnp.concatenate([scores, score_self], axis=-1)
    probs = jax.nn.softmax(full, axis=-1)
    probs_cache, prob_self = probs[..., :-1], probs[..., -1:]

    if quant_cache:
        vscale = vs[..., 0][:, :, None, None, :]
        probs_cache = (probs_cache * vscale).astype(x.dtype)
        ctx = jnp.einsum("bngqk,bnkd->bqngd", probs_cache, v8.astype(x.dtype))
    else:
        vv = cache_v[:, :, :window].astype(x.dtype)
        ctx = jnp.einsum("bngqk,bnkd->bqngd", probs_cache.astype(x.dtype), vv)
    ctx = ctx + jnp.einsum(
        "bngqk,bknd->bqngd", prob_self.astype(x.dtype), v
    )
    ctx = ctx.reshape(b, s, nh * hd)

    attn_out = _qmatmul(ctx, lp["o"]).astype(x.dtype)
    x = x + attn_out
    xn = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
    gate = _qmatmul(xn, lp["gate"])
    up = _qmatmul(xn, lp["up"])
    act = jax.nn.silu(gate) * up
    down = _qmatmul(act.astype(x.dtype), lp["down"]).astype(x.dtype)
    return x + down, k, v


def forward(
    params: dict,
    input_ids: jax.Array,
    cache: KVCache,
    cfg: LlamaConfig,
    dtype=jnp.bfloat16,
) -> tuple[jax.Array, KVCache]:
    """Run ``input_ids`` [B,S] through the model starting at ``cache.length``.

    Works for both prefill (S = prompt length, cache.length = 0) and decode
    (S = 1).  Returns (logits [B,S,vocab] float32, updated cache).
    """
    b, s = input_ids.shape
    if s > cfg.max_seq:
        raise ValueError(
            f"sequence chunk of {s} tokens exceeds KV-cache capacity "
            f"max_seq={cfg.max_seq}"
        )
    start = cache.length
    x = jnp.take(params["embed"], input_ids, axis=0).astype(dtype)

    positions = start + jnp.arange(s)
    cos, sin = rope_cos_sin(positions, cfg, jnp.float32)

    # Additive mask over the full cache buffer T=max_seq:
    # query at absolute position p attends keys with pos <= p (and only
    # positions already written).
    key_pos = jnp.arange(cfg.max_seq)
    valid = key_pos[None, :] <= positions[:, None]  # [S, T]
    mask_bias = jnp.where(valid, 0.0, -1e9).astype(jnp.float32)[None, None, :, :]

    def scan_body(carry, layer_inputs):
        x = carry
        lp, ck, cv = layer_inputs
        y, ck2, cv2 = _block(x, lp, ck, cv, start, cos, sin, mask_bias, cfg)
        return y, (ck2, cv2)

    x, (new_k, new_v) = lax.scan(
        scan_body, x, (params["layers"], cache.k, cache.v)
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = _qmatmul(x, params["lm_head"])
    new_cache = KVCache(k=new_k, v=new_v, length=start + s)
    return logits, new_cache


def prefill(params, input_ids, cfg, dtype=jnp.bfloat16):
    cache = KVCache.create(cfg, input_ids.shape[0], dtype)
    return forward(params, input_ids, cache, cfg, dtype)


def decode_step(params, token_ids, cache, cfg, dtype=jnp.bfloat16):
    """One greedy decode step: token_ids [B,1] -> (logits [B,1,V], cache)."""
    return forward(params, token_ids, cache, cfg, dtype)


def _ring_block(x, lp, cos, sin, cfg, mesh, axis_name):
    """One decoder layer with ring attention over an ``sp``-sharded
    sequence (long-prompt prefill; no cache read — the prompt IS the
    context).  x: [B,S,H] with S sharded over ``axis_name``.  Returns
    (y, k, v) where k/v are this layer's [B,S,NKV,D] cache rows (k
    rope'd, exactly what :func:`_block` writes)."""
    b, s, h = x.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    xn = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
    q = _qmatmul(xn, lp["q"]).astype(x.dtype).reshape(b, s, nh, hd)
    k = _qmatmul(xn, lp["k"]).astype(x.dtype).reshape(b, s, nkv, hd)
    v = _qmatmul(xn, lp["v"]).astype(x.dtype).reshape(b, s, nkv, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    # The ring kernel contracts [B,H,S,D] blocks with matching head
    # counts — GQA groups are repeated here (an S/n-local broadcast per
    # ring step, not the full-sequence repeat the decode path avoids).
    group = nh // nkv
    kf = jnp.repeat(k, group, axis=2) if group > 1 else k
    vf = jnp.repeat(v, group, axis=2) if group > 1 else v
    from ..ops.ring_attention import ring_attention_sharded

    ctx = ring_attention_sharded(
        q.transpose(0, 2, 1, 3),
        kf.transpose(0, 2, 1, 3),
        vf.transpose(0, 2, 1, 3),
        mesh,
        causal=True,
        axis_name=axis_name,
    )
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, nh * hd)
    x = x + _qmatmul(ctx, lp["o"]).astype(x.dtype)

    xn = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
    act = jax.nn.silu(_qmatmul(xn, lp["gate"])) * _qmatmul(xn, lp["up"])
    down = _qmatmul(act.astype(x.dtype), lp["down"]).astype(x.dtype)
    return x + down, k, v


def prefill_ring(
    params: dict,
    input_ids: jax.Array,
    cfg: LlamaConfig,
    *,
    mesh,
    last_idx: jax.Array,
    dtype=jnp.bfloat16,
    axis_name: str = "sp",
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Sequence-parallel prefill: the whole (padded) prompt in ONE pass
    with the sequence axis sharded over ``axis_name`` and exact ring
    attention (``ops.ring_attention``) in place of the dense S x S
    score matrix.

    input_ids: [1, S] padded to a bucket divisible by the sp degree;
    ``last_idx`` (traced) selects the final REAL row so only a [1, V]
    logits slice crosses the replicated boundary — never [S, V].
    Returns ``(last_logits [1,V], k_all, v_all)`` with k_all/v_all
    stacked [L, 1, S, NKV, D], the position-major seq-scratch layout
    :func:`insert_sequence` consumes.  Pad rows carry garbage K/V
    exactly like the padded chunked path — insert length caps reads.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    b, s = input_ids.shape
    x = jnp.take(params["embed"], input_ids, axis=0).astype(dtype)
    # Pin activations seq-sharded so the per-token work (norms, MLP,
    # projections) partitions over sp too, not just the attention.
    seq_sharded = NamedSharding(mesh, PartitionSpec(None, axis_name, None))
    x = lax.with_sharding_constraint(x, seq_sharded)

    positions = jnp.arange(s)
    cos, sin = rope_cos_sin(positions, cfg, jnp.float32)

    def scan_body(carry, lp):
        y, k, v = _ring_block(carry, lp, cos, sin, cfg, mesh, axis_name)
        return y, (k, v)

    x, (k_all, v_all) = lax.scan(scan_body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    last = lax.dynamic_slice_in_dim(x, last_idx, 1, axis=1)  # [1,1,H]
    logits = _qmatmul(last[:, 0], params["lm_head"])  # [1, V]
    return logits, k_all, v_all


def generate_greedy(
    params: dict,
    prompt_ids: jax.Array,
    num_new_tokens: int,
    cfg: LlamaConfig,
    dtype=jnp.bfloat16,
) -> jax.Array:
    """Greedy generation with a scanned decode loop (jit-friendly)."""
    total = prompt_ids.shape[1] + num_new_tokens
    if total > cfg.max_seq:
        raise ValueError(
            f"prompt ({prompt_ids.shape[1]}) + new tokens ({num_new_tokens}) "
            f"= {total} exceeds KV-cache capacity max_seq={cfg.max_seq}"
        )
    logits, cache = prefill(params, prompt_ids, cfg, dtype)
    next_tok = jnp.argmax(logits[:, -1:, :], axis=-1)

    def body(carry, _):
        tok, cache = carry
        logits, cache = decode_step(params, tok, cache, cfg, dtype)
        nxt = jnp.argmax(logits[:, -1:, :], axis=-1)
        return (nxt, cache), tok

    (_, _), toks = lax.scan(body, (next_tok, cache), None, length=num_new_tokens)
    # toks: [num_new, B, 1] -> [B, num_new]
    return jnp.moveaxis(toks[..., 0], 0, 1)


# ---------------------------------------------------------------------------
# Continuous batching primitives (per-row positions)
# ---------------------------------------------------------------------------


# Layer-walk strategy for decode_ragged: "fori" (default — dynamic-slice
# reads against the original cache buffers) or "scan" (cache packed as
# scan xs).  Kept switchable so the two loop forms can be A/B'd inside
# ONE process (scripts/ab_decode.py) — this environment's cross-process
# timing variance (~±20%) swamps the difference otherwise.
_DECODE_LAYER_LOOP = "fori"


def decode_ragged(
    params: dict,
    token_ids: jax.Array,
    cache: "RaggedKVCache | QuantRaggedKVCache",
    cfg: LlamaConfig,
    active: jax.Array | None = None,
    dtype=jnp.bfloat16,
    window: int | None = None,
):
    """One decode step where every batch row is at its OWN position.

    token_ids ``[B, 1]``; each row i writes K/V at ``cache.lengths[i]`` and
    attends keys ``0..lengths[i]``.  ``active`` (bool ``[B]``) gates the
    length advance so finished/empty slots don't creep toward capacity;
    their rows still compute (static shapes — the MXU does not care) and
    their outputs are ignored by the scheduler.

    Slot-reuse safety: a reused slot's stale K/V beyond the new sequence's
    current position is never attended — the cache mask is STRICT
    (``key_pos < p``), every position ``< p`` has been rewritten by the
    new occupant's prefill insert or a prior decode step's commit, and
    position ``p`` itself is attended through the exact in-flight
    self-term (never read from the cache this step; its row is written
    by the post-scan scatter for the NEXT step to read).

    ``window`` (STATIC int) bounds the attended cache prefix: callers pass
    a power-of-two bucket ``> max(lengths of active rows)`` so each window
    value compiles once but short sequences stop paying full-capacity
    cache reads.  Writes are unaffected (full buffer).  Measured on a v5e
    chip (1.35B shape, 8 slots at position 256, capacity 1024):
    window=512 is 1.11x over full-capacity in bf16, and composes with
    int8 weights to 1.24x (625 -> 772 tok/s).

    Returns (logits ``[B, 1, vocab]`` float32, cache with advanced lengths).
    """
    b, s = token_ids.shape
    if s != 1:
        raise ValueError(f"decode_ragged is single-token: got chunk of {s}")
    quant = isinstance(cache, QuantRaggedKVCache)
    lengths = cache.lengths
    x = jnp.take(params["embed"], token_ids, axis=0).astype(dtype)

    positions = lengths[:, None]  # [B, 1]
    cos, sin = rope_cos_sin(positions, cfg, jnp.float32)  # [B, 1, head_dim]

    capacity = (cache.k8 if quant else cache.k).shape[3]  # [L,B,NKV,T,D]
    if window is None:
        window = capacity
    window = min(int(window), capacity)
    key_pos = jnp.arange(window)
    # STRICT mask: the current position is attended via the exact
    # self-term inside _block_decode_deferred, not read back from the
    # cache (which stays read-only through the layer scan — see that
    # function's docstring for the traffic argument).
    valid = key_pos[None, None, :] < positions[:, :, None]  # [B, 1, W]
    mask_bias = jnp.where(valid, 0.0, -1e9).astype(jnp.float32)[:, None]  # [B,1,1,W]

    if _DECODE_LAYER_LOOP == "scan":
        def scan_body(carry, layer_inputs):
            xc = carry
            lp, ck, cv = layer_inputs
            y, k_new, v_new = _block_decode_deferred(
                xc, lp, ck, cv, cos, sin, mask_bias, cfg, window=window
            )
            return y, (k_new, v_new)

        ck0 = (cache.k8, cache.k_scale) if quant else cache.k
        cv0 = (cache.v8, cache.v_scale) if quant else cache.v
        x, (k_news, v_news) = lax.scan(
            scan_body, x, (params["layers"], ck0, cv0)
        )
        k_news = k_news[:, :, 0]  # [L, B, NKV, D]
        v_news = v_news[:, :, 0]
        return _finish_decode(
            params, x, k_news, v_news, cache, lengths, active, quant, cfg
        )

    # Default: fori_loop + dynamic_index_in_dim, NOT lax.scan with the
    # cache as xs — packing multi-GiB buffers into a scan's xs tuple can
    # make XLA copy them into loop state each step.  The fori body reads
    # each layer's weights and cache slabs with dynamic slices against
    # the ORIGINAL buffers (read-only, no loop-state packing) and
    # accumulates the tiny per-layer K/V rows in place.  A/B on chip:
    # scripts/ab_decode.py (the scan variant stays selectable above so
    # both compile in ONE process — cross-process timings on this
    # tunnel differ ±20% and cannot compare variants).
    nlayers = cfg.num_layers
    kv_dtype = x.dtype
    acc_k = jnp.zeros((nlayers, b, cfg.num_kv_heads, cfg.head_dim), kv_dtype)
    acc_v = jnp.zeros_like(acc_k)

    def idx(tree, l):
        return jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, l, axis=0, keepdims=False),
            tree,
        )

    def layer_body(l, carry):
        x, acc_k, acc_v = carry
        lp = idx(params["layers"], l)
        if quant:
            ck = (
                lax.dynamic_index_in_dim(cache.k8, l, 0, keepdims=False),
                lax.dynamic_index_in_dim(cache.k_scale, l, 0, keepdims=False),
            )
            cv = (
                lax.dynamic_index_in_dim(cache.v8, l, 0, keepdims=False),
                lax.dynamic_index_in_dim(cache.v_scale, l, 0, keepdims=False),
            )
        else:
            ck = lax.dynamic_index_in_dim(cache.k, l, 0, keepdims=False)
            cv = lax.dynamic_index_in_dim(cache.v, l, 0, keepdims=False)
        y, k_new, v_new = _block_decode_deferred(
            x, lp, ck, cv, cos, sin, mask_bias, cfg, window=window
        )
        acc_k = lax.dynamic_update_slice_in_dim(
            acc_k, k_new[None, :, 0].astype(kv_dtype), l, axis=0
        )
        acc_v = lax.dynamic_update_slice_in_dim(
            acc_v, v_new[None, :, 0].astype(kv_dtype), l, axis=0
        )
        return y, acc_k, acc_v

    x, k_news, v_news = lax.fori_loop(
        0, nlayers, layer_body, (x, acc_k, acc_v)
    )
    return _finish_decode(
        params, x, k_news, v_news, cache, lengths, active, quant, cfg
    )


def decode_multistep(
    params: dict,
    token_ids: jax.Array,
    cache: "RaggedKVCache | QuantRaggedKVCache",
    cfg: LlamaConfig,
    active: jax.Array,
    remaining: jax.Array,
    eos_ids: jax.Array,
    steps: int,
    sample_fn,
    sample_carry=None,
    dtype=jnp.bfloat16,
    window: int | None = None,
):
    """``steps`` (K) decode iterations in ONE program: a ``lax.scan``
    whose body is the existing single-step :func:`decode_ragged` forward
    plus an on-device sampling chain — each step's sampled token feeds
    the next step's embedding lookup without a host round trip, so one
    dispatch (and one blocking readback, which the engine further defers
    by a tick) serves K tokens per row.

    ``token_ids`` int32 ``[B, 1]`` is each row's pending token (last
    emitted, not yet fed); ``active`` bool ``[B]``; ``remaining`` int32
    ``[B]`` is each row's token budget (new tokens it may still emit);
    ``eos_ids`` int32 ``[B]`` is each row's stop token with ``-1`` for
    "no EOS" (token ids are non-negative, so -1 never matches).

    ``sample_fn(logits [B, V], carry) -> (carry, next [B])`` is the
    per-step token rule: greedy passes ``lambda l, c: (c, argmax(l))``
    with ``sample_carry=None``; sampling passes
    :func:`~.sampling.sample_chain_step` closed over the per-row
    temperature/top-k/top-p arrays with ``sample_carry`` = the per-row
    key batch — the carry threads through the scan so every step splits
    keys exactly like a step-by-step sampling tick.

    The EOS latch lives INSIDE the scan: a row that samples its EOS (or
    exhausts ``remaining``) drops out of ``active`` for the rest of the
    scan, so its lengths stop advancing and its K/V writes park
    (``decode_ragged``'s ``active`` gate) — over-run work is bounded by
    K and nothing past EOS is ever committed, so the host needs no K/V
    truncation, only to ignore token columns at/after ``valid[i]``.

    ``window`` (STATIC) must cover the LAST step's attended positions:
    callers pass a bucket ``>= max(lengths of active rows) + steps - 1``
    (the scan cannot grow the window mid-flight — one compiled variant
    per (steps, window) pair).

    Returns ``(tok_block [B, steps], valid [B], toks [B, 1], cache,
    active_out, remaining_out, carry_out)``: ``tok_block[i, j]`` is real
    for ``j < valid[i]`` (frozen last-token copies after), ``valid[i]``
    counts steps row ``i`` was active for, and the trailing outputs are
    the device-resident state the engine chains into the NEXT fused
    dispatch without a host sync (lag-1 readback).
    """
    def body(carry, _):
        toks, cache, act, rem, sc = carry
        logits, cache = decode_ragged(
            params, toks, cache, cfg, active=act, dtype=dtype, window=window
        )
        sc, nxt = sample_fn(logits[:, -1, :], sc)
        nxt = jnp.where(act, nxt.astype(jnp.int32), toks[:, 0])
        emitted = act
        rem = rem - act.astype(jnp.int32)
        act = act & (nxt != eos_ids) & (rem > 0)
        return (nxt[:, None], cache, act, rem, sc), (nxt, emitted)

    carry0 = (token_ids, cache, active, remaining, sample_carry)
    (toks, cache, active, remaining, sample_carry), (tok_seq, emit_seq) = (
        lax.scan(body, carry0, None, length=steps)
    )
    tok_block = jnp.moveaxis(tok_seq, 0, 1)  # [steps, B] -> [B, steps]
    valid = jnp.sum(emit_seq.astype(jnp.int32), axis=0)
    return tok_block, valid, toks, cache, active, remaining, sample_carry


def _block_verify_deferred(
    x: jax.Array,
    lp: dict,
    cache_k,
    cache_v,
    cos: jax.Array,
    sin: jax.Array,
    mask_bias: jax.Array,
    chunk_bias: jax.Array,
    cfg: LlamaConfig,
    window: int,
):
    """One decoder layer for MULTI-token ragged verify with the cache
    READ-ONLY: ``x`` is ``[B, S, H]`` where row ``i``'s S tokens sit at
    positions ``lengths[i] .. lengths[i]+S-1``.  Returns ``(y, k_new,
    v_new)`` with the chunk's fresh K/V ``[B, S, NKV, D]`` — the caller
    commits every layer with one scatter pass after the scan, exactly
    like :func:`_block_decode_deferred` (whose S == 1 case this
    generalizes; see that docstring for the deferred-write traffic
    argument).

    Attention decomposes into two exact terms: the cache window (strict
    mask ``key_pos < lengths[i]`` — no chunk position has been written
    yet) and an in-chunk causal term over the S fresh K/V rows
    (``chunk_bias``: key j attends query q iff ``j <= q``), joined in
    one softmax.  This is what verifies k draft tokens under ONE weight
    stream instead of k sequential decode steps.
    """
    b, s, h = x.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    xn = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
    q = _qmatmul(xn, lp["q"])
    k = _qmatmul(xn, lp["k"])
    v = _qmatmul(xn, lp["v"])
    q = q.astype(x.dtype).reshape(b, s, nh, hd)
    k = k.astype(x.dtype).reshape(b, s, nkv, hd)
    v = v.astype(x.dtype).reshape(b, s, nkv, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    group = nh // nkv
    qg = q.reshape(b, s, nkv, group, hd)
    quant_cache = isinstance(cache_k, tuple)
    if quant_cache:
        k8, ks = cache_k
        v8, vs = cache_v
        k8, ks = k8[:, :, :window], ks[:, :, :window]
        v8, vs = v8[:, :, :window], vs[:, :, :window]
        scores = jnp.einsum(
            "bqngd,bnkd->bngqk",
            qg,
            k8.astype(x.dtype),
            preferred_element_type=jnp.float32,
        ) / jnp.sqrt(jnp.float32(hd))
        kscale = ks[..., 0][:, :, None, None, :]
        scores = scores * kscale
    else:
        kk = cache_k[:, :, :window].astype(x.dtype)
        scores = jnp.einsum(
            "bqngd,bnkd->bngqk", qg, kk, preferred_element_type=jnp.float32
        ) / jnp.sqrt(jnp.float32(hd))
    scores = scores + mask_bias[:, None]  # [B,1,1,W] -> over (n, g, q)

    # In-chunk causal scores over the fresh (not-yet-written) K rows.
    # Only the SELF position (j == q) may use the exact full-precision
    # term — that mirrors _block_decode_deferred, where the current
    # token is attended in-flight.  Every EARLIER chunk position was, on
    # the sequential path, already committed to the cache before being
    # attended — on the int8 cache that means a quantize round-trip —
    # so the chunk term must read those positions through the same
    # round-trip (raw int8 contraction, scales folded out, exactly like
    # the cache-window term above) or verify logits diverge from plain
    # int8kv decode by the QUANTIZATION error, not mere reduction
    # rounding, and near-tie argmaxes break token parity.
    score_self = jnp.einsum(
        "bqngd,bjnd->bngqj", qg, k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.float32(hd))
    if quant_cache:
        k8c, kscc = _quant_kv(k)  # [B,S,NKV,D] / [B,S,NKV,1]
        score_rt = jnp.einsum(
            "bqngd,bjnd->bngqj",
            qg,
            k8c.astype(x.dtype),
            preferred_element_type=jnp.float32,
        ) / jnp.sqrt(jnp.float32(hd))
        kscale_c = jnp.moveaxis(kscc[..., 0], 1, 2)[:, :, None, None, :]
        score_rt = score_rt * kscale_c
        eye = jnp.eye(s, dtype=bool)[None, None, None]
        score_chunk = jnp.where(eye, score_self, score_rt)
    else:
        score_chunk = score_self
    score_chunk = score_chunk + chunk_bias  # [1,1,1,S,S]
    full = jnp.concatenate([scores, score_chunk], axis=-1)
    probs = jax.nn.softmax(full, axis=-1)
    probs_cache, probs_chunk = probs[..., :-s], probs[..., -s:]

    if quant_cache:
        vscale = vs[..., 0][:, :, None, None, :]
        probs_cache = (probs_cache * vscale).astype(x.dtype)
        ctx = jnp.einsum("bngqk,bnkd->bqngd", probs_cache, v8.astype(x.dtype))
        # Chunk V: self row full-precision, earlier rows through the
        # int8 round-trip (scales folded into the probabilities, like
        # the cache-window term).
        v8c, vscc = _quant_kv(v)
        vscale_c = jnp.moveaxis(vscc[..., 0], 1, 2)[:, :, None, None, :]
        eyef = eye.astype(probs.dtype)
        ctx = ctx + jnp.einsum(
            "bngqj,bjnd->bqngd", (probs_chunk * eyef).astype(x.dtype), v
        )
        ctx = ctx + jnp.einsum(
            "bngqj,bjnd->bqngd",
            (probs_chunk * (1.0 - eyef) * vscale_c).astype(x.dtype),
            v8c.astype(x.dtype),
        )
    else:
        vv = cache_v[:, :, :window].astype(x.dtype)
        ctx = jnp.einsum("bngqk,bnkd->bqngd", probs_cache.astype(x.dtype), vv)
        ctx = ctx + jnp.einsum(
            "bngqj,bjnd->bqngd", probs_chunk.astype(x.dtype), v
        )
    ctx = ctx.reshape(b, s, nh * hd)

    attn_out = _qmatmul(ctx, lp["o"]).astype(x.dtype)
    x = x + attn_out
    xn = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
    gate = _qmatmul(xn, lp["gate"])
    up = _qmatmul(xn, lp["up"])
    act = jax.nn.silu(gate) * up
    down = _qmatmul(act.astype(x.dtype), lp["down"]).astype(x.dtype)
    return x + down, k, v


def verify_ragged(
    params: dict,
    token_ids: jax.Array,
    cache: "RaggedKVCache | QuantRaggedKVCache",
    cfg: LlamaConfig,
    dtype=jnp.bfloat16,
    window: int | None = None,
    active: jax.Array | None = None,
):
    """Score S tokens per slot in ONE forward (self-speculative verify).

    ``token_ids`` is ``[B, S]``: row ``i``'s column 0 is the slot's last
    emitted (pending) token and columns ``1..S-1`` are drafted
    continuations; position ``j`` occupies absolute position
    ``lengths[i] + j``.  Returns ``(logits [B, S, vocab] float32, cache)``
    with every chunk position's K/V committed but ``lengths`` UNCHANGED —
    the caller advances each row by its accepted count + 1, which IS the
    rollback of rejected writes: positions at or beyond the truncated
    length are never attended (the cache mask is strict) and are
    overwritten by later writes before the sequence reaches them — the
    same invariant that makes slot reuse safe (see :func:`decode_ragged`).

    One compiled variant per (S, window) pair; S = 1 degenerates to a
    single-token decode step (the engine uses :func:`decode_ragged`
    there — this path exists for the draft lengths).

    ``active`` (bool ``[B]`` or None) parks inactive rows' K/V writes
    (see :func:`_commit_chunk`): an inactive slot may be mid-packed-
    prefill and its rows belong to the admission path this tick.
    """
    b, s = token_ids.shape
    quant = isinstance(cache, QuantRaggedKVCache)
    lengths = cache.lengths
    x = jnp.take(params["embed"], token_ids, axis=0).astype(dtype)

    positions = lengths[:, None] + jnp.arange(s)[None, :]  # [B, S]
    cos, sin = rope_cos_sin(positions, cfg, jnp.float32)  # [B, S, head_dim]

    capacity = (cache.k8 if quant else cache.k).shape[3]
    if window is None:
        window = capacity
    window = min(int(window), capacity)
    key_pos = jnp.arange(window)
    # STRICT cache mask shared by every chunk query: no chunk position has
    # been written yet, so all of them see exactly key_pos < lengths[i];
    # positions lengths[i]..lengths[i]+q-1 are the chunk's own earlier
    # tokens, attended through the exact in-chunk term.
    valid = key_pos[None, :] < lengths[:, None]  # [B, W]
    mask_bias = jnp.where(valid, 0.0, -1e9).astype(jnp.float32)[:, None, None]
    qpos = jnp.arange(s)
    chunk_causal = qpos[:, None] >= qpos[None, :]  # key j <= query q
    chunk_bias = jnp.where(chunk_causal, 0.0, -1e9).astype(jnp.float32)[
        None, None, None
    ]

    nlayers = cfg.num_layers
    kv_dtype = x.dtype
    acc_k = jnp.zeros((nlayers, b, s, cfg.num_kv_heads, cfg.head_dim), kv_dtype)
    acc_v = jnp.zeros_like(acc_k)

    def idx(tree, l):
        return jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, l, axis=0, keepdims=False),
            tree,
        )

    def layer_body(l, carry):
        x, acc_k, acc_v = carry
        lp = idx(params["layers"], l)
        if quant:
            ck = (
                lax.dynamic_index_in_dim(cache.k8, l, 0, keepdims=False),
                lax.dynamic_index_in_dim(cache.k_scale, l, 0, keepdims=False),
            )
            cv = (
                lax.dynamic_index_in_dim(cache.v8, l, 0, keepdims=False),
                lax.dynamic_index_in_dim(cache.v_scale, l, 0, keepdims=False),
            )
        else:
            ck = lax.dynamic_index_in_dim(cache.k, l, 0, keepdims=False)
            cv = lax.dynamic_index_in_dim(cache.v, l, 0, keepdims=False)
        y, k_new, v_new = _block_verify_deferred(
            x, lp, ck, cv, cos, sin, mask_bias, chunk_bias, cfg, window=window
        )
        acc_k = lax.dynamic_update_slice_in_dim(
            acc_k, k_new[None].astype(kv_dtype), l, axis=0
        )
        acc_v = lax.dynamic_update_slice_in_dim(
            acc_v, v_new[None].astype(kv_dtype), l, axis=0
        )
        return y, acc_k, acc_v

    x, k_news, v_news = lax.fori_loop(0, nlayers, layer_body, (x, acc_k, acc_v))
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = _qmatmul(x, params["lm_head"])
    return logits, _commit_chunk(cache, k_news, v_news, lengths, quant, active)


def _commit_chunk(cache, k_news, v_news, lengths, quant, active=None):
    """Commit a verify chunk's K/V: row ``b``'s token ``j`` lands at
    position ``lengths[b] + j``, ONE batched drop-scatter per buffer
    over the ``[B, S]`` index grid — sequential per-``j`` passes would
    re-pay the scatter's full-buffer walk S times (the round-5 commit
    measurements put one pass at ~3.8 ms at the 1.35B/32-slot shape),
    taxing exactly the tick speculation exists to accelerate.
    ``lengths`` is returned UNCHANGED: acceptance decides the advance.

    ``active`` (bool [B] or None) parks INACTIVE rows' writes at
    capacity so the drop-mode scatter discards them: an empty slot may
    be mid-packed-prefill (its K/V written by the admission path, not
    this tick), and the old always-write garbage row would corrupt it.
    """
    s = k_news.shape[2]
    capacity = (cache.k8 if quant else cache.k).shape[3]
    write_base = lengths
    if active is not None:
        write_base = jnp.where(active, lengths, jnp.int32(capacity))

    def commit(buf, vals):
        # buf [L, B, NKV, T, ...]; vals [L, B, S, NKV, ...].  Advanced
        # indices rows [B,1] (axis 1) and positions [B,S] (axis 3)
        # broadcast to [B, S] and move to the front: updates are
        # [B, S, L, NKV, ...].  Indices stay unique (distinct j per
        # row); rows spilling past capacity drop, never clamp.
        b = buf.shape[1]
        rows = jnp.arange(b)[:, None]
        pos = write_base[:, None] + jnp.arange(s)[None, :]
        v = jnp.moveaxis(vals, (1, 2), (0, 1)).astype(buf.dtype)
        return buf.at[:, rows, :, pos].set(
            v, mode="drop", unique_indices=True
        )

    if quant:
        kq, kqs = _quant_kv(k_news)
        vq, vqs = _quant_kv(v_news)
        return QuantRaggedKVCache(
            commit(cache.k8, kq),
            commit(cache.k_scale, kqs),
            commit(cache.v8, vq),
            commit(cache.v_scale, vqs),
            lengths,
        )
    return RaggedKVCache(
        commit(cache.k, k_news), commit(cache.v, v_news), lengths
    )


def prefill_chunks_ragged(
    params: dict,
    token_ids: jax.Array,
    cache: "RaggedKVCache | QuantRaggedKVCache",
    slots: jax.Array,
    offsets: jax.Array,
    cfg: LlamaConfig,
    dtype=jnp.bfloat16,
):
    """Packed multi-admission prefill: one forward for ``B_p`` sequences'
    next prompt chunks under ONE weight stream.

    ``token_ids`` is ``[B_p, C]``: row ``b`` is the next uncached chunk
    of an in-flight admission whose K/V lives in cache row ``slots[b]``
    and whose ``offsets[b]`` tokens (earlier chunks and/or a radix-cached
    prefix) are already written there; chunk position ``j`` occupies
    absolute position ``offsets[b] + j``.  This is :func:`verify_ragged`
    with a per-row cache-row indirection: the attention decomposes into
    the strict cache window (``key_pos < offsets[b]``, gathered from row
    ``slots[b]``) and the exact in-chunk causal term, joined in one
    softmax — so serial chunked prefill (B_p sequential batch-1 chunk
    forwards, each streaming the full weight tree) collapses to one
    forward whose weight stream is amortized across all B_p admissions.

    Rows may be PARKED by passing ``offsets[b] == capacity``: the commit
    scatter drops their writes (``mode="drop"``) and their logits are
    garbage the caller ignores — that is how a packed call padded up to
    a power-of-two B_p bucket keeps every shape static.

    Returns ``(logits [B_p, C, vocab] float32, cache)`` with each real
    row's chunk K/V committed at ``(slots[b], offsets[b] + j)`` by one
    batched drop-scatter per buffer and ``lengths`` UNCHANGED — the
    engine's finalize step sets a slot's length when its LAST chunk
    lands (until then the row stays inactive and decode ticks park
    their writes for it; see :func:`_finish_decode`).
    """
    b, s = token_ids.shape
    quant = isinstance(cache, QuantRaggedKVCache)
    x = jnp.take(params["embed"], token_ids, axis=0).astype(dtype)

    positions = offsets[:, None] + jnp.arange(s)[None, :]  # [B_p, C]
    cos, sin = rope_cos_sin(positions, cfg, jnp.float32)

    capacity = (cache.k8 if quant else cache.k).shape[3]
    key_pos = jnp.arange(capacity)
    # STRICT cache mask, exactly verify_ragged's: no chunk position has
    # been written yet, so every chunk query sees key_pos < offsets[b];
    # in-chunk positions are attended through the exact causal term.
    valid = key_pos[None, :] < offsets[:, None]  # [B_p, T]
    mask_bias = jnp.where(valid, 0.0, -1e9).astype(jnp.float32)[:, None, None]
    qpos = jnp.arange(s)
    chunk_causal = qpos[:, None] >= qpos[None, :]
    chunk_bias = jnp.where(chunk_causal, 0.0, -1e9).astype(jnp.float32)[
        None, None, None
    ]

    nlayers = cfg.num_layers
    kv_dtype = x.dtype
    acc_k = jnp.zeros((nlayers, b, s, cfg.num_kv_heads, cfg.head_dim), kv_dtype)
    acc_v = jnp.zeros_like(acc_k)

    def idx(tree, l):
        return jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, l, axis=0, keepdims=False),
            tree,
        )

    def layer_body(l, carry):
        x, acc_k, acc_v = carry
        # Gather the B_p admissions' cache rows out of the full slot
        # batch: the compute (and the weight stream it amortizes) scales
        # with the B_p bucket, not max_slots.
        if quant:
            ck = (
                lax.dynamic_index_in_dim(cache.k8, l, 0, keepdims=False)[slots],
                lax.dynamic_index_in_dim(
                    cache.k_scale, l, 0, keepdims=False
                )[slots],
            )
            cv = (
                lax.dynamic_index_in_dim(cache.v8, l, 0, keepdims=False)[slots],
                lax.dynamic_index_in_dim(
                    cache.v_scale, l, 0, keepdims=False
                )[slots],
            )
        else:
            ck = lax.dynamic_index_in_dim(cache.k, l, 0, keepdims=False)[slots]
            cv = lax.dynamic_index_in_dim(cache.v, l, 0, keepdims=False)[slots]
        y, k_new, v_new = _block_verify_deferred(
            x, idx(params["layers"], l), ck, cv, cos, sin, mask_bias,
            chunk_bias, cfg, window=capacity,
        )
        acc_k = lax.dynamic_update_slice_in_dim(
            acc_k, k_new[None].astype(kv_dtype), l, axis=0
        )
        acc_v = lax.dynamic_update_slice_in_dim(
            acc_v, v_new[None].astype(kv_dtype), l, axis=0
        )
        return y, acc_k, acc_v

    x, k_news, v_news = lax.fori_loop(0, nlayers, layer_body, (x, acc_k, acc_v))
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = _qmatmul(x, params["lm_head"])
    return logits, _commit_chunk_at(cache, k_news, v_news, slots, offsets, quant)


def _commit_chunk_at(cache, k_news, v_news, slots, offsets, quant):
    """Commit a packed prefill chunk's K/V: row ``b``'s token ``j`` lands
    at ``(slots[b], offsets[b] + j)`` — :func:`_commit_chunk` with a
    per-row cache-row indirection.  Parked rows (``offsets[b] ==
    capacity``) drop every write.  ``unique_indices`` contract — the
    (slot, position) tuples must be pairwise distinct, which holds when
    (a) REAL rows carry distinct slots (the engine reserves one cache
    row per admission) with in-range positions, and (b) PARKED rows
    carry slots distinct from each other (their positions start at
    ``capacity``, so they cannot collide with a real row's tuple even
    on an equal slot value)."""
    s = k_news.shape[2]

    def commit(buf, vals):
        rows = slots[:, None]
        pos = offsets[:, None] + jnp.arange(s)[None, :]
        v = jnp.moveaxis(vals, (1, 2), (0, 1)).astype(buf.dtype)
        return buf.at[:, rows, :, pos].set(
            v, mode="drop", unique_indices=True
        )

    if quant:
        kq, kqs = _quant_kv(k_news)
        vq, vqs = _quant_kv(v_news)
        return QuantRaggedKVCache(
            commit(cache.k8, kq),
            commit(cache.k_scale, kqs),
            commit(cache.v8, vq),
            commit(cache.v_scale, vqs),
            cache.lengths,
        )
    return RaggedKVCache(
        commit(cache.k, k_news), commit(cache.v, v_news), cache.lengths
    )


# Per-row roles for the unified super-step (super_step_ragged): what each
# batch row is doing inside ONE dispatch.  IDLE rows park every write.
ROLE_IDLE = 0
ROLE_DECODE = 1
ROLE_VERIFY = 2
ROLE_PREFILL = 3


def _commit_block_at(cache, k_news, v_news, base, counts, quant):
    """Commit a super-step chunk's K/V with PER-POSITION parking: row
    ``b``'s token ``j`` lands at ``base[b] + j`` when ``j < counts[b]``
    and parks past capacity otherwise — :func:`_commit_chunk` whose park
    granularity is a column, not a whole row, because one super-step row
    commits 1 (decode), ``draft_len+1`` (verify) or ``C`` (prefill)
    columns out of the same static-width block.

    ``unique_indices`` contract: rows are pairwise distinct, a row's
    valid positions ``base[b]..base[b]+counts[b]-1`` are strictly
    increasing and bounded by ``capacity + S - 1`` (drop-scatter spill),
    and its parked positions start at ``capacity + S`` — the two ranges
    cannot collide, so every (row, position) tuple stays distinct."""
    s = k_news.shape[2]
    capacity = (cache.k8 if quant else cache.k).shape[3]

    def commit(buf, vals):
        b = buf.shape[1]
        rows = jnp.arange(b)[:, None]
        j = jnp.arange(s)[None, :]
        pos = jnp.where(
            j < counts[:, None],
            base[:, None] + j,
            jnp.int32(capacity + s) + j,
        )
        v = jnp.moveaxis(vals, (1, 2), (0, 1)).astype(buf.dtype)
        return buf.at[:, rows, :, pos].set(
            v, mode="drop", unique_indices=True
        )

    if quant:
        kq, kqs = _quant_kv(k_news)
        vq, vqs = _quant_kv(v_news)
        return QuantRaggedKVCache(
            commit(cache.k8, kq),
            commit(cache.k_scale, kqs),
            commit(cache.v8, vq),
            commit(cache.v_scale, vqs),
            cache.lengths,
        )
    return RaggedKVCache(
        commit(cache.k, k_news), commit(cache.v, v_news), cache.lengths
    )


def super_step_ragged(
    params: dict,
    token_block: jax.Array,
    cache: "RaggedKVCache | QuantRaggedKVCache",
    cfg: LlamaConfig,
    *,
    roles: jax.Array,
    offsets: jax.Array,
    counts: jax.Array,
    draft_len: jax.Array,
    active: jax.Array,
    remaining: jax.Array,
    eos_ids: jax.Array,
    steps: int,
    sample_fn,
    sample_carry=None,
    dtype=jnp.bfloat16,
    window: int | None = None,
):
    """ONE dispatch advancing a ragged batch of MIXED roles: per row,
    a packed-prefill chunk commit (``ROLE_PREFILL``), a fused-K decode
    step with the on-device sampling chain (``ROLE_DECODE``), or a
    speculative verify (``ROLE_VERIFY``) — the engine's whole tick as a
    single program, so the compile/warmup space collapses from the
    (decode + verify-chain + multistep + packed-B_p) cross-product to
    one variant per (window, sampling-mode).

    ``token_block`` int32 ``[B, S]``: column 0 is a decode/verify row's
    pending token (last emitted, unfed) or a prefill row's first chunk
    token; verify rows carry their draft in columns ``1..draft_len``;
    prefill rows carry their chunk in columns ``0..C-1``; everything
    past ``counts[b]`` is padding.  ``offsets`` is a prefill row's
    absolute chunk write base (other roles read their cache length);
    ``counts`` is how many leading block columns really commit (0 parks
    the row — see :func:`_commit_block_at`); ``active`` gates emission
    and length advance exactly like the split programs.

    The wide forward IS :func:`verify_ragged`'s: a strict cache mask
    (``key_pos < base[b]``) joined with the exact in-chunk causal term
    in one softmax, so column 0 of a decode row is the same class of
    computation as a plain decode step (int8kv included — see
    :func:`_block_verify_deferred`), and a verify row's columns match
    :func:`verify_ragged` column-for-column.  After the wide step,
    decode rows run ``steps - 1`` more fused iterations through
    :func:`decode_multistep` — same EOS/budget latch, same per-step key
    split, so seeded sampling stays token-for-token reproducible
    against the split programs.

    ``window`` (STATIC) must cover every row's worst case: a decode
    row's ``length + steps - 1``, a verify row's ``length``, a prefill
    row's ``offset`` (see the engine's ``superstep_window`` pre-pick).

    Returns ``(logits [B, S, vocab] f32, tok_block [B, steps], valid
    [B], greedy [B, S], accepted [B], toks [B, 1], cache, active_out,
    remaining_out, carry_out)``: ``logits``/``greedy``/``accepted``
    serve the verify and prefill-finalize consumers; ``tok_block`` /
    ``valid`` are the decode rows' emissions (column layout of
    :func:`decode_multistep`); ``lengths`` advance on-device by each
    decode row's emitted count and each verify row's ``accepted + 1``
    (prefill rows advance at finalize, engine-side, exactly like the
    packed path)."""
    from .sampling import speculative_accept

    b, s = token_block.shape
    quant = isinstance(cache, QuantRaggedKVCache)
    lengths = cache.lengths
    capacity = (cache.k8 if quant else cache.k).shape[3]
    if window is None:
        window = capacity
    window = min(int(window), capacity)

    is_dec = roles == ROLE_DECODE
    is_ver = roles == ROLE_VERIFY
    is_pre = roles == ROLE_PREFILL
    # Write/read base per row: a prefill row sits at its chunk offset
    # (its length stays 0 until finalize), every other role at its
    # cache length — the one indirection that lets three programs share
    # a forward.
    base = jnp.where(is_pre, offsets, lengths).astype(jnp.int32)

    x = jnp.take(params["embed"], token_block, axis=0).astype(dtype)
    positions = base[:, None] + jnp.arange(s)[None, :]  # [B, S]
    cos, sin = rope_cos_sin(positions, cfg, jnp.float32)

    key_pos = jnp.arange(window)
    # STRICT cache mask (verify_ragged's): no block position has been
    # written yet, so every query sees exactly key_pos < base[b]; the
    # block's own earlier columns are attended via the exact in-chunk
    # causal term.
    valid_mask = key_pos[None, :] < base[:, None]  # [B, W]
    mask_bias = jnp.where(valid_mask, 0.0, -1e9).astype(jnp.float32)[
        :, None, None
    ]
    qpos = jnp.arange(s)
    chunk_causal = qpos[:, None] >= qpos[None, :]
    chunk_bias = jnp.where(chunk_causal, 0.0, -1e9).astype(jnp.float32)[
        None, None, None
    ]

    nlayers = cfg.num_layers
    kv_dtype = x.dtype
    acc_k = jnp.zeros((nlayers, b, s, cfg.num_kv_heads, cfg.head_dim), kv_dtype)
    acc_v = jnp.zeros_like(acc_k)

    def idx(tree, l):
        return jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, l, axis=0, keepdims=False),
            tree,
        )

    def layer_body(l, carry):
        x, acc_k, acc_v = carry
        if quant:
            ck = (
                lax.dynamic_index_in_dim(cache.k8, l, 0, keepdims=False),
                lax.dynamic_index_in_dim(cache.k_scale, l, 0, keepdims=False),
            )
            cv = (
                lax.dynamic_index_in_dim(cache.v8, l, 0, keepdims=False),
                lax.dynamic_index_in_dim(cache.v_scale, l, 0, keepdims=False),
            )
        else:
            ck = lax.dynamic_index_in_dim(cache.k, l, 0, keepdims=False)
            cv = lax.dynamic_index_in_dim(cache.v, l, 0, keepdims=False)
        y, k_new, v_new = _block_verify_deferred(
            x, idx(params["layers"], l), ck, cv, cos, sin, mask_bias,
            chunk_bias, cfg, window=window,
        )
        acc_k = lax.dynamic_update_slice_in_dim(
            acc_k, k_new[None].astype(kv_dtype), l, axis=0
        )
        acc_v = lax.dynamic_update_slice_in_dim(
            acc_v, v_new[None].astype(kv_dtype), l, axis=0
        )
        return y, acc_k, acc_v

    x, k_news, v_news = lax.fori_loop(0, nlayers, layer_body, (x, acc_k, acc_v))
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = _qmatmul(x, params["lm_head"])  # [B, S, vocab] f32

    cache = _commit_block_at(cache, k_news, v_news, base, counts, quant)

    # Verify consumers: exact greedy acceptance over the wide logits —
    # columns past a row's draft_len are capped out by the per-row
    # budget inside speculative_accept, so the static S padding never
    # changes the accepted count.
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, S]
    accepted, nxt_v = speculative_accept(token_block, greedy, draft_len)
    ver_act = is_ver & active
    accepted = jnp.where(ver_act, accepted, 0)

    # Decode rows' step 1 of K: sample column 0 under the same rule and
    # latch order as decode_multistep's scan body.
    act_dec = active & is_dec
    carry, sampled = sample_fn(logits[:, 0, :], sample_carry)
    nxt_d = jnp.where(act_dec, sampled.astype(jnp.int32), token_block[:, 0])
    valid0 = act_dec.astype(jnp.int32)
    remaining1 = remaining - valid0
    act1 = act_dec & (nxt_d != eos_ids) & (remaining1 > 0)

    lengths1 = lengths + valid0 + jnp.where(ver_act, accepted + 1, 0)
    cache = cache._replace(lengths=lengths1)

    toks1 = jnp.where(ver_act, nxt_v, nxt_d)[:, None]
    if steps > 1:
        (
            tok_rest, valid_rest, toks2, cache, act2, rem2, carry,
        ) = decode_multistep(
            params, toks1, cache, cfg, act1, remaining1, eos_ids,
            steps - 1, sample_fn, sample_carry=carry, dtype=dtype,
            window=window,
        )
        tok_block_out = jnp.concatenate([nxt_d[:, None], tok_rest], axis=1)
        valid = valid0 + valid_rest
    else:
        tok_block_out = nxt_d[:, None]
        valid = valid0
        toks2, act2, rem2 = toks1, act1, remaining1

    return (
        logits, tok_block_out, valid, greedy, accepted,
        toks2, cache, act2, rem2, carry,
    )


def _finish_decode(params, x, k_news, v_news, cache, lengths, active, quant, cfg):
    """Shared decode tail: final norm, lm_head, and the cache commit.

    ``k_news``/``v_news`` are ``[L, B, NKV, D]`` — every layer's new
    token row, committed with one write pass (see ``_commit_rows``).
    """
    b = x.shape[0]
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = _qmatmul(x, params["lm_head"])
    advance = (
        jnp.ones((b,), jnp.int32) if active is None else active.astype(jnp.int32)
    )
    # Inactive rows write NOTHING (positions parked at capacity, dropped
    # by the scatter): an empty slot may be mid-packed-prefill, and its
    # rows are being written by the admission path — the old
    # always-write garbage token would corrupt the prefilled prompt.
    capacity = (cache.k8 if quant else cache.k).shape[3]
    write_pos = lengths
    if active is not None:
        write_pos = jnp.where(active, lengths, jnp.int32(capacity))
    if quant:
        kq, kqs = _quant_kv(k_news)
        vq, vqs = _quant_kv(v_news)
        return logits, QuantRaggedKVCache(
            _commit_rows(cache.k8, kq, write_pos),
            _commit_rows(cache.k_scale, kqs, write_pos),
            _commit_rows(cache.v8, vq, write_pos),
            _commit_rows(cache.v_scale, vqs, write_pos),
            lengths + advance,
        )
    return logits, RaggedKVCache(
        _commit_rows(cache.k, k_news.astype(cache.k.dtype), write_pos),
        _commit_rows(cache.v, v_news.astype(cache.v.dtype), write_pos),
        lengths + advance,
    )


def _commit_rows(buf: jax.Array, vals: jax.Array, lengths: jax.Array) -> jax.Array:
    """Write row ``b``'s new K/V at its own position, in place.

    ``buf`` is head-major ``[L, B, NKV, T, ...]``, ``vals`` ``[L, B, NKV,
    ...]``; row ``b`` writes at position ``lengths[b]`` on axis 3, and a
    row parked at capacity (``lengths[b] == T``) must be DROPPED, never
    clamped onto its last real position.

    One batched scatter with drop semantics.  History, because this spot
    has flip-flopped on measurement twice: round 4 found the scatter
    forcing a full cache copy per step — but only because the layer scan
    then consumed the cache as its xs, and the xs-read + scatter
    interplay defeated XLA's copy elimination; the fix was a fori-loop
    of per-row ``dynamic_update_slice``.  Round 5's layer walk reads the
    ORIGINAL buffers via ``dynamic_index_in_dim`` (no xs packing), and
    re-measuring in the production-shaped program showed the fori form
    itself had become the step's dominant linear term — 6.0 ms of a
    14.9 ms step at 1.35B/32 slots (~0.2 ms per slot, ~1500x the bytes
    actually written) against ~3.8 ms for this scatter, with the no-op
    commit at 8.9 ms as the floor.  In-process A/B of both spellings
    plus a vmapped-DUS variant: scatter 12.68 / fori 14.92 / vmap 28.7
    ms/step at 32 slots."""
    b = buf.shape[1]
    rows = jnp.arange(b)
    # Advanced indices at axes 1 and 3 broadcast to (B,) and move to the
    # front: the updates tensor is [B, L, NKV, ...].
    v = jnp.moveaxis(vals, 1, 0).astype(buf.dtype)
    return buf.at[:, rows, :, lengths].set(
        v, mode="drop", unique_indices=True
    )


def insert_sequence(
    cache: "RaggedKVCache | QuantRaggedKVCache",
    seq: KVCache,
    slot: jax.Array,
    length: jax.Array,
):
    """Install a prefilled single-sequence cache into batch row ``slot``.

    ``seq`` comes from :func:`prefill` with batch 1 (k/v ``[L,1,Tp,...]``,
    ``Tp <= capacity``); ``length`` is the sequence's REAL token count —
    prompt padding beyond it was written by prefill but is progressively
    overwritten by decode steps before it can ever be attended (see
    ``decode_ragged``).  ``slot``/``length`` may be traced values, so one
    compiled insert serves every slot.
    """
    slot = jnp.asarray(slot, jnp.int32)
    z = jnp.zeros((), jnp.int32)
    lengths = cache.lengths.at[slot].set(jnp.asarray(length, jnp.int32))
    # prefill's KVCache is position-major [L, 1, Tp, NKV, D]; the ragged
    # cache is head-major [L, B, NKV, T, D] — one transpose per insert
    # (prefill-rate, not decode-rate, so the copy is off the hot path).
    seq_k = jnp.swapaxes(seq.k, 2, 3)
    seq_v = jnp.swapaxes(seq.v, 2, 3)
    if isinstance(cache, QuantRaggedKVCache):
        k8, ks = _quant_kv(seq_k)
        v8, vs = _quant_kv(seq_v)
        ins = lambda buf, vals: lax.dynamic_update_slice(
            buf, vals.astype(buf.dtype), (z, slot, z, z, z)
        )
        return QuantRaggedKVCache(
            ins(cache.k8, k8),
            ins(cache.k_scale, ks),
            ins(cache.v8, v8),
            ins(cache.v_scale, vs),
            lengths,
        )
    k = lax.dynamic_update_slice(
        cache.k, seq_k.astype(cache.k.dtype), (z, slot, z, z, z)
    )
    v = lax.dynamic_update_slice(
        cache.v, seq_v.astype(cache.v.dtype), (z, slot, z, z, z)
    )
    return RaggedKVCache(k, v, lengths)


# ---------------------------------------------------------------------------
# Sharding
# ---------------------------------------------------------------------------


def param_logical_axes(cfg: LlamaConfig | None = None) -> dict:
    """Logical axes (leading ``None`` on stacked layer params = scan axis)."""
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": (None, "embed"),
            "q": (None, "embed", "heads"),
            "k": (None, "embed", "kv_heads"),
            "v": (None, "embed", "kv_heads"),
            "o": (None, "heads", "embed"),
            "mlp_norm": (None, "embed"),
            "gate": (None, "embed", "mlp"),
            "up": (None, "embed", "mlp"),
            "down": (None, "mlp", "embed"),
        },
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }


def cache_logical_axes() -> KVCache:
    """Sharding for the KV cache: kv_heads on tp, batch on dp."""
    return KVCache(
        k=(None, "batch", None, "kv_heads", "head_dim"),
        v=(None, "batch", None, "kv_heads", "head_dim"),
        length=None,
    )
