"""Tabular models (baseline config 1: gradient-boosted regressor via pyfunc).

Two tiers, mirroring SURVEY §7 hard part 2 (arbitrary pyfunc models are not
jit-compilable):

- ``TreeEnsemble`` — a TPU-native decision-forest evaluator: trees are
  flattened to index arrays and traversed with ``max_depth`` rounds of
  vectorized gathers, so the whole forest is one jittable, batchable XLA
  program (no per-row Python).  Converters from sklearn forests/GBMs and
  (when installed) xgboost boosters.
- ``PyFuncPredictor`` — the fallback tier: wraps any Python ``predict``
  callable (e.g. an MLflow pyfunc) behind the same interface, running on
  host CPU while keeping one metric surface.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class TreeArrays:
    """One forest flattened to arrays of shape [n_trees, max_nodes].

    Leaf nodes self-loop (left == right == self), so ``max_depth``
    traversal rounds land every row on its leaf and stay there.
    """

    feature: jax.Array  # int32 [T, N] feature index tested at node
    threshold: jax.Array  # f32 [T, N]
    left: jax.Array  # int32 [T, N] child if x[feat] <= threshold
    right: jax.Array  # int32 [T, N]
    value: jax.Array  # f32 [T, N] leaf contribution
    max_depth: int
    base_score: float = 0.0
    n_features: int = 0  # 0 = unknown (warmup shapes then derive from splits)
    # Multi-class boosters (xgboost multi:*) train one tree group per class:
    # tree_group[t] is the class whose margin tree t contributes to.
    # n_groups == 1 keeps the scalar-output path ([B] not [B, 1]).
    tree_group: jax.Array | None = None  # int32 [T] or None
    n_groups: int = 1


def eval_forest(trees: TreeArrays, x: jax.Array) -> jax.Array:
    """Evaluate the forest: x [B, F] -> [B] summed leaf values, or
    [B, n_groups] per-class margins when the forest is multi-class.

    Each of ``max_depth`` rounds gathers (feature, threshold, children) for
    the current node of every (tree, row) pair — pure gathers/selects, TPU
    VPU-friendly, no data-dependent control flow.  The multi-class
    reduction is a [T,B]x[T,K] matmul against a one-hot group matrix
    (a vectorized per-class segment sum, MXU-friendly for big forests).
    """
    n_trees = trees.feature.shape[0]
    b = x.shape[0]
    node = jnp.zeros((n_trees, b), jnp.int32)
    xt = x.T  # [F, B]

    def step(node):
        feat = jnp.take_along_axis(trees.feature, node, axis=1)  # [T, B]
        thr = jnp.take_along_axis(trees.threshold, node, axis=1)  # [T, B]
        # For tree t, row b: x[b, feat[t, b]]  ==  xt[feat[t, b], b].
        xv = jnp.take_along_axis(xt, feat, axis=0)  # [T, B]
        go_left = xv <= thr
        l = jnp.take_along_axis(trees.left, node, axis=1)
        r = jnp.take_along_axis(trees.right, node, axis=1)
        return jnp.where(go_left, l, r)

    for _ in range(trees.max_depth):
        node = step(node)
    leaf_vals = jnp.take_along_axis(trees.value, node, axis=1)  # [T, B]
    if trees.n_groups > 1:
        onehot = jax.nn.one_hot(
            trees.tree_group, trees.n_groups, dtype=leaf_vals.dtype
        )  # [T, K]
        return leaf_vals.T @ onehot + trees.base_score  # [B, K]
    return leaf_vals.sum(axis=0) + trees.base_score


@dataclass(frozen=True)
class GemmForest:
    """A forest lowered to matmuls (the MXU-native evaluation form).

    Each leaf is one row of a ±1 "path polarity" matrix over the tree's
    internal nodes: +1 where the path takes the left (``<=``) branch, -1
    where it takes the right, 0 for nodes off the path.  With comparisons
    encoded ±1, ``A @ cmp`` counts path agreements, and a leaf is hit iff
    the count equals its path length — turning the whole data-dependent
    traversal into two einsums and a compare.  Measured on v5e (200 trees
    x depth 6 x batch 256): 14.9 ms (gather traversal) -> 1.3 ms, exact
    parity; the gather loop's per-level ``take_along_axis`` lowers to
    serial scatter/gathers the TPU hates, while this form is pure MXU.

    The predicate matmul runs in bf16 with f32 accumulation — exact,
    since inputs are ±1/0 and counts are small integers; the value
    reduction stays f32 (real-valued leaf sums).
    """

    feat: jax.Array  # int32 [T, NI] feature tested by each internal node
    thr: jax.Array  # f32 [T, NI] (+inf padding -> cmp true, A column 0)
    A: jax.Array  # f32 [T, NL, NI] path polarity (+1 left / -1 right / 0)
    plen: jax.Array  # f32 [T, NL] path length (-1 padding: never matches)
    lval: jax.Array  # f32 [T, NL] leaf value
    max_depth: int
    base_score: float = 0.0
    n_features: int = 0
    tree_group: jax.Array | None = None
    n_groups: int = 1


# A-matrix element budget for the GEMM lowering: [T, NL, NI] grows as
# 4^depth per tree, so very deep trees fall back to the gather traversal.
# 16M f32 elements = 64 MiB — comfortably HBM-resident next to a model.
_GEMM_BUDGET_ELEMS = 16_000_000


def to_gemm(trees: TreeArrays) -> GemmForest | None:
    """Lower ``TreeArrays`` to the matmul form (host-side, at load time).

    Returns None when the padded A matrix would exceed the element
    budget — the caller keeps the gather traversal instead.
    """
    F = np.asarray(trees.feature)
    TH = np.asarray(trees.threshold)
    Lc = np.asarray(trees.left)
    Rc = np.asarray(trees.right)
    V = np.asarray(trees.value)
    T = F.shape[0]

    # Budget check BEFORE the per-leaf path expansion: a deep forest (the
    # exact case the budget exists for) must take the cheap exit, not
    # materialize gigabytes of Python path lists first.  Node counts come
    # straight from the flattened arrays: leaves self-loop (left == self),
    # and padding rows (left == self == 0 with zero value) only overcount
    # — overcounting can only reject, never wrongly accept.
    node_idx = np.arange(F.shape[1], dtype=np.int32)[None, :]
    is_leaf = Lc == node_idx
    n_leaf_bound = int(is_leaf.sum(axis=1).max())
    n_int_bound = int((~is_leaf).sum(axis=1).max())
    if T * max(1, n_leaf_bound) * max(1, n_int_bound) > _GEMM_BUDGET_ELEMS:
        return None

    per_tree = []
    n_int_max = n_leaf_max = 1
    for t in range(T):
        internal: list[int] = []
        leaves: list[tuple[float, list[tuple[int, int]]]] = []
        # Iterative DFS (explicit stack): depth is unbounded by Python.
        stack: list[tuple[int, list[tuple[int, int]]]] = [(0, [])]
        while stack:
            node, path = stack.pop()
            if Lc[t, node] == node:  # leaf self-loop (TreeArrays invariant)
                leaves.append((float(V[t, node]), path))
                continue
            internal.append(node)
            stack.append((int(Rc[t, node]), path + [(node, -1)]))
            stack.append((int(Lc[t, node]), path + [(node, +1)]))
        per_tree.append((internal, leaves))
        n_int_max = max(n_int_max, len(internal))
        n_leaf_max = max(n_leaf_max, len(leaves))

    if T * n_leaf_max * n_int_max > _GEMM_BUDGET_ELEMS:
        return None

    NI, NL = n_int_max, n_leaf_max
    feat = np.zeros((T, NI), np.int32)
    thr = np.full((T, NI), np.inf, np.float32)
    A = np.zeros((T, NL, NI), np.float32)
    plen = np.full((T, NL), -1.0, np.float32)
    lval = np.zeros((T, NL), np.float32)
    for t, (internal, leaves) in enumerate(per_tree):
        pos = {n: i for i, n in enumerate(internal)}
        if internal:
            feat[t, : len(internal)] = F[t, internal]
            thr[t, : len(internal)] = TH[t, internal]
        for li, (v, path) in enumerate(leaves):
            lval[t, li] = v
            plen[t, li] = float(len(path))
            for node, pol in path:
                A[t, li, pos[node]] = pol
    return GemmForest(
        feat=jnp.asarray(feat),
        thr=jnp.asarray(thr),
        A=jnp.asarray(A),
        plen=jnp.asarray(plen),
        lval=jnp.asarray(lval),
        max_depth=trees.max_depth,
        base_score=trees.base_score,
        n_features=trees.n_features,
        tree_group=trees.tree_group,
        n_groups=trees.n_groups,
    )


def eval_forest_gemm(gf: GemmForest, x: jax.Array) -> jax.Array:
    """Evaluate the matmul-form forest: x [B, F] -> [B] (or [B, K])."""
    xt = x.T  # [F, B]
    fv = jnp.take(xt, gf.feat, axis=0)  # [T, NI, B]
    cmp_pm = jnp.where(fv <= gf.thr[..., None], 1.0, -1.0).astype(jnp.bfloat16)
    counts = jnp.einsum(
        "tln,tnb->tlb",
        gf.A.astype(jnp.bfloat16),
        cmp_pm,
        preferred_element_type=jnp.float32,
    )
    hit = (counts == gf.plen[..., None]).astype(jnp.float32)  # [T, NL, B]
    if gf.n_groups > 1:
        contrib = jnp.einsum(
            "tlb,tl->tb", hit, gf.lval, preferred_element_type=jnp.float32
        )
        onehot = jax.nn.one_hot(gf.tree_group, gf.n_groups, dtype=jnp.float32)
        return contrib.T @ onehot + gf.base_score  # [B, K]
    out = jnp.einsum(
        "tlb,tl->b", hit, gf.lval, preferred_element_type=jnp.float32
    )
    return out + gf.base_score


def lower_forest(trees: TreeArrays):
    """Pick the evaluation form: ``(eval_fn, form_name)``.

    GEMM when it fits the budget (the fast path on TPU), else the
    gather traversal.
    """
    gf = to_gemm(trees)
    if gf is None:
        return (lambda x: eval_forest(trees, x)), "gather"
    return (lambda x: eval_forest_gemm(gf, x)), "gemm"


def from_sklearn_forest(model) -> TreeArrays:
    """Convert sklearn RandomForest*/GradientBoosting* to TreeArrays."""
    if not hasattr(model, "estimators_"):
        raise TypeError(f"unsupported sklearn model {type(model).__name__}")
    raw = np.asarray(model.estimators_).ravel().tolist()
    estimators = [e.tree_ for e in raw]
    # RandomForest averages trees; GradientBoosting sums lr-scaled trees on
    # top of the init estimator's constant prediction.
    if type(model).__name__.startswith("RandomForest"):
        scale, base = 1.0 / len(estimators), 0.0
    else:
        scale = float(model.learning_rate)
        init = getattr(model, "init_", None)
        base = float(np.ravel(init.constant_)[0]) if hasattr(init, "constant_") else 0.0

    max_nodes = max(t.node_count for t in estimators)
    max_depth = max(t.max_depth for t in estimators)
    T = len(estimators)
    feature = np.zeros((T, max_nodes), np.int32)
    threshold = np.zeros((T, max_nodes), np.float32)
    left = np.zeros((T, max_nodes), np.int32)
    right = np.zeros((T, max_nodes), np.int32)
    value = np.zeros((T, max_nodes), np.float32)
    for ti, t in enumerate(estimators):
        n = t.node_count
        is_leaf = t.children_left[:n] == -1
        feature[ti, :n] = np.where(is_leaf, 0, t.feature[:n])
        threshold[ti, :n] = np.where(is_leaf, 0.0, t.threshold[:n])
        idx = np.arange(n)
        left[ti, :n] = np.where(is_leaf, idx, t.children_left[:n])
        right[ti, :n] = np.where(is_leaf, idx, t.children_right[:n])
        value[ti, :n] = np.where(is_leaf, t.value[:n, 0, 0] * scale, 0.0)
    return TreeArrays(
        feature=jnp.asarray(feature),
        threshold=jnp.asarray(threshold),
        left=jnp.asarray(left),
        right=jnp.asarray(right),
        value=jnp.asarray(value),
        max_depth=int(max_depth),
        base_score=float(base),
    )


def from_xgboost(booster) -> tuple[TreeArrays, str]:
    """Convert a live xgboost Booster via its JSON dump (no xgboost import
    here — the caller already has the booster)."""
    import json as _json

    raw = booster.save_raw(raw_format="json")
    return from_xgboost_json(_json.loads(bytes(raw)))


def from_xgboost_json(model: Any) -> tuple[TreeArrays, str]:
    """Parse xgboost's JSON model format into ``(TreeArrays, objective)``.

    Reads the format ``Booster.save_model("model.json")`` writes — pure
    JSON, so serving xgboost models (baseline config 1, ``BASELINE.json``
    configs[1]) needs no xgboost dependency.  Semantics honored:

    - routing is ``x[feat] < cond`` (strict, unlike sklearn's ``<=``); we
      store ``nextafter(cond, -inf)`` so the shared ``<=`` evaluator
      reproduces the strict comparison exactly in float32;
    - leaf values live in ``split_conditions`` at leaf nodes (already
      learning-rate scaled by xgboost);
    - ``base_score`` is in probability space for ``binary:*``; the margin
      sum starts from ``logit(base_score)`` there, identity elsewhere.

    The returned objective string tells the caller which output transform
    to apply (``binary:logistic`` -> sigmoid; ``reg:*`` -> identity).
    """
    if isinstance(model, (str, bytes)):
        import json as _json

        model = _json.loads(model)
    learner = model.get("learner")
    if not isinstance(learner, dict):
        raise ValueError("not an xgboost JSON model: missing 'learner'")
    booster = learner.get("gradient_booster", {})
    booster_name = booster.get("name", "gbtree")
    if booster_name not in ("gbtree", "dart"):
        raise NotImplementedError(
            f"xgboost booster {booster_name!r} has no TPU-native lowering "
            "(only tree boosters); use the pyfunc tier"
        )
    lmp = learner.get("learner_model_param", {})
    num_class = int(lmp.get("num_class", "0") or 0)
    objective = (learner.get("objective") or {}).get("name", "reg:squarederror")
    base = float(lmp.get("base_score", "0.5"))
    if objective.startswith("binary:"):
        # ProbToMargin: stored base_score is a probability.
        eps = 1e-7
        p = min(max(base, eps), 1 - eps)
        base = float(np.log(p / (1 - p)))
    if booster_name == "dart":
        weights = [float(w) for w in booster.get("weight_drop", [])]
        booster = booster.get("gbtree", booster)
    else:
        weights = []
    trees_json = (booster.get("model") or {}).get("trees", [])
    if not trees_json:
        raise ValueError("xgboost model contains no trees")
    # Multi-class (multi:softprob/softmax): one tree group per class,
    # recorded per tree in tree_info; margins reduce per class in
    # eval_forest.  base_score stays a raw margin here — softmax has no
    # ProbToMargin transform (unlike binary:*'s logit above).
    if objective.startswith("multi:") and num_class < 2:
        # Without a trustworthy num_class the [B] margin vector would be
        # softmaxed ACROSS THE BATCH downstream — reject at load time.
        raise ValueError(
            f"objective {objective!r} requires num_class >= 2 in "
            f"learner_model_param, got {num_class}"
        )
    n_groups = num_class if num_class > 1 else 1
    tree_group = None
    if n_groups > 1:
        tree_info = (booster.get("model") or {}).get("tree_info", [])
        if len(tree_info) != len(trees_json):
            raise ValueError(
                f"multi-class model: tree_info has {len(tree_info)} entries "
                f"for {len(trees_json)} trees"
            )
        tree_group = np.asarray(tree_info, np.int32)
        if tree_group.size and (
            tree_group.min() < 0 or tree_group.max() >= n_groups
        ):
            raise ValueError(
                f"tree_info class ids outside [0, {n_groups}): "
                f"[{tree_group.min()}, {tree_group.max()}]"
            )

    T = len(trees_json)
    max_nodes = max(len(t["left_children"]) for t in trees_json)
    feature = np.zeros((T, max_nodes), np.int32)
    threshold = np.zeros((T, max_nodes), np.float32)
    left = np.zeros((T, max_nodes), np.int32)
    right = np.zeros((T, max_nodes), np.int32)
    value = np.zeros((T, max_nodes), np.float32)
    max_depth = 1
    for ti, t in enumerate(trees_json):
        leaf_vec = int((t.get("tree_param") or {}).get("size_leaf_vector", "1") or 1)
        if leaf_vec > 1:
            # xgboost >= 2.0 multi_strategy="multi_output_tree": one tree
            # emits a vector of per-class leaf values.  The flattened
            # scalar-leaf evaluator would silently sum every margin into
            # class 0 — reject instead of serving wrong probabilities.
            raise NotImplementedError(
                f"vector-leaf tree (size_leaf_vector={leaf_vec}, "
                "multi_output_tree strategy) has no TPU-native lowering; "
                "train with one-tree-per-class (default) or use the "
                "pyfunc tier"
            )
        lc = np.asarray(t["left_children"], np.int32)
        rc = np.asarray(t["right_children"], np.int32)
        cond = np.asarray(t["split_conditions"], np.float32)
        sidx = np.asarray(t["split_indices"], np.int32)
        n = lc.shape[0]
        is_leaf = lc == -1
        idx = np.arange(n, dtype=np.int32)
        feature[ti, :n] = np.where(is_leaf, 0, sidx)
        # Strict '<' via nextafter: x < c  <=>  x <= nextafter(c, -inf) in f32.
        threshold[ti, :n] = np.where(
            is_leaf, 0.0, np.nextafter(cond, np.float32(-np.inf))
        )
        left[ti, :n] = np.where(is_leaf, idx, lc)
        right[ti, :n] = np.where(is_leaf, idx, rc)
        scale = weights[ti] if ti < len(weights) else 1.0
        value[ti, :n] = np.where(is_leaf, cond * scale, 0.0)
        # Depth of this tree from the child links (root is node 0).
        depth = np.zeros(n, np.int32)
        order = [0]
        while order:
            node = order.pop()
            for child in (lc[node], rc[node]):
                if child != -1:
                    depth[child] = depth[node] + 1
                    order.append(int(child))
        max_depth = max(max_depth, int(depth.max()) + 1 if n > 1 else 1)
    return (
        TreeArrays(
            feature=jnp.asarray(feature),
            threshold=jnp.asarray(threshold),
            left=jnp.asarray(left),
            right=jnp.asarray(right),
            value=jnp.asarray(value),
            max_depth=max_depth,
            base_score=base,
            n_features=int(lmp.get("num_feature", "0") or 0)
            or int(feature.max()) + 1,
            tree_group=None if tree_group is None else jnp.asarray(tree_group),
            n_groups=n_groups,
        ),
        objective,
    )


class PyFuncPredictor:
    """Fallback tier: any Python callable behind the predictor interface.

    Not jittable; runs on host.  Used for MLflow pyfunc artifacts whose
    flavor has no TPU-native lowering.
    """

    def __init__(self, predict: Callable[[np.ndarray], np.ndarray], name: str = "pyfunc"):
        self._predict = predict
        self.name = name
        self.jittable = False

    def __call__(self, x: Any) -> np.ndarray:
        return np.asarray(self._predict(np.asarray(x)))
