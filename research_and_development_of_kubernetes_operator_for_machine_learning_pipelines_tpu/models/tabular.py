"""Tabular models (baseline config 1: gradient-boosted regressor via pyfunc).

Two tiers, mirroring SURVEY §7 hard part 2 (arbitrary pyfunc models are not
jit-compilable):

- ``TreeEnsemble`` — a TPU-native decision-forest evaluator: trees are
  flattened to index arrays and traversed with ``max_depth`` rounds of
  vectorized gathers, so the whole forest is one jittable, batchable XLA
  program (no per-row Python).  Converters from sklearn forests/GBMs and
  (when installed) xgboost boosters.
- ``PyFuncPredictor`` — the fallback tier: wraps any Python ``predict``
  callable (e.g. an MLflow pyfunc) behind the same interface, running on
  host CPU while keeping one metric surface.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class TreeArrays:
    """One forest flattened to arrays of shape [n_trees, max_nodes].

    Leaf nodes self-loop (left == right == self), so ``max_depth``
    traversal rounds land every row on its leaf and stay there.
    """

    feature: jax.Array  # int32 [T, N] feature index tested at node
    threshold: jax.Array  # f32 [T, N]
    left: jax.Array  # int32 [T, N] child if x[feat] <= threshold
    right: jax.Array  # int32 [T, N]
    value: jax.Array  # f32 [T, N] leaf contribution
    max_depth: int
    base_score: float = 0.0


def eval_forest(trees: TreeArrays, x: jax.Array) -> jax.Array:
    """Evaluate the forest: x [B, F] -> [B] summed leaf values.

    Each of ``max_depth`` rounds gathers (feature, threshold, children) for
    the current node of every (tree, row) pair — pure gathers/selects, TPU
    VPU-friendly, no data-dependent control flow.
    """
    n_trees = trees.feature.shape[0]
    b = x.shape[0]
    node = jnp.zeros((n_trees, b), jnp.int32)
    xt = x.T  # [F, B]

    def step(node):
        feat = jnp.take_along_axis(trees.feature, node, axis=1)  # [T, B]
        thr = jnp.take_along_axis(trees.threshold, node, axis=1)  # [T, B]
        # For tree t, row b: x[b, feat[t, b]]  ==  xt[feat[t, b], b].
        xv = jnp.take_along_axis(xt, feat, axis=0)  # [T, B]
        go_left = xv <= thr
        l = jnp.take_along_axis(trees.left, node, axis=1)
        r = jnp.take_along_axis(trees.right, node, axis=1)
        return jnp.where(go_left, l, r)

    for _ in range(trees.max_depth):
        node = step(node)
    leaf_vals = jnp.take_along_axis(trees.value, node, axis=1)  # [T, B]
    return leaf_vals.sum(axis=0) + trees.base_score


def from_sklearn_forest(model) -> TreeArrays:
    """Convert sklearn RandomForest*/GradientBoosting* to TreeArrays."""
    if not hasattr(model, "estimators_"):
        raise TypeError(f"unsupported sklearn model {type(model).__name__}")
    raw = np.asarray(model.estimators_).ravel().tolist()
    estimators = [e.tree_ for e in raw]
    # RandomForest averages trees; GradientBoosting sums lr-scaled trees on
    # top of the init estimator's constant prediction.
    if type(model).__name__.startswith("RandomForest"):
        scale, base = 1.0 / len(estimators), 0.0
    else:
        scale = float(model.learning_rate)
        init = getattr(model, "init_", None)
        base = float(np.ravel(init.constant_)[0]) if hasattr(init, "constant_") else 0.0

    max_nodes = max(t.node_count for t in estimators)
    max_depth = max(t.max_depth for t in estimators)
    T = len(estimators)
    feature = np.zeros((T, max_nodes), np.int32)
    threshold = np.zeros((T, max_nodes), np.float32)
    left = np.zeros((T, max_nodes), np.int32)
    right = np.zeros((T, max_nodes), np.int32)
    value = np.zeros((T, max_nodes), np.float32)
    for ti, t in enumerate(estimators):
        n = t.node_count
        is_leaf = t.children_left[:n] == -1
        feature[ti, :n] = np.where(is_leaf, 0, t.feature[:n])
        threshold[ti, :n] = np.where(is_leaf, 0.0, t.threshold[:n])
        idx = np.arange(n)
        left[ti, :n] = np.where(is_leaf, idx, t.children_left[:n])
        right[ti, :n] = np.where(is_leaf, idx, t.children_right[:n])
        value[ti, :n] = np.where(is_leaf, t.value[:n, 0, 0] * scale, 0.0)
    return TreeArrays(
        feature=jnp.asarray(feature),
        threshold=jnp.asarray(threshold),
        left=jnp.asarray(left),
        right=jnp.asarray(right),
        value=jnp.asarray(value),
        max_depth=int(max_depth),
        base_score=float(base),
    )


def from_xgboost(booster) -> TreeArrays:  # pragma: no cover - xgboost optional
    """Convert an xgboost Booster (gated: xgboost not in the base image)."""
    raise NotImplementedError(
        "xgboost is not available in this environment; use PyFuncPredictor "
        "or convert via sklearn's GradientBoosting equivalent"
    )


class PyFuncPredictor:
    """Fallback tier: any Python callable behind the predictor interface.

    Not jittable; runs on host.  Used for MLflow pyfunc artifacts whose
    flavor has no TPU-native lowering.
    """

    def __init__(self, predict: Callable[[np.ndarray], np.ndarray], name: str = "pyfunc"):
        self._predict = predict
        self.name = name
        self.jittable = False

    def __call__(self, x: Any) -> np.ndarray:
        return np.asarray(self._predict(np.asarray(x)))
