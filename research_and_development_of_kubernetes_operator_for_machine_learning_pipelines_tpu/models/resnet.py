"""ResNet-50 image classifier (baseline config 2: canary traffic-shift).

Inference-mode pure-JAX implementation: NHWC layout (TPU-native; conv
feature maps tile onto the MXU as NHWC), batch-norm folded to scale/bias
from running statistics at load time — a serving model never updates BN, so
folding removes 53 elementwise ops from the graph and lets XLA fuse the
remaining scale/bias straight into the convolutions.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax


@dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    width: int = 64

    @classmethod
    def resnet50(cls, **kw) -> "ResNetConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "ResNetConfig":
        defaults = dict(stage_sizes=(1, 1), num_classes=10, width=8)
        defaults.update(kw)
        return cls(**defaults)


def _conv(x: jax.Array, w: jax.Array, stride: int = 1, padding="SAME") -> jax.Array:
    return lax.conv_general_dilated(
        x,
        w.astype(x.dtype),
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)


def _scale_bias(x: jax.Array, sb: dict) -> jax.Array:
    """Folded batch-norm: y = x * scale + bias."""
    return x * sb["scale"].astype(x.dtype) + sb["bias"].astype(x.dtype)


def fold_batchnorm(gamma, beta, mean, var, eps: float = 1e-5) -> dict:
    """Fold BN running stats into an affine scale/bias pair."""
    scale = gamma / jnp.sqrt(var + eps)
    return {"scale": scale, "bias": beta - mean * scale}


def _init_conv(key, kh, kw, cin, cout) -> jax.Array:
    fan_in = kh * kw * cin
    std = jnp.sqrt(2.0 / fan_in)
    return std * jax.random.normal(key, (kh, kw, cin, cout), jnp.float32)


def _init_bn(c) -> dict:
    return {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}


def init(key: jax.Array, cfg: ResNetConfig) -> dict:
    """He-normal random init (BN pre-folded to identity scale/bias)."""
    n_blocks = sum(cfg.stage_sizes)
    keys = iter(jax.random.split(key, 3 + 4 * n_blocks + len(cfg.stage_sizes)))
    w = cfg.width
    params: dict = {
        "stem": {"conv": _init_conv(next(keys), 7, 7, 3, w), "bn": _init_bn(w)},
        "stages": [],
    }
    cin = w
    for si, n in enumerate(cfg.stage_sizes):
        cmid = w * (2**si)
        cout = cmid * 4
        stage = []
        for bi in range(n):
            stride = _block_stride(si, bi)
            block = {
                "conv1": _init_conv(next(keys), 1, 1, cin, cmid),
                "bn1": _init_bn(cmid),
                "conv2": _init_conv(next(keys), 3, 3, cmid, cmid),
                "bn2": _init_bn(cmid),
                "conv3": _init_conv(next(keys), 1, 1, cmid, cout),
                "bn3": _init_bn(cout),
            }
            if cin != cout or stride != 1:
                block["proj"] = _init_conv(next(keys), 1, 1, cin, cout)
                block["proj_bn"] = _init_bn(cout)
            stage.append(block)
            cin = cout
        params["stages"].append(stage)
    params["fc"] = {
        "w": 0.01 * jax.random.normal(next(keys), (cin, cfg.num_classes), jnp.float32),
        "b": jnp.zeros((cfg.num_classes,)),
    }
    return params


def _block_stride(stage_index: int, block_index: int) -> int:
    return 2 if (stage_index > 0 and block_index == 0) else 1


def _bottleneck(x: jax.Array, p: dict, stride: int) -> jax.Array:
    out = jax.nn.relu(_scale_bias(_conv(x, p["conv1"]), p["bn1"]))
    out = jax.nn.relu(_scale_bias(_conv(out, p["conv2"], stride=stride), p["bn2"]))
    out = _scale_bias(_conv(out, p["conv3"]), p["bn3"])
    if "proj" in p:
        x = _scale_bias(_conv(x, p["proj"], stride=stride), p["proj_bn"])
    return jax.nn.relu(out + x)


def forward(params: dict, images: jax.Array, cfg: ResNetConfig) -> jax.Array:
    """images [B,H,W,3] float -> logits [B,num_classes] float32."""
    x = _scale_bias(_conv(images, params["stem"]["conv"], stride=2), params["stem"]["bn"])
    x = jax.nn.relu(x)
    x = lax.reduce_window(
        x,
        -jnp.inf,
        lax.max,
        window_dimensions=(1, 3, 3, 1),
        window_strides=(1, 2, 2, 1),
        padding=((0, 0), (1, 1), (1, 1), (0, 0)),
    )
    for si, stage in enumerate(params["stages"]):
        for bi, block in enumerate(stage):
            x = _bottleneck(x, block, _block_stride(si, bi))
    x = jnp.mean(x, axis=(1, 2))  # global average pool
    logits = x @ params["fc"]["w"].astype(x.dtype) + params["fc"]["b"].astype(x.dtype)
    return logits.astype(jnp.float32)


def param_logical_axes(params: dict):
    """Conv weights replicated (ResNet-50 fits on one chip; DP over batch);
    only the FC layer is worth sharding at vocab-scale widths, left whole."""
    return jax.tree.map(lambda _: None, params)
