"""Token sampling (temperature / top-k / top-p), jit-friendly and batched.

The reference serves stateless predictors and has no notion of decoding at
all (SURVEY §2.3: no model code); a first-party text-gen data plane needs
the standard sampling controls.  Everything here is shape-static and traced
once: per-row parameters are ARRAYS (``[B]``), so one compiled program
serves every request mix — greedy rows, hot-temperature rows, and top-p
rows decode together in the same continuous batch.

Conventions (per row):
- ``temperature <= 0``  → greedy argmax (the sampling path is still
  computed — the MXU does not care — and discarded by a ``where``);
- ``top_k <= 0``        → k filtering disabled;
- ``top_p >= 1``        → nucleus filtering disabled.

Filtering happens in sorted-logit space: one descending sort per row, a
rank mask (top-k) AND an exclusive-cumulative-probability mask (top-p,
"smallest set whose mass >= p" — the first token is always kept), then a
categorical draw over the surviving logits mapped back through the sort
permutation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def sample_logits(
    logits: jax.Array,
    keys: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    top_p: jax.Array,
) -> jax.Array:
    """Draw one token per row.

    logits ``[B, V]`` (any float dtype); keys ``[B]`` typed PRNG keys;
    temperature/top_p float ``[B]``; top_k int32 ``[B]``.
    Returns int32 ``[B]``.
    """
    b, v = logits.shape
    logits = logits.astype(jnp.float32)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # Scale: clamp temperature away from zero — greedy rows take the
    # argmax branch below, this only keeps the math finite.
    temp = jnp.maximum(temperature.astype(jnp.float32), 1e-6)[:, None]
    scaled = logits / temp

    order = jnp.argsort(-scaled, axis=-1)  # descending
    sorted_logits = jnp.take_along_axis(scaled, order, axis=-1)

    ranks = jnp.arange(v)[None, :]
    k = jnp.where(top_k <= 0, v, top_k).astype(jnp.int32)[:, None]
    keep_k = ranks < k

    probs = jax.nn.softmax(sorted_logits, axis=-1)
    # Exclusive cumsum: keep tokens while the mass BEFORE them is < p, so
    # the smallest prefix reaching p survives (first token always kept).
    cum_before = jnp.cumsum(probs, axis=-1) - probs
    p = jnp.clip(top_p.astype(jnp.float32), 0.0, 1.0)[:, None]
    keep_p = cum_before < p

    masked = jnp.where(keep_k & keep_p, sorted_logits, _NEG_INF)

    def draw(key, row):
        return jax.random.categorical(key, row)

    choice = jax.vmap(draw)(keys, masked)  # index into sorted order
    sampled_tok = jnp.take_along_axis(
        order, choice[:, None], axis=-1
    )[:, 0].astype(jnp.int32)

    return jnp.where(temperature > 0, sampled_tok, greedy_tok)


def speculative_accept(
    tokens: jax.Array,
    greedy: jax.Array,
    draft_len: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Exact greedy acceptance for self-speculative verify.

    ``tokens`` int32 ``[B, S]``: column 0 is the row's last emitted
    (pending) token, columns ``1..S-1`` the drafted continuation (padded
    past ``draft_len``).  ``greedy`` int32 ``[B, S]`` is the verify
    forward's argmax at each position — ``greedy[:, j]`` is the model's
    true next token AFTER ``tokens[:, j]``.  ``draft_len`` int32 ``[B]``
    caps acceptance at each row's REAL draft count (padding can match by
    coincidence, but a matching token is by definition the greedy token —
    the cap only exists so rows never accept positions they did not
    propose, e.g. when their remaining-token budget is short).

    Returns ``(accepted, next_token)``: ``accepted[i]`` in
    ``[0, draft_len[i]]`` is the longest draft prefix that agrees with
    greedy argmax, and ``next_token[i] = greedy[i, accepted[i]]`` is the
    bonus token — emitted tokens are the accepted drafts plus this one,
    so every verify yields at least one token (never slower in tokens
    per forward than the plain step).  Greedy-exact by construction:
    accepted tokens ARE the argmax chain the non-speculative path would
    have produced.
    """
    b, s = tokens.shape
    if s == 1:
        return jnp.zeros((b,), jnp.int32), greedy[:, 0]
    match = (tokens[:, 1:] == greedy[:, :-1]).astype(jnp.int32)  # [B, S-1]
    prefix = jnp.cumprod(match, axis=-1)
    in_budget = (jnp.arange(s - 1)[None, :] < draft_len[:, None]).astype(
        jnp.int32
    )
    accepted = jnp.sum(prefix * in_budget, axis=-1).astype(jnp.int32)
    nxt = jnp.take_along_axis(greedy, accepted[:, None], axis=1)[:, 0]
    return accepted, nxt.astype(jnp.int32)


def split_keys(keys: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Advance a batch of per-row PRNG keys: returns (carry, use)."""
    pairs = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
    return pairs[:, 0], pairs[:, 1]


def sample_chain_step(
    logits: jax.Array,
    keys: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    top_p: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """One on-device sampling step usable as a ``lax.scan`` body stage.

    Advances EVERY row's key chain and draws one token per row — the
    exact key discipline of the engine's single-step sampling tick
    (``split_keys`` then :func:`sample_logits` over all rows, greedy
    rows discarding the draw), so a fused K-step decode scan that calls
    this once per step reproduces the step-by-step token stream
    token-for-token, seeded sampling included.

    Returns ``(carry_keys, tokens)``: thread ``carry_keys`` into the
    next step's call.
    """
    carry, use = split_keys(keys)
    return carry, sample_logits(logits, use, temperature, top_k, top_p)
