"""Token sampling (temperature / top-k / top-p), jit-friendly and batched.

The reference serves stateless predictors and has no notion of decoding at
all (SURVEY §2.3: no model code); a first-party text-gen data plane needs
the standard sampling controls.  Everything here is shape-static and traced
once: per-row parameters are ARRAYS (``[B]``), so one compiled program
serves every request mix — greedy rows, hot-temperature rows, and top-p
rows decode together in the same continuous batch.

Conventions (per row):
- ``temperature <= 0``  → greedy argmax (the sampling path is still
  computed — the MXU does not care — and discarded by a ``where``);
- ``top_k <= 0``        → k filtering disabled;
- ``top_p >= 1``        → nucleus filtering disabled.

Filtering happens in sorted-logit space: one descending sort per row, a
rank mask (top-k) AND an exclusive-cumulative-probability mask (top-p,
"smallest set whose mass >= p" — the first token is always kept), then a
categorical draw over the surviving logits mapped back through the sort
permutation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def sample_logits(
    logits: jax.Array,
    keys: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    top_p: jax.Array,
) -> jax.Array:
    """Draw one token per row.

    logits ``[B, V]`` (any float dtype); keys ``[B]`` typed PRNG keys;
    temperature/top_p float ``[B]``; top_k int32 ``[B]``.
    Returns int32 ``[B]``.
    """
    b, v = logits.shape
    logits = logits.astype(jnp.float32)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # Scale: clamp temperature away from zero — greedy rows take the
    # argmax branch below, this only keeps the math finite.
    temp = jnp.maximum(temperature.astype(jnp.float32), 1e-6)[:, None]
    scaled = logits / temp

    order = jnp.argsort(-scaled, axis=-1)  # descending
    sorted_logits = jnp.take_along_axis(scaled, order, axis=-1)

    ranks = jnp.arange(v)[None, :]
    k = jnp.where(top_k <= 0, v, top_k).astype(jnp.int32)[:, None]
    keep_k = ranks < k

    probs = jax.nn.softmax(sorted_logits, axis=-1)
    # Exclusive cumsum: keep tokens while the mass BEFORE them is < p, so
    # the smallest prefix reaching p survives (first token always kept).
    cum_before = jnp.cumsum(probs, axis=-1) - probs
    p = jnp.clip(top_p.astype(jnp.float32), 0.0, 1.0)[:, None]
    keep_p = cum_before < p

    masked = jnp.where(keep_k & keep_p, sorted_logits, _NEG_INF)

    def draw(key, row):
        return jax.random.categorical(key, row)

    choice = jax.vmap(draw)(keys, masked)  # index into sorted order
    sampled_tok = jnp.take_along_axis(
        order, choice[:, None], axis=-1
    )[:, 0].astype(jnp.int32)

    return jnp.where(temperature > 0, sampled_tok, greedy_tok)


def split_keys(keys: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Advance a batch of per-row PRNG keys: returns (carry, use)."""
    pairs = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
    return pairs[:, 0], pairs[:, 1]
