// tpumlops-router — native weighted canary router (data-plane executor).
//
// The reference outsources traffic splitting to Istio + the Seldon
// executor: the operator only writes `traffic:` weights into the
// SeldonDeployment (mlflow_operator.py:205,:220,:322-324) and reads the
// executor's `seldon_api_executor_*` histograms back from Prometheus
// (:367-415).  This binary is the first-party equivalent of that pair for
// the TPU data plane: an HTTP/1.1 reverse proxy that
//
//   * splits traffic between predictor versions by smooth weighted
//     round-robin (nginx algorithm — deterministic, no sampling noise at
//     a 10% canary split, unlike random pick);
//   * accepts live weight updates over `/router/weights` (the operator's
//     promotion loop PUTs here instead of patching an Istio VirtualService);
//   * emits gate-compatible Prometheus text on `/router/metrics`:
//     `seldon_api_executor_client_requests_seconds` +
//     `seldon_api_executor_server_requests_seconds` histograms keyed by
//     {deployment_name, predictor_name, namespace}, so the reference's
//     PromQL (and our judge) reads the router exactly as it read Seldon.
//
// Design: single-threaded epoll event loop, non-blocking sockets,
// keep-alive connection pool per backend.  No third-party dependencies —
// POSIX + libc only.  A single loop saturates far beyond the request
// rates a per-chip predictor sustains (requests are ms-scale TPU batches),
// and it makes weight updates and metric reads race-free by construction.
//
// Protocol support: HTTP/1.1 with Content-Length or chunked bodies in
// both directions (chunked responses are framed-forwarded verbatim).
//
// Build: g++ -O2 -std=c++17 -o tpumlops-router router.cc
// (clients/router.py builds and supervises it; tests/test_router.py
// exercises split ratios, live reweighting, 502s, and the metric surface.)

#include <arpa/inet.h>
#include <cerrno>
#include <cstdarg>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <deque>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Small utilities
// ---------------------------------------------------------------------------

double now_s() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return double(ts.tv_sec) + double(ts.tv_nsec) * 1e-9;
}

double wall_s() {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return double(ts.tv_sec) + double(ts.tv_nsec) * 1e-9;
}

// Process clock anchors (set once in main): journey timestamps export as
// microseconds since g_t0_mono, and started_unix lets the fleet-trace
// stitcher shift router journeys and replica flight-recorder tracks onto
// one unix-epoch timeline.
double g_t0_mono = 0.0;
double g_t0_unix = 0.0;

// splitmix64: mints trace/span ids.  Not cryptographic — the ids only
// need to be collision-unlikely within one trace retention window.
uint64_t g_rng_state = 0;
uint64_t rng_next() {
  uint64_t z = (g_rng_state += 0x9e3779b97f4a7c15ull);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  return z ^ (z >> 31);
}

// n random bytes as 2n lowercase hex chars (8 -> a W3C span id,
// 16 -> a trace id).
std::string hex_id(int nbytes) {
  static const char* kHex = "0123456789abcdef";
  std::string out;
  out.reserve(size_t(nbytes) * 2);
  for (int i = 0; i < nbytes; i += 8) {
    uint64_t v = rng_next();
    for (int b = 0; b < 8 && i + b < nbytes; b++) {
      out += kHex[(v >> 60) & 0xf];
      out += kHex[(v >> 56) & 0xf];
      v <<= 8;
    }
  }
  return out;
}

// JSON string escaping for values that originate outside this process
// (client-supplied request ids and request paths land in /router/debug
// payloads and the access log).  Bytes >= 0x7f are \u-escaped as their
// latin-1 code points: the raw request line can carry arbitrary bytes,
// and one lone UTF-8 continuation byte emitted verbatim would make
// every consumer's json.loads fail for the whole ring.
std::string json_escape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (unsigned char c : s) {
    if (c == '"' || c == '\\') {
      out += '\\';
      out += char(c);
    } else if (c < 0x20 || c >= 0x7f) {
      char buf[8];
      snprintf(buf, sizeof(buf), "\\u%04x", c);
      out += buf;
    } else {
      out += char(c);
    }
  }
  return out;
}

void die(const char* fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  vfprintf(stderr, fmt, ap);
  va_end(ap);
  fputc('\n', stderr);
  exit(1);
}

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

std::string lower(std::string s) {
  for (auto& c : s) c = char(tolower(c));
  return s;
}

// Matches server/metrics.py _LATENCY_BUCKETS (gate-compatible histograms).
const double kBuckets[] = {0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                           0.25,  0.5,    1.0,   2.5,  5.0,   10.0};
constexpr int kNumBuckets = sizeof(kBuckets) / sizeof(kBuckets[0]);

struct Histogram {
  uint64_t bucket_counts[kNumBuckets] = {};
  uint64_t count = 0;
  double sum = 0.0;

  void observe(double v) {
    for (int i = 0; i < kNumBuckets; i++)
      if (v <= kBuckets[i]) bucket_counts[i]++;
    count++;
    sum += v;
  }
};

// ---------------------------------------------------------------------------
// Backend table + smooth weighted round-robin
// ---------------------------------------------------------------------------

// Multi-model multiplexing (--mux-models 1, or a "muxModels" key on
// /router/config): the model id parsed from a POST's /v2/models/<m>/
// path joins the routing decision — requests go only to a backend whose
// attached model matches, park per-model when none does, and the park
// release fires when an attach (a /router/config commit tagging a
// backend with the model) lands, not merely when a weight flips.
// 0 (the default) keeps routing, parking, metrics exposition, and every
// admin body byte-for-byte the single-model router.
int g_mux = 0;

// Model id of a V2 request path ("/v2/models/<m>/generate" -> "<m>");
// "" when the path is not model-scoped.
std::string request_model(const std::string& path) {
  static const std::string pre = "/v2/models/";
  if (path.compare(0, pre.size(), pre) != 0) return "";
  size_t start = pre.size();
  size_t end = path.find('/', start);
  if (end == std::string::npos) end = path.size();
  size_t q = path.find('?', start);
  if (q != std::string::npos && q < end) end = q;
  return path.substr(start, end - start);
}

// ---------------------------------------------------------------------------
// Fleet timeseries ring (--timeseries-ring N): per-backend 1 s history
//
// The journey ring (below) answers "what happened to request X"; the
// timeseries ring answers "how has backend Y behaved over the last N
// seconds" — the router-side twin of the server's /debug/timeseries.
// Each backend keeps a bounded deque of finalized per-second buckets
// (leg count, leg wall p50/p99 ms, error count, failover departures)
// plus one open bucket; a router-level ring counts park admissions the
// same way (a park means NO backend took the request, so it cannot be
// attributed to one).  The operator's anomaly detector compares these
// per-replica leg-latency series across peers — proxy-visible slowness
// (a slow pod, a slow link) shows up here even when the replica's own
// server-side ITL looks healthy.  --timeseries-ring 0 (the default)
// keeps the router byte-for-byte: no buckets, no allocation on the
// request path, 404 on the debug endpoint.
// ---------------------------------------------------------------------------

int g_timeseries_ring = 0;  // --timeseries-ring (0 = ring off)
// A /router/debug scrape serializes every ring on the single-threaded
// event loop (same bound rationale as kMaxJourneyRing), but a bucket is
// tiny (~48 B) so a day of seconds per backend stays a few MiB.
constexpr int kMaxTimeseriesRing = 86400;
// Raw leg walls kept per open bucket: quantiles past the cap are over
// the first kTsSampleCap legs of that second (fixed-memory contract,
// same cap as the server ring's BUCKET_SAMPLE_CAP).
constexpr size_t kTsSampleCap = 256;

struct TsSample {  // one finalized 1 s bucket
  long t = 0;              // unix second
  uint32_t n = 0;          // completed legs
  double p50_ms = 0.0;     // leg wall quantiles (nearest-rank)
  double p99_ms = 0.0;
  uint32_t errors = 0;     // legs that answered >= 500
  uint32_t failovers = 0;  // legs re-dispatched AWAY from this backend
  uint32_t parks = 0;      // router-level ring only: park admissions
};

struct TsRing {
  long open_t = -1;           // unix second of the open bucket (-1 = none)
  std::vector<double> walls;  // capped raw leg walls (seconds)
  TsSample open;              // counters of the open bucket
  std::deque<TsSample> samples;

  TsSample finalize_open() {
    TsSample s = open;
    s.t = open_t;
    std::sort(walls.begin(), walls.end());
    if (!walls.empty()) {
      size_t i50 = std::min(walls.size() - 1, size_t(0.50 * walls.size()));
      size_t i99 = std::min(walls.size() - 1, size_t(0.99 * walls.size()));
      s.p50_ms = walls[i50] * 1e3;
      s.p99_ms = walls[i99] * 1e3;
    }
    return s;
  }

  // Finalize the open bucket once the wall clock leaves its second.
  void roll() {
    long sec = long(wall_s());
    if (open_t < 0) {
      open_t = sec;
      return;
    }
    if (sec <= open_t) return;
    samples.push_back(finalize_open());
    while (int(samples.size()) > g_timeseries_ring) samples.pop_front();
    open_t = sec;
    open = TsSample{};
    walls.clear();
  }

  void observe_leg(double seconds, bool error) {
    roll();
    open.n++;
    if (error) open.errors++;
    if (walls.size() < kTsSampleCap) walls.push_back(seconds);
  }

  void inc_failover() {
    roll();
    open.failovers++;
  }

  void inc_park() {
    roll();
    open.parks++;
  }

  void clear() {
    samples.clear();
    walls.clear();
    open = TsSample{};
    open_t = -1;
  }
};

// Router-level ring: park admissions (no backend took the request).
TsRing g_router_ts;

struct Backend {
  std::string name;  // predictor_name label, e.g. "v3"
  std::string host;
  int port = 0;
  int weight = 0;
  // Multiplexing: model id this replica currently holds ("" = none /
  // unknown).  Set from the config's per-backend "model" key (RouterSync
  // forwards the operator's attach plan); consulted by every pick only
  // while g_mux is on.
  std::string model;
  // Disaggregated-fleet role: "unified" (default) serves everything;
  // "decode" joins the prefix-affinity ring and receives KV imports;
  // "prefill" is EXCLUDED from the general SWRR pick — it serves
  // /admin/kv/export relays only (its chips do prefill, not decode).
  std::string role = "unified";
  int swrr_current = 0;  // smooth-WRR running counter
  // Prefix hashes whose KV this (decode) backend is known to hold —
  // because this router handed it off there.  Bounded; cleared on
  // repoint (a different pod holds nothing we gave its predecessor).
  std::set<uint64_t> known_prefixes;
  sockaddr_in addr{};    // resolved at config time (getaddrinfo)
  uint32_t addr_epoch = 0;  // bumped on repoint; gates pool admission

  // Passive health (--health-probes): consecutive connect/5xx failures
  // trip the circuit, ejecting the backend from the SWRR pick and the
  // affinity ring until a half-open GET /healthz probe (capped
  // exponential interval) answers 200.  All zeroed on repoint — a new
  // pod starts with a clean record.
  int consecutive_failures = 0;
  bool circuit_open = false;
  bool probe_inflight = false;
  int probe_fd = -1;             // in-flight probe socket (-1 = none)
  double probe_deadline_t = 0.0; // when the in-flight probe is declared wedged
  double next_probe_t = 0.0;     // monotonic; earliest next probe
  double probe_interval = 0.0;   // current backoff (doubles, capped)
  uint64_t circuit_open_total = 0;  // times the circuit tripped

  Histogram client_latency;  // client_requests_seconds (predictions only)
  // server_requests_seconds{code=,service=} keyed (code, service): the
  // gate counts errors across services (mlflow_operator.py:375) and
  // feedback volume via service="feedback" (:410-415).
  std::map<std::pair<std::string, std::string>, Histogram> by_code;
  std::vector<int> idle_conns;  // keep-alive pool (fds)
  // Per-second leg history (--timeseries-ring): never touched — zero
  // bytes of samples — with the ring off.
  TsRing ts;
};

// Resolve host:port once at config time (k8s service names and "localhost"
// are valid backend hosts, not just dotted quads).  Config-time resolution
// keeps DNS lookups out of the request path and turns a typo'd host into
// an immediate 400 instead of per-request 502s the gate would read as a
// failing canary.
bool resolve_backend(Backend* b) {
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  char portstr[16];
  snprintf(portstr, sizeof(portstr), "%d", b->port);
  if (getaddrinfo(b->host.c_str(), portstr, &hints, &res) != 0 || !res)
    return false;
  b->addr = *reinterpret_cast<sockaddr_in*>(res->ai_addr);
  freeaddrinfo(res);
  return true;
}

// Backends are shared_ptr so an in-flight request whose backend is removed
// by a concurrent /router/config replace still has a live object to record
// its final latency into (the orphaned histogram is then dropped with the
// last reference — metrics for removed predictors stop being exported,
// matching Seldon executor behavior when a predictor is deleted).
using BackendPtr = std::shared_ptr<Backend>;

// ---------------------------------------------------------------------------
// Failure containment knobs + counters (--health-probes / --failover-retries)
//
// Defaults keep the router byte-for-byte: no circuits, no probes, a dead
// upstream still answers the classic bare 502.  With health probes on, a
// backend accumulating --health-threshold consecutive connect/5xx
// failures is ejected from every pick (SWRR, prefill SWRR, affinity
// ring) and re-admitted only by half-open probing; with failover on, a
// request whose upstream dies before ANY response byte retries on
// another healthy backend (generation has not started — idempotent),
// and exhaustion yields a TYPED 503 {reason: upstream_failed}, never a
// bare 502.
// ---------------------------------------------------------------------------

int g_health_probes = 0;        // --health-probes (0 = off, old behavior)
int g_health_threshold = 3;     // consecutive failures that trip a circuit
double g_probe_interval_s = 0.5;  // half-open probe base interval
int g_failover_retries = 0;     // --failover-retries (0 = old bare-502)
constexpr double kProbeBackoffCap = 8.0;  // interval caps at 8x base
// A probe whose backend accepted the connect but never answers (wedged
// pod, conntrack blackhole) must not hold probe_inflight forever —
// circuit-open backends are excluded from every pick, so no live
// request could ever close the circuit either.  Timed out at
// max(2x base interval, floor); a timeout counts as a failed probe.
constexpr double kProbeTimeoutFloorS = 1.0;
double probe_timeout_s() {
  return std::max(2.0 * g_probe_interval_s, kProbeTimeoutFloorS);
}

uint64_t g_failover_total = 0;  // requests re-dispatched to another backend
Histogram g_probe_seconds;      // half-open probe round-trip walls

// A backend is pickable when it carries weight AND (health probing off,
// or its circuit is closed).  One predicate shared by every pick path
// so the SWRR, the prefill SWRR, the affinity ring, and the park
// release can never disagree about who is alive.
bool backend_usable(const Backend& b) {
  return b.weight > 0 && (!g_health_probes || !b.circuit_open);
}

struct RouterState {
  std::string ns = "default";
  std::string deployment = "router";
  std::vector<BackendPtr> backends;
  uint64_t proxied_total = 0;

  BackendPtr find(const std::string& name) {
    for (auto& b : backends)
      if (b->name == name) return b;
    return nullptr;
  }

  // nginx smooth weighted round-robin: deterministic interleave, exact
  // long-run proportions.  Returns nullptr when all weights are 0 (or,
  // with health probes on, every weighted backend's circuit is open).
  // Prefill-role backends are excluded: they serve KV-export relays,
  // not client traffic (no prefill role configured = old behavior).
  // ``exclude`` (may be null) holds backends already tried by this
  // request's failover budget — shared_ptrs, same lifetime contract as
  // pick_prefill's list.
  // ``model`` (may be null/empty) restricts the pick to backends whose
  // attached model matches — the multiplexing filter; no-op with g_mux
  // off so the single-model interleave is untouched.
  BackendPtr pick(const std::vector<BackendPtr>* exclude = nullptr,
                  const std::string* model = nullptr) {
    BackendPtr best;
    int total = 0;
    for (auto& b : backends) {
      if (!backend_usable(*b) || b->role == "prefill") continue;
      if (g_mux && model && !model->empty() && b->model != *model) continue;
      if (exclude) {
        bool skip = false;
        for (const BackendPtr& e : *exclude)
          if (e == b) skip = true;
        if (skip) continue;
      }
      b->swrr_current += b->weight;
      total += b->weight;
      if (!best || b->swrr_current > best->swrr_current) best = b;
    }
    if (best) best->swrr_current -= total;
    return best;
  }

  // SWRR restricted to prefill-role backends (the relay's export leg).
  // ``exclude`` holds backends already tried this relay (retry budget)
  // — shared_ptrs, so a backend removed by a mid-relay /router/config
  // commit stays alive (and comparable) instead of dangling.
  BackendPtr pick_prefill(const std::vector<BackendPtr>& exclude) {
    BackendPtr best;
    int total = 0;
    for (auto& b : backends) {
      if (!backend_usable(*b) || b->role != "prefill") continue;
      bool skip = false;
      for (const BackendPtr& e : exclude)
        if (e == b) skip = true;
      if (skip) continue;
      b->swrr_current += b->weight;
      total += b->weight;
      if (!best || b->swrr_current > best->swrr_current) best = b;
    }
    if (best) best->swrr_current -= total;
    return best;
  }
};

RouterState g_state;

// ---------------------------------------------------------------------------
// Fleet trace plane: per-request journey records (--journey-ring N)
//
// With the ring enabled the router becomes a first-class trace
// participant: it adopts (or mints) X-Request-Id + a W3C traceparent on
// every inbound request, propagates both on EVERY outbound leg (client
// forward, kv export/import relay legs, failover retries, park-release
// forwards), echoes the id on every response including typed sheds, and
// keeps a bounded ring of JourneyRecords — arrival, affinity decision,
// per-leg backend/bytes/wall, park hold spans, failover attempts,
// circuit state consulted, final outcome — served as
// GET /router/debug/requests (JSON) and GET /router/debug/trace?format=
// chrome (Perfetto: one track per backend, async request spans keyed by
// request id).  --journey-ring 0 (the default) keeps the router
// byte-for-byte: no header minting, no injection, no new metric
// families, 404 on the debug endpoints.
//
// --access-log (independent of the ring) emits one JSON line per
// completed/shed request on stderr, mirroring the server's
// ``tpumlops.request`` logger contract.
// ---------------------------------------------------------------------------

int g_journey_ring = 0;  // --journey-ring (0 = trace plane off)
int g_access_log = 0;    // --access-log 0|1
// Hard cap: a /router/debug scrape serializes the whole ring into one
// response ON the single-threaded event loop, so the ring bound is
// also the bound on how long a debug scrape can stall the data plane
// (64Ki records * ~0.5 KiB ≈ tens of MB worst case, sub-second).
constexpr int kMaxJourneyRing = 1 << 16;

struct JourneyLeg {
  std::string kind;  // forward | export | import | relay-forward
  std::string backend;
  int status = 0;        // 0 = transport failure / never completed
  double t0 = 0.0, t1 = 0.0;  // monotonic; t1 == 0 while in flight
  size_t bytes = 0;      // response bytes observed on this leg
};

struct Journey {
  std::string request_id;
  std::string trace_id;
  double t_arrival = 0.0;    // monotonic
  double wall_arrival = 0.0; // unix epoch
  std::string method, path;
  std::string affinity = "none";  // none | hit | miss | fallback
  std::string model;  // mux: request's model id (field emitted only with mux on)
  int failovers = 0;
  int circuits_open = 0;  // open circuits at dispatch time
  std::string backend;    // backend that produced the final response
  std::string role;
  std::string outcome;    // ok | client_error | upstream_error | shed_* |
                          // bare_502 | abandoned
  int status = 0;
  double handoff_ms = -1.0;  // router-measured KV handoff (-1 = none)
  double park_ms = 0.0;      // cumulative park hold
  double park_t0 = 0.0;      // current park span start (0 = not parked)
  double t_finish = 0.0;
  std::vector<JourneyLeg> legs;
  std::vector<std::pair<double, double>> parks;  // completed hold spans
};

std::deque<Journey> g_journeys;   // bounded by g_journey_ring
uint64_t g_journeys_total = 0;    // lifetime completions (rotation visible)
// tpumlops_router_request_seconds{outcome=...}: per-outcome wall from
// request receipt to final byte handed to the client.  Families appear
// in /router/metrics only with the journey ring on.
std::map<std::string, Histogram> g_request_seconds;

bool journey_tracking() { return g_journey_ring > 0 || g_access_log; }

// Inbound identity, mirroring server/app.py request_id_from_headers:
// X-Request-Id verbatim (printable ASCII, <= 128 chars), else the
// traceparent trace id, else minted.  Bytes >= 0x80 are dropped, not
// kept: the id lands in JSON exports that must stay valid UTF-8, and a
// lone continuation byte would make json.loads on /router/debug/*
// (and the fleet stitcher behind it) fail for the whole ring.
std::string sanitize_rid(const std::string& raw) {
  std::string out;
  for (char c : raw) {
    if (out.size() >= 128) break;
    // Space included — the server's rule keeps it, and the router's
    // access log must record the same id the replica journals.
    if ((unsigned char)c >= 0x20 && (unsigned char)c < 0x7f) out += c;
  }
  return out;
}

bool is_hex(const std::string& s) {
  for (char c : s)
    if (!isxdigit((unsigned char)c)) return false;
  return !s.empty();
}

// version-traceid-spanid-flags; returns false unless every field has the
// exact W3C width.
bool parse_traceparent(const std::string& tp, std::string* trace_id) {
  if (tp.size() < 55 || tp[2] != '-' || tp[35] != '-' || tp[52] != '-')
    return false;
  std::string tid = lower(tp.substr(3, 32));
  if (!is_hex(tid) || tid == std::string(32, '0')) return false;
  *trace_id = tid;
  return true;
}

// ---------------------------------------------------------------------------
// Prefix affinity: consistent-hash ring over decode-role backends
//
// The router hashes the first --affinity-tokens prompt_ids of a
// /generate request and maps the hash onto a ring of virtual nodes, so
// a repeated template prefix lands on the decode replica that already
// holds its KV — cache hit rate survives scale-out instead of diluting
// 1/N.  --affinity-tokens 0 (default) disables everything here:
// routing, relays, and metrics stay byte-for-byte the old router.
// ---------------------------------------------------------------------------

int g_affinity_tokens = 0;   // leading prompt ids hashed (0 = disabled)
int g_handoff_enabled = 1;   // --kv-handoff 0 disables the relay leg
int g_handoff_retries = 1;   // prefill replicas tried per cold prompt
constexpr size_t kMaxKnownPrefixes = 4096;  // per decode backend

uint64_t g_affinity_hits = 0;
uint64_t g_affinity_misses = 0;
uint64_t g_kv_handoff_bytes = 0;
uint64_t g_kv_handoff_failures = 0;
Histogram g_kv_handoff_seconds;

constexpr int kRingVnodes = 32;  // virtual nodes per decode backend
std::vector<std::pair<uint64_t, Backend*>> g_ring;  // sorted by hash

uint64_t fnv1a(const void* data, size_t n, uint64_t h = 1469598103934665603ull) {
  const unsigned char* p = static_cast<const unsigned char*>(data);
  for (size_t i = 0; i < n; i++) {
    h ^= p[i];
    h *= 1099511628211ull;
  }
  return h;
}

// Rebuilt on every config commit (the only place backends are added or
// removed, so the raw pointers can never dangle).
void rebuild_ring() {
  g_ring.clear();
  for (auto& b : g_state.backends) {
    if (b->role != "decode") continue;
    for (int i = 0; i < kRingVnodes; i++) {
      std::string vnode = b->name + "#" + std::to_string(i);
      g_ring.push_back({fnv1a(vnode.data(), vnode.size()), b.get()});
    }
  }
  std::sort(g_ring.begin(), g_ring.end());
}

// First clockwise ring entry that is usable (consistent hashing:
// adding/removing one replica remaps only its arc, so most repeat
// prefixes keep landing where their KV lives).  A circuit-open backend
// is skipped exactly like a weight-0 one — its keys re-hash to the
// survivors until half-open probing re-admits it.
BackendPtr pick_decode(uint64_t h) {
  if (g_ring.empty()) return nullptr;
  auto it = std::lower_bound(
      g_ring.begin(), g_ring.end(), std::make_pair(h, (Backend*)nullptr));
  for (size_t i = 0; i < g_ring.size(); i++) {
    if (it == g_ring.end()) it = g_ring.begin();
    Backend* b = it->second;
    if (backend_usable(*b)) return g_state.find(b->name);
    ++it;
  }
  return nullptr;
}

// Extract up to g_affinity_tokens leading integers of the request's
// "prompt_ids" (first sequence when nested) and FNV-1a them.  Returns
// false when the body carries no parseable prompt — the request then
// routes through the plain SWRR pick.
bool affinity_hash(const std::string& body, uint64_t* out) {
  size_t pos = body.find("\"prompt_ids\"");
  if (pos == std::string::npos) return false;
  pos = body.find(':', pos);
  if (pos == std::string::npos) return false;
  pos = body.find('[', pos);
  if (pos == std::string::npos) return false;
  pos++;
  // Nested form [[...]]: step into the first row.
  while (pos < body.size() &&
         (body[pos] == ' ' || body[pos] == '\n' || body[pos] == '\t'))
    pos++;
  if (pos < body.size() && body[pos] == '[') pos++;
  uint64_t h = 1469598103934665603ull;
  int count = 0;
  while (pos < body.size() && count < g_affinity_tokens) {
    while (pos < body.size() &&
           (body[pos] == ',' || body[pos] == ' ' || body[pos] == '\n' ||
            body[pos] == '\t'))
      pos++;
    if (pos >= body.size() || body[pos] == ']') break;
    char* end = nullptr;
    long v = strtol(body.c_str() + pos, &end, 10);
    if (end == body.c_str() + pos) return false;  // not an integer
    uint64_t le = (uint64_t)v;
    h = fnv1a(&le, sizeof(le), h);
    count++;
    pos = size_t(end - body.c_str());
  }
  if (count == 0) return false;
  *out = h;
  return true;
}

void remember_prefix(const BackendPtr& b, uint64_t h) {
  if (b->known_prefixes.size() >= kMaxKnownPrefixes)
    b->known_prefixes.clear();  // crude bound; repeats re-learn fast
  b->known_prefixes.insert(h);
}

// ---------------------------------------------------------------------------
// Minimal JSON: parse flat {"name": int} maps and the config document
// {"namespace": "...", "deployment": "...",
//  "backends": [{"name": "...", "host": "...", "port": 1, "weight": 1}, ...]}
// Hand-rolled because the only JSON this binary sees is its own admin API.
// ---------------------------------------------------------------------------

struct JsonParser {
  const char* p;
  const char* end;
  bool ok = true;

  explicit JsonParser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) p++;
  }
  bool consume(char c) {
    skip_ws();
    if (p < end && *p == c) {
      p++;
      return true;
    }
    ok = false;
    return false;
  }
  bool peek(char c) {
    skip_ws();
    return p < end && *p == c;
  }
  std::string parse_string() {
    skip_ws();
    std::string out;
    if (p >= end || *p != '"') {
      ok = false;
      return out;
    }
    p++;
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) p++;  // keep escaped char verbatim
      out += *p++;
    }
    if (p < end) p++;  // closing quote
    else ok = false;
    return out;
  }
  double parse_number() {
    skip_ws();
    char* q = nullptr;
    double v = strtod(p, &q);
    if (q == p) ok = false;
    p = q;
    return v;
  }
  // Skip any JSON value (for unknown keys).
  void skip_value() {
    skip_ws();
    if (p >= end) { ok = false; return; }
    if (*p == '"') { parse_string(); return; }
    if (*p == '{' || *p == '[') {
      char open = *p, close = (*p == '{') ? '}' : ']';
      int depth = 0;
      bool in_str = false;
      while (p < end) {
        char c = *p++;
        if (in_str) {
          if (c == '\\' && p < end) p++;
          else if (c == '"') in_str = false;
        } else if (c == '"') in_str = true;
        else if (c == open) depth++;
        else if (c == close && --depth == 0) return;
      }
      ok = false;
      return;
    }
    while (p < end && *p != ',' && *p != '}' && *p != ']') p++;
  }
};

bool parse_weights(const std::string& body, std::map<std::string, int>* out) {
  JsonParser j(body);
  if (!j.consume('{')) return false;
  if (j.peek('}')) { j.consume('}'); return j.ok; }
  while (j.ok) {
    std::string key = j.parse_string();
    if (!j.consume(':')) break;
    int w = int(j.parse_number());
    if (!j.ok) break;
    (*out)[key] = w;
    if (j.peek(',')) { j.consume(','); continue; }
    j.consume('}');
    break;
  }
  return j.ok;
}

struct BackendSpec {
  std::string name, host;
  int port = 0, weight = 0;
  std::string role;  // "" = keep survivor's role (or "unified")
  std::string model;      // mux: attached model id ("" + model_set = detach)
  bool model_set = false; // absent key = keep the survivor's model
};

bool parse_config(const std::string& body, std::string* ns, std::string* dep,
                  std::vector<BackendSpec>* specs,
                  int* journey_ring = nullptr, int* mux_models = nullptr,
                  int* timeseries_ring = nullptr) {
  JsonParser j(body);
  if (!j.consume('{')) return false;
  while (j.ok && !j.peek('}')) {
    std::string key = j.parse_string();
    if (!j.consume(':')) return false;
    if (key == "namespace") *ns = j.parse_string();
    else if (key == "deployment") *dep = j.parse_string();
    else if (key == "journeyRing") {
      // Range-check as a DOUBLE before casting: int(out-of-range
      // double) is UB, and a negative/overflowing value must become a
      // visible 400 (-2 sentinel), never a silent no-op 200.
      double v = j.parse_number();
      if (journey_ring)
        *journey_ring =
            (v < 0 || v > double(kMaxJourneyRing)) ? -2 : int(v);
    }
    else if (key == "timeseriesRing") {
      // Same range-check-as-double rationale as journeyRing.
      double v = j.parse_number();
      if (timeseries_ring)
        *timeseries_ring =
            (v < 0 || v > double(kMaxTimeseriesRing)) ? -2 : int(v);
    }
    else if (key == "muxModels") {
      // Same always-sent contract as journeyRing: RouterSync forwards
      // the manifest's tpumlops.dev/mux-models annotation (absent = 0)
      // so disabling multiplexing on the CR actually disables it here.
      double v = j.parse_number();
      if (mux_models) *mux_models = (v < 0 || v > 1) ? -2 : int(v);
    }
    else if (key == "backends") {
      if (!j.consume('[')) return false;
      while (j.ok && !j.peek(']')) {
        if (!j.consume('{')) return false;
        BackendSpec s;
        while (j.ok && !j.peek('}')) {
          std::string k2 = j.parse_string();
          if (!j.consume(':')) return false;
          if (k2 == "name") s.name = j.parse_string();
          else if (k2 == "host") s.host = j.parse_string();
          else if (k2 == "port") s.port = int(j.parse_number());
          else if (k2 == "weight") s.weight = int(j.parse_number());
          else if (k2 == "role") s.role = j.parse_string();
          else if (k2 == "model") { s.model = j.parse_string(); s.model_set = true; }
          else j.skip_value();
          if (j.peek(',')) j.consume(',');
        }
        j.consume('}');
        specs->push_back(s);
        if (j.peek(',')) j.consume(',');
      }
      j.consume(']');
    } else {
      j.skip_value();
    }
    if (j.peek(',')) j.consume(',');
  }
  j.consume('}');
  return j.ok;
}

// ---------------------------------------------------------------------------
// HTTP message framing
// ---------------------------------------------------------------------------

// Hard caps: a single misbehaving local client (or backend) must not be
// able to balloon the router's RSS — the router fronts EVERY predictor, so
// an OOM kill here takes down the whole data plane.
constexpr size_t kMaxHeaderBytes = 1 << 20;        // 1 MiB of headers
constexpr size_t kMaxMessageBytes = 64u << 20;     // 64 MiB framed message

// Incrementally-parsed HTTP/1.1 message (request or response).
struct HttpMsg {
  std::string buf;         // raw bytes accumulated so far
  size_t header_end = 0;   // offset just past "\r\n\r\n" (0 = headers incomplete)
  // parsed request fields
  std::string method, path, version;
  int status = 0;             // for responses
  std::string request_method;  // for responses: method that elicited this
  std::unordered_map<std::string, std::string> headers;  // lowercased keys
  ssize_t content_length = -1;  // -1 = absent
  bool chunked = false;
  size_t body_start = 0;

  bool headers_complete() const { return header_end != 0; }

  // Returns false on malformed input.
  bool try_parse_headers(bool is_request) {
    size_t pos = buf.find("\r\n\r\n");
    if (pos == std::string::npos) return true;  // need more bytes
    header_end = pos + 4;
    body_start = header_end;

    size_t line_end = buf.find("\r\n");
    std::string start_line = buf.substr(0, line_end);
    if (is_request) {
      size_t sp1 = start_line.find(' ');
      size_t sp2 = start_line.rfind(' ');
      if (sp1 == std::string::npos || sp2 == sp1) return false;
      method = start_line.substr(0, sp1);
      path = start_line.substr(sp1 + 1, sp2 - sp1 - 1);
      version = start_line.substr(sp2 + 1);
    } else {
      size_t sp1 = start_line.find(' ');
      if (sp1 == std::string::npos) return false;
      version = start_line.substr(0, sp1);
      status = atoi(start_line.c_str() + sp1 + 1);
    }

    size_t cur = line_end + 2;
    while (cur < pos) {
      size_t eol = buf.find("\r\n", cur);
      if (eol == std::string::npos || eol > pos) break;
      std::string line = buf.substr(cur, eol - cur);
      size_t colon = line.find(':');
      if (colon != std::string::npos) {
        std::string k = lower(line.substr(0, colon));
        size_t v0 = colon + 1;
        while (v0 < line.size() && line[v0] == ' ') v0++;
        headers[k] = line.substr(v0);
      }
      cur = eol + 2;
    }
    auto it = headers.find("content-length");
    if (it != headers.end()) content_length = atoll(it->second.c_str());
    it = headers.find("transfer-encoding");
    if (it != headers.end() && lower(it->second).find("chunked") != std::string::npos)
      chunked = true;
    return true;
  }

  // Offset one past the end of the framed message, or -1 while incomplete.
  // `eof` marks peer close (terminates close-delimited response bodies).
  // Bytes past this offset belong to the NEXT message on the connection
  // (keep-alive clients may send request N+1 early) and must not be
  // forwarded as part of this one.
  ssize_t message_end(bool is_request, bool eof) const {
    if (!headers_complete()) return -1;
    if (!is_request &&
        (status == 204 || status == 304 || (status >= 100 && status < 200) ||
         request_method == "HEAD")) {
      // RFC 7230 §3.3.3: these responses carry no body regardless of
      // Content-Length/Transfer-Encoding headers (a HEAD response
      // advertises the length the GET would have had).
      return ssize_t(body_start);
    }
    if (chunked) {
      // Scan chunk frames from body_start.
      size_t pos = body_start;
      while (true) {
        size_t eol = buf.find("\r\n", pos);
        if (eol == std::string::npos) return -1;
        long sz = strtol(buf.c_str() + pos, nullptr, 16);
        size_t data = eol + 2;
        if (sz == 0) {
          // terminator: "0\r\n\r\n", or trailers ending in a blank line
          size_t term = buf.find("\r\n\r\n", eol);
          if (term != std::string::npos) return ssize_t(term + 4);
          return -1;
        }
        pos = data + size_t(sz) + 2;  // skip data + CRLF
        if (pos > buf.size()) return -1;
      }
    }
    if (content_length >= 0) {
      size_t end = body_start + size_t(content_length);
      return buf.size() >= end ? ssize_t(end) : -1;
    }
    if (is_request) return ssize_t(body_start);  // request without body
    return eof ? ssize_t(buf.size()) : -1;       // close-delimited response
  }

  bool complete(bool is_request, bool eof) const {
    return message_end(is_request, eof) >= 0;
  }

  void reset() { *this = HttpMsg(); }
};

std::string http_response(int code, const std::string& reason,
                          const std::string& content_type,
                          const std::string& body,
                          const std::string& extra_headers = "") {
  char head[768];
  snprintf(head, sizeof(head),
           "HTTP/1.1 %d %s\r\nContent-Type: %s\r\nContent-Length: %zu\r\n"
           "Connection: keep-alive\r\n%s\r\n",
           code, reason.c_str(), content_type.c_str(), body.size(),
           extra_headers.c_str());
  return std::string(head) + body;
}

// ---------------------------------------------------------------------------
// Connection state machines
// ---------------------------------------------------------------------------

enum class FdKind { Listener, Client, Upstream };

struct ClientConn;

struct UpstreamConn {
  int fd = -1;
  BackendPtr backend;
  uint32_t addr_epoch = 0;       // backend->addr_epoch at connect time
  ClientConn* client = nullptr;  // request being served (null = idle in pool)
  std::string out;               // bytes to write to backend
  size_t out_off = 0;
  HttpMsg resp;
  bool connecting = false;
  bool reused = false;  // taken from the keep-alive pool (stale-retry eligible)
  // Half-open health probe (GET /healthz): no client, never pooled.
  bool probe = false;
  double probe_t0 = 0.0;  // probe dispatch time (monotonic)
};

// KV-handoff relay stages (prefix-affinity miss on a cold prompt):
//   Export  — POST the original body to a prefill backend's
//             /admin/kv/export; the response body is the KV blob.
//   Import  — POST the blob to the chosen decode backend's
//             /admin/kv/import.
//   Forward — the original request to the decode backend, carrying the
//             x-tpumlops-handoff header; response handling is the
//             normal proxy path.
// Any sub-request failure falls back to unified serving: the original
// request forwards to the decode backend WITHOUT a handoff (it holds
// the full model, so nothing is lost — just slower).
enum class RelayStage { None, Export, Import, Forward };

struct ClientConn {
  int fd = -1;
  HttpMsg req;
  std::string pending;  // bytes past the current request (next keep-alive req)
  std::string out;      // bytes to write back to client
  size_t out_off = 0;
  UpstreamConn* upstream = nullptr;
  BackendPtr backend;  // chosen for current request
  double t_start = 0;  // request receipt time
  int retries = 0;     // stale pooled-connection retries this request
  bool closing = false;   // close after out drains
  bool feedback = false;  // current request is /api/v1.0/feedback
  // Multiplexing: model id of the current request ("" = not model-
  // scoped, or mux off).  Drives the model-filtered pick, per-model
  // parking, and the model label on the parked gauge.
  std::string model;
  bool parked = false;    // held in the scale-to-zero park buffer
  double park_t = 0;      // when parking began (monotonic)
  // FIRST park instant of the current request (0 = never parked):
  // survives release/re-park cycles, so the --park-timeout-s bound is
  // CUMULATIVE — a request released to a draining replica that loses
  // its backend and re-parks must not restart the clock (it would hang
  // past the timeout for as long as the weights keep flapping).
  double park_first_t = 0;
  // Before-first-byte failover (--failover-retries): backends already
  // tried by this request.  Same shared_ptr lifetime contract as
  // relay_tried.
  int failover_attempts = 0;
  std::vector<BackendPtr> failover_tried;
  // KV-handoff relay state (RelayStage::None outside a relay).
  RelayStage relay_stage = RelayStage::None;
  BackendPtr relay_decode;   // ring-chosen decode target
  uint64_t relay_hash = 0;   // affinity hash of the prompt prefix
  double relay_t0 = 0;       // handoff start (monotonic)
  int relay_attempts = 0;    // export legs attempted
  std::vector<BackendPtr> relay_tried;  // prefill backends already tried
  std::string relay_out;     // the synthesized sub-request bytes
  size_t relay_blob_bytes = 0;  // exported KV blob size (metrics only —
                                // the blob itself lives in relay_out;
                                // a second copy would hold multi-MB
                                // handoffs 3x per in-flight relay)
  // Fleet trace plane: the current request's journey record (null when
  // tracking is off or the request is a /router/* admin call).  Owned
  // here until journey_finish moves it into the ring.
  Journey* journey = nullptr;
};

// ---------------------------------------------------------------------------
// Journey lifecycle (trace-plane hooks on the proxy state machine)
// ---------------------------------------------------------------------------

// Start tracking a (non-admin) request: adopt or mint identity, note
// the circuit state consulted by this dispatch.
void journey_begin(ClientConn* c, double t_start) {
  delete c->journey;
  c->journey = nullptr;
  if (!journey_tracking()) return;
  auto* j = new Journey();
  j->t_arrival = t_start;
  j->wall_arrival = g_t0_unix + (t_start - g_t0_mono);
  // Bounded copies: the header cap admits ~1 MiB request lines, and a
  // ring of journeys must not pin that per record.
  j->method = c->req.method.substr(0, 16);
  j->path = c->req.path.substr(0, 512);
  auto it = c->req.headers.find("x-request-id");
  std::string rid = it != c->req.headers.end() ? sanitize_rid(it->second) : "";
  std::string tid;
  auto tp = c->req.headers.find("traceparent");
  if (tp != c->req.headers.end()) parse_traceparent(tp->second, &tid);
  if (tid.empty()) tid = hex_id(16);
  if (rid.empty()) rid = tid;
  j->request_id = rid;
  j->trace_id = tid;
  for (auto& b : g_state.backends)
    if (b->circuit_open) j->circuits_open++;
  c->journey = j;
}

// Outbound trace context for one upstream leg: the adopted/minted id
// plus a traceparent carrying the journey's trace id and a FRESH span id
// per leg.  Empty (no wire change) unless the journey ring is on.
std::string trace_headers(const ClientConn* c) {
  if (g_journey_ring <= 0 || !c->journey) return "";
  return "x-request-id: " + c->journey->request_id +
         "\r\ntraceparent: 00-" + c->journey->trace_id + "-" + hex_id(8) +
         "-01\r\n";
}

// "X-Request-Id: <rid>\r\n" for router-generated responses (typed
// sheds, 502s) — empty with the plane off, so those responses stay
// byte-for-byte.
std::string echo_header(const ClientConn* c) {
  if (g_journey_ring <= 0 || !c->journey) return "";
  return "X-Request-Id: " + c->journey->request_id + "\r\n";
}

// ``,"request_id":"<rid>"`` for typed JSON shed bodies (empty = plane
// off).  Spliced before the closing brace by callers.
std::string rid_json_field(const ClientConn* c) {
  if (g_journey_ring <= 0 || !c->journey) return "";
  return ",\"request_id\":\"" + json_escape(c->journey->request_id) + "\"";
}

void journey_leg_start(ClientConn* c, const BackendPtr& b) {
  if (!c->journey) return;
  JourneyLeg leg;
  switch (c->relay_stage) {
    case RelayStage::Export:
      leg.kind = "export";
      break;
    case RelayStage::Import:
      leg.kind = "import";
      break;
    case RelayStage::Forward:
      leg.kind = "relay-forward";
      break;
    default:
      leg.kind = "forward";
      break;
  }
  leg.backend = b ? b->name : "";
  leg.t0 = now_s();
  c->journey->legs.push_back(std::move(leg));
}

// Close the newest open leg (status 0 = transport failure).
void journey_leg_done(ClientConn* c, int status, size_t bytes) {
  if (!c->journey) return;
  for (auto it = c->journey->legs.rbegin(); it != c->journey->legs.rend();
       ++it) {
    if (it->t1 == 0.0) {
      it->status = status;
      it->bytes = bytes;
      it->t1 = now_s();
      return;
    }
  }
}

void journey_park_begin(ClientConn* c) {
  if (c->journey && c->journey->park_t0 == 0.0)
    c->journey->park_t0 = now_s();
}

void journey_park_end(ClientConn* c) {
  if (!c->journey || c->journey->park_t0 == 0.0) return;
  double t1 = now_s();
  c->journey->parks.push_back({c->journey->park_t0, t1});
  c->journey->park_ms += (t1 - c->journey->park_t0) * 1000.0;
  c->journey->park_t0 = 0.0;
}

// One journey is over: classify, observe, retain, log, free.
void journey_finish(ClientConn* c, int status, const char* outcome) {
  if (!c->journey) return;
  Journey* j = c->journey;
  c->journey = nullptr;
  if (j->park_t0 != 0.0) {
    double t1 = now_s();
    j->parks.push_back({j->park_t0, t1});
    j->park_ms += (t1 - j->park_t0) * 1000.0;
    j->park_t0 = 0.0;
  }
  j->status = status;
  j->outcome = outcome;
  j->t_finish = now_s();
  double dur = j->t_finish - j->t_arrival;
  if (g_journey_ring > 0) {
    g_request_seconds[j->outcome].observe(dur);
    g_journeys_total++;
    g_journeys.push_back(*j);
    while (int(g_journeys.size()) > g_journey_ring) g_journeys.pop_front();
  }
  if (g_access_log) {
    // One JSON object per line on stderr — the same field contract as
    // the server's ``tpumlops.request`` completion line.
    fprintf(stderr,
            "{\"logger\":\"tpumlops.router.access\","
            "\"request_id\":\"%s\",\"trace_id\":\"%s\","
            "\"method\":\"%s\",\"path\":\"%s\","
            "\"backend\":\"%s\",\"role\":\"%s\","
            "\"outcome\":\"%s\",\"code\":%d,"
            "\"duration_ms\":%.3f,\"handoff_ms\":%.3f,"
            "\"park_ms\":%.3f,\"failover_count\":%d,"
            "\"affinity\":\"%s\"}\n",
            json_escape(j->request_id).c_str(),
            json_escape(j->trace_id).c_str(),
            json_escape(j->method).c_str(), json_escape(j->path).c_str(),
            json_escape(j->backend).c_str(), json_escape(j->role).c_str(),
            j->outcome.c_str(), j->status, dur * 1000.0,
            j->handoff_ms < 0 ? 0.0 : j->handoff_ms, j->park_ms,
            j->failovers, j->affinity.c_str());
  }
  delete j;
}

const char* outcome_for_status(int status) {
  if (status >= 200 && status < 400) return "ok";
  if (status >= 400 && status < 500) return "client_error";
  return "upstream_error";
}

// Inject "x-request-id: <rid>" into a fully-buffered upstream response
// whose headers lack it, so every byte the client sees carries the
// correlatable id even when the backend does not echo.
void ensure_response_request_id(std::string* resp, const std::string& rid) {
  size_t hdr_end = resp->find("\r\n\r\n");
  size_t line_end = resp->find("\r\n");
  if (hdr_end == std::string::npos || line_end == std::string::npos) return;
  std::string head = lower(resp->substr(0, hdr_end + 2));
  if (head.find("\r\nx-request-id:") != std::string::npos) return;
  resp->insert(line_end + 2, "x-request-id: " + rid + "\r\n");
}

// ---------------------------------------------------------------------------
// Scale-to-zero request parking
//
// When every backend's weight is 0 — the operator parked the CR's
// Deployment at zero replicas — incoming requests are HELD (bounded
// buffer, FIFO) instead of 503'd: the park count is the operator's wake
// signal, and once capacity returns (a weight flips positive via
// /router/weights or /router/config) the queue releases in arrival
// order.  Overflow and timeout get a TYPED 503 + Retry-After so clients
// know to back off, not fail.  --park-buffer 0 (default) preserves the
// old immediate-503 behavior byte-for-byte.
// ---------------------------------------------------------------------------

int g_park_max = 0;             // --park-buffer (0 = parking disabled)
double g_park_timeout_s = 30.0; // --park-timeout-s
std::vector<ClientConn*> g_parked;  // FIFO arrival order
uint64_t g_parked_total = 0;        // ever parked
uint64_t g_park_released_total = 0; // released to a live backend
uint64_t g_park_overflow_total = 0; // 503'd: buffer full
uint64_t g_park_timeout_total = 0;  // 503'd: waited past the timeout
Histogram g_park_wait_seconds;      // park duration of released requests

std::string park_503_body(const char* why, int retry_after_s,
                          const ClientConn* c = nullptr) {
  // std::string assembly: the escaped request id can reach ~256 bytes
  // (128 chars of '"'/'\\'), which would truncate a fixed buffer into
  // an unparseable typed body.
  std::string body = "{\"error\":\"no live backend\",\"reason\":\"" +
                     std::string(why) + "\",\"retry_after_s\":" +
                     std::to_string(retry_after_s) +
                     (c ? rid_json_field(c) : "") + "}";
  std::string hdr = "Retry-After: " + std::to_string(retry_after_s) +
                    "\r\n" + (c ? echo_header(c) : "");
  return http_response(503, "Service Unavailable", "application/json", body,
                       hdr);
}

void unpark(ClientConn* c) {
  c->parked = false;
  for (auto it = g_parked.begin(); it != g_parked.end(); ++it)
    if (*it == c) {
      g_parked.erase(it);
      break;
    }
}

// ---------------------------------------------------------------------------
// Passive backend health (circuit breaking)
// ---------------------------------------------------------------------------

void release_parked();  // defined with the proxy path below

void reset_swrr() {
  // Membership of the pick set changed: restart the interleave so the
  // new split takes effect cleanly (same rule as /router/weights).
  for (auto& b : g_state.backends) b->swrr_current = 0;
}

// One connect/5xx failure observed against ``b``.  Trips the circuit at
// the threshold: ejected from every pick, first half-open probe due
// after the base interval.
void note_backend_failure(const BackendPtr& b) {
  if (!g_health_probes || !b) return;
  b->consecutive_failures++;
  if (!b->circuit_open && b->consecutive_failures >= g_health_threshold) {
    b->circuit_open = true;
    b->circuit_open_total++;
    b->probe_interval = g_probe_interval_s;
    b->next_probe_t = now_s() + b->probe_interval;
    reset_swrr();
    fprintf(stderr,
            "tpumlops-router: circuit OPEN for backend %s (%d consecutive "
            "failures); half-open probes every %.2fs (capped x%g)\n",
            b->name.c_str(), b->consecutive_failures, b->probe_interval,
            kProbeBackoffCap);
  }
}

// A healthy response observed against ``b``: clears the failure streak,
// and — if an in-flight request beat the prober to the recovery — closes
// the circuit early.
void note_backend_success(const BackendPtr& b) {
  if (!g_health_probes || !b) return;
  b->consecutive_failures = 0;
  if (b->circuit_open) {
    b->circuit_open = false;
    b->probe_interval = 0.0;
    reset_swrr();
    fprintf(stderr,
            "tpumlops-router: circuit CLOSED for backend %s (live response)\n",
            b->name.c_str());
    release_parked();
  }
}

bool any_circuit_open() {
  if (!g_health_probes) return false;
  for (auto& b : g_state.backends)
    if (b->circuit_open || b->probe_inflight) return true;
  return false;
}

// Any backend a client pick could ever return once circuits recover —
// decides park-vs-shed when no backend is usable right now.
bool any_weighted_client_backend() {
  for (auto& b : g_state.backends)
    if (b->weight > 0 && b->role != "prefill") return true;
  return false;
}

struct FdEntry {
  FdKind kind;
  ClientConn* client = nullptr;
  UpstreamConn* upstream = nullptr;
  uint32_t gen = 0;  // registration generation (stale-event guard)
};

int g_epoll = -1;
std::unordered_map<int, FdEntry> g_fds;
uint32_t g_gen = 0;

// Events carry (generation << 32 | fd).  Within one epoll_wait batch an
// earlier event can close an fd whose number the kernel immediately
// recycles for a new connection; a still-queued event for the OLD socket
// must not be delivered to the NEW one.  The generation check in the main
// loop drops such stale events.
uint64_t event_key(int fd) { return (uint64_t(g_fds[fd].gen) << 32) | uint32_t(fd); }

void epoll_set(int fd, uint32_t events) {
  epoll_event ev{};
  ev.events = events;
  ev.data.u64 = event_key(fd);
  epoll_ctl(g_epoll, EPOLL_CTL_MOD, fd, &ev);
}

// Registers fd (caller must have inserted its g_fds entry already).
void epoll_add(int fd, uint32_t events) {
  g_fds[fd].gen = ++g_gen;
  epoll_event ev{};
  ev.events = events;
  ev.data.u64 = event_key(fd);
  epoll_ctl(g_epoll, EPOLL_CTL_ADD, fd, &ev);
}

void close_upstream(UpstreamConn* u) {
  if (!u) return;
  if (u->fd >= 0) {
    // Scrub the fd from its backend's keep-alive pool: a closed fd number
    // is recycled by the kernel, and a stale pool entry would alias the
    // next connection that happens to get the same number.
    if (u->backend) {
      auto& pool = u->backend->idle_conns;
      for (auto it = pool.begin(); it != pool.end(); ++it)
        if (*it == u->fd) {
          pool.erase(it);
          break;
        }
    }
    epoll_ctl(g_epoll, EPOLL_CTL_DEL, u->fd, nullptr);
    g_fds.erase(u->fd);
    close(u->fd);
  }
  delete u;
}

void close_client(ClientConn* c) {
  if (!c) return;
  // A journey still open here means the client vanished mid-flight
  // (disconnect, EPOLLERR): record the abandonment rather than leak it.
  journey_finish(c, 499, "abandoned");
  if (c->parked) unpark(c);  // a gone client must not be "released" later
  if (c->upstream) {
    c->upstream->client = nullptr;
    close_upstream(c->upstream);
    c->upstream = nullptr;
  }
  if (c->fd >= 0) {
    epoll_ctl(g_epoll, EPOLL_CTL_DEL, c->fd, nullptr);
    g_fds.erase(c->fd);
    close(c->fd);
  }
  delete c;
}

void client_send(ClientConn* c, const std::string& data) {
  c->out += data;
  epoll_set(c->fd, EPOLLIN | EPOLLOUT);
}

// ---------------------------------------------------------------------------
// Half-open recovery probes (GET /healthz against circuit-open backends)
// ---------------------------------------------------------------------------

void probe_done(UpstreamConn* u, bool ok) {
  BackendPtr b = u->backend;
  uint32_t probe_epoch = u->addr_epoch;
  g_probe_seconds.observe(now_s() - u->probe_t0);
  close_upstream(u);
  if (!b) return;
  b->probe_inflight = false;
  b->probe_fd = -1;
  if (probe_epoch != b->addr_epoch) return;  // repointed mid-probe: the
                                             // answer describes the OLD pod
  if (ok) {
    b->circuit_open = false;
    b->consecutive_failures = 0;
    b->probe_interval = 0.0;
    reset_swrr();
    fprintf(stderr,
            "tpumlops-router: circuit CLOSED for backend %s (healthz probe "
            "answered 200)\n",
            b->name.c_str());
    // Capacity may just have returned to a fully-tripped fleet.
    release_parked();
  } else {
    // Capped exponential backoff: a dead pod is probed gently, a
    // restarting one is re-admitted within 2x the current interval.
    b->probe_interval =
        std::min(b->probe_interval * 2.0, g_probe_interval_s * kProbeBackoffCap);
    if (b->probe_interval <= 0.0) b->probe_interval = g_probe_interval_s;
    b->next_probe_t = now_s() + b->probe_interval;
  }
}

void handle_probe_event(UpstreamConn* u, uint32_t events) {
  if (events & EPOLLERR) {
    probe_done(u, false);
    return;
  }
  if (events & EPOLLHUP) events |= EPOLLIN;  // drain whatever was written
  u->connecting = false;
  if (events & EPOLLOUT) {
    while (u->out_off < u->out.size()) {
      ssize_t n =
          write(u->fd, u->out.data() + u->out_off, u->out.size() - u->out_off);
      if (n > 0) {
        u->out_off += size_t(n);
      } else {
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        probe_done(u, false);
        return;
      }
    }
    if (u->out_off >= u->out.size()) epoll_set(u->fd, EPOLLIN);
  }
  if (events & EPOLLIN) {
    char tmp[8192];
    bool eof = false;
    while (true) {
      ssize_t n = read(u->fd, tmp, sizeof(tmp));
      if (n > 0) {
        u->resp.buf.append(tmp, size_t(n));
      } else if (n == 0) {
        eof = true;
        break;
      } else {
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        eof = true;
        break;
      }
    }
    if (!u->resp.headers_complete())
      u->resp.try_parse_headers(/*is_request=*/false);
    if (u->resp.headers_complete() &&
        u->resp.complete(/*is_request=*/false, eof)) {
      probe_done(u, u->resp.status == 200);
      return;
    }
    if (eof) probe_done(u, false);
  }
}

// Launch probes for every circuit-open backend whose backoff expired.
// One in flight per backend; results re-arm the next interval.
void start_due_probes() {
  if (!g_health_probes) return;
  double now = now_s();
  for (auto& b : g_state.backends) {
    if (b->probe_inflight) {
      // Wedged-probe guard: a backend that accepted the connect but
      // never answers would otherwise pin probe_inflight forever and
      // the backend would stay ejected past recovery.
      if (now >= b->probe_deadline_t) {
        auto it = g_fds.find(b->probe_fd);
        if (b->probe_fd >= 0 && it != g_fds.end() && it->second.upstream &&
            it->second.upstream->probe) {
          probe_done(it->second.upstream, false);  // timeout = failed probe
        } else {  // stale bookkeeping (fd already gone)
          b->probe_inflight = false;
          b->probe_fd = -1;
        }
      }
      continue;
    }
    if (!b->circuit_open || now < b->next_probe_t)
      continue;
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) continue;
    set_nonblock(fd);
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    sockaddr_in addr = b->addr;
    int rc = connect(fd, (sockaddr*)&addr, sizeof(addr));
    if (rc < 0 && errno != EINPROGRESS) {
      close(fd);
      // Immediate refusal still counts as a completed (failed) probe.
      g_probe_seconds.observe(0.0);
      b->probe_interval = std::min(b->probe_interval * 2.0,
                                   g_probe_interval_s * kProbeBackoffCap);
      if (b->probe_interval <= 0.0) b->probe_interval = g_probe_interval_s;
      b->next_probe_t = now + b->probe_interval;
      continue;
    }
    auto* u = new UpstreamConn();
    u->fd = fd;
    u->backend = b;
    u->addr_epoch = b->addr_epoch;
    u->connecting = (rc < 0);
    u->probe = true;
    u->probe_t0 = now;
    u->resp.request_method = "GET";
    u->out =
        "GET /healthz HTTP/1.1\r\nhost: tpumlops-router\r\n"
        "connection: close\r\n\r\n";
    u->out_off = 0;
    b->probe_inflight = true;
    b->probe_fd = fd;
    b->probe_deadline_t = now + probe_timeout_s();
    g_fds[fd] = {FdKind::Upstream, nullptr, u};
    epoll_add(fd, EPOLLIN | EPOLLOUT);
  }
}

// ---------------------------------------------------------------------------
// Metrics exposition
// ---------------------------------------------------------------------------

void emit_histogram(std::string* out, const std::string& family,
                    const std::string& labels, const Histogram& h) {
  char line[512];
  uint64_t cum = 0;
  for (int i = 0; i < kNumBuckets; i++) {
    cum = h.bucket_counts[i];
    snprintf(line, sizeof(line), "%s_bucket{%s,le=\"%g\"} %llu\n", family.c_str(),
             labels.c_str(), kBuckets[i], (unsigned long long)cum);
    *out += line;
  }
  snprintf(line, sizeof(line), "%s_bucket{%s,le=\"+Inf\"} %llu\n", family.c_str(),
           labels.c_str(), (unsigned long long)h.count);
  *out += line;
  snprintf(line, sizeof(line), "%s_sum{%s} %.9f\n", family.c_str(), labels.c_str(),
           h.sum);
  *out += line;
  snprintf(line, sizeof(line), "%s_count{%s} %llu\n", family.c_str(), labels.c_str(),
           (unsigned long long)h.count);
  *out += line;
}

// Exact per-request router-internal latencies (headers-complete ->
// upstream response complete), as microseconds in a bounded ring.  The
// Prometheus histogram's buckets are decades wide at the hundreds-of-ms
// range, useless for attributing a ~20 ms p99 delta; this ring lets a
// bench read the router's OWN tail exactly and split "inside the proxy"
// from "kernel + client scheduling" (VERDICT r3 weak #4).  Drained (read
// -and-clear) via GET /router/latencies.
constexpr size_t kMaxRecent = 8192;
std::vector<uint32_t> g_recent_us;

std::string metrics_text() {
  std::string out;
  out += "# TYPE seldon_api_executor_client_requests_seconds histogram\n";
  for (auto& b : g_state.backends) {
    char labels[256];
    snprintf(labels, sizeof(labels),
             "deployment_name=\"%s\",predictor_name=\"%s\",namespace=\"%s\"",
             g_state.deployment.c_str(), b->name.c_str(), g_state.ns.c_str());
    emit_histogram(&out, "seldon_api_executor_client_requests_seconds", labels,
                   b->client_latency);
  }
  out += "# TYPE seldon_api_executor_server_requests_seconds histogram\n";
  for (auto& b : g_state.backends) {
    for (auto& [key, hist] : b->by_code) {
      const auto& [code, service] = key;
      char labels[320];
      snprintf(labels, sizeof(labels),
               "deployment_name=\"%s\",predictor_name=\"%s\",namespace=\"%s\","
               "code=\"%s\",service=\"%s\"",
               g_state.deployment.c_str(), b->name.c_str(), g_state.ns.c_str(),
               code.c_str(), service.c_str());
      emit_histogram(&out, "seldon_api_executor_server_requests_seconds", labels,
                     hist);
    }
  }
  out += "# TYPE tpumlops_router_proxied_total counter\n";
  char line[256];
  snprintf(line, sizeof(line), "tpumlops_router_proxied_total %llu\n",
           (unsigned long long)g_state.proxied_total);
  out += line;
  // Scale-to-zero park buffer: the gauge is the operator's wake signal
  // (sum over routers = requests waiting on a CR with zero replicas).
  // Identity labels deployment/namespace only — parking happens BEFORE
  // any predictor is picked.
  char plabels[192];
  snprintf(plabels, sizeof(plabels),
           "deployment_name=\"%s\",namespace=\"%s\"",
           g_state.deployment.c_str(), g_state.ns.c_str());
  out += "# TYPE tpumlops_router_parked_requests gauge\n";
  if (g_mux) {
    // Multiplexing: the gauge grows a model label so the operator wakes
    // the RIGHT model from zero (a fleet-wide number cannot say whose
    // requests wait).  "" = parked before a model-scoped path matched.
    std::map<std::string, size_t> per_model;
    for (ClientConn* pc : g_parked) per_model[pc->model]++;
    if (per_model.empty()) {
      snprintf(line, sizeof(line),
               "tpumlops_router_parked_requests{%s} 0\n", plabels);
      out += line;
    } else {
      for (auto& [m, n] : per_model) {
        out += "tpumlops_router_parked_requests{" + std::string(plabels) +
               ",model=\"" + json_escape(m) + "\"} " + std::to_string(n) +
               "\n";
      }
    }
  } else {
    snprintf(line, sizeof(line), "tpumlops_router_parked_requests{%s} %zu\n",
             plabels, g_parked.size());
    out += line;
  }
  out += "# TYPE tpumlops_router_parked_total counter\n";
  snprintf(line, sizeof(line), "tpumlops_router_parked_total{%s} %llu\n",
           plabels, (unsigned long long)g_parked_total);
  out += line;
  out += "# TYPE tpumlops_router_park_released_total counter\n";
  snprintf(line, sizeof(line),
           "tpumlops_router_park_released_total{%s} %llu\n", plabels,
           (unsigned long long)g_park_released_total);
  out += line;
  out += "# TYPE tpumlops_router_park_overflow_total counter\n";
  snprintf(line, sizeof(line),
           "tpumlops_router_park_overflow_total{%s} %llu\n", plabels,
           (unsigned long long)g_park_overflow_total);
  out += line;
  out += "# TYPE tpumlops_router_park_timeouts_total counter\n";
  snprintf(line, sizeof(line),
           "tpumlops_router_park_timeouts_total{%s} %llu\n", plabels,
           (unsigned long long)g_park_timeout_total);
  out += line;
  out += "# TYPE tpumlops_router_park_wait_seconds histogram\n";
  emit_histogram(&out, "tpumlops_router_park_wait_seconds", plabels,
                 g_park_wait_seconds);
  if (g_mux) {
    // Multiplexing attachment table: usable replicas per model.  0 for a
    // model some backend is tagged with but whose holders are all down —
    // the operator's re-attach signal.  Family absent with mux off
    // (byte-for-byte exposition).
    out += "# TYPE tpumlops_router_model_backends gauge\n";
    std::map<std::string, int> holders;
    for (auto& b : g_state.backends)
      if (!b->model.empty())
        holders[b->model] += backend_usable(*b) && b->role != "prefill";
    for (auto& [m, n] : holders)
      out += "tpumlops_router_model_backends{" + std::string(plabels) +
             ",model=\"" + json_escape(m) + "\"} " + std::to_string(n) + "\n";
  }
  // Disaggregated-fleet routing: affinity ring outcomes and the KV
  // handoff relay.  Deployment-scoped like the park series — the
  // decision happens before any predictor is picked.
  out += "# TYPE tpumlops_router_affinity_hits counter\n";
  snprintf(line, sizeof(line), "tpumlops_router_affinity_hits{%s} %llu\n",
           plabels, (unsigned long long)g_affinity_hits);
  out += line;
  out += "# TYPE tpumlops_router_affinity_misses counter\n";
  snprintf(line, sizeof(line), "tpumlops_router_affinity_misses{%s} %llu\n",
           plabels, (unsigned long long)g_affinity_misses);
  out += line;
  out += "# TYPE tpumlops_router_kv_handoff_bytes counter\n";
  snprintf(line, sizeof(line), "tpumlops_router_kv_handoff_bytes{%s} %llu\n",
           plabels, (unsigned long long)g_kv_handoff_bytes);
  out += line;
  out += "# TYPE tpumlops_router_kv_handoff_failures counter\n";
  snprintf(line, sizeof(line),
           "tpumlops_router_kv_handoff_failures{%s} %llu\n", plabels,
           (unsigned long long)g_kv_handoff_failures);
  out += line;
  out += "# TYPE tpumlops_router_kv_handoff_seconds histogram\n";
  emit_histogram(&out, "tpumlops_router_kv_handoff_seconds", plabels,
                 g_kv_handoff_seconds);
  // Failure containment: per-backend circuit state (healthy == circuit
  // closed; always 1 with --health-probes off) and trip counts, plus the
  // deployment-scoped failover tally and half-open probe walls.
  char hline[640];
  out += "# TYPE tpumlops_router_backend_healthy gauge\n";
  for (auto& b : g_state.backends) {
    char labels[256];
    snprintf(labels, sizeof(labels),
             "deployment_name=\"%s\",predictor_name=\"%s\",namespace=\"%s\"",
             g_state.deployment.c_str(), b->name.c_str(), g_state.ns.c_str());
    snprintf(hline, sizeof(hline), "tpumlops_router_backend_healthy{%s} %d\n",
             labels, b->circuit_open ? 0 : 1);
    out += hline;
  }
  out += "# TYPE tpumlops_router_circuit_open_total counter\n";
  for (auto& b : g_state.backends) {
    char labels[256];
    snprintf(labels, sizeof(labels),
             "deployment_name=\"%s\",predictor_name=\"%s\",namespace=\"%s\"",
             g_state.deployment.c_str(), b->name.c_str(), g_state.ns.c_str());
    snprintf(hline, sizeof(hline),
             "tpumlops_router_circuit_open_total{%s} %llu\n", labels,
             (unsigned long long)b->circuit_open_total);
    out += hline;
  }
  out += "# TYPE tpumlops_router_failover_total counter\n";
  snprintf(line, sizeof(line), "tpumlops_router_failover_total{%s} %llu\n",
           plabels, (unsigned long long)g_failover_total);
  out += line;
  out += "# TYPE tpumlops_router_probe_seconds histogram\n";
  emit_histogram(&out, "tpumlops_router_probe_seconds", plabels,
                 g_probe_seconds);
  if (g_journey_ring > 0) {
    // Fleet trace plane: per-outcome request walls.  The family exists
    // only with the journey ring on — byte-for-byte exposition at
    // --journey-ring 0.  The "ok" child is touched eagerly so the
    // family is visible (and pinnable) before the first request.
    g_request_seconds["ok"];
    out += "# TYPE tpumlops_router_request_seconds histogram\n";
    for (auto& [outcome, hist] : g_request_seconds) {
      char labels[320];
      snprintf(labels, sizeof(labels), "%s,outcome=\"%s\"", plabels,
               outcome.c_str());
      emit_histogram(&out, "tpumlops_router_request_seconds", labels, hist);
    }
  }
  return out;
}

std::string config_json() {
  std::string out = "{\"namespace\":\"" + g_state.ns + "\",\"deployment\":\"" +
                    g_state.deployment + "\",";
  if (g_journey_ring > 0)
    // Emitted only when enabled so the default config shape stays
    // byte-for-byte what callers have pinned.
    out += "\"journeyRing\":" + std::to_string(g_journey_ring) + ",";
  if (g_timeseries_ring > 0)
    out += "\"timeseriesRing\":" + std::to_string(g_timeseries_ring) + ",";
  if (g_mux) out += "\"muxModels\":1,";
  out += "\"backends\":[";
  bool first = true;
  for (auto& b : g_state.backends) {
    if (!first) out += ",";
    first = false;
    char item[512];
    snprintf(item, sizeof(item),
             "{\"name\":\"%s\",\"host\":\"%s\",\"port\":%d,\"weight\":%d,"
             "\"role\":\"%s\"",
             b->name.c_str(), b->host.c_str(), b->port, b->weight,
             b->role.c_str());
    out += item;
    if (g_mux) out += ",\"model\":\"" + json_escape(b->model) + "\"";
    out += "}";
  }
  out += "]}";
  return out;
}

// ---------------------------------------------------------------------------
// Timeseries ring exposition (/router/debug/timeseries)
// ---------------------------------------------------------------------------

void ts_samples_json(std::string* out, TsRing* r, bool router_level) {
  // roll() first so a bucket whose second has passed is finalized even
  // on an idle ring; the still-open bucket is appended as a view with
  // "open":true (same contract as the server's /debug/timeseries).
  r->roll();
  *out += "[";
  char buf[192];
  bool first = true;
  auto emit = [&](const TsSample& s, bool open) {
    if (!first) *out += ",";
    first = false;
    if (router_level) {
      snprintf(buf, sizeof(buf), "{\"t\":%ld,\"parks\":%u", s.t, s.parks);
    } else {
      snprintf(buf, sizeof(buf),
               "{\"t\":%ld,\"n\":%u,\"p50_ms\":%.4f,\"p99_ms\":%.4f,"
               "\"errors\":%u,\"failovers\":%u",
               s.t, s.n, s.p50_ms, s.p99_ms, s.errors, s.failovers);
    }
    *out += buf;
    if (open) *out += ",\"open\":true";
    *out += "}";
  };
  for (const TsSample& s : r->samples) emit(s, false);
  if (r->open_t >= 0) emit(r->finalize_open(), true);
  *out += "]";
}

std::string timeseries_json() {
  std::string out = "{\"capacity\":" + std::to_string(g_timeseries_ring) +
                    ",\"resolution_s\":1,\"router\":{\"samples\":";
  ts_samples_json(&out, &g_router_ts, /*router_level=*/true);
  out += "},\"backends\":{";
  bool first = true;
  for (auto& b : g_state.backends) {
    if (!first) out += ",";
    first = false;
    out += "\"" + json_escape(b->name) + "\":{\"samples\":";
    ts_samples_json(&out, &b->ts, /*router_level=*/false);
    out += "}";
  }
  out += "}}";
  return out;
}

// ---------------------------------------------------------------------------
// Journey ring exposition (/router/debug/requests, /router/debug/trace)
// ---------------------------------------------------------------------------

int64_t journey_us(double t_mono) {
  return int64_t((t_mono - g_t0_mono) * 1e6);
}

// Journey JSON assembly: every client-controlled string (request id,
// path, backend names) concatenates through std::string — a fixed
// snprintf buffer here would TRUNCATE mid-JSON-string on a long path
// (the header cap admits ~1 MiB) and corrupt the whole export.  Fixed
// buffers are used for numbers only.
std::string journey_json(const Journey& j) {
  char num[192];
  std::string out = "{\"request_id\":\"" + json_escape(j.request_id) +
                    "\",\"trace_id\":\"" + json_escape(j.trace_id) + "\",";
  snprintf(num, sizeof(num), "\"ts_us\":%lld,\"wall\":%.6f,",
           (long long)journey_us(j.t_arrival), j.wall_arrival);
  out += num;
  out += "\"method\":\"" + json_escape(j.method) + "\",\"path\":\"" +
         json_escape(j.path) + "\",\"affinity\":\"" + j.affinity + "\",";
  if (g_mux)  // mux only: the export shape stays pinned with mux off
    out += "\"model\":\"" + json_escape(j.model) + "\",";
  out += "\"backend\":\"" + json_escape(j.backend) + "\",\"role\":\"" +
         json_escape(j.role) + "\",\"outcome\":\"" + j.outcome + "\",";
  snprintf(num, sizeof(num),
           "\"status\":%d,\"failovers\":%d,\"circuits_open\":%d,",
           j.status, j.failovers, j.circuits_open);
  out += num;
  if (j.handoff_ms >= 0)
    snprintf(num, sizeof(num), "\"handoff_ms\":%.3f,", j.handoff_ms);
  else
    snprintf(num, sizeof(num), "\"handoff_ms\":null,");
  out += num;
  snprintf(num, sizeof(num), "\"park_ms\":%.3f,\"duration_ms\":%.3f,",
           j.park_ms, (j.t_finish - j.t_arrival) * 1000.0);
  out += num;
  out += "\"legs\":[";
  for (size_t i = 0; i < j.legs.size(); i++) {
    const JourneyLeg& leg = j.legs[i];
    if (i) out += ",";
    double t1 = leg.t1 > 0 ? leg.t1 : leg.t0;
    out += "{\"kind\":\"" + leg.kind + "\",\"backend\":\"" +
           json_escape(leg.backend) + "\",";
    snprintf(num, sizeof(num),
             "\"status\":%d,\"ts_us\":%lld,\"dur_us\":%lld,\"bytes\":%zu}",
             leg.status, (long long)journey_us(leg.t0),
             (long long)std::max<int64_t>(0, int64_t((t1 - leg.t0) * 1e6)),
             leg.bytes);
    out += num;
  }
  out += "],\"parks\":[";
  for (size_t i = 0; i < j.parks.size(); i++) {
    if (i) out += ",";
    snprintf(num, sizeof(num), "{\"ts_us\":%lld,\"dur_us\":%lld}",
             (long long)journey_us(j.parks[i].first),
             (long long)std::max<int64_t>(
                 0, int64_t((j.parks[i].second - j.parks[i].first) * 1e6)));
    out += num;
  }
  out += "]}";
  return out;
}

std::string journeys_json() {
  // format_version lets offline consumers (the SLO planner's trace
  // loader) reject a drifted export typed instead of mis-parsing it;
  // readers tolerate its absence (older exports are version 1).
  char buf[224];
  snprintf(buf, sizeof(buf),
           "{\"format_version\":1,\"capacity\":%d,\"recorded\":%llu,"
           "\"started_unix\":%.6f,\"requests\":[",
           g_journey_ring, (unsigned long long)g_journeys_total, g_t0_unix);
  std::string out = buf;
  bool first = true;
  for (const Journey& j : g_journeys) {
    if (!first) out += ",";
    first = false;
    out += journey_json(j);
  }
  out += "]}";
  return out;
}

// Chrome trace-event JSON over the journey ring: tid 0 is the router
// track (async request spans keyed by request id + park hold spans),
// tid N >= 1 one track per backend carrying that backend's legs —
// the same conventions as the server's /debug/trace, so the fleet
// stitcher (scripts/stitch_trace.py) merges both into one timeline.
std::string journeys_chrome() {
  // started_unix rides top-level so the fleet stitcher reads its clock
  // anchor from THIS payload instead of downloading the whole raw ring
  // a second time.
  char anchor[64];
  snprintf(anchor, sizeof(anchor), "{\"started_unix\":%.6f,", g_t0_unix);
  std::string out = std::string(anchor) +
      "\"displayTimeUnit\":\"ms\",\"traceEvents\":["
      "{\"name\":\"process_name\",\"ph\":\"M\",\"pid\":1,\"tid\":0,"
      "\"args\":{\"name\":\"tpumlops-router\"}},"
      "{\"name\":\"thread_name\",\"ph\":\"M\",\"pid\":1,\"tid\":0,"
      "\"args\":{\"name\":\"router\"}}";
  // One track per backend: current config order first, then any name a
  // retained journey still references (removed backends keep their
  // history readable).
  std::vector<std::string> names;
  std::map<std::string, int> tid_of;
  auto track = [&](const std::string& name) {
    if (name.empty() || tid_of.count(name)) return;
    tid_of[name] = int(names.size()) + 1;
    names.push_back(name);
  };
  for (auto& b : g_state.backends) track(b->name);
  for (const Journey& j : g_journeys)
    for (const JourneyLeg& leg : j.legs) track(leg.backend);
  // Client-controlled strings concatenate through std::string (a fixed
  // buffer would truncate on long paths/ids and corrupt the JSON);
  // fixed buffers carry numbers only.
  char num[192];
  for (const std::string& name : names) {
    snprintf(num, sizeof(num),
             ",{\"name\":\"thread_name\",\"ph\":\"M\",\"pid\":1,"
             "\"tid\":%d,\"args\":{\"name\":\"backend ",
             tid_of[name]);
    out += num;
    out += json_escape(name) + "\"}}";
  }
  for (const Journey& j : g_journeys) {
    long long b_ts = journey_us(j.t_arrival);
    long long e_ts = std::max(b_ts, (long long)journey_us(j.t_finish));
    std::string rid = json_escape(j.request_id);
    out += ",{\"name\":\"request\",\"cat\":\"request\",\"ph\":\"b\","
           "\"id\":\"" + rid + "\",";
    snprintf(num, sizeof(num), "\"ts\":%lld,\"pid\":1,\"tid\":0,", b_ts);
    out += num;
    out += "\"args\":{\"trace_id\":\"" + json_escape(j.trace_id) +
           "\",\"path\":\"" + json_escape(j.path) + "\"}}";
    for (const JourneyLeg& leg : j.legs) {
      double t1 = leg.t1 > 0 ? leg.t1 : leg.t0;
      int tid = leg.backend.empty() ? 0 : tid_of[leg.backend];
      out += ",{\"name\":\"" + leg.kind + "\",\"cat\":\"leg\",\"ph\":\"X\",";
      snprintf(num, sizeof(num), "\"ts\":%lld,\"dur\":%lld,\"pid\":1,"
               "\"tid\":%d,",
               (long long)journey_us(leg.t0),
               (long long)std::max<int64_t>(0, int64_t((t1 - leg.t0) * 1e6)),
               tid);
      out += num;
      out += "\"args\":{\"request_id\":\"" + rid + "\",";
      snprintf(num, sizeof(num), "\"status\":%d,\"bytes\":%zu}}",
               leg.status, leg.bytes);
      out += num;
    }
    for (const auto& span : j.parks) {
      out += ",{\"name\":\"parked\",\"cat\":\"park\",\"ph\":\"X\",";
      snprintf(num, sizeof(num), "\"ts\":%lld,\"dur\":%lld,\"pid\":1,"
               "\"tid\":0,",
               (long long)journey_us(span.first),
               (long long)std::max<int64_t>(
                   0, int64_t((span.second - span.first) * 1e6)));
      out += num;
      out += "\"args\":{\"request_id\":\"" + rid + "\"}}";
    }
    out += ",{\"name\":\"request\",\"cat\":\"request\",\"ph\":\"e\","
           "\"id\":\"" + rid + "\",";
    snprintf(num, sizeof(num), "\"ts\":%lld,\"pid\":1,\"tid\":0,", e_ts);
    out += num;
    out += "\"args\":{\"outcome\":\"" + j.outcome + "\",";
    snprintf(num, sizeof(num), "\"status\":%d,", j.status);
    out += num;
    out += "\"affinity\":\"" + j.affinity + "\",";
    snprintf(num, sizeof(num), "\"failovers\":%d,\"park_ms\":%.3f,",
             j.failovers, j.park_ms);
    out += num;
    out += "\"backend\":\"" + json_escape(j.backend) + "\"}}";
  }
  out += "]}";
  return out;
}

// ---------------------------------------------------------------------------
// Admin endpoints (/router/*)
// ---------------------------------------------------------------------------

// Drain a backend's keep-alive pool (close_upstream scrubs the pool
// entry itself; copy the list first since it mutates under us).
void drain_pool(Backend* b) {
  std::vector<int> fds = b->idle_conns;
  for (int fd : fds) {
    auto it = g_fds.find(fd);
    if (it != g_fds.end()) close_upstream(it->second.upstream);
  }
  b->idle_conns.clear();
}

// Returns a one-line error message naming the first invalid backend
// (unresolvable host / unknown role), or "" on success.  Two-phase:
// resolve/validate EVERY spec first, then commit — a rejected update
// must leave the running config fully intact (the operator treats a
// 400 as "nothing changed"; a half-applied weight table would silently
// shift live traffic).
std::string apply_config(const std::string& ns, const std::string& dep,
                         const std::vector<BackendSpec>& specs,
                         int journey_ring = -1, int mux_models = -1,
                         int timeseries_ring = -1) {
  if (journey_ring == -2 || journey_ring > kMaxJourneyRing)
    return "journeyRing out of range (0.." +
           std::to_string(kMaxJourneyRing) + ")";
  if (mux_models == -2) return "muxModels must be 0 or 1";
  if (timeseries_ring == -2 || timeseries_ring > kMaxTimeseriesRing)
    return "timeseriesRing out of range (0.." +
           std::to_string(kMaxTimeseriesRing) + ")";
  struct Staged {
    BackendPtr survivor;  // null for new backends
    BackendSpec spec;
    sockaddr_in addr{};
    bool addr_changed = false;
  };
  std::vector<Staged> staged;
  for (const auto& s : specs) {
    Staged st;
    st.spec = s;
    st.survivor = g_state.find(s.name);
    Backend probe;
    probe.host = !s.host.empty() ? s.host
                 : st.survivor   ? st.survivor->host
                                 : "127.0.0.1";
    probe.port = s.port ? s.port : (st.survivor ? st.survivor->port : 0);
    st.spec.host = probe.host;
    st.spec.port = probe.port;
    st.addr_changed = !st.survivor || probe.host != st.survivor->host ||
                      probe.port != st.survivor->port;
    if (st.addr_changed) {
      if (!resolve_backend(&probe))
        return "unresolvable backend host: " + s.name;
      st.addr = probe.addr;
    } else {
      st.addr = st.survivor->addr;
    }
    staged.push_back(std::move(st));
  }

  // Validate roles before commit (same atomicity contract as addresses).
  for (const auto& st : staged) {
    const std::string& r = st.spec.role;
    if (!r.empty() && r != "unified" && r != "prefill" && r != "decode")
      return "invalid role '" + r + "' for backend '" + st.spec.name +
             "' (use unified, prefill, or decode)";
  }

  // Commit. Preserve histograms of surviving backends (promotion changes
  // weights, not identity; metrics must stay cumulative).
  std::vector<BackendPtr> next;
  std::vector<Backend*> repointed;
  for (auto& st : staged) {
    if (st.survivor) {
      st.survivor->host = st.spec.host;
      st.survivor->port = st.spec.port;
      if (st.addr_changed) {
        st.survivor->addr = st.addr;
        st.survivor->addr_epoch++;  // in-flight conns to the old address
                                    // must not re-enter the pool
        // A repointed backend is a different pod: nothing we handed the
        // old one is known to the new one — and the old pod's failure
        // record must not keep the new one's circuit open.
        st.survivor->known_prefixes.clear();
        if (!st.spec.model_set) st.survivor->model.clear();
        st.survivor->circuit_open = false;
        st.survivor->consecutive_failures = 0;
        st.survivor->probe_interval = 0.0;
        repointed.push_back(st.survivor.get());
      }
      st.survivor->weight = st.spec.weight;
      if (!st.spec.role.empty()) st.survivor->role = st.spec.role;
      // Attach/replace/detach lands here: an explicit "model" key (even
      // "") is authoritative; an absent key keeps the survivor's model
      // (weight-only syncs must not amnesia the attachment table).
      if (st.spec.model_set) st.survivor->model = st.spec.model;
      next.push_back(st.survivor);
    } else {
      auto b = std::make_shared<Backend>();
      b->name = st.spec.name;
      b->host = st.spec.host;
      b->port = st.spec.port;
      b->weight = st.spec.weight;
      if (!st.spec.role.empty()) b->role = st.spec.role;
      if (st.spec.model_set) b->model = st.spec.model;
      b->addr = st.addr;
      next.push_back(std::move(b));
    }
  }
  if (!ns.empty()) g_state.ns = ns;
  if (!dep.empty()) g_state.deployment = dep;
  // Survivors whose address changed must not reuse sockets to the old
  // address — pooled conns would silently keep serving the old version.
  for (Backend* b : repointed) drain_pool(b);
  // Drop pooled conns of removed backends.
  std::vector<BackendPtr> removed;
  for (auto& b : g_state.backends) {
    bool kept = false;
    for (auto& n : next)
      if (n == b) kept = true;
    if (!kept) removed.push_back(b);
  }
  g_state.backends = std::move(next);
  for (auto& b : removed) drain_pool(b.get());
  rebuild_ring();  // membership/roles may have changed
  if (mux_models >= 0) g_mux = mux_models;
  if (journey_ring >= 0 && journey_ring != g_journey_ring) {
    // Operator-driven trace plane (RouterSync sends the manifest's
    // tpumlops.dev/fleet-journey-ring annotation).  Shrinking trims the
    // oldest records; 0 drops the ring and stops header minting.
    g_journey_ring = journey_ring;
    if (g_journey_ring == 0) {
      g_journeys.clear();
      g_request_seconds.clear();
      g_journeys_total = 0;
    }
    while (int(g_journeys.size()) > g_journey_ring) g_journeys.pop_front();
  }
  if (timeseries_ring >= 0 && timeseries_ring != g_timeseries_ring) {
    // Operator-driven (RouterSync sends the manifest's
    // tpumlops.dev/fleet-timeseries-ring annotation).  Shrinking trims
    // the oldest buckets; 0 drops every ring.
    g_timeseries_ring = timeseries_ring;
    if (g_timeseries_ring == 0) {
      g_router_ts.clear();
      for (auto& b : g_state.backends) b->ts.clear();
    } else {
      while (int(g_router_ts.samples.size()) > g_timeseries_ring)
        g_router_ts.samples.pop_front();
      for (auto& b : g_state.backends)
        while (int(b->ts.samples.size()) > g_timeseries_ring)
          b->ts.samples.pop_front();
    }
  }
  return "";
}

void release_parked();  // defined with the proxy path below

void handle_admin(ClientConn* c) {
  const std::string& path = c->req.path;
  std::string body = c->req.buf.substr(c->req.body_start);

  if (path == "/router/healthz") {
    client_send(c, http_response(200, "OK", "text/plain", "ok\n"));
  } else if (path == "/router/parked") {
    // Park-buffer state: the wake signal an operator polls for a CR at
    // zero replicas (also exported as tpumlops_router_parked_requests).
    double oldest = 0.0;
    double now = now_s();
    for (ClientConn* pc : g_parked) {
      double wait = now - pc->park_t;
      if (wait > oldest) oldest = wait;
    }
    char head[256];
    snprintf(head, sizeof(head),
             "{\"parked\":%zu,\"capacity\":%d,\"oldest_wait_s\":%.3f,"
             "\"parked_total\":%llu,\"released_total\":%llu,"
             "\"overflow_total\":%llu,\"timeout_total\":%llu",
             g_parked.size(), g_park_max, oldest,
             (unsigned long long)g_parked_total,
             (unsigned long long)g_park_released_total,
             (unsigned long long)g_park_overflow_total,
             (unsigned long long)g_park_timeout_total);
    std::string out = head;
    if (g_mux) {
      // Per-model breakdown (multiplexing only — the body stays
      // byte-for-byte with mux off): which model's requests wait, so
      // the bin-packer attaches the RIGHT one.
      std::map<std::string, size_t> per_model;
      for (ClientConn* pc : g_parked) per_model[pc->model]++;
      out += ",\"models\":{";
      bool first = true;
      for (auto& [m, n] : per_model) {
        if (!first) out += ",";
        first = false;
        out += "\"" + json_escape(m) + "\":" + std::to_string(n);
      }
      out += "}";
    }
    out += "}";
    client_send(c, http_response(200, "OK", "application/json", out));
  } else if (path == "/router/fleet") {
    // Disaggregated-fleet introspection: ring size, affinity and
    // handoff tallies, per-backend role + known-prefix counts.
    std::string out = "{";
    char buf[256];
    snprintf(buf, sizeof(buf),
             "\"affinity_tokens\":%d,\"ring_vnodes\":%zu,"
             "\"affinity_hits\":%llu,\"affinity_misses\":%llu,"
             "\"kv_handoffs\":%llu,\"kv_handoff_bytes\":%llu,"
             "\"kv_handoff_failures\":%llu,"
             "\"health_probes\":%d,\"failovers\":%llu,\"backends\":[",
             g_affinity_tokens, g_ring.size(),
             (unsigned long long)g_affinity_hits,
             (unsigned long long)g_affinity_misses,
             (unsigned long long)g_kv_handoff_seconds.count,
             (unsigned long long)g_kv_handoff_bytes,
             (unsigned long long)g_kv_handoff_failures,
             g_health_probes, (unsigned long long)g_failover_total);
    out += buf;
    bool first = true;
    for (auto& b : g_state.backends) {
      if (!first) out += ",";
      first = false;
      snprintf(buf, sizeof(buf),
               "{\"name\":\"%s\",\"role\":\"%s\",\"weight\":%d,"
               "\"known_prefixes\":%zu,\"healthy\":%s,"
               "\"consecutive_failures\":%d,\"circuit_opened\":%llu",
               b->name.c_str(), b->role.c_str(), b->weight,
               b->known_prefixes.size(),
               b->circuit_open ? "false" : "true",
               b->consecutive_failures,
               (unsigned long long)b->circuit_open_total);
      out += buf;
      if (g_mux)  // attachment table rides the fleet view with mux on
        out += ",\"model\":\"" + json_escape(b->model) + "\"";
      out += "}";
    }
    out += "]}";
    client_send(c, http_response(200, "OK", "application/json", out));
  } else if (path == "/router/latencies") {
    // Read-and-clear: exact router-internal per-request latencies (us)
    // since the previous drain.
    std::string out = "{\"recent_us\":[";
    for (size_t i = 0; i < g_recent_us.size(); i++) {
      if (i) out += ",";
      out += std::to_string(g_recent_us[i]);
    }
    out += "]}";
    g_recent_us.clear();
    client_send(c, http_response(200, "OK", "application/json", out));
  } else if (path == "/router/debug/requests" ||
             path.rfind("/router/debug/trace", 0) == 0) {
    // Fleet trace plane introspection: the journey ring as raw JSON or
    // a Chrome trace (one track per backend, async request spans keyed
    // by request id).  404 names the knob when the ring is off, same
    // contract as the server's /debug/device.
    if (g_journey_ring <= 0) {
      client_send(c, http_response(
          404, "Not Found", "application/json",
          "{\"error\":\"journey ring disabled; enable --journey-ring N "
          "(spec.fleet.observability.journeyRing)\"}"));
    } else if (path == "/router/debug/requests") {
      client_send(c, http_response(200, "OK", "application/json",
                                   journeys_json()));
    } else {
      std::string fmt = "chrome";
      size_t q = path.find("format=");
      if (q != std::string::npos) {
        fmt = path.substr(q + 7);
        size_t amp = fmt.find('&');
        if (amp != std::string::npos) fmt = fmt.substr(0, amp);
      }
      if (fmt == "chrome")
        client_send(c, http_response(200, "OK", "application/json",
                                     journeys_chrome()));
      else if (fmt == "json")
        client_send(c, http_response(200, "OK", "application/json",
                                     journeys_json()));
      else
        client_send(c, http_response(400, "Bad Request", "text/plain",
                                     "unknown format '" + fmt + "'\n"));
    }
  } else if (path == "/router/debug/timeseries") {
    // Per-backend 1 s history; 404 names the knob when the ring is
    // off, same contract as the journey endpoints above.
    if (g_timeseries_ring <= 0) {
      client_send(c, http_response(
          404, "Not Found", "application/json",
          "{\"error\":\"timeseries ring disabled; enable --timeseries-ring N "
          "(spec.tpu.observability.timeseriesRing)\"}"));
    } else {
      client_send(c, http_response(200, "OK", "application/json",
                                   timeseries_json()));
    }
  } else if (path == "/router/metrics") {
    client_send(c, http_response(200, "OK", "text/plain; version=0.0.4",
                                 metrics_text()));
  } else if (path == "/router/config" && c->req.method == "GET") {
    client_send(c, http_response(200, "OK", "application/json", config_json()));
  } else if (path == "/router/config") {  // PUT/POST replace
    std::string ns, dep;
    std::vector<BackendSpec> specs;
    int journey_ring = -1;  // absent = keep the running ring
    int mux_models = -1;    // absent = keep the running mux mode
    int timeseries_ring = -1;  // absent = keep the running ring
    if (parse_config(body, &ns, &dep, &specs, &journey_ring, &mux_models,
                     &timeseries_ring)) {
      std::string bad = apply_config(ns, dep, specs, journey_ring, mux_models,
                                     timeseries_ring);
      if (bad.empty()) {
        client_send(c, http_response(200, "OK", "application/json", config_json()));
        // Capacity may just have returned (a replica came back / the
        // operator woke the CR): release the park queue FIFO.
        release_parked();
      } else {
        client_send(c, http_response(400, "Bad Request", "text/plain",
                                     bad + "\n"));
      }
    } else {
      client_send(c, http_response(400, "Bad Request", "text/plain",
                                   "malformed config\n"));
    }
  } else if (path == "/router/weights") {
    if (c->req.method == "GET") {
      std::string out = "{";
      bool first = true;
      for (auto& b : g_state.backends) {
        if (!first) out += ",";
        first = false;
        out += "\"" + b->name + "\":" + std::to_string(b->weight);
      }
      out += "}";
      client_send(c, http_response(200, "OK", "application/json", out));
    } else {
      std::map<std::string, int> w;
      if (!parse_weights(body, &w)) {
        client_send(c, http_response(400, "Bad Request", "text/plain",
                                     "malformed weights\n"));
      } else {
        bool unknown = false;
        for (auto& [name, _] : w)
          if (!g_state.find(name)) unknown = true;
        if (unknown) {
          client_send(c, http_response(404, "Not Found", "text/plain",
                                       "unknown backend\n"));
        } else {
          for (auto& [name, weight] : w) g_state.find(name)->weight = weight;
          // Reset SWRR counters so the new split takes effect cleanly.
          for (auto& b : g_state.backends) b->swrr_current = 0;
          client_send(c, http_response(200, "OK", "application/json", "{}"));
          release_parked();  // a positive weight wakes the park queue
        }
      }
    }
  } else {
    client_send(c, http_response(404, "Not Found", "text/plain", "not found\n"));
  }
}

// ---------------------------------------------------------------------------
// Proxying
// ---------------------------------------------------------------------------

void finish_request(const BackendPtr& b, int code, double seconds,
                    bool feedback) {
  // Feedback posts count under their own service label but stay out of
  // the latency histogram the gate's p95/mean queries read.
  if (!feedback) b->client_latency.observe(seconds);
  // The timeseries ring mirrors the histogram's scope (predictions
  // only) so its per-second p50/p99 and the gate's queries agree.
  if (g_timeseries_ring > 0 && !feedback)
    b->ts.observe_leg(seconds, code >= 500);
  b->by_code[{std::to_string(code), feedback ? "feedback" : "predictions"}]
      .observe(seconds);
  // The exact-latency ring mirrors the histogram's scope: predictions
  // only, so concurrent feedback posts (a different code path) cannot
  // contaminate the router-internal tail attribution.
  if (!feedback && g_recent_us.size() < kMaxRecent)
    g_recent_us.push_back((uint32_t)(seconds * 1e6));
  g_state.proxied_total++;
}

void advance_client(ClientConn* c);  // defined below
void relay_sub_failed(ClientConn* c);  // defined with the relay logic
void connect_upstream(ClientConn* c, bool allow_pool);  // defined below

bool any_usable_client_backend() {
  for (auto& b : g_state.backends)
    if (backend_usable(*b) && b->role != "prefill") return true;
  return false;
}

// Multiplexing-aware capacity check: with mux on and a model-scoped
// request, only a usable backend HOLDING the model counts — a fleet
// full of healthy replicas serving other models is still "no capacity"
// for this request (it parks until an attach lands).  Collapses to
// any_usable_client_backend with mux off or a model-less request.
bool any_usable_for_model(const std::string& model) {
  for (auto& b : g_state.backends) {
    if (!backend_usable(*b) || b->role == "prefill") continue;
    if (g_mux && !model.empty() && b->model != model) continue;
    return true;
  }
  return false;
}

// An upstream leg failed.  ``first_byte_seen`` = response bytes had
// arrived before the failure (generation may have started; the request
// is no longer failover-idempotent).  With --failover-retries 0 (the
// default) every path below collapses to the classic bare 502,
// byte-for-byte.
void fail_502(ClientConn* c, const char* why, bool first_byte_seen = false) {
  journey_leg_done(c, 0, 0);  // the in-flight leg died at the transport
  if (c->relay_stage == RelayStage::Export ||
      c->relay_stage == RelayStage::Import) {
    // A relay SUB-request failed (prefill replica died mid-handoff,
    // import refused): the client request is untouched in c->req —
    // retry the relay or fall back to unified serving, never 502 the
    // client over an internal leg.
    if (c->upstream) {
      c->upstream->client = nullptr;
      close_upstream(c->upstream);
      c->upstream = nullptr;
    }
    note_backend_failure(c->backend);  // passive health sees relay legs too
    relay_sub_failed(c);
    return;
  }
  c->relay_stage = RelayStage::None;  // Forward leg fails like any proxy
  if (c->upstream) {
    c->upstream->client = nullptr;
    close_upstream(c->upstream);
    c->upstream = nullptr;
  }
  note_backend_failure(c->backend);
  // Before-first-byte failover: the upstream died without producing a
  // single response byte, so generation never started — the request
  // retries verbatim on another healthy backend.  Feedback posts never
  // REPLAY (retry or park — a reward the backend recorded before dying
  // would double-count), but they still shed the typed 503 below, never
  // the bare 502.
  if (g_failover_retries > 0) {
    if (c->backend) c->failover_tried.push_back(c->backend);
    const bool replayable = !first_byte_seen && !c->feedback;
    if (replayable && c->failover_attempts < g_failover_retries) {
      BackendPtr next = g_state.pick(&c->failover_tried, &c->model);
      if (next) {
        c->failover_attempts++;
        g_failover_total++;
        if (c->journey) c->journey->failovers++;
        // Attributed to the backend being LEFT: a straggler sheds load
        // onto its peers, and that departure count is the signal.
        if (g_timeseries_ring > 0 && c->backend) c->backend->ts.inc_failover();
        c->backend = next;
        c->retries = 0;
        connect_upstream(c, /*allow_pool=*/true);
        return;
      }
    }
    // Exhausted: never a bare 502.  A fully-tripped fleet PARKS when
    // parking is on — the request waits for a probe to re-admit
    // capacity instead of bouncing 503s — but ONLY while replay is
    // idempotent: a response that had started (generation may have
    // run) sheds typed instead of being re-dispatched from the park.
    if (replayable && !any_usable_for_model(c->model) && g_park_max > 0) {
      if (int(g_parked.size()) < g_park_max) {
        c->parked = true;
        c->park_t = now_s();
        if (c->park_first_t == 0) c->park_first_t = c->park_t;
        journey_park_begin(c);
        g_parked.push_back(c);
        g_parked_total++;
        if (g_timeseries_ring > 0) g_router_ts.inc_park();
        return;
      }
      g_park_overflow_total++;
      if (c->backend)
        finish_request(c->backend, 503, now_s() - c->t_start, c->feedback);
      client_send(c, park_503_body("park_overflow", int(g_park_timeout_s),
                                   c));
      journey_finish(c, 503, "shed_park_overflow");
    } else {
      if (c->backend)
        finish_request(c->backend, 503, now_s() - c->t_start, c->feedback);
      // std::string assembly — the escaped request id alone can reach
      // ~256 bytes, past any comfortable fixed buffer.
      std::string body =
          "{\"error\":\"upstream failed (" + std::string(why) +
          ") and failover budget exhausted\","
          "\"reason\":\"upstream_failed\",\"retry_after_s\":1" +
          rid_json_field(c) + "}";
      std::string hdrs = "Retry-After: 1\r\n" + echo_header(c);
      client_send(c, http_response(503, "Service Unavailable",
                                   "application/json", body, hdrs));
      journey_finish(c, 503, "shed_upstream_failed");
    }
  } else {
    if (c->backend)
      finish_request(c->backend, 502, now_s() - c->t_start, c->feedback);
    client_send(c, http_response(502, "Bad Gateway", "text/plain",
                                 std::string(why) + "\n"));
    journey_finish(c, 502, "bare_502");
  }
  c->req.reset();
  // A pipelined next request must still be answered (same contract as the
  // success path in on_upstream_event).
  if (!c->pending.empty()) {
    c->req.buf = std::move(c->pending);
    c->pending.clear();
    advance_client(c);
  }
}

// Decode a complete chunked body into its raw payload.
std::string dechunk(const std::string& framed) {
  std::string out;
  size_t pos = 0;
  while (pos < framed.size()) {
    size_t eol = framed.find("\r\n", pos);
    if (eol == std::string::npos) break;
    long sz = strtol(framed.c_str() + pos, nullptr, 16);
    if (sz <= 0) break;
    size_t data = eol + 2;
    if (data + size_t(sz) > framed.size()) break;
    out.append(framed, data, size_t(sz));
    pos = data + size_t(sz) + 2;
  }
  return out;
}

// Build the request to forward.  The body is re-framed with an explicit
// Content-Length (chunked requests are decoded first) and the client's own
// framing headers are dropped: forwarding a request that carries BOTH
// Transfer-Encoding and Content-Length verbatim invites request-smuggling
// desync on the pooled backend connection if the backend frames by the
// other header than we did.  ``extra_headers`` rides complete "k: v\r\n"
// lines (the relay's x-tpumlops-handoff stamp, the trace plane's
// x-request-id/traceparent).  ``replace_trace_ids`` drops the client's
// OWN x-request-id/traceparent — the journey's adopted/minted context
// in ``extra_headers`` replaces them, so every leg of one request
// carries one consistent identity.
std::string build_upstream_request(const HttpMsg& req,
                                   const std::string& extra_headers = "",
                                   bool replace_trace_ids = false) {
  std::string body = req.buf.substr(req.body_start);
  if (req.chunked) body = dechunk(body);
  std::string out = req.method + " " + req.path + " HTTP/1.1\r\n";
  for (auto& [k, v] : req.headers) {
    if (k == "connection" || k == "keep-alive" || k == "proxy-connection" ||
        k == "te" || k == "upgrade" || k == "trailer" ||
        k == "content-length" || k == "transfer-encoding" ||
        k == "x-tpumlops-handoff")  // router-asserted only: a client
      continue;                     // must not forge relay stamps
    if (replace_trace_ids && (k == "x-request-id" || k == "traceparent"))
      continue;
    out += k + ": " + v + "\r\n";
  }
  out += extra_headers;
  out += "content-length: " + std::to_string(body.size()) + "\r\n";
  out += "connection: keep-alive\r\n\r\n";
  out += body;
  return out;
}

// A synthesized relay sub-request (export/import legs).  ``trace_hdrs``
// carries the journey's propagated context so the prefill/decode
// replicas' flight recorders journal the SAME request id the client
// forward will carry.
std::string relay_request(const std::string& path,
                          const std::string& content_type,
                          const std::string& body,
                          const std::string& trace_hdrs = "") {
  std::string out = "POST " + path + " HTTP/1.1\r\n";
  out += "host: tpumlops-router\r\n";
  out += trace_hdrs;
  out += "content-type: " + content_type + "\r\n";
  out += "content-length: " + std::to_string(body.size()) + "\r\n";
  out += "connection: keep-alive\r\n\r\n";
  out += body;
  return out;
}

// A complete upstream response's body bytes (chunked frames decoded).
std::string response_body(const HttpMsg& resp, bool eof) {
  ssize_t end = resp.message_end(/*is_request=*/false, eof);
  if (end < 0) return "";
  std::string framed = resp.buf.substr(
      resp.body_start, size_t(end) - resp.body_start);
  if (resp.chunked) return dechunk(framed);
  return framed;
}

// Attach the client's buffered request to a backend connection (pooled or
// fresh).  Assumes c->backend is set.  On fresh-connect failure → 502.
void connect_upstream(ClientConn* c, bool allow_pool) {
  BackendPtr b = c->backend;
  journey_leg_start(c, b);
  UpstreamConn* u = nullptr;
  // Reuse a pooled keep-alive connection when available.
  while (allow_pool && !b->idle_conns.empty()) {
    int fd = b->idle_conns.back();
    b->idle_conns.pop_back();
    auto it = g_fds.find(fd);
    if (it == g_fds.end()) continue;
    u = it->second.upstream;
    u->reused = true;
    break;
  }
  if (!u) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return fail_502(c, "socket() failed");
    set_nonblock(fd);
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    sockaddr_in addr = b->addr;  // resolved at config time
    int rc = connect(fd, (sockaddr*)&addr, sizeof(addr));
    if (rc < 0 && errno != EINPROGRESS) {
      close(fd);
      return fail_502(c, "connect failed");
    }
    u = new UpstreamConn();
    u->fd = fd;
    u->backend = b;
    u->addr_epoch = b->addr_epoch;
    u->connecting = (rc < 0);
    u->reused = false;
    g_fds[fd] = {FdKind::Upstream, nullptr, u};
    epoll_add(fd, EPOLLIN | EPOLLOUT);
  } else {
    epoll_set(u->fd, EPOLLIN | EPOLLOUT);
  }
  u->client = c;
  u->resp.reset();
  // Relay sub-requests (and the handoff-stamped forward) carry
  // pre-built bytes; everything else re-frames the client request.
  if (c->relay_stage != RelayStage::None) {
    u->resp.request_method = "POST";
    u->out = c->relay_out;
  } else {
    u->resp.request_method = c->req.method;  // HEAD: no response body
    std::string th = trace_headers(c);
    u->out = build_upstream_request(c->req, th, !th.empty());
  }
  u->out_off = 0;
  c->upstream = u;
}

// ---------------------------------------------------------------------------
// KV-handoff relay (prefix-affinity miss on a cold prompt)
// ---------------------------------------------------------------------------

void relay_clear(ClientConn* c) {
  c->relay_stage = RelayStage::None;
  c->relay_decode = nullptr;
  c->relay_out.clear();
  c->relay_blob_bytes = 0;
  c->relay_tried.clear();
}

// The client's (dechunked) request body — the export leg forwards it
// verbatim so the prefill replica sees the exact prompt_ids.
std::string client_body(const ClientConn* c) {
  std::string body = c->req.buf.substr(c->req.body_start);
  if (c->req.chunked) body = dechunk(body);
  return body;
}

void start_relay_export(ClientConn* c, const BackendPtr& prefill) {
  c->relay_stage = RelayStage::Export;
  c->relay_attempts++;
  c->relay_tried.push_back(prefill);
  c->relay_out = relay_request(
      "/admin/kv/export", "application/json", client_body(c),
      trace_headers(c));
  c->backend = prefill;
  c->retries = 0;
  connect_upstream(c, /*allow_pool=*/true);
}

// Forward the ORIGINAL request to the decode (or any) backend without a
// handoff — unified serving, the typed fallback for every relay
// failure.  The request is never lost: every replica holds the full
// model, a failed handoff only costs the local prefill.
void relay_fallback(ClientConn* c, const char* why,
                    bool count_failure = true) {
  (void)why;
  if (count_failure) g_kv_handoff_failures++;
  if (c->journey) c->journey->affinity = "fallback";
  BackendPtr target = c->relay_decode ? c->relay_decode : g_state.pick();
  if (target && backend_usable(*target)) {
    // The unified fallback prefills LOCALLY on the ring target, which
    // warms its radix cache — record that so the next repeat of this
    // prefix routes straight there as a hit instead of re-relaying.
    remember_prefix(target, c->relay_hash);
  }
  relay_clear(c);
  if (!target || !backend_usable(*target)) target = g_state.pick();
  if (!target) {
    // Past the retry budget with NOTHING able to serve: typed 503.
    client_send(c, http_response(
        503, "Service Unavailable", "application/json",
        "{\"error\":\"kv handoff failed and no decode backend has "
        "positive weight\",\"reason\":\"no_decode_backend\","
        "\"retry_after_s\":1" + rid_json_field(c) + "}",
        "Retry-After: 1\r\n" + echo_header(c)));
    journey_finish(c, 503, "shed_no_decode_backend");
    c->req.reset();
    if (!c->pending.empty()) {
      c->req.buf = std::move(c->pending);
      c->pending.clear();
      advance_client(c);
    }
    return;
  }
  c->backend = target;
  c->retries = 0;
  connect_upstream(c, /*allow_pool=*/true);
}

// An Export/Import sub-request failed at the transport level (or the
// peer answered non-200): retry the export on an untried prefill
// replica while the budget lasts, else fall back to unified serving.
void relay_sub_failed(ClientConn* c) {
  if (c->relay_stage == RelayStage::Export &&
      c->relay_attempts <= g_handoff_retries) {
    BackendPtr next = g_state.pick_prefill(c->relay_tried);
    if (next) {
      start_relay_export(c, next);
      return;
    }
  }
  relay_fallback(c, "sub-request failed");
}

// A relay sub-request's response arrived complete.
void relay_on_response(ClientConn* c, int status, std::string body) {
  if (c->relay_stage == RelayStage::Export) {
    if (status >= 400 && status < 500) {
      // A 4xx export is DETERMINISTIC: the prompt itself is handoff-
      // ineligible (shorter than one radix chunk, multi-sequence body),
      // so every prefill replica would answer the same — retrying adds
      // round trips to TTFT for nothing, and counting a "failure" for a
      // request that was never handoff-eligible poisons the metric.
      // Fall straight back to unified serving; the fallback remembers
      // the prefix, so this prompt shape relays at most once.
      relay_fallback(c, "export ineligible", /*count_failure=*/false);
      return;
    }
    if (status != 200 || body.empty()) {
      relay_sub_failed(c);
      return;
    }
    c->relay_blob_bytes = body.size();
    c->relay_stage = RelayStage::Import;
    c->relay_out = relay_request(
        "/admin/kv/import", "application/octet-stream", body,
        trace_headers(c));
    c->backend = c->relay_decode;
    c->retries = 0;
    connect_upstream(c, /*allow_pool=*/true);
    return;
  }
  // Import leg.
  if (status != 200) {
    relay_fallback(c, "import refused");
    return;
  }
  double handoff_s = now_s() - c->relay_t0;
  g_kv_handoff_seconds.observe(handoff_s);
  g_kv_handoff_bytes += c->relay_blob_bytes;
  remember_prefix(c->relay_decode, c->relay_hash);
  if (c->journey) c->journey->handoff_ms = handoff_s * 1000.0;
  // Final leg: the original request, stamped so the server's request
  // trace carries the router-measured handoff wall.
  char hdr[64];
  snprintf(hdr, sizeof(hdr), "x-tpumlops-handoff: %.3f\r\n",
           handoff_s * 1000.0);
  c->relay_stage = RelayStage::Forward;
  std::string th = trace_headers(c);
  c->relay_out = build_upstream_request(c->req, std::string(hdr) + th,
                                        !th.empty());
  c->backend = c->relay_decode;
  c->retries = 0;
  connect_upstream(c, /*allow_pool=*/true);
}

// Prefix-affinity routing for a /generate POST.  Returns true when the
// request was taken over (affinity forward or relay started); false =
// fall through to the plain SWRR pick.
bool try_affinity_route(ClientConn* c) {
  if (g_affinity_tokens <= 0 || c->req.method != "POST") return false;
  const std::string& p = c->req.path;
  const std::string tail = "/generate";
  if (p.size() < tail.size() ||
      p.compare(p.size() - tail.size(), tail.size(), tail) != 0)
    return false;
  uint64_t h = 0;
  if (!affinity_hash(client_body(c), &h)) return false;
  if (g_mux && !c->model.empty()) {
    // The model id joins the affinity key: identical prompts of two
    // DIFFERENT models must not collide on one ring slot (the cache a
    // hit would reuse belongs to the other model's weights).
    for (char ch : c->model) {
      h ^= (unsigned char)ch;
      h *= 1099511628211ULL;  // FNV-1a prime, same mix as affinity_hash
    }
  }
  BackendPtr d = pick_decode(h);
  if (!d) return false;  // no live decode pool: plain routing
  if (g_mux && !c->model.empty() && d->model != c->model)
    return false;  // ring target serves another model: model-filtered pick
  c->relay_hash = h;
  if (d->known_prefixes.count(h)) {
    g_affinity_hits++;
    if (c->journey) c->journey->affinity = "hit";
    c->backend = d;
    c->retries = 0;
    connect_upstream(c, /*allow_pool=*/true);
    return true;
  }
  g_affinity_misses++;
  if (c->journey) c->journey->affinity = "miss";
  if (g_handoff_enabled) {
    BackendPtr prefill = g_state.pick_prefill({});
    if (prefill) {
      c->relay_decode = d;
      c->relay_t0 = now_s();
      c->relay_attempts = 0;
      c->relay_tried.clear();
      start_relay_export(c, prefill);
      return true;
    }
  }
  // No prefill pool (or handoff off): serve on the ring target anyway —
  // its local prefill warms its cache, so the NEXT repeat is a hit.
  remember_prefix(d, h);
  c->backend = d;
  c->retries = 0;
  connect_upstream(c, /*allow_pool=*/true);
  return true;
}

void start_proxy(ClientConn* c) {
  // Model-scoped POSTs only: a GET (readiness poll, metadata) must
  // never park behind a missing attachment — it routes anywhere.
  c->model = (g_mux && c->req.method == "POST")
                 ? request_model(c->req.path)
                 : std::string();
  if (c->journey && g_mux) c->journey->model = c->model;
  if (try_affinity_route(c)) return;
  BackendPtr b = g_state.pick(nullptr, &c->model);
  if (!b) {
    if (g_park_max > 0) {
      if (int(g_parked.size()) < g_park_max) {
        // Hold the fully-buffered request; released FIFO once capacity
        // returns (a weight flips positive, or a half-open probe closes
        // a circuit on a fully-tripped fleet), expired after
        // --park-timeout-s.  c->req stays intact for the re-dispatch;
        // park_first_t survives release/re-park cycles so the timeout
        // bound is cumulative.
        c->parked = true;
        c->park_t = now_s();
        if (c->park_first_t == 0) c->park_first_t = c->park_t;
        journey_park_begin(c);
        g_parked.push_back(c);
        g_parked_total++;
        if (g_timeseries_ring > 0) g_router_ts.inc_park();
        return;
      }
      g_park_overflow_total++;
      client_send(c, park_503_body("park_overflow",
                                   int(g_park_timeout_s), c));
      journey_finish(c, 503, "shed_park_overflow");
      c->req.reset();
      return;
    }
    if (g_mux && !c->model.empty() && any_usable_client_backend()) {
      // Parking disabled, healthy capacity exists, but no replica holds
      // this model: typed, retryable — the operator's next convergence
      // pass attaches it.  Never the bare no-backend 503 (capacity is
      // NOT the problem).
      std::string body =
          "{\"error\":\"no replica holds model " + json_escape(c->model) +
          "\",\"reason\":\"model_not_attached\",\"retry_after_s\":1" +
          rid_json_field(c) + "}";
      std::string hdr = "Retry-After: 1\r\n" + echo_header(c);
      client_send(c, http_response(503, "Service Unavailable",
                                   "application/json", body, hdr));
      journey_finish(c, 503, "shed_model_not_attached");
      c->req.reset();
      return;
    }
    if (g_health_probes && any_weighted_client_backend()) {
      // Weighted capacity exists but every circuit is open: a typed
      // 503 with a Retry-After matched to the probe cadence (the
      // fleet re-admits within ~2x the current probe interval).
      int retry = int(g_probe_interval_s * 2.0) + 1;
      std::string body =
          "{\"error\":\"every backend circuit is open\","
          "\"reason\":\"no_healthy_backend\",\"retry_after_s\":" +
          std::to_string(retry) + rid_json_field(c) + "}";
      std::string hdr = "Retry-After: " + std::to_string(retry) + "\r\n" +
                        echo_header(c);
      client_send(c, http_response(503, "Service Unavailable",
                                   "application/json", body, hdr));
      journey_finish(c, 503, "shed_no_healthy_backend");
      c->req.reset();
      return;
    }
    client_send(c, http_response(503, "Service Unavailable", "text/plain",
                                 "no backend with positive weight\n",
                                 echo_header(c)));
    journey_finish(c, 503, "shed_no_backend");
    c->req.reset();
    return;
  }
  c->backend = b;
  c->retries = 0;
  connect_upstream(c, /*allow_pool=*/true);
}

// A weight flipped positive: release the park buffer in arrival order.
// Each released request re-enters start_proxy (and may re-park if the
// weights dropped to zero again mid-release).
void release_parked() {
  if (g_parked.empty()) return;
  bool capacity = false;
  for (auto& b : g_state.backends)
    if (backend_usable(*b)) capacity = true;
  if (!capacity) return;
  // Multiplexing: release ONLY requests whose model a usable backend now
  // holds — an attach (config commit tagging a backend) wakes exactly
  // that model's queue; everyone else keeps waiting for theirs.  With
  // mux off every entry passes the filter, so the whole buffer releases
  // FIFO exactly as before.
  std::vector<ClientConn*> waiting, keep;
  for (ClientConn* c : g_parked)
    (any_usable_for_model(c->model) ? waiting : keep).push_back(c);
  if (waiting.empty()) return;
  g_parked = std::move(keep);
  for (ClientConn* c : waiting) {
    c->parked = false;
    // CUMULATIVE wait (first park of this request): a release/re-park
    // cycle must not report two short waits for one long hold.
    g_park_wait_seconds.observe(now_s() - c->park_first_t);
    g_park_released_total++;
    journey_park_end(c);  // the hold span closes; a re-park opens a new one
    // Fresh failover budget for the re-dispatch: the backends that
    // failed before the park are exactly the ones a probe may just
    // have re-admitted.
    c->failover_attempts = 0;
    c->failover_tried.clear();
    start_proxy(c);
  }
}

// Expire parked requests older than the timeout with a typed 503 —
// a client must never hang forever on a CR that refuses to wake.
void expire_parked() {
  if (g_parked.empty()) return;
  double now = now_s();
  std::vector<ClientConn*> keep;
  std::vector<ClientConn*> expired;
  // Expiry counts from the FIRST park of the request: release/re-park
  // cycles (a replica draining to weight 0 under the parked queue, a
  // failover exhaustion re-parking) must not extend the bound — the
  // client sheds typed at the advertised timeout, never hangs.
  for (ClientConn* c : g_parked)
    (now - c->park_first_t >= g_park_timeout_s ? expired : keep).push_back(c);
  if (expired.empty()) return;
  g_parked.swap(keep);
  for (ClientConn* c : expired) {
    c->parked = false;
    g_park_timeout_total++;
    client_send(c, park_503_body("park_timeout", int(g_park_timeout_s), c));
    journey_finish(c, 503, "shed_park_timeout");
    c->req.reset();
    // Same contract as fail_502: a pipelined next request buffered
    // while parked must still be answered, not hang until the client
    // happens to write again.
    if (!c->pending.empty()) {
      c->req.buf = std::move(c->pending);
      c->pending.clear();
      advance_client(c);
    }
  }
}

// A pooled keep-alive connection can always lose a race with the backend's
// idle timeout: the backend closes just as we reuse the socket.  If that
// happens before any response byte arrives, retry the request on a FRESH
// connection (same backend, so the metric split is unaffected) — standard
// reverse-proxy behavior; without it a promotion run sees phantom 502s.
// Returns true if the request was retried (u is gone).
bool retry_stale_upstream(UpstreamConn* u, ClientConn* c) {
  if (!u->reused || !u->resp.buf.empty() || c->retries >= 2) return false;
  c->retries++;
  c->upstream = nullptr;
  u->client = nullptr;
  close_upstream(u);
  journey_leg_done(c, 0, 0);  // the stale pooled attempt, closed as failed
  connect_upstream(c, /*allow_pool=*/false);
  return true;
}

// Client request fully buffered: admin or proxy.
void dispatch_request(ClientConn* c) {
  c->t_start = now_s();
  c->park_first_t = 0;  // a NEW request gets its own cumulative bound
  c->failover_attempts = 0;
  c->failover_tried.clear();
  if (c->req.path.rfind("/router/", 0) == 0) {
    handle_admin(c);
    c->req.reset();
  } else {
    c->feedback = c->req.path == "/api/v1.0/feedback";
    journey_begin(c, c->t_start);
    start_proxy(c);
  }
}

// Dispatch as many fully-buffered requests as possible.  A keep-alive
// client may send request N+1 before N's response (pipelining); bytes past
// the current message are held in c->pending and fed back here after each
// response completes, so nothing is dropped and bodies forwarded upstream
// are framed exactly (no smuggling of the next request's bytes).
void advance_client(ClientConn* c) {
  while (!c->upstream && !c->closing && !c->parked &&
         c->relay_stage == RelayStage::None) {
    if (!c->req.headers_complete()) {
      if (!c->req.try_parse_headers(/*is_request=*/true)) {
        client_send(c, http_response(400, "Bad Request", "text/plain",
                                     "bad request\n"));
        c->closing = true;
        return;
      }
      if (!c->req.headers_complete()) return;  // need more bytes
    }
    ssize_t end = c->req.message_end(/*is_request=*/true, /*eof=*/false);
    if (end < 0) return;  // body incomplete
    // Stash bytes of the next message before dispatching this one.
    if (size_t(end) < c->req.buf.size()) {
      c->pending.insert(0, c->req.buf.substr(size_t(end)));
      c->req.buf.resize(size_t(end));
    }
    dispatch_request(c);  // resets c->req (admin/503/502) or sets upstream
    if (c->upstream) return;  // next request advances when the response lands
    if (c->parked) return;    // held intact for the release re-dispatch
    if (c->pending.empty()) return;
    c->req.buf = std::move(c->pending);
    c->pending.clear();
  }
}

void on_client_readable(ClientConn* c) {
  char tmp[65536];
  // Parked counts as in flight: the buffered request must stay intact
  // for the release re-dispatch, so later pipelined bytes go to pending.
  // A relay in any stage likewise: c->req is the original request the
  // final Forward leg still needs.
  bool in_flight = c->upstream != nullptr || c->parked ||
                   c->relay_stage != RelayStage::None;
  while (true) {
    ssize_t n = read(c->fd, tmp, sizeof(tmp));
    if (n > 0) {
      // While a request is being proxied, c->req holds the DISPATCHED
      // message; new bytes belong to the next one.
      (in_flight ? c->pending : c->req.buf).append(tmp, size_t(n));
    } else if (n == 0) {
      close_client(c);
      return;
    } else {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      close_client(c);
      return;
    }
  }
  // Caps: one greedy client must not balloon the router's memory.
  if (!c->req.headers_complete() && c->req.buf.size() > kMaxHeaderBytes) {
    client_send(c, http_response(431, "Request Header Fields Too Large",
                                 "text/plain", "headers too large\n"));
    c->closing = true;
    return;
  }
  if (c->req.buf.size() > kMaxMessageBytes ||
      c->pending.size() > kMaxMessageBytes) {
    client_send(c, http_response(413, "Payload Too Large", "text/plain",
                                 "request too large\n"));
    c->closing = true;
    return;
  }
  if (!in_flight) advance_client(c);
}

void on_client_writable(ClientConn* c) {
  while (c->out_off < c->out.size()) {
    ssize_t n = write(c->fd, c->out.data() + c->out_off, c->out.size() - c->out_off);
    if (n > 0) {
      c->out_off += size_t(n);
    } else {
      if (errno == EAGAIN || errno == EWOULDBLOCK) return;
      close_client(c);
      return;
    }
  }
  c->out.clear();
  c->out_off = 0;
  if (c->closing) {
    close_client(c);
    return;
  }
  epoll_set(c->fd, EPOLLIN);
}

// Detach-time connection disposal, shared by the normal proxy path and
// the relay legs (one copy of the reuse rules, so they can never
// diverge): return the upstream to its backend's keep-alive pool
// unless the response/backend semantics force a close.  Caller must
// have detached u from its client already; u->resp is consumed.
// Returns true when the response was close-delimited (the CLIENT can
// then only find the body's end by connection close).
bool pool_or_close_upstream(UpstreamConn* u, bool eof) {
  // A close-delimited response (no Content-Length, not chunked, not a
  // no-body status) completed only because eof arrived.
  bool close_delimited =
      u->resp.message_end(/*is_request=*/false, /*eof=*/false) < 0;
  // HTTP/1.0 defaults to close (http.server-style backends); HTTP/1.1
  // to keep-alive; an explicit Connection header overrides either.  A
  // conn whose backend was repointed since connect must not re-enter
  // the pool — it still talks to the OLD address/version.
  auto conn_hdr = u->resp.headers.find("connection");
  bool http10 = u->resp.version == "HTTP/1.0";
  bool backend_close = eof || close_delimited;
  if (conn_hdr != u->resp.headers.end()) {
    std::string cv = lower(conn_hdr->second);
    backend_close |= cv.find("close") != std::string::npos;
    if (cv.find("keep-alive") != std::string::npos) http10 = false;
  }
  backend_close |= http10;
  backend_close |= u->addr_epoch != u->backend->addr_epoch;
  if (backend_close) {
    close_upstream(u);
  } else {
    u->resp.reset();
    u->backend->idle_conns.push_back(u->fd);
    epoll_set(u->fd, EPOLLIN);  // observe idle-close
  }
  return close_delimited;
}

void on_upstream_event(UpstreamConn* u, uint32_t events) {
  if (u->probe) {
    // Half-open health probe: no client, never pooled — its own state
    // machine entirely.
    handle_probe_event(u, events);
    return;
  }
  if (events & (EPOLLERR | EPOLLHUP)) {
    if (!u->client) {
      // Idle pooled connection died (close_upstream scrubs the pool entry).
      close_upstream(u);
      return;
    }
    if (events & EPOLLERR) {
      ClientConn* c = u->client;
      if (retry_stale_upstream(u, c)) return;
      bool first_byte = !u->resp.buf.empty();
      c->upstream = nullptr;
      u->client = nullptr;
      close_upstream(u);
      fail_502(c, "backend connection error", first_byte);
      return;
    }
    // EPOLLHUP with an active request: drain whatever the backend wrote
    // before closing — the read path below observes EOF and either
    // completes a close-delimited response or 502s.
    events |= EPOLLIN;
  }

  u->connecting = false;

  if (events & EPOLLOUT) {
    while (u->out_off < u->out.size()) {
      ssize_t n = write(u->fd, u->out.data() + u->out_off, u->out.size() - u->out_off);
      if (n > 0) {
        u->out_off += size_t(n);
      } else {
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        ClientConn* c = u->client;
        if (c && retry_stale_upstream(u, c)) return;
        bool first_byte = !u->resp.buf.empty();
        u->client = nullptr;
        if (c) {
          c->upstream = nullptr;
          fail_502(c, "backend write failed", first_byte);
        }
        close_upstream(u);
        return;
      }
    }
    if (u->out_off >= u->out.size()) epoll_set(u->fd, EPOLLIN);
  }

  if (events & EPOLLIN) {
    char tmp[65536];
    bool eof = false;
    while (true) {
      ssize_t n = read(u->fd, tmp, sizeof(tmp));
      if (n > 0) {
        u->resp.buf.append(tmp, size_t(n));
      } else if (n == 0) {
        eof = true;
        break;
      } else {
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        eof = true;
        break;
      }
    }
    ClientConn* c = u->client;
    if (!c) {  // response bytes on an idle conn: stale; drop it
      close_upstream(u);
      return;
    }
    if (u->resp.buf.size() > kMaxMessageBytes) {
      u->client = nullptr;
      c->upstream = nullptr;
      fail_502(c, "backend response too large", /*first_byte_seen=*/true);
      close_upstream(u);
      return;
    }
    if (!u->resp.headers_complete()) u->resp.try_parse_headers(/*is_request=*/false);
    if (u->resp.headers_complete() && u->resp.complete(/*is_request=*/false, eof)) {
      if (c->relay_stage == RelayStage::Export ||
          c->relay_stage == RelayStage::Import) {
        // Internal relay leg: the response never reaches the client and
        // never lands in the gate histograms (these are admin calls,
        // not predictions).  Detach + pool the connection exactly like
        // the normal path, then advance the relay state machine.
        int status = u->resp.status;
        std::string body = response_body(u->resp, eof);
        BackendPtr leg_backend = u->backend;
        journey_leg_done(c, status, body.size());
        c->upstream = nullptr;
        u->client = nullptr;
        pool_or_close_upstream(u, eof);
        // Relay legs feed passive health like any other response: a
        // prefill replica answering 5xx exports is as tripped as one
        // refusing connections.
        if (status >= 500) note_backend_failure(leg_backend);
        else note_backend_success(leg_backend);
        relay_on_response(c, status, std::move(body));
        return;
      }
      c->relay_stage = RelayStage::None;  // Forward leg completed
      double dt = now_s() - c->t_start;
      finish_request(u->backend, u->resp.status, dt, c->feedback);
      if (u->resp.status >= 500) note_backend_failure(u->backend);
      else note_backend_success(u->backend);
      journey_leg_done(c, u->resp.status, u->resp.buf.size());
      if (c->journey) {
        c->journey->backend = u->backend ? u->backend->name : "";
        c->journey->role = u->backend ? u->backend->role : "";
        if (g_journey_ring > 0)
          // Every byte the client sees carries the correlatable id,
          // even when the upstream did not echo it.
          ensure_response_request_id(&u->resp.buf,
                                     c->journey->request_id);
      }
      client_send(c, u->resp.buf);
      journey_finish(c, u->resp.status, outcome_for_status(u->resp.status));
      c->req.reset();
      c->upstream = nullptr;
      u->client = nullptr;
      // Pool BEFORE advancing the client so a pipelined next request
      // can reuse this very connection.  A close-delimited response is
      // forwarded verbatim — the CLIENT can then only find the body's
      // end by connection close, so close our side too.
      if (pool_or_close_upstream(u, eof)) c->closing = true;
      // A pipelined next request may be waiting; dispatch it now.
      if (!c->pending.empty()) {
        c->req.buf = std::move(c->pending);
        c->pending.clear();
      }
      advance_client(c);
      return;
    }
    if (eof) {  // EOF before the message completed
      if (retry_stale_upstream(u, c)) return;
      bool first_byte = !u->resp.buf.empty();
      u->client = nullptr;
      c->upstream = nullptr;
      fail_502(c, "backend EOF mid-response", first_byte);
      close_upstream(u);
    }
  }
}

// ---------------------------------------------------------------------------
// main
// ---------------------------------------------------------------------------

void usage() {
  die("usage: tpumlops-router --port N [--namespace ns] [--deployment name]\n"
      "       [--backend name=host:port:weight[:role]]...\n"
      "       [--park-buffer N] [--park-timeout-s S]\n"
      "       [--affinity-tokens N] [--kv-handoff 0|1] [--handoff-retries N]\n"
      "       [--health-probes 0|1] [--health-threshold N]\n"
      "       [--probe-interval-s S] [--failover-retries N]\n"
      "       [--journey-ring N] [--timeseries-ring N] [--access-log 0|1]\n"
      "       [--mux-models 0|1]");
}

}  // namespace

int main(int argc, char** argv) {
  int port = 0;
  std::vector<BackendSpec> specs;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() -> std::string {
      if (i + 1 >= argc) usage();
      return argv[++i];
    };
    if (a == "--port") port = atoi(next().c_str());
    else if (a == "--namespace") g_state.ns = next();
    else if (a == "--deployment") g_state.deployment = next();
    else if (a == "--park-buffer") g_park_max = atoi(next().c_str());
    else if (a == "--park-timeout-s") g_park_timeout_s = atof(next().c_str());
    else if (a == "--affinity-tokens") g_affinity_tokens = atoi(next().c_str());
    else if (a == "--kv-handoff") g_handoff_enabled = atoi(next().c_str());
    else if (a == "--handoff-retries") g_handoff_retries = atoi(next().c_str());
    else if (a == "--health-probes") g_health_probes = atoi(next().c_str());
    else if (a == "--health-threshold") g_health_threshold = atoi(next().c_str());
    else if (a == "--probe-interval-s") g_probe_interval_s = atof(next().c_str());
    else if (a == "--failover-retries") g_failover_retries = atoi(next().c_str());
    else if (a == "--journey-ring") g_journey_ring = atoi(next().c_str());
    else if (a == "--timeseries-ring") g_timeseries_ring = atoi(next().c_str());
    else if (a == "--access-log") g_access_log = atoi(next().c_str());
    else if (a == "--mux-models") g_mux = atoi(next().c_str());
    else if (a == "--backend") {
      // name=host:port:weight[:role]
      std::string v = next();
      BackendSpec s;
      size_t eq = v.find('=');
      size_t c1 = v.find(':', eq);
      size_t c2 = v.find(':', c1 + 1);
      if (eq == std::string::npos || c1 == std::string::npos ||
          c2 == std::string::npos)
        usage();
      size_t c3 = v.find(':', c2 + 1);
      s.name = v.substr(0, eq);
      s.host = v.substr(eq + 1, c1 - eq - 1);
      s.port = atoi(v.substr(c1 + 1, c2 - c1 - 1).c_str());
      if (c3 == std::string::npos) {
        s.weight = atoi(v.substr(c2 + 1).c_str());
      } else {
        s.weight = atoi(v.substr(c2 + 1, c3 - c2 - 1).c_str());
        s.role = v.substr(c3 + 1);
      }
      specs.push_back(s);
    } else usage();
  }
  if (!port) usage();
  if (g_journey_ring < 0 || g_journey_ring > kMaxJourneyRing)
    die("--journey-ring must be in [0, %d]", kMaxJourneyRing);
  if (g_timeseries_ring < 0 || g_timeseries_ring > kMaxTimeseriesRing)
    die("--timeseries-ring must be in [0, %d]", kMaxTimeseriesRing);
  // Trace-plane clock anchors + id-minting seed.
  g_t0_mono = now_s();
  g_t0_unix = wall_s();
  g_rng_state = uint64_t(g_t0_unix * 1e6) ^ (uint64_t(getpid()) << 32);
  std::string bad = apply_config("", "", specs);
  if (!bad.empty()) die("%s", bad.c_str());

  signal(SIGPIPE, SIG_IGN);

  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) die("socket: %s", strerror(errno));
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(uint16_t(port));
  if (bind(lfd, (sockaddr*)&addr, sizeof(addr)) < 0)
    die("bind %d: %s", port, strerror(errno));
  if (listen(lfd, 512) < 0) die("listen: %s", strerror(errno));
  set_nonblock(lfd);

  g_epoll = epoll_create1(0);
  g_fds[lfd] = {FdKind::Listener, nullptr, nullptr};
  epoll_add(lfd, EPOLLIN);

  fprintf(stderr, "tpumlops-router listening on 127.0.0.1:%d (%zu backends)\n",
          port, g_state.backends.size());

  epoll_event events[256];
  while (true) {
    // Bounded wait while requests are parked (timeouts must fire
    // without traffic) or circuits are open (half-open probes must
    // fire on schedule); -1 (block forever) otherwise.
    int timeout = -1;
    if (!g_parked.empty()) timeout = 250;
    if (any_circuit_open()) timeout = timeout < 0 ? 50 : std::min(timeout, 50);
    int n = epoll_wait(g_epoll, events, 256, timeout);
    if (n < 0) {
      if (errno == EINTR) continue;
      die("epoll_wait: %s", strerror(errno));
    }
    expire_parked();
    start_due_probes();
    for (int i = 0; i < n; i++) {
      uint64_t key = events[i].data.u64;
      int fd = int(uint32_t(key));
      uint32_t gen = uint32_t(key >> 32);
      auto it = g_fds.find(fd);
      if (it == g_fds.end() || it->second.gen != gen) continue;  // stale event
      FdEntry ent = it->second;
      if (ent.kind == FdKind::Listener) {
        while (true) {
          int cfd = accept(lfd, nullptr, nullptr);
          if (cfd < 0) break;
          set_nonblock(cfd);
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          auto* c = new ClientConn();
          c->fd = cfd;
          g_fds[cfd] = {FdKind::Client, c, nullptr};
          epoll_add(cfd, EPOLLIN);
        }
      } else if (ent.kind == FdKind::Client) {
        ClientConn* c = ent.client;
        if (events[i].events & (EPOLLERR | EPOLLHUP)) {
          close_client(c);
          continue;
        }
        if (events[i].events & EPOLLIN) on_client_readable(c);
        // Re-look up: the readable handler may have closed this conn (and
        // the fd number may even have been reused for an upstream socket).
        auto again = g_fds.find(fd);
        if (again != g_fds.end() && again->second.gen == gen &&
            again->second.kind == FdKind::Client && again->second.client == c &&
            ((events[i].events & EPOLLOUT) || !c->out.empty()))
          on_client_writable(c);
      } else {
        on_upstream_event(ent.upstream, events[i].events);
      }
    }
  }
}
