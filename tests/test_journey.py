"""Fleet trace plane: router journey ring, W3C context propagation,
access log, trace stitching, and SLO error-budget accounting.

Drives the real compiled router binary against in-process HTTP backends
(the tests/test_router.py harness) plus the pure-Python stitcher and the
reconciler SLO step against the fakes.  The chaos-driven LIVE e2e
(relay → failover → park reconstructed as one chrome trace) lives in
tests/test_e2e_localplane.py.
"""

from __future__ import annotations

import http.server
import json
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from tpumlops.clients.base import MLFLOWMODEL, ModelMetrics, ObjectRef
from tpumlops.clients.chaos import ChaosProxy
from tpumlops.clients.fakes import FakeKube, FakeMetrics, FakeRegistry
from tpumlops.clients.router import RouterProcess, RouterSync, build_router
from tpumlops.operator.reconciler import Reconciler
from tpumlops.utils.clock import FakeClock
from tpumlops.utils.trace_stitch import (
    filter_request,
    request_ids_by_pid,
    stitch_chrome_traces,
)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _Echo(http.server.BaseHTTPRequestHandler):
    """Replies with the trace headers it saw; tallies them per class."""

    tag = "?"
    seen: list  # class-level, set per subclass

    def _reply(self, code=200):
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        type(self).seen.append(
            {
                "rid": self.headers.get("X-Request-Id"),
                "tp": self.headers.get("traceparent"),
                "path": self.path,
            }
        )
        payload = json.dumps(
            {
                "who": self.tag,
                "rid": self.headers.get("X-Request-Id"),
                "tp": self.headers.get("traceparent"),
                "echo": body.decode() or None,
            }
        ).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    do_GET = _reply
    do_POST = _reply

    def log_message(self, *a):  # noqa: N802
        pass


class _FleetEcho(_Echo):
    """Stub fleet replica: /admin/kv/export serves a blob, /admin/kv/
    import acknowledges — both tallying the trace headers they saw."""

    def do_POST(self):  # noqa: N802
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        type(self).seen.append(
            {
                "rid": self.headers.get("X-Request-Id"),
                "tp": self.headers.get("traceparent"),
                "path": self.path,
            }
        )
        if self.path == "/admin/kv/export":
            payload = b"KVBLOB-" + self.tag.encode()
            ctype = "application/octet-stream"
        elif self.path == "/admin/kv/import":
            payload = b'{"imported_tokens":8}'
            ctype = "application/json"
        else:
            payload = json.dumps(
                {
                    "who": self.tag,
                    "rid": self.headers.get("X-Request-Id"),
                    "handoff": self.headers.get("X-Tpumlops-Handoff"),
                }
            ).encode()
            ctype = "application/json"
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)


def start_backend(tag: str, handler=_Echo):
    cls = type(f"Journey_{tag}", (handler,), {"tag": tag, "seen": []})
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), cls)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_address[1], cls


def ask(port: int, path="/predict", body=None, headers=None):
    data = json.dumps(body).encode() if body is not None else b"{}"
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, headers=headers or {}
    )
    resp = urllib.request.urlopen(req, timeout=10)
    return resp, json.loads(resp.read())


@pytest.fixture(scope="module")
def binary():
    return build_router()


@pytest.fixture()
def traced(binary):
    srv, bport, cls = start_backend("v1")
    router = RouterProcess(
        port=free_port(),
        backends={"v1": ("127.0.0.1", bport, 100)},
        namespace="models",
        deployment="llm",
        binary=binary,
        journey_ring=8,
        access_log=True,
    ).start()
    yield router, cls
    router.stop()
    srv.shutdown()


# ---------------------------------------------------------------------------
# Identity: adopt-or-mint + propagation + echo
# ---------------------------------------------------------------------------


def test_mints_identity_and_propagates_when_absent(traced):
    router, cls = traced
    resp, body = ask(router.port)
    rid = resp.headers.get("X-Request-Id")
    # Minted: 32-hex trace id doubles as the request id (the server's
    # own adoption rule), echoed to the client AND sent upstream.
    assert rid and len(rid) == 32 and int(rid, 16) >= 0
    assert body["rid"] == rid
    assert body["tp"].startswith("00-" + rid + "-")
    assert body["tp"].endswith("-01")


def test_adopts_client_identity_verbatim(traced):
    router, cls = traced
    tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    resp, body = ask(
        router.port,
        headers={"X-Request-Id": "my-req-7", "traceparent": tp},
    )
    assert resp.headers.get("X-Request-Id") == "my-req-7"
    assert body["rid"] == "my-req-7"
    # Trace id adopted from the traceparent; span id is the ROUTER's
    # fresh leg span, not the client's.
    assert body["tp"].startswith("00-" + "ab" * 16 + "-")
    assert ("cd" * 8) not in body["tp"]


def test_fresh_span_id_per_leg(traced):
    router, cls = traced
    ask(router.port)
    ask(router.port)
    spans = {rec["tp"].split("-")[2] for rec in cls.seen}
    assert len(spans) == len(cls.seen)  # never reused


# ---------------------------------------------------------------------------
# Ring bounds, eviction, journey shape
# ---------------------------------------------------------------------------


def test_ring_bounds_and_eviction(traced):
    router, cls = traced
    rids = []
    for i in range(12):
        resp, _ = ask(router.port, headers={"X-Request-Id": f"req-{i}"})
        rids.append(f"req-{i}")
    j = router.admin.journeys()
    assert j["capacity"] == 8
    assert j["recorded"] == 12
    kept = [r["request_id"] for r in j["requests"]]
    assert kept == rids[-8:]  # FIFO eviction, arrival order preserved
    rec = j["requests"][-1]
    assert rec["outcome"] == "ok" and rec["status"] == 200
    assert rec["backend"] == "v1" and rec["role"] == "unified"
    assert rec["legs"][0]["kind"] == "forward"
    assert rec["legs"][0]["backend"] == "v1"
    assert rec["legs"][0]["status"] == 200
    assert rec["legs"][0]["bytes"] > 0
    assert rec["duration_ms"] >= 0
    assert rec["handoff_ms"] is None and rec["parks"] == []
    assert "started_unix" in j


def test_chrome_export_validity_over_live_http(traced):
    router, cls = traced
    for i in range(3):
        ask(router.port, headers={"X-Request-Id": f"c-{i}"})
    trace = router.admin.journey_trace()
    evs = trace["traceEvents"]
    assert trace["displayTimeUnit"] == "ms"
    for ev in evs:
        assert {"name", "ph", "pid"} <= set(ev)
        if ev["ph"] != "M":
            assert ev["ts"] >= 0
    # Async b/e pairs balance per request id.
    b = [e["id"] for e in evs if e["ph"] == "b"]
    e = [e["id"] for e in evs if e["ph"] == "e"]
    assert sorted(b) == sorted(e) and set(b) >= {"c-0", "c-1", "c-2"}
    # One thread per backend, legs land on it.
    names = {
        e["args"]["name"] for e in evs if e["name"] == "thread_name"
    }
    assert {"router", "backend v1"} <= names
    legs = [e for e in evs if e.get("cat") == "leg"]
    assert legs and all(ev["tid"] == 1 for ev in legs)
    # ?format=json returns the raw ring; unknown formats are a 400.
    assert router.admin.journey_trace("json")["requests"]
    with pytest.raises(urllib.error.HTTPError) as err:
        router.admin.journey_trace("perfetto")
    assert err.value.code == 400


def test_access_log_contract(traced):
    router, cls = traced
    ask(router.port, headers={"X-Request-Id": "logged-1"})
    deadline = time.monotonic() + 5
    lines = []
    while time.monotonic() < deadline:
        lines = [
            rec for rec in router.access_log_lines()
            if rec["request_id"] == "logged-1"
        ]
        if lines:
            break
        time.sleep(0.05)
    assert lines, "access log line never appeared"
    rec = lines[0]
    # The satellite contract: mirrors the server's tpumlops.request line.
    for key in (
        "request_id", "backend", "role", "outcome", "code",
        "handoff_ms", "park_ms", "failover_count", "duration_ms",
    ):
        assert key in rec, key
    assert rec["backend"] == "v1" and rec["outcome"] == "ok"
    assert rec["code"] == 200 and rec["failover_count"] == 0


# ---------------------------------------------------------------------------
# Defaults off = byte-for-byte
# ---------------------------------------------------------------------------


def test_journey_ring_zero_is_byte_for_byte(binary):
    srv, bport, cls = start_backend("v1")
    router = RouterProcess(
        port=free_port(),
        backends={"v1": ("127.0.0.1", bport, 100)},
        binary=binary,
    ).start()
    try:
        resp, body = ask(router.port)
        # No minting, no injection, no echo: the wire is the old router.
        assert body["rid"] is None and body["tp"] is None
        assert resp.headers.get("X-Request-Id") is None
        # Client-supplied ids pass through verbatim (old passthrough).
        resp, body = ask(router.port, headers={"X-Request-Id": "keep-me"})
        assert body["rid"] == "keep-me"
        # Debug endpoints 404 naming the knob.
        with pytest.raises(urllib.error.HTTPError) as err:
            router.admin.journeys()
        assert err.value.code == 404
        assert b"journey-ring" in err.value.read()
        # No new metric family, not even a header line.
        assert "tpumlops_router_request_seconds" not in (
            router.admin.metrics_text()
        )
    finally:
        router.stop()
        srv.shutdown()


def test_router_sync_threads_journey_ring_annotation(binary):
    """spec.fleet.observability.journeyRing -> builder annotation ->
    RouterSync -> live router ring (and back to 0 when the annotation
    goes away — the manifest is the source of truth)."""
    srv, bport, cls = start_backend("v1")
    router = RouterProcess(
        port=free_port(),
        backends={"v1": ("127.0.0.1", bport, 100)},
        binary=binary,
    ).start()
    try:
        sync = RouterSync(
            router.admin, resolve=lambda name: ("127.0.0.1", bport)
        )
        manifest = {
            "metadata": {
                "name": "llm",
                "namespace": "models",
                "annotations": {"tpumlops.dev/fleet-journey-ring": "32"},
            },
            "spec": {"predictors": [{"name": "v1", "traffic": 100}]},
        }
        sync.sync_manifest(manifest)
        assert router.admin.get_config().get("journeyRing") == 32
        ask(router.port, headers={"X-Request-Id": "synced"})
        assert router.admin.journeys()["requests"][0]["request_id"] == (
            "synced"
        )
        # Annotation removed: the next sync disables the plane.
        manifest["metadata"]["annotations"] = {}
        sync.sync_manifest(manifest)
        assert "journeyRing" not in router.admin.get_config()
        with pytest.raises(urllib.error.HTTPError):
            router.admin.journeys()
    finally:
        router.stop()
        srv.shutdown()


# ---------------------------------------------------------------------------
# Propagation through relay / failover / park (ChaosProxy-driven)
# ---------------------------------------------------------------------------


def test_relay_legs_carry_one_identity(binary):
    servers, classes, ports = {}, {}, {}
    for tag in ("p1", "d1"):
        servers[tag], ports[tag], classes[tag] = start_backend(
            tag, _FleetEcho
        )
    router = RouterProcess(
        port=free_port(),
        backends={
            "p1": ("127.0.0.1", ports["p1"], 100, "prefill"),
            "d1": ("127.0.0.1", ports["d1"], 100, "decode"),
        },
        namespace="models",
        deployment="fleet",
        binary=binary,
        affinity_tokens=4,
        journey_ring=8,
    ).start()
    try:
        resp, body = ask(
            router.port,
            path="/v2/models/m/generate",
            body={"prompt_ids": [7, 7, 7, 7, 1], "max_new_tokens": 2},
            headers={"X-Request-Id": "relay-1"},
        )
        assert body["who"] == "d1" and body["handoff"] is not None
        assert resp.headers.get("X-Request-Id") == "relay-1"
        # Every leg — export on p1, import + forward on d1 — carried the
        # SAME propagated id with per-leg span ids.
        p1 = [r for r in classes["p1"].seen if r["path"].endswith("export")]
        d1_paths = {r["path"]: r for r in classes["d1"].seen}
        assert p1 and p1[0]["rid"] == "relay-1"
        assert d1_paths["/admin/kv/import"]["rid"] == "relay-1"
        assert d1_paths["/v2/models/m/generate"]["rid"] == "relay-1"
        spans = {
            r["tp"].split("-")[2]
            for r in classes["p1"].seen + classes["d1"].seen
        }
        assert len(spans) == 3  # one fresh span per leg
        # The journey records all three legs in order.
        rec = router.admin.journeys()["requests"][-1]
        assert [leg["kind"] for leg in rec["legs"]] == [
            "export", "import", "relay-forward",
        ]
        assert [leg["backend"] for leg in rec["legs"]] == ["p1", "d1", "d1"]
        assert rec["affinity"] == "miss"
        assert rec["handoff_ms"] >= 0
        assert rec["outcome"] == "ok"
    finally:
        router.stop()
        for srv in servers.values():
            srv.shutdown()


def test_failover_retry_propagates_same_identity(binary):
    srv_b, bport, cls_b = start_backend("b")
    chaos = ChaosProxy(free_port())  # nothing behind it: dead upstream
    chaos.stop()
    router = RouterProcess(
        port=free_port(),
        backends={
            "a": ("127.0.0.1", chaos.port, 50),
            "b": ("127.0.0.1", bport, 50),
        },
        binary=build_router(),
        failover_retries=2,
        journey_ring=8,
    ).start()
    try:
        # Drive until a request lands on the dead 'a' first and fails
        # over to 'b' (SWRR alternates, so at most a few tries).
        for i in range(6):
            resp, body = ask(
                router.port, headers={"X-Request-Id": f"fo-{i}"}
            )
            assert body["who"] == "b"
        journeys = router.admin.journeys()["requests"]
        failed_over = [r for r in journeys if r["failovers"] > 0]
        assert failed_over, journeys
        rec = failed_over[0]
        assert rec["outcome"] == "ok" and rec["backend"] == "b"
        # Two forward legs: the dead attempt (status 0) + the retry.
        kinds = [(leg["kind"], leg["status"]) for leg in rec["legs"]]
        assert ("forward", 0) in kinds and ("forward", 200) in kinds
        # The retry carried the SAME request id.
        assert rec["request_id"] in {r["rid"] for r in cls_b.seen}
        # The per-outcome histogram saw the ok outcome.
        mt = router.admin.metrics_text()
        assert 'tpumlops_router_request_seconds_count{' in mt
        assert 'outcome="ok"' in mt
    finally:
        router.stop()
        srv_b.shutdown()


def test_park_hold_span_recorded_and_shed_typed_carries_id(binary):
    srv, bport, cls = start_backend("v1")
    router = RouterProcess(
        port=free_port(),
        backends={"v1": ("127.0.0.1", bport, 0)},  # weight 0: parks
        binary=binary,
        park_buffer=4,
        park_timeout_s=30.0,
        journey_ring=8,
    ).start()
    results = []

    def send():
        try:
            resp, body = ask(
                router.port, headers={"X-Request-Id": "parked-1"}
            )
            results.append((resp.status, body))
        except urllib.error.HTTPError as e:
            results.append((e.code, json.loads(e.read())))

    try:
        t = threading.Thread(target=send, daemon=True)
        t.start()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if router.admin.parked()["parked"] == 1:
                break
            time.sleep(0.02)
        assert router.admin.parked()["parked"] == 1
        time.sleep(0.15)  # measurable hold
        router.admin.set_weights({"v1": 100})  # the wake
        t.join(timeout=10)
        assert results and results[0][0] == 200
        rec = router.admin.journeys()["requests"][-1]
        assert rec["request_id"] == "parked-1"
        assert rec["outcome"] == "ok"
        assert len(rec["parks"]) == 1
        assert rec["park_ms"] >= 100
        # The park span renders on the router track in the chrome view.
        evs = router.admin.journey_trace()["traceEvents"]
        parked = [e for e in evs if e["name"] == "parked"]
        assert parked and parked[0]["tid"] == 0
        assert parked[0]["args"]["request_id"] == "parked-1"

        # Park OVERFLOW sheds typed WITH the id (body + header): fill
        # the buffer, then one more must shed.
        router.admin.set_weights({"v1": 0})
        results.clear()
        req = urllib.request.Request(
            f"http://127.0.0.1:{router.port}/predict", data=b"{}",
            headers={"X-Request-Id": "filler"},
        )
        threads = []
        for i in range(4):
            th = threading.Thread(
                target=lambda: urllib.request.urlopen(req, timeout=3),
                daemon=True,
            )
            th.start()
            threads.append(th)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if router.admin.parked()["parked"] == 4:
                break
            time.sleep(0.02)
        try:
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{router.port}/predict", data=b"{}",
                    headers={"X-Request-Id": "overflowed"},
                ),
                timeout=5,
            )
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert e.headers.get("X-Request-Id") == "overflowed"
            shed = json.loads(e.read())
            assert shed["reason"] == "park_overflow"
            assert shed["request_id"] == "overflowed"
        shed_rec = [
            r for r in router.admin.journeys()["requests"]
            if r["request_id"] == "overflowed"
        ]
        assert shed_rec and shed_rec[0]["outcome"] == "shed_park_overflow"
    finally:
        router.admin.set_weights({"v1": 100})  # release before teardown
        time.sleep(0.1)
        router.stop()
        srv.shutdown()


def test_failover_exhaustion_shed_carries_id(binary):
    chaos = ChaosProxy(free_port())
    chaos.stop()  # dead from the start
    router = RouterProcess(
        port=free_port(),
        backends={"a": ("127.0.0.1", chaos.port, 100)},
        binary=binary,
        failover_retries=1,
        journey_ring=8,
    ).start()
    try:
        try:
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{router.port}/predict", data=b"{}",
                    headers={"X-Request-Id": "exhausted-1"},
                ),
                timeout=5,
            )
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            body = json.loads(e.read())
            assert body["reason"] == "upstream_failed"
            assert body["request_id"] == "exhausted-1"
            assert e.headers.get("X-Request-Id") == "exhausted-1"
        rec = router.admin.journeys()["requests"][-1]
        assert rec["outcome"] == "shed_upstream_failed"
        assert rec["status"] == 503
        mt = router.admin.metrics_text()
        assert 'outcome="shed_upstream_failed"' in mt
    finally:
        router.stop()


# ---------------------------------------------------------------------------
# Stitching (pure)
# ---------------------------------------------------------------------------


def _mini_source(name, started, rid, ts=10):
    return {
        "name": name,
        "started_unix": started,
        "trace": {
            "traceEvents": [
                {
                    "name": "process_name", "ph": "M", "pid": 1, "tid": 0,
                    "args": {"name": "original"},
                },
                {
                    "name": "request", "cat": "request", "ph": "b",
                    "id": rid, "ts": ts, "pid": 1, "tid": 0,
                },
                {
                    "name": "request", "cat": "request", "ph": "e",
                    "id": rid, "ts": ts + 5, "pid": 1, "tid": 0,
                },
            ]
        },
    }


def test_stitch_shifts_onto_common_clock_and_renames_pids():
    merged = stitch_chrome_traces(
        [
            _mini_source("router", 100.0, "r1", ts=10),
            _mini_source("replica-0", 100.5, "r1", ts=10),
        ]
    )
    evs = merged["traceEvents"]
    pids = {e["pid"] for e in evs}
    assert pids == {1, 2}
    names = {
        e["pid"]: e["args"]["name"]
        for e in evs
        if e["name"] == "process_name"
    }
    assert names == {1: "router", 2: "replica-0"}
    # The later-started source's events shifted by the anchor delta.
    b_ts = {e["pid"]: e["ts"] for e in evs if e["ph"] == "b"}
    assert b_ts[1] == 10 and b_ts[2] == 10 + 500_000
    assert request_ids_by_pid(merged) == {1: {"r1"}, 2: {"r1"}}


def test_filter_request_keeps_one_span_tree_plus_metadata():
    merged = stitch_chrome_traces(
        [
            _mini_source("router", 100.0, "keep"),
            _mini_source("replica", 100.0, "drop"),
        ]
    )
    only = filter_request(merged, "keep")
    ids = {e.get("id") for e in only["traceEvents"] if e["ph"] != "M"}
    assert ids == {"keep"}
    assert any(e["ph"] == "M" for e in only["traceEvents"])


def test_stitched_live_router_trace_parses(traced):
    """A live router journey trace round-trips through the stitcher."""
    router, cls = traced
    ask(router.port, headers={"X-Request-Id": "stitch-live"})
    j = router.admin.journeys()
    merged = stitch_chrome_traces(
        [
            {
                "name": "router",
                "started_unix": j["started_unix"],
                "trace": router.admin.journey_trace(),
            }
        ]
    )
    assert "stitch-live" in request_ids_by_pid(merged)[1]


# ---------------------------------------------------------------------------
# SLO accounting (operator/slo.py through the reconciler)
# ---------------------------------------------------------------------------

NS, NAME = "models", "llm"


def _slo_world(slo_spec, engine_metrics=None, model_metrics=None):
    from tpumlops.clients.base import EngineMetrics

    kube = FakeKube()
    registry = FakeRegistry()
    metrics = FakeMetrics()
    spec = {
        "modelName": NAME,
        "modelAlias": "champion",
        "minioSecret": "m",
        "observability": {"historyLimit": 16},
    }
    if slo_spec is not None:
        spec["slo"] = slo_spec
    kube.create(
        ObjectRef(namespace=NS, name=NAME, **MLFLOWMODEL),
        {
            "apiVersion": "mlflow.nizepart.com/v1alpha1",
            "kind": "MlflowModel",
            "metadata": {"name": NAME, "namespace": NS},
            "spec": spec,
        },
    )
    registry.register(NAME, "1", "mlflow-artifacts:/1/aaa/artifacts/model")
    registry.set_alias(NAME, "champion", "1")
    if model_metrics is not None:
        metrics.set_metrics(NAME, "v1", NS, model_metrics)
    if engine_metrics is not None:
        metrics.set_engine_metrics(NAME, "v1", NS, engine_metrics)
    rec = Reconciler(NAME, NS, kube, registry, metrics, FakeClock())
    return kube, metrics, rec


def _cr(kube):
    return kube.get(ObjectRef(namespace=NS, name=NAME, **MLFLOWMODEL))


def test_slo_absent_is_byte_for_byte(monkeypatch):
    kube, metrics, rec = _slo_world(None)
    out = rec.reconcile(_cr(kube))
    assert out.slo is None
    status = _cr(kube)["status"]
    assert "slo" not in json.dumps(status)
    # No engine/model scrapes beyond what the rollout machinery does.
    assert rec._slo_tracker is None


def test_slo_attainment_and_gauges_within_budget():
    from tpumlops.clients.base import EngineMetrics

    kube, metrics, rec = _slo_world(
        {"ttftP99Ms": 500, "availabilityPct": 99.0, "windowMinutes": 10},
        engine_metrics=EngineMetrics(ttft_p99_s=0.2),
        model_metrics=ModelMetrics(
            latency_p95=0.1, error_rate=0.0, latency_avg=0.05,
            request_count=100,
        ),
    )
    out = rec.reconcile(_cr(kube))
    assert set(out.slo) == {"ttft_p99", "availability"}
    ev = out.slo["ttft_p99"]
    assert ev.attainment == 1.0
    assert ev.burn_rate == 0.0
    assert ev.budget_remaining == 1.0
    assert ev.observed == pytest.approx(200.0)
    assert ev.target == 500.0
    # First evaluation journals the armed within_budget state.
    history = _cr(kube)["status"]["history"]
    slo_recs = [r for r in history if r["kind"] == "slo"]
    assert {r["slo"] for r in slo_recs} == {"ttft_p99", "availability"}
    assert all(r["state"] == "within_budget" for r in slo_recs)


def test_slo_budget_exhaustion_journals_and_warns():
    from tpumlops.clients.base import EngineMetrics

    kube, metrics, rec = _slo_world(
        {"ttftP99Ms": 100, "availabilityPct": 99.0, "windowMinutes": 10},
        engine_metrics=EngineMetrics(ttft_p99_s=0.5),  # 500ms >> 100ms
        model_metrics=ModelMetrics(
            latency_p95=0.1, error_rate=0.0, latency_avg=0.05,
            request_count=100,
        ),
    )
    out = rec.reconcile(_cr(kube))
    ev = out.slo["ttft_p99"]
    assert ev.attainment == 0.0
    assert ev.burn_rate == pytest.approx(100.0)
    assert ev.budget_remaining == 0.0
    history = _cr(kube)["status"]["history"]
    exhausted = [
        r for r in history
        if r["kind"] == "slo" and r["state"] == "budget_exhausted"
    ]
    assert exhausted and exhausted[0]["slo"] == "ttft_p99"
    assert exhausted[0]["burnRate"] == pytest.approx(100.0)
    assert "SloBudgetExhausted" in kube.event_reasons()
    # A second identical step journals nothing new (state unchanged).
    n = len(_cr(kube)["status"]["history"])
    rec.reconcile(_cr(kube))
    assert len(_cr(kube)["status"]["history"]) == n


def test_slo_unobservable_signal_contributes_no_sample():
    kube, metrics, rec = _slo_world(
        {"ttftP99Ms": 100, "availabilityPct": 99.0, "windowMinutes": 10},
        # No engine metrics scripted, no traffic: every signal dark.
    )
    out = rec.reconcile(_cr(kube))
    ev = out.slo["ttft_p99"]
    assert ev.samples == 0
    assert ev.attainment is None and ev.burn_rate is None
    assert ev.state is None  # no budget claim either way
    history = (_cr(kube)["status"] or {}).get("history") or []
    assert not [r for r in history if r["kind"] == "slo"]


def test_slo_recovery_journals_transition_back():
    from tpumlops.clients.base import EngineMetrics

    kube, metrics, rec = _slo_world(
        {"ttftP99Ms": 100, "availabilityPct": 90.0, "windowMinutes": 10},
        engine_metrics=EngineMetrics(ttft_p99_s=0.5),
    )
    rec.reconcile(_cr(kube))  # exhausted
    # Recovery: fast TTFT for enough steps to climb back over 90%.
    metrics.set_engine_metrics(
        NAME, "v1", NS, EngineMetrics(ttft_p99_s=0.01)
    )
    for _ in range(12):
        rec.reconcile(_cr(kube))
    history = _cr(kube)["status"]["history"]
    states = [
        (r["slo"], r["state"]) for r in history if r["kind"] == "slo"
    ]
    assert ("ttft_p99", "budget_exhausted") in states
    assert states[-1] == ("ttft_p99", "within_budget")


# ---------------------------------------------------------------------------
# Builder threading: spec.fleet.observability.journeyRing -> annotation
# ---------------------------------------------------------------------------


def test_builder_stamps_journey_ring_annotation_only_when_set():
    from tpumlops.operator.builder import build_deployment
    from tpumlops.utils.config import OperatorConfig

    def build(fleet=None):
        spec = {
            "modelName": "llm",
            "modelAlias": "champion",
            "backend": "tpu",
            "tpu": {"meshShape": {"dp": 1, "tp": 1}, "tpuTopology": "v5e-1"},
        }
        if fleet is not None:
            spec["fleet"] = fleet
        cfg = OperatorConfig.from_spec(spec)
        return build_deployment(
            "llm", NS, "uid-1", cfg, "1", "s3://m/1", 100
        )

    # Default: the annotation is ABSENT — manifests byte-for-byte.
    base = build()
    assert "tpumlops.dev/fleet-journey-ring" not in (
        base["metadata"]["annotations"]
    )
    assert build(fleet={"observability": {"journeyRing": 0}}) == base
    # Set: stamped, with or without disaggregation.
    on = build(fleet={"observability": {"journeyRing": 128}})
    assert on["metadata"]["annotations"][
        "tpumlops.dev/fleet-journey-ring"
    ] == "128"


def test_adversarial_ids_and_paths_never_corrupt_the_export(binary):
    """Review regression: client-controlled strings (long paths, ids
    full of JSON metacharacters) must neither truncate the journey
    export mid-string nor produce an unparseable typed shed body."""
    srv, bport, cls = start_backend("v1")
    router = RouterProcess(
        port=free_port(),
        backends={"v1": ("127.0.0.1", bport, 100)},
        binary=binary,
        journey_ring=8,
    ).start()
    evil_rid = "\\" * 64 + '"' * 64  # 128 chars, escapes to ~256 bytes
    long_path = "/predict/" + "x" * 800
    try:
        ask(router.port, path=long_path,
            headers={"X-Request-Id": evil_rid})
        j = router.admin.journeys()  # json.loads inside: must parse
        rec = j["requests"][-1]
        assert rec["request_id"] == evil_rid
        assert rec["path"].startswith("/predict/x")
        assert len(rec["path"]) == 512  # bounded copy, not the full URL
        trace = router.admin.journey_trace()  # chrome export parses too
        assert any(
            e.get("id") == evil_rid for e in trace["traceEvents"]
        )
        assert trace["started_unix"] > 0  # the stitcher's clock anchor
        # Hostile bytes (raw socket: stdlib clients refuse to send
        # them): a lone UTF-8 continuation byte in the id is DROPPED at
        # adoption (ASCII-only), and raw high bytes in the PATH are
        # \u-escaped — json.loads above would have failed on either
        # leaking through verbatim.
        with socket.create_connection(("127.0.0.1", router.port)) as sk:
            sk.sendall(
                b"POST /predict/\xc3( HTTP/1.1\r\n"
                b"host: x\r\nx-request-id: ok-prefix\xc3suffix\r\n"
                b"content-length: 2\r\nconnection: close\r\n\r\n{}"
            )
            sk.settimeout(5)
            assert b"200" in sk.recv(65536).split(b"\r\n", 1)[0]
        rec = router.admin.journeys()["requests"][-1]
        assert rec["request_id"] == "ok-prefixsuffix"
        assert "\xc3" in rec["path"]  # \u00c3-escaped on the wire, so
        # json.loads round-trips it as U+00C3 instead of failing
    finally:
        router.stop()
        srv.shutdown()
    # Typed shed with the same id: the JSON body must survive escaping.
    router = RouterProcess(
        port=free_port(),
        backends={"v1": ("127.0.0.1", free_port(), 100)},  # dead
        binary=binary,
        journey_ring=8,
        failover_retries=1,
    ).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            ask(router.port, headers={"X-Request-Id": evil_rid})
        body = json.loads(err.value.read())
        assert body["reason"] == "upstream_failed"
        assert body["request_id"] == evil_rid
    finally:
        router.stop()
