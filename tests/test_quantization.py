"""Weight-only int8 quantization: accuracy, pytree mechanics, serving path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpumlops.models import llama
from tpumlops.models.quantization import (
    dequantize_tensor,
    is_quantized,
    quantize_llama,
    quantize_tensor,
    quantized_bytes,
)


def test_quantize_tensor_roundtrip_error_bound():
    w = jax.random.normal(jax.random.key(0), (4, 64, 128), jnp.float32) * 0.02
    q = quantize_tensor(w)
    assert q["q8"].dtype == jnp.int8 and q["q8"].shape == w.shape
    assert q["scale"].shape == (4, 1, 128)
    back = dequantize_tensor(q, jnp.float32)
    # Symmetric int8: per-channel max error is scale/2.
    max_err = jnp.abs(back - w).max()
    assert max_err <= float(q["scale"].max()) / 2 + 1e-7
    # Storage really is ~half of bf16.
    assert quantized_bytes(q) < 0.6 * w.size * 2


def test_quantized_llama_logits_close_and_greedy_stable():
    cfg = llama.LlamaConfig.tiny(max_seq=32)
    params = llama.init(jax.random.key(0), cfg, dtype=jnp.float32)
    qparams = quantize_llama(params)
    assert is_quantized(qparams["layers"]["q"])
    assert is_quantized(qparams["lm_head"])
    assert not is_quantized(qparams["embed"])  # gather path stays raw

    ids = jnp.asarray([[5, 9, 2, 11, 7]], jnp.int32)
    lf, _ = llama.prefill(params, ids, cfg, dtype=jnp.float32)
    lq, _ = llama.prefill(qparams, ids, cfg, dtype=jnp.float32)
    # Per-channel int8 keeps logits close in relative terms.
    rel = float(jnp.abs(lq - lf).max() / (jnp.abs(lf).max() + 1e-9))
    assert rel < 0.15, rel
    cos = float(
        jnp.sum(lq[0, -1] * lf[0, -1])
        / (jnp.linalg.norm(lq[0, -1]) * jnp.linalg.norm(lf[0, -1]))
    )
    assert cos > 0.999, cos


@pytest.mark.slow
def test_quantized_params_flow_through_generation_engine():
    from tpumlops.server.generation import GenerationEngine

    cfg = llama.LlamaConfig.tiny(max_seq=64)
    params = llama.init(jax.random.key(1), cfg, dtype=jnp.float32)
    qparams = quantize_llama(params)
    engine = GenerationEngine(qparams, cfg, max_slots=2, dtype=jnp.float32)
    engine.start(warmup=True)
    try:
        out = engine.generate([5, 9, 2], 6)
        assert out.shape == (6,)
        out2 = engine.generate([5, 9, 2], 6)
        assert out.tolist() == out2.tolist()  # greedy: deterministic
    finally:
        engine.shutdown()


def test_loader_quantize_plumbing(tmp_path):
    from tpumlops.server.loader import ModelLoadError, load_predictor, save_native_model

    cfg = llama.LlamaConfig.tiny(max_seq=64)
    params = llama.init(jax.random.key(2), cfg, dtype=jnp.float32)
    art = tmp_path / "llm"
    save_native_model(
        art,
        "llama-generate",
        params,
        config={
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "num_layers": cfg.num_layers,
            "num_heads": cfg.num_heads,
            "num_kv_heads": cfg.num_kv_heads,
            "intermediate_size": cfg.intermediate_size,
            "max_seq": cfg.max_seq,
        },
    )
    pred = load_predictor(str(art), quantize="int8")
    assert is_quantized(pred.causal_lm["params"]["lm_head"])
    # Every layer matmul too (regression: the streaming loader's leaf
    # name list must use the npz flat-key separator, or layers silently
    # stay full-precision while lm_head matches by accident).
    for name in ("q", "k", "v", "o", "gate", "up", "down"):
        assert is_quantized(pred.causal_lm["params"]["layers"][name]), name
    out = pred.predict(np.ones((1, 4), np.int32))
    assert np.asarray(out).shape[0] == 1

    # Non-causal flavors reject quantization loudly.
    from sklearn.datasets import load_iris
    from sklearn.linear_model import LogisticRegression

    from tpumlops.server.loader import save_sklearn_model

    X, y = load_iris(return_X_y=True)
    iris = tmp_path / "iris"
    save_sklearn_model(iris, LogisticRegression(max_iter=200).fit(X, y), "sklearn-linear")
    with pytest.raises(ModelLoadError, match="llama-generate"):
        load_predictor(str(iris), flavor="sklearn-linear", quantize="int8")


def test_quantize_with_tp_sharding():
    """Quantizing sharded params keeps shardings and stays serveable."""
    from tpumlops.parallel import build_mesh, shard_pytree

    cfg = llama.LlamaConfig.tiny(max_seq=32, num_kv_heads=4)
    params = llama.init(jax.random.key(3), cfg, dtype=jnp.float32)
    mesh = build_mesh({"dp": 2, "tp": 4})
    sharded = shard_pytree(params, llama.param_logical_axes(cfg), mesh)
    q = quantize_llama(sharded)
    ids = jnp.asarray([[5, 9, 2]], jnp.int32)
    lf, _ = llama.prefill(params, ids, cfg, dtype=jnp.float32)
    lq, _ = llama.prefill(q, ids, cfg, dtype=jnp.float32)
    cos = float(
        jnp.sum(lq[0, -1] * lf[0, -1])
        / (jnp.linalg.norm(lq[0, -1]) * jnp.linalg.norm(lf[0, -1]))
    )
    assert cos > 0.999, cos


def test_dequantize_bf16_single_rounding():
    """The dequant product must round once (f32 multiply -> bf16), not
    twice (bf16 scale then bf16 multiply)."""
    w = jax.random.normal(jax.random.key(5), (64, 128), jnp.float32) * 0.02
    q = quantize_tensor(w)
    good = dequantize_tensor(q, jnp.bfloat16).astype(jnp.float32)
    double_rounded = (
        q["q8"].astype(jnp.bfloat16) * q["scale"].astype(jnp.bfloat16)
    ).astype(jnp.float32)
    err_good = float(jnp.abs(good - w).max())
    err_double = float(jnp.abs(double_rounded - w).max())
    assert err_good <= err_double
    # And bf16 dequant stays within int8 quantization error + bf16 ulp.
    assert err_good <= float(q["scale"].max()) / 2 + 0.01 * float(jnp.abs(w).max())


# ---------------------------------------------------------------------------
# KV-cache int8 (quantize: int8kv)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_quant_kv_cache_decode_close_to_full_precision():
    from tpumlops.models.llama import QuantRaggedKVCache, RaggedKVCache

    cfg = llama.LlamaConfig.tiny(max_seq=32)
    params = llama.init(jax.random.key(0), cfg, dtype=jnp.float32)
    prompt = jnp.asarray([[5, 9, 2, 11]], jnp.int32)
    logits, seq = llama.prefill(params, prompt, cfg, dtype=jnp.float32)
    tok = jnp.tile(jnp.argmax(logits[:, -1:], axis=-1).astype(jnp.int32), (2, 1))

    full = llama.insert_sequence(
        RaggedKVCache.create(cfg, 2, jnp.float32), seq, jnp.int32(0), jnp.int32(4)
    )
    quant = llama.insert_sequence(
        QuantRaggedKVCache.create(cfg, 2), seq, jnp.int32(0), jnp.int32(4)
    )
    active = jnp.asarray([True, False])
    for _ in range(6):
        lf, full = llama.decode_ragged(
            params, tok, full, cfg, active, dtype=jnp.float32
        )
        lq, quant = llama.decode_ragged(
            params, tok, quant, cfg, active, dtype=jnp.float32
        )
        cos = float(
            jnp.sum(lq[0, -1] * lf[0, -1])
            / (jnp.linalg.norm(lq[0, -1]) * jnp.linalg.norm(lf[0, -1]))
        )
        assert cos > 0.995, cos
        tok = jnp.tile(
            jnp.argmax(lf[0:1, -1:], axis=-1).astype(jnp.int32), (2, 1)
        )
    # storage really is int8
    assert quant.k8.dtype == jnp.int8
    assert quant.lengths[0] == full.lengths[0]


@pytest.mark.slow
def test_engine_kv_quant_end_to_end():
    from tpumlops.server.generation import GenerationEngine

    cfg = llama.LlamaConfig.tiny(max_seq=64)
    params = llama.init(jax.random.key(1), cfg, dtype=jnp.float32)
    engine = GenerationEngine(
        quantize_llama(params), cfg, max_slots=2, dtype=jnp.float32, kv_quant=True
    )
    engine.start(warmup=True)
    try:
        out = engine.generate([5, 9, 2], 6)
        assert out.shape == (6,)
        # deterministic (greedy) and reproducible with a quantized cache
        assert engine.generate([5, 9, 2], 6).tolist() == out.tolist()
        # sampled path over the quantized cache
        s1 = engine.generate([7, 1], 5, temperature=0.9, seed=3)
        s2 = engine.generate([7, 1], 5, temperature=0.9, seed=3)
        assert s1.tolist() == s2.tolist()
    finally:
        engine.shutdown()


def test_loader_int8kv_mode(tmp_path):
    from tpumlops.server.loader import load_predictor, save_native_model

    cfg = llama.LlamaConfig.tiny(max_seq=64)
    params = llama.init(jax.random.key(2), cfg, dtype=jnp.float32)
    art = tmp_path / "llm"
    save_native_model(
        art,
        "llama-generate",
        params,
        config={
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "num_layers": cfg.num_layers,
            "num_heads": cfg.num_heads,
            "num_kv_heads": cfg.num_kv_heads,
            "intermediate_size": cfg.intermediate_size,
            "max_seq": cfg.max_seq,
        },
    )
    pred = load_predictor(str(art), quantize="int8kv")
    assert is_quantized(pred.causal_lm["params"]["lm_head"])


# ---------------------------------------------------------------------------
# Int8 BERT classify (VERDICT round 1, next #4)
# ---------------------------------------------------------------------------


def test_bert_int8_classify_matches_bf16():
    """Dynamic-activation int8 BERT must track the bf16 logits closely
    (the two int8 roundings are the only approximation)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpumlops.models import bert
    from tpumlops.models.quantization import quantize_bert

    cfg = bert.BertConfig.tiny(num_labels=4)
    params = bert.init(jax.random.key(0), cfg)
    qparams = quantize_bert(params)
    ids = jax.random.randint(jax.random.key(1), (8, 32), 0, cfg.vocab_size)

    ref = np.asarray(
        jax.jit(lambda p, i: bert.classify(p, i, cfg=cfg, dtype=jnp.float32))(
            params, ids
        )
    )
    got = np.asarray(
        jax.jit(lambda p, i: bert.classify(p, i, cfg=cfg, dtype=jnp.float32))(
            qparams, ids
        )
    )
    # Logit-scale agreement: quant noise well under the logit spread.
    spread = np.abs(ref).max()
    assert np.abs(got - ref).max() < 0.05 * max(spread, 1.0), (
        np.abs(got - ref).max(), spread
    )


def test_quantize_bert_only_touches_layer_matmuls():
    import jax
    import jax.numpy as jnp

    from tpumlops.models import bert
    from tpumlops.models.quantization import is_quantized, quantize_bert

    cfg = bert.BertConfig.tiny(num_labels=2)
    params = bert.init(jax.random.key(0), cfg)
    q = quantize_bert(params)
    for layer in q["layers"]:
        for g, n in (("attn", "q"), ("attn", "k"), ("attn", "v"),
                     ("attn", "o"), ("mlp", "up"), ("mlp", "down")):
            assert is_quantized(layer[g][n]["w"])
            assert layer[g][n]["b"].dtype == jnp.float32
        assert layer["attn"]["ln"]["scale"].dtype == jnp.float32
    # embeddings / pooler / classifier stay full precision
    assert q["embeddings"]["word"].dtype == jnp.float32
    assert not is_quantized(q["pooler"]["w"])
    assert not is_quantized(q["classifier"]["w"])


def test_loader_bert_int8(tmp_path):
    """spec.tpu.quantize: int8 now applies to bert-classifier (the MXU
    int8 path), with int8kv still rejected (no KV cache)."""
    import pytest

    from tpumlops.models import bert
    from tpumlops.server.loader import ModelLoadError, load_predictor, save_native_model

    cfg = bert.BertConfig.tiny(num_labels=3)
    params = bert.init(jax.random.key(4), cfg)
    art = tmp_path / "bertq"
    save_native_model(
        art,
        "bert-classifier",
        params,
        config={
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "num_layers": cfg.num_layers,
            "num_heads": cfg.num_heads,
            "intermediate_size": cfg.intermediate_size,
            "max_position_embeddings": cfg.max_position_embeddings,
            "num_labels": cfg.num_labels,
        },
    )
    pred = load_predictor(str(art), quantize="int8")
    ids = np.ones((2, 16), np.int32)
    ref = load_predictor(str(art))
    got = np.asarray(pred.predict(ids))
    want = np.asarray(ref.predict(ids))
    assert got.shape == want.shape == (2, 3)
    assert np.abs(got - want).max() < 0.05 * max(np.abs(want).max(), 1.0)
    with pytest.raises(ModelLoadError, match="int8kv"):
        load_predictor(str(art), quantize="int8kv")


def test_streamed_host_quantize_matches_device_quantize(tmp_path, monkeypatch):
    """The loader's host-side (numpy) quantize-on-arrival — the
    TPUMLOPS_HOST_QUANTIZE=1 fallback since round 4 made on-device
    quantize the streaming default — must implement the same scheme as
    quantization.quantize_tensor: identical scales and q8 within one
    rounding ulp."""
    import jax
    import jax.numpy as jnp

    monkeypatch.setenv("TPUMLOPS_HOST_QUANTIZE", "1")

    from tpumlops.models import llama
    from tpumlops.models.quantization import quantize_llama

    from tpumlops.server.loader import load_predictor, save_native_model

    cfg = llama.LlamaConfig.tiny()
    params = llama.init(jax.random.key(7), cfg, dtype=jnp.bfloat16)
    art = tmp_path / "llq"
    save_native_model(
        art, "llama-generate", params,
        config={
            "vocab_size": cfg.vocab_size, "hidden_size": cfg.hidden_size,
            "num_layers": cfg.num_layers, "num_heads": cfg.num_heads,
            "num_kv_heads": cfg.num_kv_heads,
            "intermediate_size": cfg.intermediate_size, "max_seq": cfg.max_seq,
        },
    )
    streamed = load_predictor(str(art), quantize="int8").causal_lm["params"]
    ref = quantize_llama(
        load_predictor(str(art)).causal_lm["params"]
    )
    for name in ("q", "k", "v", "o", "gate", "up", "down"):
        s_leaf = streamed["layers"][name]
        r_leaf = ref["layers"][name]
        np.testing.assert_allclose(
            np.asarray(s_leaf["scale"]), np.asarray(r_leaf["scale"]),
            rtol=1e-6, err_msg=name,
        )
        diff = np.abs(
            np.asarray(s_leaf["q8"], np.int32) - np.asarray(r_leaf["q8"], np.int32)
        )
        assert diff.max() <= 1, (name, diff.max())  # rounding-tie ulp only


def test_streamed_device_quantize_is_exact(tmp_path):
    """The default streaming path quantizes ON DEVICE through the one
    canonical quantize_tensor, so its output must be bit-identical to
    quantizing the loaded bf16 tree in one shot."""
    import jax
    import jax.numpy as jnp

    from tpumlops.models import llama
    from tpumlops.models.quantization import quantize_llama
    from tpumlops.server.loader import load_predictor, save_native_model

    cfg = llama.LlamaConfig.tiny()
    params = llama.init(jax.random.key(11), cfg, dtype=jnp.bfloat16)
    art = tmp_path / "llq2"
    save_native_model(
        art, "llama-generate", params,
        config={
            "vocab_size": cfg.vocab_size, "hidden_size": cfg.hidden_size,
            "num_layers": cfg.num_layers, "num_heads": cfg.num_heads,
            "num_kv_heads": cfg.num_kv_heads,
            "intermediate_size": cfg.intermediate_size, "max_seq": cfg.max_seq,
        },
    )
    streamed = load_predictor(str(art), quantize="int8").causal_lm["params"]
    ref = quantize_llama(load_predictor(str(art)).causal_lm["params"])
    for name in ("q", "k", "v", "o", "gate", "up", "down"):
        np.testing.assert_array_equal(
            np.asarray(streamed["layers"][name]["q8"]),
            np.asarray(ref["layers"][name]["q8"]), err_msg=name,
        )
        np.testing.assert_array_equal(
            np.asarray(streamed["layers"][name]["scale"]),
            np.asarray(ref["layers"][name]["scale"]), err_msg=name,
        )
    np.testing.assert_array_equal(
        np.asarray(streamed["lm_head"]["q8"]), np.asarray(ref["lm_head"]["q8"])
    )
