"""Multi-host predictor unit: lockstep dispatch + manifest wiring.

SURVEY §7 hard part 5 — one predictor = N pods.  The N-host unit is
exercised in one process via LocalGroupTransport (threads as hosts);
the real DCN path (JaxProcessTransport) is covered in its single-process
degenerate form, which exercises the same encode/size-header logic.
"""

import threading

import numpy as np
import pytest

from tpumlops.models.registry import Predictor
from tpumlops.server.engine import InferenceEngine
from tpumlops.server.multihost import (
    JaxProcessTransport,
    MultihostEngine,
    _LocalGroup,
    decode_message,
    encode_message,
    follower_loop,
)


def _engine(jittable=True):
    return InferenceEngine(
        Predictor(
            name="double",
            predict=lambda x: x * 2.0,
            jittable=jittable,
            example_input=lambda b: np.zeros((b, 3), np.float32),
        ),
        max_batch_size=4,
    )


def _unit(n_hosts):
    """Build a leader engine + started follower threads; returns
    (leader MultihostEngine, follower step-count results, threads)."""
    group = _LocalGroup(n_hosts)
    transports = group.transports()
    leader = MultihostEngine(_engine(), transports[0])
    results = [None] * (n_hosts - 1)
    threads = []
    for i, t in enumerate(transports[1:]):
        def run(i=i, t=t):
            results[i] = follower_loop(_engine(), t)

        th = threading.Thread(target=run, daemon=True)
        th.start()
        threads.append(th)
    return leader, results, threads


def test_followers_execute_in_lockstep():
    leader, results, threads = _unit(3)
    x = np.ones((2, 3), np.float32)
    out = leader.predict({"x": x})
    np.testing.assert_allclose(out, x * 2.0)
    leader.predict({"x": x})
    leader.shutdown()
    for th in threads:
        th.join(timeout=10)
    assert results == [2, 2]  # both followers ran both steps


def test_warmup_broadcasts_every_bucket():
    leader, results, threads = _unit(2)
    leader.warmup()
    leader.shutdown()
    threads[0].join(timeout=10)
    # buckets 1, 2, 4 for max_batch_size=4
    assert results[0] == 3


def test_leader_concurrency_does_not_desync():
    leader, results, threads = _unit(2)
    errors = []

    def hammer():
        try:
            for _ in range(10):
                leader.predict({"x": np.ones((1, 3), np.float32)})
        except Exception as e:  # pragma: no cover
            errors.append(e)

    hammers = [threading.Thread(target=hammer) for _ in range(4)]
    for h in hammers:
        h.start()
    for h in hammers:
        h.join(timeout=30)
    leader.shutdown()
    threads[0].join(timeout=10)
    assert not errors
    assert results[0] == 40


def test_follower_refuses_leader_role_and_vice_versa():
    group = _LocalGroup(2)
    leader_t, follower_t = group.transports()
    with pytest.raises(ValueError):
        MultihostEngine(_engine(), follower_t)
    with pytest.raises(ValueError):
        follower_loop(_engine(), leader_t)


def test_message_roundtrip():
    x = {"a": np.arange(6, dtype=np.int32).reshape(2, 3)}
    op, inputs = decode_message(encode_message("predict", x))
    assert op == "predict"
    np.testing.assert_array_equal(inputs["a"], x["a"])
    op, inputs = decode_message(encode_message("shutdown"))
    assert op == "shutdown" and inputs is None


def test_jax_transport_single_process_degenerate():
    # process_count()==1 in tests: broadcast is identity, but the header
    # round and byte plumbing are the same code the DCN path runs.
    t = JaxProcessTransport()
    assert t.is_leader
    payload = encode_message("predict", {"x": np.zeros((1, 3), np.float32)})
    assert t.broadcast(payload) == payload


# ---------------------------------------------------------------------------
# Builder wiring
# ---------------------------------------------------------------------------


def _tpu_manifest(topology, mesh):
    from tpumlops.operator.builder import build_deployment
    from tpumlops.utils.config import OperatorConfig

    cfg = OperatorConfig.from_spec(
        {
            "modelName": "m",
            "modelAlias": "champion",
            "backend": "tpu",
            "tpu": {"tpuTopology": topology, "meshShape": mesh},
        }
    )
    return build_deployment(
        name="m",
        namespace="ns",
        owner_uid="uid",
        config=cfg,
        current_version="7",
        new_model_uri="s3://mlflow/7",
        traffic_current=100,
    )


def test_builder_multihost_unit_wiring():
    sd = _tpu_manifest("v5e-16", {"dp": 1, "tp": 16})
    (pred,) = sd["spec"]["predictors"]
    unit = pred["tpuWorkerUnit"]
    assert unit["hosts"] == 4
    assert unit["chipsPerHost"] == 4
    assert unit["name"] == "m-v7-workers"
    assert unit["serviceSelectorExtra"] == {"apps.kubernetes.io/pod-index": "0"}
    # routing-only predictor: pods belong to the StatefulSet, and a Seldon
    # controller consuming this CR must not double-materialize them
    assert "componentSpecs" not in pred


def test_builder_worker_unit_manifests():
    from tpumlops.operator.builder import build_worker_unit_manifests
    from tpumlops.utils.config import OperatorConfig

    cfg = OperatorConfig.from_spec(
        {
            "modelName": "m",
            "modelAlias": "champion",
            "backend": "tpu",
            "tpu": {"tpuTopology": "v5e-16", "meshShape": {"dp": 1, "tp": 16}},
        }
    )
    headless, routed, sts = build_worker_unit_manifests(
        "m", "ns", "uid", cfg, "7", "s3://mlflow/7"
    )
    assert headless["spec"]["clusterIP"] == "None"
    assert headless["spec"]["publishNotReadyAddresses"] is True
    assert routed["spec"]["selector"]["apps.kubernetes.io/pod-index"] == "0"
    assert routed["metadata"]["name"] == "m-v7"  # matches warmup URL template

    assert sts["spec"]["replicas"] == 4
    assert sts["spec"]["podManagementPolicy"] == "Parallel"
    container = sts["spec"]["template"]["spec"]["containers"][0]
    env = {e["name"]: e for e in container["env"]}
    assert env["JAX_NUM_PROCESSES"]["value"] == "4"
    assert (
        env["JAX_COORDINATOR_ADDRESS"]["value"]
        == "m-v7-workers-0.m-v7-workers.ns.svc.cluster.local:8476"
    )
    assert (
        env["JAX_PROCESS_ID"]["valueFrom"]["fieldRef"]["fieldPath"]
        == "metadata.labels['apps.kubernetes.io/pod-index']"
    )
    # the TPU request is per-host, not per-slice
    assert container["resources"]["limits"]["google.com/tpu"] == "4"

    # single-host: no units at all
    cfg8 = OperatorConfig.from_spec(
        {
            "modelName": "m",
            "modelAlias": "champion",
            "backend": "tpu",
            "tpu": {"tpuTopology": "v5e-8", "meshShape": {"dp": 1, "tp": 8}},
        }
    )
    assert build_worker_unit_manifests("m", "ns", "uid", cfg8, "7", "u") == []


def test_multihost_replicas_rejected():
    from tpumlops.utils.config import OperatorConfig

    with pytest.raises(ValueError, match="replicas"):
        OperatorConfig.from_spec(
            {
                "modelName": "m",
                "modelAlias": "champion",
                "backend": "tpu",
                "tpu": {
                    "tpuTopology": "v5e-16",
                    "meshShape": {"dp": 1, "tp": 16},
                    "replicas": 2,
                },
            }
        )


def test_predict_after_shutdown_raises():
    leader, results, threads = _unit(2)
    leader.shutdown()
    threads[0].join(timeout=10)
    with pytest.raises(RuntimeError, match="shut down"):
        leader.predict({"x": np.ones((1, 3), np.float32)})
    leader.shutdown()  # idempotent


def test_follower_survives_model_error():
    group = _LocalGroup(2)
    leader_t, follower_t = group.transports()

    def bad_predict(x):
        raise ValueError("bad input")

    bad_engine = InferenceEngine(
        Predictor(name="bad", predict=bad_predict, jittable=False)
    )
    result = {}

    def run():
        result["n"] = follower_loop(bad_engine, follower_t)

    th = threading.Thread(target=run, daemon=True)
    th.start()
    leader = MultihostEngine(_engine(), leader_t)
    # leader succeeds; follower's predict raises but it keeps lockstep
    leader.predict({"x": np.ones((1, 3), np.float32)})
    leader.predict({"x": np.ones((1, 3), np.float32)})
    leader.shutdown()
    th.join(timeout=10)
    assert result["n"] == 2


def test_builder_single_host_has_no_unit_block():
    sd = _tpu_manifest("v5e-8", {"dp": 1, "tp": 8})
    (pred,) = sd["spec"]["predictors"]
    assert "tpuWorkerUnit" not in pred
    container = pred["componentSpecs"][0]["spec"]["containers"][0]
    assert container["resources"]["limits"]["google.com/tpu"] == "8"
    assert not any(
        e["name"].startswith("JAX_COORDINATOR") for e in container["env"]
    )


def test_topology_table_consistency():
    from tpumlops.utils.config import TPU_TOPOLOGIES

    for name, info in TPU_TOPOLOGIES.items():
        assert info.chips % info.hosts == 0, name
        # tuple-style compat for (accelerator, topology, chips) consumers
        assert info[0] == info.accelerator
        assert info[2] == info.chips


# ---------------------------------------------------------------------------
# Reconciler materialization of worker units
# ---------------------------------------------------------------------------


def _mh_world():
    from tpumlops.clients.base import MLFLOWMODEL, ModelMetrics, ObjectRef
    from tpumlops.clients.fakes import FakeKube, FakeMetrics, FakeRegistry
    from tpumlops.operator.reconciler import Reconciler
    from tpumlops.utils.clock import FakeClock

    kube, registry, metrics, clock = FakeKube(), FakeRegistry(), FakeMetrics(), FakeClock()
    kube.create(
        ObjectRef(namespace="ns", name="m", **MLFLOWMODEL),
        {
            "apiVersion": "mlflow.nizepart.com/v1alpha1",
            "kind": "MlflowModel",
            "metadata": {"name": "m", "namespace": "ns"},
            "spec": {
                "modelName": "m",
                "modelAlias": "champion",
                "backend": "tpu",
                "tpu": {"tpuTopology": "v5e-16", "meshShape": {"dp": 1, "tp": 16}},
                "canary": {"stepInterval": 1, "attemptDelay": 1},
            },
        },
    )
    registry.register("m", "1", "mlflow-artifacts:/1/aaa/artifacts/model")
    registry.set_alias("m", "champion", "1")
    good = ModelMetrics(latency_p95=0.1, error_rate=0.01, latency_avg=0.05, request_count=500)
    metrics.set_metrics("m", "v1", "ns", good)
    metrics.set_metrics("m", "v2", "ns", good)
    rec = Reconciler("m", "ns", kube, registry, metrics, clock)
    return kube, registry, metrics, clock, rec


def _sts_names(kube):
    from tpumlops.clients.base import ObjectRef

    ref = ObjectRef(group="apps", version="v1", namespace="ns", plural="statefulsets", name="")
    return sorted(o["metadata"]["name"] for o in kube.list(ref))


def _svc_names(kube):
    from tpumlops.clients.base import ObjectRef

    ref = ObjectRef(group="", version="v1", namespace="ns", plural="services", name="")
    return sorted(o["metadata"]["name"] for o in kube.list(ref))


def test_reconciler_materializes_and_gcs_worker_units():
    from tpumlops.clients.base import MLFLOWMODEL, ObjectRef
    from tpumlops.operator.state import Phase

    kube, registry, metrics, clock, rec = _mh_world()
    cr = ObjectRef(namespace="ns", name="m", **MLFLOWMODEL)

    out = rec.reconcile(kube.get(cr))
    assert out.state.phase == Phase.STABLE
    assert _sts_names(kube) == ["m-v1-workers"]
    assert _svc_names(kube) == ["m-v1", "m-v1-workers"]
    sts = kube.get(ObjectRef(group="apps", version="v1", namespace="ns",
                             plural="statefulsets", name="m-v1-workers"))
    assert sts["spec"]["replicas"] == 4
    assert sts["spec"]["podManagementPolicy"] == "Parallel"
    assert sts["spec"]["serviceName"] == "m-v1-workers"

    # new version -> canary: both versions' units exist side-by-side
    registry.register("m", "2", "mlflow-artifacts:/1/bbb/artifacts/model")
    registry.set_alias("m", "champion", "2")
    out = rec.reconcile(kube.get(cr))
    assert out.state.phase == Phase.CANARY
    assert _sts_names(kube) == ["m-v1-workers", "m-v2-workers"]

    # drive promotion to 100%: the old unit is garbage-collected
    for _ in range(40):
        clock.advance(2)
        out = rec.reconcile(kube.get(cr))
        if out.state.phase == Phase.STABLE:
            break
    assert out.state.phase == Phase.STABLE
    assert _sts_names(kube) == ["m-v2-workers"]
    assert _svc_names(kube) == ["m-v2", "m-v2-workers"]

    # CR teardown deletes the remaining unit
    rec._delete_deployment()
    assert _sts_names(kube) == []
    assert _svc_names(kube) == []


# ---------------------------------------------------------------------------
# Multi-host continuous-batching generation (lockstep replay)
# ---------------------------------------------------------------------------


def _gen_unit(n_hosts, cfg, params, dtype):
    """Leader GenerationEngine + follower replay threads over a local group.

    Each 'host' owns an independent GenerationEngine (same params/config);
    lockstep means their device state evolves identically from the same
    broadcast op stream."""
    from tpumlops.server.generation import GenerationEngine
    from tpumlops.server.multihost import UnitChannel

    group = _LocalGroup(n_hosts)
    transports = group.transports()
    channel = UnitChannel(transports[0])
    leader = GenerationEngine(params, cfg, max_slots=2, dtype=dtype, channel=channel)
    followers = []
    results = [None] * (n_hosts - 1)
    threads = []
    for i, t in enumerate(transports[1:]):
        f = GenerationEngine(params, cfg, max_slots=2, dtype=dtype)
        followers.append(f)

        def run(i=i, t=t, f=f):
            results[i] = follower_loop(_engine(), t, gen_engine=f)

        th = threading.Thread(target=run, daemon=True)
        th.start()
        threads.append(th)
    return leader, followers, results, threads, channel


@pytest.mark.slow
def test_multihost_generation_lockstep_and_state_parity():
    import jax
    import jax.numpy as jnp

    from tpumlops.models import llama
    from tpumlops.server.multihost import OP_SHUTDOWN

    jax.config.update("jax_enable_x64", True)
    try:
        cfg = llama.LlamaConfig.tiny(max_seq=64)
        params = llama.init(jax.random.key(0), cfg, dtype=jnp.float64)
        ref = np.asarray(
            llama.generate_greedy(
                params, jnp.asarray([[5, 9, 2]], jnp.int32), 6, cfg,
                dtype=jnp.float64,
            )
        )[0].tolist()

        leader, followers, results, threads, channel = _gen_unit(
            2, cfg, params, jnp.float64
        )
        leader.start(warmup=True)
        try:
            out = leader.generate([5, 9, 2], 6).tolist()
            sampled = leader.generate(
                [7, 1], 5, temperature=0.9, top_k=4, seed=11
            ).tolist()
        finally:
            leader.shutdown()
            channel.close_with(encode_message(OP_SHUTDOWN))
        for th in threads:
            th.join(timeout=30)

        assert out == ref
        assert len(sampled) == 5
        # The follower executed every broadcast op and its device state
        # converged to the leader's (same tokens, lengths, cache).
        assert results[0] is not None and results[0] > 0
        f = followers[0]
        np.testing.assert_array_equal(
            np.asarray(leader._tokens), np.asarray(f._tokens)
        )
        np.testing.assert_array_equal(
            np.asarray(leader._lengths), np.asarray(f._lengths)
        )
        np.testing.assert_allclose(
            np.asarray(leader._cache_k), np.asarray(f._cache_k)
        )
    finally:
        jax.config.update("jax_enable_x64", False)


def test_multihost_generation_interleaved_with_predict():
    import jax
    import jax.numpy as jnp

    from tpumlops.models import llama
    from tpumlops.server.generation import GenerationEngine
    from tpumlops.server.multihost import OP_SHUTDOWN, UnitChannel

    cfg = llama.LlamaConfig.tiny(max_seq=64)
    params = llama.init(jax.random.key(1), cfg, dtype=jnp.float32)

    group = _LocalGroup(2)
    transports = group.transports()
    leader_pred = MultihostEngine(_engine(), transports[0])
    gen = GenerationEngine(
        params, cfg, max_slots=2, dtype=jnp.float32,
        channel=leader_pred.channel,
    )
    follower_gen = GenerationEngine(params, cfg, max_slots=2, dtype=jnp.float32)
    result = {}

    def run():
        result["steps"] = follower_loop(
            _engine(), transports[1], gen_engine=follower_gen
        )

    th = threading.Thread(target=run, daemon=True)
    th.start()

    gen.start(warmup=False)
    try:
        x = np.ones((2, 3), np.float32)
        out = leader_pred.predict({"x": x})  # predict op on the shared channel
        np.testing.assert_allclose(np.asarray(out), x * 2.0)
        toks = gen.generate([5, 9, 2], 4)
        assert toks.shape == (4,)
    finally:
        gen.shutdown()
        leader_pred.shutdown()  # closes the shared channel
    th.join(timeout=30)
    assert result["steps"] >= 3  # 1 predict + admit + decode ticks


def test_multihost_gen_reset_broadcast_on_leader_failure():
    """A leader-side gen failure must broadcast OP_GEN_RESET so followers
    drop to the same fresh state instead of silently diverging."""
    import jax
    import jax.numpy as jnp

    from tpumlops.models import llama
    from tpumlops.server.generation import GenerationEngine
    from tpumlops.server.multihost import OP_SHUTDOWN, UnitChannel

    cfg = llama.LlamaConfig.tiny(max_seq=64)
    params = llama.init(jax.random.key(2), cfg, dtype=jnp.float32)
    group = _LocalGroup(2)
    transports = group.transports()
    channel = UnitChannel(transports[0])
    leader = GenerationEngine(
        params, cfg, max_slots=2, dtype=jnp.float32, channel=channel
    )
    follower = GenerationEngine(params, cfg, max_slots=2, dtype=jnp.float32)
    result = {}

    def run():
        result["steps"] = follower_loop(
            _engine(), transports[1], gen_engine=follower
        )

    th = threading.Thread(target=run, daemon=True)
    th.start()
    leader.start(warmup=False)
    try:
        assert leader.generate([5, 9, 2], 3).shape == (3,)

        # Poison one decode variant; next request fails, engine recovers.
        real = leader._decode_greedy

        def bomb(*a, **kw):
            raise RuntimeError("injected")

        leader._decode_greedy = bomb
        import pytest as _pytest

        with _pytest.raises(RuntimeError):
            leader.generate([7, 1], 4, timeout=30)
        leader._decode_greedy = real

        # Post-recovery request works AND follower state converges again.
        out = leader.generate([5, 9, 2], 3)
        assert out.shape == (3,)
    finally:
        leader.shutdown()
        channel.close_with(encode_message(OP_SHUTDOWN))
    th.join(timeout=30)
    np.testing.assert_array_equal(
        np.asarray(leader._lengths), np.asarray(follower._lengths)
    )
    np.testing.assert_array_equal(
        np.asarray(leader._tokens), np.asarray(follower._tokens)
    )


@pytest.mark.slow
def test_multihost_chunked_prefill_lockstep():
    import jax
    import jax.numpy as jnp

    from tpumlops.models import llama
    from tpumlops.server.generation import GenerationEngine
    from tpumlops.server.multihost import OP_SHUTDOWN, UnitChannel

    jax.config.update("jax_enable_x64", True)
    try:
        cfg = llama.LlamaConfig.tiny(max_seq=64)
        params = llama.init(jax.random.key(0), cfg, dtype=jnp.float64)
        prompt = list(range(2, 23))  # 3 chunks of 8
        ref = np.asarray(
            llama.generate_greedy(
                params, jnp.asarray([prompt], jnp.int32), 5, cfg,
                dtype=jnp.float64,
            )
        )[0].tolist()

        group = _LocalGroup(2)
        transports = group.transports()
        channel = UnitChannel(transports[0])
        leader = GenerationEngine(
            params, cfg, max_slots=2, dtype=jnp.float64,
            channel=channel, prefill_chunk=8,
        )
        follower = GenerationEngine(
            params, cfg, max_slots=2, dtype=jnp.float64, prefill_chunk=8
        )
        result = {}

        def run():
            result["steps"] = follower_loop(
                _engine(), transports[1], gen_engine=follower
            )

        th = threading.Thread(target=run, daemon=True)
        th.start()
        leader.start(warmup=True)
        try:
            out = leader.generate(prompt, 5).tolist()
        finally:
            leader.shutdown()
            channel.close_with(encode_message(OP_SHUTDOWN))
        th.join(timeout=30)
        assert out == ref
        np.testing.assert_array_equal(
            np.asarray(leader._lengths), np.asarray(follower._lengths)
        )
        np.testing.assert_array_equal(
            np.asarray(leader._tokens), np.asarray(follower._tokens)
        )
    finally:
        jax.config.update("jax_enable_x64", False)
