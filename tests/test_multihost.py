"""Multi-host predictor unit: lockstep dispatch + manifest wiring.

SURVEY §7 hard part 5 — one predictor = N pods.  The N-host unit is
exercised in one process via LocalGroupTransport (threads as hosts);
the real DCN path (JaxProcessTransport) is covered in its single-process
degenerate form, which exercises the same encode/size-header logic.
"""

import threading

import numpy as np
import pytest

from tpumlops.models.registry import Predictor
from tpumlops.server.engine import InferenceEngine
from tpumlops.server.multihost import (
    JaxProcessTransport,
    LocalGroupTransport,
    MultihostEngine,
    _LocalGroup,
    decode_message,
    encode_message,
    follower_loop,
)


def _engine(jittable=True):
    return InferenceEngine(
        Predictor(
            name="double",
            predict=lambda x: x * 2.0,
            jittable=jittable,
            example_input=lambda b: np.zeros((b, 3), np.float32),
        ),
        max_batch_size=4,
    )


def _unit(n_hosts):
    """Build a leader engine + started follower threads; returns
    (leader MultihostEngine, follower step-count results, threads)."""
    group = _LocalGroup(n_hosts)
    transports = group.transports()
    leader = MultihostEngine(_engine(), transports[0])
    results = [None] * (n_hosts - 1)
    threads = []
    for i, t in enumerate(transports[1:]):
        def run(i=i, t=t):
            results[i] = follower_loop(_engine(), t)

        th = threading.Thread(target=run, daemon=True)
        th.start()
        threads.append(th)
    return leader, results, threads


def test_followers_execute_in_lockstep():
    leader, results, threads = _unit(3)
    x = np.ones((2, 3), np.float32)
    out = leader.predict({"x": x})
    np.testing.assert_allclose(out, x * 2.0)
    leader.predict({"x": x})
    leader.shutdown()
    for th in threads:
        th.join(timeout=10)
    assert results == [2, 2]  # both followers ran both steps


def test_warmup_broadcasts_every_bucket():
    leader, results, threads = _unit(2)
    leader.warmup()
    leader.shutdown()
    threads[0].join(timeout=10)
    # buckets 1, 2, 4 for max_batch_size=4
    assert results[0] == 3


def test_leader_concurrency_does_not_desync():
    leader, results, threads = _unit(2)
    errors = []

    def hammer():
        try:
            for _ in range(10):
                leader.predict({"x": np.ones((1, 3), np.float32)})
        except Exception as e:  # pragma: no cover
            errors.append(e)

    hammers = [threading.Thread(target=hammer) for _ in range(4)]
    for h in hammers:
        h.start()
    for h in hammers:
        h.join(timeout=30)
    leader.shutdown()
    threads[0].join(timeout=10)
    assert not errors
    assert results[0] == 40


def test_follower_refuses_leader_role_and_vice_versa():
    group = _LocalGroup(2)
    leader_t, follower_t = group.transports()
    with pytest.raises(ValueError):
        MultihostEngine(_engine(), follower_t)
    with pytest.raises(ValueError):
        follower_loop(_engine(), leader_t)


def test_message_roundtrip():
    x = {"a": np.arange(6, dtype=np.int32).reshape(2, 3)}
    op, inputs = decode_message(encode_message("predict", x))
    assert op == "predict"
    np.testing.assert_array_equal(inputs["a"], x["a"])
    op, inputs = decode_message(encode_message("shutdown"))
    assert op == "shutdown" and inputs is None


def test_jax_transport_single_process_degenerate():
    # process_count()==1 in tests: broadcast is identity, but the header
    # round and byte plumbing are the same code the DCN path runs.
    t = JaxProcessTransport()
    assert t.is_leader
    payload = encode_message("predict", {"x": np.zeros((1, 3), np.float32)})
    assert t.broadcast(payload) == payload


# ---------------------------------------------------------------------------
# Builder wiring
# ---------------------------------------------------------------------------


def _tpu_manifest(topology, mesh):
    from tpumlops.operator.builder import build_deployment
    from tpumlops.utils.config import OperatorConfig

    cfg = OperatorConfig.from_spec(
        {
            "modelName": "m",
            "modelAlias": "champion",
            "backend": "tpu",
            "tpu": {"tpuTopology": topology, "meshShape": mesh},
        }
    )
    return build_deployment(
        name="m",
        namespace="ns",
        owner_uid="uid",
        config=cfg,
        current_version="7",
        new_model_uri="s3://mlflow/7",
        traffic_current=100,
    )


def test_builder_multihost_unit_wiring():
    sd = _tpu_manifest("v5e-16", {"dp": 1, "tp": 16})
    (pred,) = sd["spec"]["predictors"]
    unit = pred["tpuWorkerUnit"]
    assert unit["hosts"] == 4
    assert unit["chipsPerHost"] == 4
    assert unit["name"] == "m-v7-workers"
    assert unit["serviceSelectorExtra"] == {"apps.kubernetes.io/pod-index": "0"}
    # routing-only predictor: pods belong to the StatefulSet, and a Seldon
    # controller consuming this CR must not double-materialize them
    assert "componentSpecs" not in pred


def test_builder_worker_unit_manifests():
    from tpumlops.operator.builder import build_worker_unit_manifests
    from tpumlops.utils.config import OperatorConfig

    cfg = OperatorConfig.from_spec(
        {
            "modelName": "m",
            "modelAlias": "champion",
            "backend": "tpu",
            "tpu": {"tpuTopology": "v5e-16", "meshShape": {"dp": 1, "tp": 16}},
        }
    )
    headless, routed, sts = build_worker_unit_manifests(
        "m", "ns", "uid", cfg, "7", "s3://mlflow/7"
    )
    assert headless["spec"]["clusterIP"] == "None"
    assert headless["spec"]["publishNotReadyAddresses"] is True
    assert routed["spec"]["selector"]["apps.kubernetes.io/pod-index"] == "0"
    assert routed["metadata"]["name"] == "m-v7"  # matches warmup URL template

    assert sts["spec"]["replicas"] == 4
    assert sts["spec"]["podManagementPolicy"] == "Parallel"
    container = sts["spec"]["template"]["spec"]["containers"][0]
    env = {e["name"]: e for e in container["env"]}
    assert env["JAX_NUM_PROCESSES"]["value"] == "4"
    assert (
        env["JAX_COORDINATOR_ADDRESS"]["value"]
        == "m-v7-workers-0.m-v7-workers.ns.svc.cluster.local:8476"
    )
    assert (
        env["JAX_PROCESS_ID"]["valueFrom"]["fieldRef"]["fieldPath"]
        == "metadata.labels['apps.kubernetes.io/pod-index']"
    )
    # the TPU request is per-host, not per-slice
    assert container["resources"]["limits"]["google.com/tpu"] == "4"

    # single-host: no units at all
    cfg8 = OperatorConfig.from_spec(
        {
            "modelName": "m",
            "modelAlias": "champion",
            "backend": "tpu",
            "tpu": {"tpuTopology": "v5e-8", "meshShape": {"dp": 1, "tp": 8}},
        }
    )
    assert build_worker_unit_manifests("m", "ns", "uid", cfg8, "7", "u") == []


def test_multihost_replicas_rejected():
    from tpumlops.utils.config import OperatorConfig

    with pytest.raises(ValueError, match="replicas"):
        OperatorConfig.from_spec(
            {
                "modelName": "m",
                "modelAlias": "champion",
                "backend": "tpu",
                "tpu": {
                    "tpuTopology": "v5e-16",
                    "meshShape": {"dp": 1, "tp": 16},
                    "replicas": 2,
                },
            }
        )


def test_predict_after_shutdown_raises():
    leader, results, threads = _unit(2)
    leader.shutdown()
    threads[0].join(timeout=10)
    with pytest.raises(RuntimeError, match="shut down"):
        leader.predict({"x": np.ones((1, 3), np.float32)})
    leader.shutdown()  # idempotent


def test_follower_survives_model_error():
    group = _LocalGroup(2)
    leader_t, follower_t = group.transports()

    def bad_predict(x):
        raise ValueError("bad input")

    bad_engine = InferenceEngine(
        Predictor(name="bad", predict=bad_predict, jittable=False)
    )
    result = {}

    def run():
        result["n"] = follower_loop(bad_engine, follower_t)

    th = threading.Thread(target=run, daemon=True)
    th.start()
    leader = MultihostEngine(_engine(), leader_t)
    # leader succeeds; follower's predict raises but it keeps lockstep
    leader.predict({"x": np.ones((1, 3), np.float32)})
    leader.predict({"x": np.ones((1, 3), np.float32)})
    leader.shutdown()
    th.join(timeout=10)
    assert result["n"] == 2


def test_builder_single_host_has_no_unit_block():
    sd = _tpu_manifest("v5e-8", {"dp": 1, "tp": 8})
    (pred,) = sd["spec"]["predictors"]
    assert "tpuWorkerUnit" not in pred
    container = pred["componentSpecs"][0]["spec"]["containers"][0]
    assert container["resources"]["limits"]["google.com/tpu"] == "8"
    assert not any(
        e["name"].startswith("JAX_COORDINATOR") for e in container["env"]
    )


def test_topology_table_consistency():
    from tpumlops.utils.config import TPU_TOPOLOGIES

    for name, info in TPU_TOPOLOGIES.items():
        assert info.chips % info.hosts == 0, name
        # tuple-style compat for (accelerator, topology, chips) consumers
        assert info[0] == info.accelerator
        assert info[2] == info.chips


# ---------------------------------------------------------------------------
# Reconciler materialization of worker units
# ---------------------------------------------------------------------------


def _mh_world():
    from tpumlops.clients.base import MLFLOWMODEL, ModelMetrics, ObjectRef
    from tpumlops.clients.fakes import FakeKube, FakeMetrics, FakeRegistry
    from tpumlops.operator.reconciler import Reconciler
    from tpumlops.utils.clock import FakeClock

    kube, registry, metrics, clock = FakeKube(), FakeRegistry(), FakeMetrics(), FakeClock()
    kube.create(
        ObjectRef(namespace="ns", name="m", **MLFLOWMODEL),
        {
            "apiVersion": "mlflow.nizepart.com/v1alpha1",
            "kind": "MlflowModel",
            "metadata": {"name": "m", "namespace": "ns"},
            "spec": {
                "modelName": "m",
                "modelAlias": "champion",
                "backend": "tpu",
                "tpu": {"tpuTopology": "v5e-16", "meshShape": {"dp": 1, "tp": 16}},
                "canary": {"stepInterval": 1, "attemptDelay": 1},
            },
        },
    )
    registry.register("m", "1", "mlflow-artifacts:/1/aaa/artifacts/model")
    registry.set_alias("m", "champion", "1")
    good = ModelMetrics(latency_p95=0.1, error_rate=0.01, latency_avg=0.05, request_count=500)
    metrics.set_metrics("m", "v1", "ns", good)
    metrics.set_metrics("m", "v2", "ns", good)
    rec = Reconciler("m", "ns", kube, registry, metrics, clock)
    return kube, registry, metrics, clock, rec


def _sts_names(kube):
    from tpumlops.clients.base import ObjectRef

    ref = ObjectRef(group="apps", version="v1", namespace="ns", plural="statefulsets", name="")
    return sorted(o["metadata"]["name"] for o in kube.list(ref))


def _svc_names(kube):
    from tpumlops.clients.base import ObjectRef

    ref = ObjectRef(group="", version="v1", namespace="ns", plural="services", name="")
    return sorted(o["metadata"]["name"] for o in kube.list(ref))


def test_reconciler_materializes_and_gcs_worker_units():
    from tpumlops.clients.base import MLFLOWMODEL, ObjectRef
    from tpumlops.operator.state import Phase

    kube, registry, metrics, clock, rec = _mh_world()
    cr = ObjectRef(namespace="ns", name="m", **MLFLOWMODEL)

    out = rec.reconcile(kube.get(cr))
    assert out.state.phase == Phase.STABLE
    assert _sts_names(kube) == ["m-v1-workers"]
    assert _svc_names(kube) == ["m-v1", "m-v1-workers"]
    sts = kube.get(ObjectRef(group="apps", version="v1", namespace="ns",
                             plural="statefulsets", name="m-v1-workers"))
    assert sts["spec"]["replicas"] == 4
    assert sts["spec"]["podManagementPolicy"] == "Parallel"
    assert sts["spec"]["serviceName"] == "m-v1-workers"

    # new version -> canary: both versions' units exist side-by-side
    registry.register("m", "2", "mlflow-artifacts:/1/bbb/artifacts/model")
    registry.set_alias("m", "champion", "2")
    out = rec.reconcile(kube.get(cr))
    assert out.state.phase == Phase.CANARY
    assert _sts_names(kube) == ["m-v1-workers", "m-v2-workers"]

    # drive promotion to 100%: the old unit is garbage-collected
    for _ in range(40):
        clock.advance(2)
        out = rec.reconcile(kube.get(cr))
        if out.state.phase == Phase.STABLE:
            break
    assert out.state.phase == Phase.STABLE
    assert _sts_names(kube) == ["m-v2-workers"]
    assert _svc_names(kube) == ["m-v2", "m-v2-workers"]

    # CR teardown deletes the remaining unit
    rec._delete_deployment()
    assert _sts_names(kube) == []
    assert _svc_names(kube) == []
